"""Swing — item-similarity recommendation from user-item interactions
(the upstream Flink ML recommendation operator).

For every item pair (i, j), similarity sums over user pairs (u, v) that
both interacted with both items:

    sim(i, j) = Σ_{u,v ∈ U_i ∩ U_j, u<v}  w_u · w_v / (α₁ + |I_u ∩ I_v|)
    w_u = 1 / (α₂ + |I_u|)^β

The "swing" intuition: two users sharing MANY items are weak evidence
for any one pair (the 1/(α₁+overlap) damping); a user pair whose ONLY
overlap is {i, j} is strong evidence.

An AlgoOperator: output is one row per item with its top-k similar
items and scores. Combinatorial set intersection is host work
(``maxUserNumPerItem`` bounds the per-item user-pair blowup exactly as
the upstream operator does); numpy sorted-array intersections do the
counting.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.params import FloatParam, IntParam, ParamValidators, StringParam
from flinkml_tpu.table import Table


class Swing(AlgoOperator):
    USER_COL = StringParam("userCol", "User id column.", "user")
    ITEM_COL = StringParam("itemCol", "Item id column.", "item")
    K = IntParam(
        "k", "How many similar items to keep per item.", 100,
        ParamValidators.gt(0),
    )
    MIN_USER_BEHAVIOR = IntParam(
        "minUserBehavior",
        "Users with fewer interactions are ignored.", 10,
        ParamValidators.gt(0),
    )
    MAX_USER_BEHAVIOR = IntParam(
        "maxUserBehavior",
        "Users with more interactions are ignored (bot guard).", 1000,
        ParamValidators.gt(0),
    )
    MAX_USER_NUM_PER_ITEM = IntParam(
        "maxUserNumPerItem",
        "Cap on each item's user list (bounds the user-pair blowup).",
        1000, ParamValidators.gt(0),
    )
    ALPHA1 = FloatParam(
        "alpha1", "Overlap damping in 1/(alpha1 + |I_u ∩ I_v|).", 15.0,
        ParamValidators.gt_eq(0.0),
    )
    ALPHA2 = FloatParam(
        "alpha2", "Smoothing in the user weight denominator.", 0.0,
        ParamValidators.gt_eq(0.0),
    )
    BETA = FloatParam(
        "beta", "User-activity damping exponent.", 0.3,
        ParamValidators.gt_eq(0.0),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        users = np.asarray(table.column(self.get(self.USER_COL)))
        items = np.asarray(table.column(self.get(self.ITEM_COL)))
        if users.shape[0] != items.shape[0]:
            raise ValueError("user and item columns must have equal length")
        min_b = self.get(self.MIN_USER_BEHAVIOR)
        max_b = self.get(self.MAX_USER_BEHAVIOR)
        if min_b > max_b:
            raise ValueError(
                f"minUserBehavior {min_b} > maxUserBehavior {max_b}"
            )
        user_ids, u_idx = np.unique(users, return_inverse=True)
        item_ids, i_idx = np.unique(items, return_inverse=True)

        # Deduplicated per-user sorted item arrays; pair_codes is sorted,
        # so one searchsorted split groups all users in O(N + U log N).
        pair_codes = np.unique(u_idx.astype(np.int64) * len(item_ids) + i_idx)
        pu = pair_codes // len(item_ids)
        pi = pair_codes % len(item_ids)
        user_items: List[np.ndarray] = np.split(
            pi, np.searchsorted(pu, np.arange(1, len(user_ids)))
        )
        counts = np.asarray([len(v) for v in user_items])
        eligible = (counts >= min_b) & (counts <= max_b)

        alpha1 = self.get(self.ALPHA1)
        alpha2 = self.get(self.ALPHA2)
        beta = self.get(self.BETA)
        weights = 1.0 / np.power(
            alpha2 + np.maximum(counts, 1), beta
        )

        # Per-item eligible user lists, capped (first maxUserNumPerItem in
        # user order, the upstream behavior). The cap GATES contributions:
        # a user evicted from an item's list must not contribute to any
        # similarity involving that item.
        cap = self.get(self.MAX_USER_NUM_PER_ITEM)
        item_users: List[List[int]] = [[] for _ in item_ids]
        item_user_sets: List[set] = [set() for _ in item_ids]
        for u in range(len(user_ids)):
            if not eligible[u]:
                continue
            for it in user_items[u]:
                if len(item_users[it]) < cap:
                    item_users[it].append(u)
                    item_user_sets[it].add(u)

        # Unique user pairs that co-occur on some item's capped list.
        seen_pairs = set()
        for ulist in item_users:
            for a in range(len(ulist)):
                for b in range(a + 1, len(ulist)):
                    seen_pairs.add((ulist[a], ulist[b]))

        sims: Dict[Tuple[int, int], float] = {}
        for u, v in seen_pairs:
            common = np.intersect1d(
                user_items[u], user_items[v], assume_unique=True
            )
            # Damping uses the users' full behavioral overlap; the pair
            # only scores items where BOTH survived the per-item cap.
            m = len(common)
            if m < 2:
                continue
            capped = [
                it for it in common
                if u in item_user_sets[it] and v in item_user_sets[it]
            ]
            if len(capped) < 2:
                continue
            contrib = weights[u] * weights[v] / (alpha1 + m)
            for a in range(len(capped)):
                ia = capped[a]
                for b in range(a + 1, len(capped)):
                    key = (ia, capped[b])
                    sims[key] = sims.get(key, 0.0) + contrib

        # Top-k per item.
        per_item: Dict[int, List[Tuple[float, int]]] = {}
        for (ia, ib), s in sims.items():
            per_item.setdefault(ia, []).append((s, ib))
            per_item.setdefault(ib, []).append((s, ia))
        k = self.get(self.K)
        main_items, similar, scores = [], [], []
        for it in range(len(item_ids)):
            ranked = sorted(
                per_item.get(it, []), key=lambda t: (-t[0], t[1])
            )[:k]
            main_items.append(item_ids[it])
            similar.append(np.asarray([item_ids[j] for _, j in ranked]))
            scores.append(np.asarray([s for s, _ in ranked]))
        sim_col = np.empty(len(main_items), dtype=object)
        score_col = np.empty(len(main_items), dtype=object)
        for i, (sv, sc) in enumerate(zip(similar, scores)):
            sim_col[i] = sv
            score_col[i] = sc
        return (
            Table({
                self.get(self.ITEM_COL): np.asarray(main_items),
                "similarItems": sim_col,
                "scores": score_col,
            }),
        )
