"""BinaryClassificationEvaluator — threshold-curve metrics.

Member of the wider Flink ML operator family (the reference snapshot has
no evaluator; apache/flink-ml's ``BinaryClassificationEvaluator`` defines
the metric set mirrored here): ``areaUnderROC``, ``areaUnderPR``, ``ks``
(max |TPR - FPR|), ``accuracy`` (at the 0.5 threshold), and ``logLoss``
(clipped cross-entropy over probability scores). Weighted rows
supported; ties in the score column are handled exactly (metrics are
computed on the unique-threshold step curve, not per-row).

Computation is a single host-side sort + cumulative sums: evaluation is a
one-pass reduction over an already host-resident column, so there is no
device program to win with.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.common_params import (
    HasLabelCol,
    HasPredictionCol,
    HasRawPredictionCol,
    HasWeightCol,
)
from flinkml_tpu.params import StringArrayParam
from flinkml_tpu.table import Table

_SUPPORTED = ("areaUnderROC", "areaUnderPR", "ks", "accuracy", "logLoss")


def binary_metrics(scores, labels, weights=None, predictions=None) -> dict:
    """Exact weighted binary metrics from scores (higher = more positive).

    ``accuracy`` uses ``predictions`` (0/1 per row) when given — the
    model's own decision rule; otherwise it thresholds ``scores`` at 0.5,
    which is only meaningful for probability scores (NOT for unbounded
    margins like LinearSVC's — pass the prediction column for those).
    """
    s = np.asarray(scores, dtype=np.float64).reshape(-1)
    y = np.asarray(labels, dtype=np.float64).reshape(-1)
    w = (np.ones_like(s) if weights is None
         else np.asarray(weights, dtype=np.float64).reshape(-1))
    if not np.isfinite(s).all():
        raise ValueError("scores contain NaN/inf")
    if not ((y == 0) | (y == 1)).all():
        raise ValueError("labels must be 0/1")
    if s.shape != y.shape or s.shape != w.shape:
        raise ValueError("scores/labels/weights lengths differ")
    pos = float(np.sum(w * y))
    neg = float(np.sum(w * (1.0 - y)))
    if pos == 0 or neg == 0:
        raise ValueError("both classes must be present (weighted)")

    order = np.argsort(-s, kind="stable")
    s_sorted, y_sorted, w_sorted = s[order], y[order], w[order]
    tp = np.cumsum(w_sorted * y_sorted)
    fp = np.cumsum(w_sorted * (1.0 - y_sorted))
    # Unique-threshold boundaries: last row of each tied score group.
    boundary = np.append(s_sorted[1:] != s_sorted[:-1], True)
    tpr = np.concatenate([[0.0], tp[boundary] / pos])
    fpr = np.concatenate([[0.0], fp[boundary] / neg])
    precision = np.concatenate(
        [[1.0], tp[boundary] / np.maximum(tp[boundary] + fp[boundary], 1e-300)]
    )
    recall = tpr

    # np.trapezoid is numpy>=2; numpy 1.x spells it np.trapz.
    _trapezoid = getattr(np, "trapezoid", None) or np.trapz
    auc_roc = float(_trapezoid(tpr, fpr))
    auc_pr = float(_trapezoid(precision, recall))
    ks = float(np.max(np.abs(tpr - fpr)))
    if predictions is not None:
        pred = np.asarray(predictions, dtype=np.float64).reshape(-1)
        if pred.shape != y.shape:
            raise ValueError("predictions/labels lengths differ")
    else:
        pred = (s >= 0.5).astype(np.float64)
    accuracy = float(np.sum(w * (pred == y)) / np.sum(w))
    # logLoss needs probability scores; clip to keep finite on hard 0/1
    # outputs (sklearn's convention). Meaningless for unbounded margins —
    # same caveat as the 0.5-threshold accuracy above.
    p_clip = np.clip(s, 1e-15, 1 - 1e-15)
    log_loss = float(
        -np.sum(w * (y * np.log(p_clip) + (1 - y) * np.log1p(-p_clip)))
        / np.sum(w)
    )
    return {
        "areaUnderROC": auc_roc,
        "areaUnderPR": auc_pr,
        "ks": ks,
        "accuracy": accuracy,
        "logLoss": log_loss,
    }


class BinaryClassificationEvaluator(
    HasLabelCol, HasRawPredictionCol, HasPredictionCol, HasWeightCol,
    AlgoOperator,
):
    METRICS_NAMES = StringArrayParam(
        "metricsNames",
        "Names of the output metrics.",
        ["areaUnderROC", "areaUnderPR"],
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        names = self.get(self.METRICS_NAMES)
        unknown = [n for n in names if n not in _SUPPORTED]
        if unknown:
            raise ValueError(
                f"unsupported metrics {unknown}; supported: {list(_SUPPORTED)}"
            )
        raw = np.asarray(table.column(self.get(self.RAW_PREDICTION_COL)))
        # Accept either a score column [n] or a [n, 2] probability pair
        # (the rawPrediction layout our classifiers emit: [1-p, p]).
        scores = raw[:, 1] if raw.ndim == 2 else raw
        labels = table.column(self.get(self.LABEL_COL))
        weight_col = self.get(self.WEIGHT_COL)
        weights = table.column(weight_col) if weight_col else None
        # Accuracy uses the model's own prediction column when present
        # (required for margin-style scores like LinearSVC's).
        pred_col = self.get(self.PREDICTION_COL)
        predictions = (
            table.column(pred_col) if pred_col in table.column_names else None
        )
        metrics = binary_metrics(scores, labels, weights, predictions)
        return (Table({n: np.asarray([metrics[n]]) for n in names}),)
