"""Factorization machines: FMClassifier (logistic) and FMRegressor
(squared loss).

Second-order FMs (Rendle): ``ŷ(x) = w₀ + w·x + ½ Σ_f [(x·V_f)² −
(x² · V_f²)]`` — the pairwise-interaction term computed with the
O(n·d·k) "sum-of-squares" identity, which on TPU is two batched MXU
matmuls (``x @ V`` and ``x² @ V²``); no explicit feature-pair loop
exists. Training rides the shared whole-run Adam device trainer
(``_adam.make_adam_trainer``): one program, psum'd minibatch steps over
the data-sharded mesh. L2 regularization applies to w and V (not the
intercept), scaled per-minibatch like the loss.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasReg,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from flinkml_tpu.models._adam import make_adam_trainer
from flinkml_tpu.models._data import (
    check_binary_labels,
    features_matrix,
    labeled_data,
)
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _FMParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasRawPredictionCol,
    HasWeightCol, HasMaxIter, HasLearningRate, HasGlobalBatchSize, HasReg,
    HasTol, HasSeed,
):
    FACTOR_SIZE = IntParam(
        "factorSize", "Dimensionality of the interaction factors.", 8,
        ParamValidators.gt(0),
    )


def _fm_margin(params, xb):
    """params = (w0 [1], w [d], v [d, k]); returns [n] margins."""
    w0, w, v = params
    linear = xb @ w
    xv = xb @ v                       # [n, k] on the MXU
    x2v2 = (xb * xb) @ (v * v)        # [n, k]
    pair = 0.5 * jnp.sum(xv * xv - x2v2, axis=1)
    return w0[0] + linear + pair


def _fm_logistic_loss_builder():
    def local_loss(params, xb, yb, wb):
        margin = _fm_margin(params[:3], xb)
        # params[3] is a [1] array holding the L2 strength (a constant
        # carried through the tuple so the builder stays argument-free).
        nll = jnp.logaddexp(0.0, margin) - yb * margin
        w0, w, v = params[:3]
        reg = params[3][0] * (jnp.sum(w * w) + jnp.sum(v * v))
        return jnp.sum(nll * wb) + reg * jnp.sum(wb)

    return local_loss


def _fm_squared_loss_builder():
    def local_loss(params, xb, yb, wb):
        err = _fm_margin(params[:3], xb) - yb
        w0, w, v = params[:3]
        reg = params[3][0] * (jnp.sum(w * w) + jnp.sum(v * v))
        return 0.5 * jnp.sum(err * err * wb) + reg * jnp.sum(wb)

    return local_loss


# -- the embedding-sharded factor path ---------------------------------------
#
# FM's factor matrix V [d, k] IS an embedding table over the feature
# space — the first wall recsys-scale FM hits (100M hashed features x
# k factors x 3 Adam-state copies). The sharded fit stores V, w, and
# their Adam m/v slots row-sharded per an EMBEDDING-family ShardingPlan
# (rows whole, dim intact — "optimizer state shards like its table"),
# with the feature COLUMNS of x sharded to match, so both FM matmuls
# (x·V and x²·V²) contract locally and one batch-sized psum of the
# [bs, k] partials completes the margins. The sparse lookup/exchange
# primitive does NOT apply here — FM features are dense vectors, not
# ids — and the fit refuses plans that split factor rows loudly; what
# the subsystem contributes is the layout, validation, and checkpoint
# family.

#: Parameter names of the sharded-FM state — the ``*embedding*``
#: suffixes land V and w (and, via the shared family rule, their Adam
#: slots) in the plan's EMBEDDING family.
_FM_V_PARAM = "fm/v_embedding"
_FM_W_PARAM = "fm/w_embedding"


@functools.lru_cache(maxsize=16)
def _fm_sharded_trainer(mesh, row_entry, n_shards: int, emu_bs: int,
                        logistic: bool):
    """Whole-run Adam trainer with V/w (+ their m/v slots) row-sharded
    over ``row_entry``'s axes and x column-sharded to match.

    Reproduces the dense :func:`~flinkml_tpu.models._adam.
    make_adam_trainer` SAMPLING trajectory for a data world of
    ``n_shards``: the same per-step ``fold_in`` key draws the same
    ``emu_bs`` local row positions, applied to each of the ``n_shards``
    contiguous row blocks (exactly the rows the dense trainer's devices
    would sample from their shards). Per-step margins and gradients
    agree with the dense trainer up to f32 summation order (pinned
    against autodiff in ``tests/test_embeddings.py``); per-COORDINATE
    parameter parity over many steps is deliberately NOT pinned — Adam's
    first-order update is ``±lr·sign(ĝ)``, which amplifies summation-
    order noise on near-zero gradients into full ``lr``-sized jumps, so
    the end-model pin is quality parity (loss/accuracy/prediction
    agreement), the same contract the convergence-parity suite uses.
    Gradients are the closed-form FM gradients (the scaffold's
    no-collectives-inside-grad discipline, by construction)."""
    from flinkml_tpu.sharding.plan import entry_axes

    axes = entry_axes(row_entry)
    axes_arg = axes if len(axes) > 1 else axes[0]

    def local(x, y, wt, w0, w_sh, v_sh, reg, lr, max_iter, tol, key):
        n_rows = x.shape[0]
        n_block = n_rows // n_shards

        def mb_step(params, m, v, step):
            w0_, w_, v_ = params
            k = jax.random.fold_in(key, step)
            idx = jax.random.randint(k, (emu_bs,), 0, n_block)
            gidx = (
                idx[None, :] + (jnp.arange(n_shards) * n_block)[:, None]
            ).reshape(-1)                       # the dense global batch
            xb = x[gidx]                        # [B, cols_local]
            yb, wb = y[gidx], wt[gidx]
            xv = jax.lax.psum(xb @ v_, axes_arg)              # [B, k]
            x2v2 = jax.lax.psum((xb * xb) @ (v_ * v_), axes_arg)
            lin = jax.lax.psum(xb @ w_, axes_arg)             # [B]
            margin = w0_[0] + lin + 0.5 * jnp.sum(xv * xv - x2v2, axis=1)
            if logistic:
                nll = jnp.logaddexp(0.0, margin) - yb * margin
                g = (jax.nn.sigmoid(margin) - yb) * wb
            else:
                err = margin - yb
                nll = 0.5 * err * err
                g = err * wb
            total_w = jnp.maximum(jnp.sum(wb), 1e-12)
            sq = jax.lax.psum(jnp.sum(w_ * w_) + jnp.sum(v_ * v_),
                              axes_arg)
            loss = (jnp.sum(nll * wb)
                    + reg[0] * sq * jnp.sum(wb)) / total_w
            # Closed-form FM gradients (all local once the [B, k]
            # forward partials are psum'd).
            gw0 = jnp.sum(g)[None] / total_w
            gw = (xb.T @ g + 2.0 * reg[0] * w_ * jnp.sum(wb)) / total_w
            gv = (xb.T @ (g[:, None] * xv)
                  - ((xb * xb).T @ g)[:, None] * v_
                  + 2.0 * reg[0] * v_ * jnp.sum(wb)) / total_w
            grads = (gw0, gw, gv)
            t = (step + 1).astype(jnp.float32)
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree.map(lambda a, gg: b1 * a + (1 - b1) * gg,
                             m, grads)
            v2 = jax.tree.map(lambda a, gg: b2 * a + (1 - b2) * gg * gg,
                              v, grads)
            params = jax.tree.map(
                lambda pp, mm, vv: pp - lr * (mm / (1 - b1 ** t))
                / (jnp.sqrt(vv / (1 - b2 ** t)) + eps),
                params, m, v2,
            )
            return params, m, v2, loss

        params0 = (w0, w_sh, v_sh)
        m0 = jax.tree.map(jnp.zeros_like, params0)
        v0 = jax.tree.map(jnp.zeros_like, params0)

        def cond(state):
            step, _, _, _, prev, cur = state
            return (step < max_iter) & (jnp.abs(prev - cur) > tol)

        def body(state):
            step, params, m, v, _, last = state
            params, m, v, loss = mb_step(params, m, v, step)
            return step + 1, params, m, v, last, loss

        inf = jnp.asarray(jnp.inf, jnp.float32)
        state = (jnp.asarray(0, jnp.int32), params0, m0, v0, inf, -inf)
        step, params, m, v, _, loss = jax.lax.while_loop(cond, body, state)
        return params, step, loss

    col_sh = P(None, row_entry)
    param_specs = (P(), P(row_entry), P(row_entry, None))
    return jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(col_sh, P(), P(), P(), P(row_entry), P(row_entry, None),
                  P(), P(), P(), P(), P()),
        out_specs=(param_specs, P(), P()),
    ))


class _FMBase(StreamingEstimatorMixin, _FMParams, Estimator):
    """``fit`` also accepts an iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the
    out-of-core path (the shared streamed-Adam runner,
    :func:`flinkml_tpu.models._adam.run_streamed_adam`; reference replay
    parity ``ReplayOperator.java:62-250``). ``checkpoint_manager`` +
    ``checkpoint_interval`` snapshot the full Adam state every N epochs;
    ``resume=True`` (durable DataCache input required) continues
    bit-exactly."""

    _LOGISTIC = True

    #: The FM trainers thread an EMBEDDING-family ShardingPlan through
    #: the factor matrix (see the sharded-factor section above).
    _SHARDING_PLAN_AWARE = True

    def _loss_builder(self):
        return (
            _fm_logistic_loss_builder if self._LOGISTIC
            else _fm_squared_loss_builder
        )

    def _params0(self, d: int):
        """Initial flat params tuple (bias, w, V, frozen reg tail) — the
        single source for the in-RAM and streamed paths."""
        k = self.get(self.FACTOR_SIZE)
        v0 = jax.random.normal(
            jax.random.PRNGKey(self.get_seed()), (d, k), jnp.float32
        ) * 0.01
        return (
            jnp.zeros(1, jnp.float32),
            jnp.zeros(d, jnp.float32),
            v0,
            jnp.asarray([self.get(self.REG)], jnp.float32),
        )

    def _make_model(self, params):
        model = (FMClassifierModel if self._LOGISTIC else FMRegressorModel)()
        model.copy_params_from(self)
        model._set(np.asarray(params[0], np.float64)[0],
                   np.asarray(params[1], np.float64),
                   np.asarray(params[2], np.float64))
        return model

    def _fit_stream(self, source):
        """Out-of-core FM via the shared streamed-Adam runner; the reg
        strength rides as the frozen params-tuple tail, exactly as in
        the in-RAM path."""
        from flinkml_tpu.models._adam import run_streamed_adam

        if self.sharding_plan is not None:
            # Loud refusal (the embedding subsystem's contract): the
            # streamed runner replays cache chunks through the shared
            # replicated-params Adam trainer — silently dropping the
            # plan would replicate the factor matrix, exactly the OOM
            # the plan was configured to avoid.
            raise ValueError(
                f"{type(self).__name__} streamed fit does not thread a "
                "sharding_plan yet: the cache-replay trainer keeps "
                "factors replicated. Use the in-RAM fit (which shards "
                "V/w + Adam slots per the plan's embedding family), or "
                "drop the plan."
            )

        features_col = self.get(self.FEATURES_COL)
        label_col = self.get(self.LABEL_COL)
        weight_col = self.get(self.WEIGHT_COL)
        mesh = self.mesh or DeviceMesh()

        def prepare_y(y):
            y = np.asarray(y, np.float32)
            if self._LOGISTIC:
                check_binary_labels(y, type(self).__name__)
            return y

        def ingest(t):
            x, y, w = labeled_data(t, features_col, label_col, weight_col)
            return {
                "x": x.astype(np.float32),
                "y": prepare_y(y),
                "w": w.astype(np.float32),
            }

        params = run_streamed_adam(
            source,
            what="FM streamed fit",
            mesh=mesh,
            cache_dir=self.cache_dir,
            cache_memory_budget_bytes=self.cache_memory_budget_bytes,
            ingest=ingest,
            place_y=prepare_y,
            loss_builder=self._loss_builder(),
            n_params=4,
            params0_fn=self._params0,
            lr=self.get(self.LEARNING_RATE),
            global_bs=self.get(self.GLOBAL_BATCH_SIZE),
            max_iter=self.get(self.MAX_ITER),
            tol=self.get(self.TOL),
            seed=self.get_seed(),
            frozen_tail=1,
            **self._checkpoint_kwargs(),
        )
        return self._make_model(params)

    def _fit_sharded(self, x, y, w):
        """The embedding-sharded factor fit (see the module section):
        V/w + Adam slots row-sharded per ``self.sharding_plan``, x
        column-sharded to match; refuses loudly where the layout cannot
        host the trainer."""
        from flinkml_tpu.parallel import DeviceMesh
        from flinkml_tpu.sharding.apply import validate_plan
        from flinkml_tpu.sharding.plan import entry_axes

        plan = self.sharding_plan
        spec = plan.spec_for(_FM_V_PARAM, ndim=2)
        row_entry = spec[0] if spec else None
        if any(entry_axes(e) for e in spec[1:]):
            raise ValueError(
                f"plan {plan.name!r} shards the FM factor matrix's "
                "factor dim (dim 1): the sharded trainer keeps factor "
                "rows whole (the embedding-family layout). Use the "
                "EMBEDDING or FSDP preset."
            )
        if not entry_axes(row_entry):
            raise ValueError(
                f"plan {plan.name!r} leaves the FM factor family "
                f"({_FM_V_PARAM!r}) replicated — pass a plan whose "
                "embedding family shards rows (EMBEDDING/FSDP), or drop "
                "sharding_plan to train replicated."
            )
        mesh = self.mesh or DeviceMesh.for_plan(plan)
        sizes = dict(mesh.mesh.shape)
        n_shards = 1
        for axis in entry_axes(row_entry):
            n_shards *= int(sizes.get(axis, 1))
        d = x.shape[1]
        k = self.get(self.FACTOR_SIZE)
        d_pad = -(-d // n_shards) * n_shards
        validate_plan(
            plan, mesh,
            param_shapes={_FM_V_PARAM: (d_pad, k), _FM_W_PARAM: (d_pad,)},
            optimizer_slots=2,  # Adam m/v shard like their table
        )
        n_pad = -(-x.shape[0] // n_shards) * n_shards
        xp = np.zeros((n_pad, d_pad), np.float32)
        xp[: x.shape[0], :d] = x
        yp = np.zeros(n_pad, np.float32)
        yp[: x.shape[0]] = y
        wp = np.zeros(n_pad, np.float32)
        wp[: x.shape[0]] = w[: x.shape[0]]
        w0_0, _, v0, reg = self._params0(d)
        v0p = np.zeros((d_pad, k), np.float32)
        v0p[:d] = np.asarray(v0)
        emu_bs = max(1, self.get(self.GLOBAL_BATCH_SIZE) // n_shards)
        trainer = _fm_sharded_trainer(
            mesh.mesh, row_entry, n_shards, emu_bs, self._LOGISTIC
        )
        f32 = lambda val: jnp.asarray(val, jnp.float32)
        (w0, w_sh, v_sh), steps, loss = trainer(
            xp, yp, wp, np.asarray(w0_0), np.zeros(d_pad, np.float32),
            v0p, np.asarray(reg),
            f32(self.get(self.LEARNING_RATE)),
            jnp.asarray(self.get(self.MAX_ITER), jnp.int32),
            f32(self.get(self.TOL)),
            jax.random.fold_in(jax.random.PRNGKey(self.get_seed()), 321),
        )
        return self._make_model((
            np.asarray(w0), np.asarray(w_sh)[:d], np.asarray(v_sh)[:d],
        ))

    def fit(self, *inputs):
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        self._reject_in_ram_checkpointing()
        x, y, w = labeled_data(
            table, self.get(self.FEATURES_COL), self.get(self.LABEL_COL),
            self.get(self.WEIGHT_COL),
        )
        if self._LOGISTIC:
            check_binary_labels(y, type(self).__name__)
        if self.sharding_plan is not None:
            return self._fit_sharded(x, y, w)
        d = x.shape[1]
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        x_pad, n_valid = pad_to_multiple(x.astype(np.float32), p)
        y_pad, _ = pad_to_multiple(y.astype(np.float32), p)
        w_pad = np.zeros(x_pad.shape[0], np.float32)
        w_pad[:n_valid] = w[:n_valid].astype(np.float32)
        local_bs = max(1, self.get(self.GLOBAL_BATCH_SIZE) // p)
        trainer = make_adam_trainer(
            mesh.mesh, DeviceMesh.DATA_AXIS, local_bs, self._loss_builder(),
            4, frozen_tail=1,
        )
        f32 = lambda val: jnp.asarray(val, jnp.float32)
        params, steps, loss = trainer(
            mesh.shard_batch(x_pad), mesh.shard_batch(y_pad),
            mesh.shard_batch(w_pad), self._params0(d),
            f32(self.get(self.LEARNING_RATE)),
            jnp.asarray(self.get(self.MAX_ITER), jnp.int32),
            f32(self.get(self.TOL)),
            jax.random.fold_in(jax.random.PRNGKey(self.get_seed()), 321),
        )
        return self._make_model(params)


class _FMModelBase(_FMParams, Model):
    def __init__(self):
        super().__init__()
        self._w0: Optional[float] = None
        self._w: Optional[np.ndarray] = None
        self._v: Optional[np.ndarray] = None

    def _set(self, w0, w, v):
        self._w0, self._w, self._v = float(w0), np.asarray(w), np.asarray(v)

    def set_model_data(self, *inputs: Table):
        (table,) = inputs
        self._set(
            float(np.asarray(table.column("w0"))[0]),
            np.asarray(table.column("w"), np.float64)[0],
            np.asarray(table.column("v"), np.float64)[0],
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "w0": np.asarray([self._w0]),
            "w": self._w[None, :],
            "v": self._v[None, :, :],
        })]

    def _require(self) -> None:
        if self._w is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def _margin(self, table: Table) -> np.ndarray:
        from flinkml_tpu.models._data import sparse_features

        vecs = sparse_features(table, self.get(self.FEATURES_COL))
        if vecs is not None:
            return self._margin_sparse(vecs)
        x = features_matrix(table, self.get(self.FEATURES_COL))
        xv = x @ self._v
        x2v2 = (x * x) @ (self._v * self._v)
        return self._w0 + x @ self._w + 0.5 * (xv * xv - x2v2).sum(axis=1)

    def _margin_sparse(self, vecs) -> np.ndarray:
        """O(nnz·k) sparse margin over a padded-ELL block — the FM
        identity only ever touches the nonzero columns, so an all-
        SparseVector column never densifies to ``[n, dim]`` (ruinous at
        hashed-feature dims). Linear term rides the gated SpMV kernel;
        the pairwise term gathers factor rows (``v[indices]`` is
        O(nnz·k)) and contracts with two einsums. ELL padding (index 0
        / value 0) is exact: value 0 zeroes both the gather product and
        the squared term. Runs under x64 so the float64 model
        parameters keep full precision, matching the dense path."""
        import jax

        from flinkml_tpu import kernels
        from flinkml_tpu.ops.sparse import BatchedCSR

        ib, vb, d = BatchedCSR.pack_sparse_vectors(vecs, dtype=np.float64)
        if d != self._w.shape[0]:
            raise ValueError(
                f"sparse features have dim {d}, model expects "
                f"{self._w.shape[0]}"
            )
        if vb.shape[1] == 0:  # all-empty rows: margin is the intercept
            return np.full(vb.shape[0], self._w0)
        with jax.experimental.enable_x64(True):
            linear = np.asarray(kernels.spmv(ib, vb, self._w))
        gathered = self._v[ib]                       # [n, s, k]
        xv = np.einsum("ns,nsk->nk", vb, gathered)
        x2v2 = np.einsum("ns,nsk->nk", vb * vb, gathered * gathered)
        return self._w0 + linear + 0.5 * (xv * xv - x2v2).sum(axis=1)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {
            "w0": np.asarray(self._w0), "w": self._w, "v": self._v,
        })

    @classmethod
    def load(cls, path: str):
        model, arrays, _ = cls._load_with_arrays(path)
        model._set(float(arrays["w0"]), arrays["w"], arrays["v"])
        return model


class FMClassifier(_FMBase):
    """Binary factorization-machine classifier (logistic loss)."""

    _LOGISTIC = True


class FMClassifierModel(_FMModelBase):
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        margin = self._margin(table)
        prob = 1.0 / (1.0 + np.exp(-margin))
        out = table.with_column(
            self.get(self.PREDICTION_COL), (margin >= 0).astype(np.float64)
        )
        out = out.with_column(
            self.get(self.RAW_PREDICTION_COL),
            np.stack([1.0 - prob, prob], axis=1),
        )
        return (out,)


class FMRegressor(_FMBase):
    """Factorization-machine regressor (squared loss)."""

    _LOGISTIC = False


class FMRegressorModel(_FMModelBase):
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        return (
            table.with_column(
                self.get(self.PREDICTION_COL), self._margin(table)
            ),
        )
