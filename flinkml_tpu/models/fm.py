"""Factorization machines: FMClassifier (logistic) and FMRegressor
(squared loss).

Second-order FMs (Rendle): ``ŷ(x) = w₀ + w·x + ½ Σ_f [(x·V_f)² −
(x² · V_f²)]`` — the pairwise-interaction term computed with the
O(n·d·k) "sum-of-squares" identity, which on TPU is two batched MXU
matmuls (``x @ V`` and ``x² @ V²``); no explicit feature-pair loop
exists. Training rides the shared whole-run Adam device trainer
(``_adam.make_adam_trainer``): one program, psum'd minibatch steps over
the data-sharded mesh. L2 regularization applies to w and V (not the
intercept), scaled per-minibatch like the loss.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasReg,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from flinkml_tpu.models._adam import make_adam_trainer
from flinkml_tpu.models._data import (
    check_binary_labels,
    features_matrix,
    labeled_data,
)
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _FMParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasRawPredictionCol,
    HasWeightCol, HasMaxIter, HasLearningRate, HasGlobalBatchSize, HasReg,
    HasTol, HasSeed,
):
    FACTOR_SIZE = IntParam(
        "factorSize", "Dimensionality of the interaction factors.", 8,
        ParamValidators.gt(0),
    )


def _fm_margin(params, xb):
    """params = (w0 [1], w [d], v [d, k]); returns [n] margins."""
    w0, w, v = params
    linear = xb @ w
    xv = xb @ v                       # [n, k] on the MXU
    x2v2 = (xb * xb) @ (v * v)        # [n, k]
    pair = 0.5 * jnp.sum(xv * xv - x2v2, axis=1)
    return w0[0] + linear + pair


def _fm_logistic_loss_builder():
    def local_loss(params, xb, yb, wb):
        margin = _fm_margin(params[:3], xb)
        # params[3] is a [1] array holding the L2 strength (a constant
        # carried through the tuple so the builder stays argument-free).
        nll = jnp.logaddexp(0.0, margin) - yb * margin
        w0, w, v = params[:3]
        reg = params[3][0] * (jnp.sum(w * w) + jnp.sum(v * v))
        return jnp.sum(nll * wb) + reg * jnp.sum(wb)

    return local_loss


def _fm_squared_loss_builder():
    def local_loss(params, xb, yb, wb):
        err = _fm_margin(params[:3], xb) - yb
        w0, w, v = params[:3]
        reg = params[3][0] * (jnp.sum(w * w) + jnp.sum(v * v))
        return 0.5 * jnp.sum(err * err * wb) + reg * jnp.sum(wb)

    return local_loss


class _FMBase(StreamingEstimatorMixin, _FMParams, Estimator):
    """``fit`` also accepts an iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the
    out-of-core path (the shared streamed-Adam runner,
    :func:`flinkml_tpu.models._adam.run_streamed_adam`; reference replay
    parity ``ReplayOperator.java:62-250``). ``checkpoint_manager`` +
    ``checkpoint_interval`` snapshot the full Adam state every N epochs;
    ``resume=True`` (durable DataCache input required) continues
    bit-exactly."""

    _LOGISTIC = True


    def _loss_builder(self):
        return (
            _fm_logistic_loss_builder if self._LOGISTIC
            else _fm_squared_loss_builder
        )

    def _params0(self, d: int):
        """Initial flat params tuple (bias, w, V, frozen reg tail) — the
        single source for the in-RAM and streamed paths."""
        k = self.get(self.FACTOR_SIZE)
        v0 = jax.random.normal(
            jax.random.PRNGKey(self.get_seed()), (d, k), jnp.float32
        ) * 0.01
        return (
            jnp.zeros(1, jnp.float32),
            jnp.zeros(d, jnp.float32),
            v0,
            jnp.asarray([self.get(self.REG)], jnp.float32),
        )

    def _make_model(self, params):
        model = (FMClassifierModel if self._LOGISTIC else FMRegressorModel)()
        model.copy_params_from(self)
        model._set(np.asarray(params[0], np.float64)[0],
                   np.asarray(params[1], np.float64),
                   np.asarray(params[2], np.float64))
        return model

    def _fit_stream(self, source):
        """Out-of-core FM via the shared streamed-Adam runner; the reg
        strength rides as the frozen params-tuple tail, exactly as in
        the in-RAM path."""
        from flinkml_tpu.models._adam import run_streamed_adam

        features_col = self.get(self.FEATURES_COL)
        label_col = self.get(self.LABEL_COL)
        weight_col = self.get(self.WEIGHT_COL)
        mesh = self.mesh or DeviceMesh()

        def prepare_y(y):
            y = np.asarray(y, np.float32)
            if self._LOGISTIC:
                check_binary_labels(y, type(self).__name__)
            return y

        def ingest(t):
            x, y, w = labeled_data(t, features_col, label_col, weight_col)
            return {
                "x": x.astype(np.float32),
                "y": prepare_y(y),
                "w": w.astype(np.float32),
            }

        params = run_streamed_adam(
            source,
            what="FM streamed fit",
            mesh=mesh,
            cache_dir=self.cache_dir,
            cache_memory_budget_bytes=self.cache_memory_budget_bytes,
            ingest=ingest,
            place_y=prepare_y,
            loss_builder=self._loss_builder(),
            n_params=4,
            params0_fn=self._params0,
            lr=self.get(self.LEARNING_RATE),
            global_bs=self.get(self.GLOBAL_BATCH_SIZE),
            max_iter=self.get(self.MAX_ITER),
            tol=self.get(self.TOL),
            seed=self.get_seed(),
            frozen_tail=1,
            **self._checkpoint_kwargs(),
        )
        return self._make_model(params)

    def fit(self, *inputs):
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        self._reject_in_ram_checkpointing()
        x, y, w = labeled_data(
            table, self.get(self.FEATURES_COL), self.get(self.LABEL_COL),
            self.get(self.WEIGHT_COL),
        )
        if self._LOGISTIC:
            check_binary_labels(y, type(self).__name__)
        d = x.shape[1]
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        x_pad, n_valid = pad_to_multiple(x.astype(np.float32), p)
        y_pad, _ = pad_to_multiple(y.astype(np.float32), p)
        w_pad = np.zeros(x_pad.shape[0], np.float32)
        w_pad[:n_valid] = w[:n_valid].astype(np.float32)
        local_bs = max(1, self.get(self.GLOBAL_BATCH_SIZE) // p)
        trainer = make_adam_trainer(
            mesh.mesh, DeviceMesh.DATA_AXIS, local_bs, self._loss_builder(),
            4, frozen_tail=1,
        )
        f32 = lambda val: jnp.asarray(val, jnp.float32)
        params, steps, loss = trainer(
            mesh.shard_batch(x_pad), mesh.shard_batch(y_pad),
            mesh.shard_batch(w_pad), self._params0(d),
            f32(self.get(self.LEARNING_RATE)),
            jnp.asarray(self.get(self.MAX_ITER), jnp.int32),
            f32(self.get(self.TOL)),
            jax.random.fold_in(jax.random.PRNGKey(self.get_seed()), 321),
        )
        return self._make_model(params)


class _FMModelBase(_FMParams, Model):
    def __init__(self):
        super().__init__()
        self._w0: Optional[float] = None
        self._w: Optional[np.ndarray] = None
        self._v: Optional[np.ndarray] = None

    def _set(self, w0, w, v):
        self._w0, self._w, self._v = float(w0), np.asarray(w), np.asarray(v)

    def set_model_data(self, *inputs: Table):
        (table,) = inputs
        self._set(
            float(np.asarray(table.column("w0"))[0]),
            np.asarray(table.column("w"), np.float64)[0],
            np.asarray(table.column("v"), np.float64)[0],
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "w0": np.asarray([self._w0]),
            "w": self._w[None, :],
            "v": self._v[None, :, :],
        })]

    def _require(self) -> None:
        if self._w is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def _margin(self, table: Table) -> np.ndarray:
        x = features_matrix(table, self.get(self.FEATURES_COL))
        xv = x @ self._v
        x2v2 = (x * x) @ (self._v * self._v)
        return self._w0 + x @ self._w + 0.5 * (xv * xv - x2v2).sum(axis=1)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {
            "w0": np.asarray(self._w0), "w": self._w, "v": self._v,
        })

    @classmethod
    def load(cls, path: str):
        model, arrays, _ = cls._load_with_arrays(path)
        model._set(float(arrays["w0"]), arrays["w"], arrays["v"])
        return model


class FMClassifier(_FMBase):
    """Binary factorization-machine classifier (logistic loss)."""

    _LOGISTIC = True


class FMClassifierModel(_FMModelBase):
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        margin = self._margin(table)
        prob = 1.0 / (1.0 + np.exp(-margin))
        out = table.with_column(
            self.get(self.PREDICTION_COL), (margin >= 0).astype(np.float64)
        )
        out = out.with_column(
            self.get(self.RAW_PREDICTION_COL),
            np.stack([1.0 - prob, prob], axis=1),
        )
        return (out,)


class FMRegressor(_FMBase):
    """Factorization-machine regressor (squared loss)."""

    _LOGISTIC = False


class FMRegressorModel(_FMModelBase):
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        return (
            table.with_column(
                self.get(self.PREDICTION_COL), self._margin(table)
            ),
        )
