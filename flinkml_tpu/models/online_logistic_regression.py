"""OnlineLogisticRegression — FTRL-proximal over a stream of mini-batches.

Capability target: BASELINE.json config #4 ("OnlineLogisticRegression FTRL —
unbounded streaming iteration"). The reference snapshot's unbounded mode is
``Iterations.iterateUnboundedStreams`` (``Iterations.java:118-127``,
SURVEY.md §5 long-context note); flink-ml's later OnlineLogisticRegression
shapes the API this mirrors: per-arriving-batch FTRL updates, a model
version incremented per batch, and a model-data stream of versioned
coefficients.

TPU mapping: the unbounded stream is a Python iterable of batches feeding
``Iterations.iterate_unbounded_streams``; each batch triggers ONE jitted
FTRL update (z/n accumulators + closed-form weights). Standard
FTRL-proximal (McMahan et al.):

    g      = mean logistic gradient on the batch
    σ      = (√(n+g²) − √n) / α
    z     += g − σ·w ;  n += g²
    w_i    = 0                            if |z_i| ≤ λ1
           = −(z_i − sign(z_i)·λ1) / ((β+√n_i)/α + λ2)   otherwise

with λ1 = reg·elasticNet, λ2 = reg·(1−elasticNet).
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasBatchStrategy,
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasPredictionCol,
    HasRawPredictionCol,
    HasReg,
    HasWeightCol,
)
from flinkml_tpu.iteration import IterationConfig, TerminateOnMaxIter, iterate
from flinkml_tpu.models._data import features_matrix, labeled_data
from flinkml_tpu.params import FloatParam, ParamValidators
from flinkml_tpu.table import Table


class _OnlineLogisticRegressionParams(
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasBatchStrategy,
    HasGlobalBatchSize,
    HasReg,
    HasElasticNet,
    HasPredictionCol,
    HasRawPredictionCol,
):
    ALPHA = FloatParam("alpha", "The alpha parameter of FTRL.", 0.1, ParamValidators.gt(0.0))
    BETA = FloatParam("beta", "The beta parameter of FTRL.", 0.1, ParamValidators.gt(0.0))


def _ftrl_algebra(z, n, w_coef, g, alpha, beta, l1, l2):
    """The FTRL-proximal state update given the (already-reduced) mean
    gradient — one definition shared by the single-controller and the
    multi-process psum'd steps, so their numerics can never drift."""
    sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / alpha
    z = z + g - sigma * w_coef
    n = n + g * g
    new_coef = jnp.where(
        jnp.abs(z) <= l1,
        0.0,
        -(z - jnp.sign(z) * l1) / ((beta + jnp.sqrt(n)) / alpha + l2),
    )
    return z, n, new_coef


@jax.jit
def _ftrl_update(z, n, w_coef, x, y, weight, alpha, beta, l1, l2):
    """One FTRL-proximal step on a batch; returns (z, n, new_coef, loss)."""
    dot = x @ w_coef
    p = jax.nn.sigmoid(dot)
    wsum = jnp.maximum(jnp.sum(weight), 1e-12)
    g = x.T @ (weight * (p - y)) / wsum
    z, n, new_coef = _ftrl_algebra(z, n, w_coef, g, alpha, beta, l1, l2)
    ys = 2.0 * y - 1.0
    loss = jnp.sum(weight * jax.nn.softplus(-dot * ys)) / wsum
    return z, n, new_coef, loss


@functools.lru_cache(maxsize=16)
def _ftrl_sharded_fn(mesh, axis: str):
    """Multi-process FTRL step: per-device partial gradients combined
    with one ``psum`` — the reference's per-mini-batch allReduce of
    parallel subtask gradients (``AllReduceImpl.java:52-299`` under
    flink-ml's online training). Zero-weight (padding / dummy) rows are
    exact no-ops; an all-zero-weight global step leaves the state
    unchanged (g = 0)."""
    from jax.sharding import PartitionSpec as P

    def local(xl, yl, wl, z, n, w_coef, alpha, beta, l1, l2):
        dot = xl @ w_coef
        p = jax.nn.sigmoid(dot)
        wsum = jnp.maximum(jax.lax.psum(jnp.sum(wl), axis), 1e-12)
        g = jax.lax.psum(xl.T @ (wl * (p - yl)), axis) / wsum
        z, n, new_coef = _ftrl_algebra(z, n, w_coef, g, alpha, beta, l1, l2)
        ys = 2.0 * yl - 1.0
        loss = jax.lax.psum(
            jnp.sum(wl * jax.nn.softplus(-dot * ys)), axis
        ) / wsum
        return z, n, new_coef, loss

    a, r = P(axis), P()
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(a, a, a, r, r, r, r, r, r, r),
            out_specs=(r, r, r, r),
        )
    )


class OnlineLogisticRegression(_OnlineLogisticRegressionParams, Estimator):
    def __init__(self, mesh=None):
        super().__init__()
        self.mesh = mesh
        self._initial_coefficient: Optional[np.ndarray] = None

    def set_initial_model_data(self, *inputs: Table) -> "OnlineLogisticRegression":
        """Warm-start from an offline model's coefficient table (flink-ml's
        OnlineLogisticRegression requires an initial model the same way)."""
        (table,) = inputs
        self._initial_coefficient = np.asarray(
            table.column("coefficient"), dtype=np.float64
        ).reshape(-1)
        return self

    def fit(self, *inputs: Table) -> "OnlineLogisticRegressionModel":
        """Consume the table as a stream of globalBatchSize mini-batches."""
        (table,) = inputs
        batch_size = self.get(_OnlineLogisticRegressionParams.GLOBAL_BATCH_SIZE)
        return self.fit_stream(table.batches(batch_size))

    def fit_stream(
        self,
        batches: Iterable[Table],
        *,
        checkpoint_manager=None,
        checkpoint_interval: int = 0,
        resume: bool = False,
        stream_resume: str = "replay",
        sentinel=None,
        recovery=None,
    ) -> "OnlineLogisticRegressionModel":
        """True unbounded mode: one FTRL update per arriving batch.

        Crash safety (ISSUE 4): pass ``checkpoint_manager`` (+
        ``checkpoint_interval``) to snapshot the full FTRL carry — z/n
        accumulators, coefficients, model version — every N consumed
        batches, and ``resume=True`` to continue bit-exactly from the
        newest VALID snapshot after a crash or TPU preemption (torn or
        corrupt snapshots are verified and skipped —
        ``CheckpointManager.restore_latest``). ``stream_resume`` sets the
        cursor contract of a resumed run: ``'replay'`` for restartable
        sources (the iterable re-presents the stream from the beginning;
        already-consumed batches are skipped), ``'continue'`` for live
        one-shot streams already positioned at "now".

        Self-healing (ISSUE 9): ``sentinel`` (a
        :class:`~flinkml_tpu.recovery.NumericsSentinel`) verifies the
        carry + loss finite on-device at every epoch boundary, raising a
        typed ``NumericsError`` before a NaN'd model can be snapshotted
        or published; ``recovery`` (a
        :class:`~flinkml_tpu.recovery.RecoveryPolicy`, implies a default
        sentinel) heals the raise in-loop — rollback to the newest valid
        snapshot, quarantine of the poisoned batch (ledgered in the
        snapshot so resume honors it), jittered-backoff retry. See
        ``docs/development/fault_tolerance.md`` ("Self-healing").

        Multi-process (round 4): each process feeds its OWN arriving
        stream partition; every update is one psum'd global FTRL step
        in SPMD lockstep (``stream_sync.synced_stream`` — exhausted
        ranks contribute zero-weight dummy batches until every stream
        ends), the reference's per-mini-batch allReduce of parallel
        subtask gradients. The fitted model is identical on every rank.
        """
        alpha = self.get(_OnlineLogisticRegressionParams.ALPHA)
        beta = self.get(_OnlineLogisticRegressionParams.BETA)
        reg = self.get(_OnlineLogisticRegressionParams.REG)
        en = self.get(_OnlineLogisticRegressionParams.ELASTIC_NET)
        l1, l2 = reg * en, reg * (1.0 - en)
        if jax.process_count() > 1:
            if (checkpoint_manager is not None or resume
                    or sentinel is not None or recovery is not None):
                raise NotImplementedError(
                    "checkpoint/resume and sentinel/recovery for the "
                    "multi-process online stream path are not wired yet; "
                    "run the checkpointing/self-healing fit "
                    "single-process, or use the bounded multi-process "
                    "streamed fits (train_*_stream) which support "
                    "save_agreed commits"
                )
            return self._fit_stream_multiprocess(batches, alpha, beta, l1, l2)

        from flinkml_tpu.iteration.checkpoint import begin_resume
        from flinkml_tpu.models._streaming import feed_world_size

        # Single-controller online fit: the rescale guard pins the
        # FEED's world (a Dataset's shard count / an ElasticFeed's
        # world; 1 for plain iterables) — snapshots record the true
        # data-plane parallelism, and a manager with rescale="reshard"
        # restores them at any other world (the FTRL carry is
        # replicated, so elastic resume is bit-exact).
        restore_epoch = begin_resume(checkpoint_manager, resume,
                                     world_size=feed_world_size(batches))

        fcol = self.get(_OnlineLogisticRegressionParams.FEATURES_COL)
        lcol = self.get(_OnlineLogisticRegressionParams.LABEL_COL)
        wcol = self.get(_OnlineLogisticRegressionParams.WEIGHT_COL)

        # Peek the first batch to fix the feature dim, so the loop carry is
        # a full array pytree from epoch 0 — the checkpointable structure
        # (restore needs `like` to match the committed snapshots). A
        # flinkml_tpu.data.Dataset is handed to iterate() whole, so the
        # runtime checkpoints/restores its cursor (docs/operators/data.md).
        from flinkml_tpu.models._streaming import peek_stream

        first, stream = peek_stream(batches)
        if first is None:
            empty = self._model_from_empty_stream(
                checkpoint_manager, restore_epoch
            )
            if empty is not None:
                return empty
            raise ValueError("training stream is empty")
        x0, _, _ = labeled_data(first, fcol, lcol, wcol)
        dim = x0.shape[1]
        if self._initial_coefficient is None:
            coef0 = jnp.zeros(dim)
            z0 = jnp.zeros(dim)
        else:
            coef0 = jnp.asarray(self._initial_coefficient)
            # Warm start: choose z so the FTRL closed form yields coef0 at
            # n=0. Inverting w = -(z - sign(z)·l1)/D with D = beta/alpha +
            # l2 and sign(z) = -sign(w) gives z = -w·D - sign(w)·l1 (and
            # |z| = |w|·D + l1 > l1).
            z0 = -coef0 * (beta / alpha + l2) - jnp.sign(coef0) * l1
            z0 = jnp.where(coef0 == 0.0, 0.0, z0)
        state = {"z": z0, "n": jnp.zeros(dim), "coef": coef0, "version": 0}

        def step(carry, batch_table, epoch):
            x, y, w = labeled_data(batch_table, fcol, lcol, wcol)
            z, n, coef, loss = _ftrl_update(
                carry["z"], carry["n"], carry["coef"],
                jnp.asarray(x), jnp.asarray(y), jnp.asarray(w),
                alpha, beta, l1, l2,
            )
            carry.update(z=z, n=n, coef=coef)
            carry["version"] = int(carry["version"]) + 1
            return carry, float(loss)

        result = iterate(
            step, state, stream,
            IterationConfig(
                TerminateOnMaxIter(2**31 - 1),
                checkpoint_interval=checkpoint_interval,
                checkpoint_manager=checkpoint_manager,
                stream_resume=stream_resume,
                sentinel=sentinel,
                recovery=recovery,
            ),
            resume=resume,
        )
        final = result.state
        model = OnlineLogisticRegressionModel()
        model.copy_params_from(self)
        model._coefficient = np.asarray(final["coef"])
        model._model_version = int(final["version"])
        # Self-healing record of the fit (None without a recovery
        # policy): rollbacks, retries by class, quarantined batches.
        model.recovery_summary = result.recovery
        return model

    def _model_from_empty_stream(
        self, manager, restore_epoch
    ) -> Optional["OnlineLogisticRegressionModel"]:
        """The zero-batch cases that are NOT errors: a resumed run whose
        stream is already exhausted returns the checkpointed model
        (resume-as-noop on a fully consumed 'continue' tail), and a
        warm-started run returns the initial coefficient at version 0
        (the pre-ISSUE-4 contract). Returns None when the empty stream is
        a genuine error."""
        if restore_epoch is not None and manager is not None:
            # Leaf VALUES in `like` are irrelevant — only the structure.
            state, _ = manager.restore_latest(
                like={"z": 0, "n": 0, "coef": 0, "version": 0}
            )
            model = OnlineLogisticRegressionModel()
            model.copy_params_from(self)
            model._coefficient = np.asarray(state["coef"])
            model._model_version = int(state["version"])
            return model
        if self._initial_coefficient is not None:
            model = OnlineLogisticRegressionModel()
            model.copy_params_from(self)
            model._coefficient = np.asarray(self._initial_coefficient)
            model._model_version = 0
            return model
        return None

    def _fit_stream_multiprocess(
        self, batches, alpha, beta, l1, l2
    ) -> "OnlineLogisticRegressionModel":
        """The multi-host unbounded mode (see :meth:`fit_stream`)."""
        import itertools

        from flinkml_tpu.iteration.stream_sync import (
            agree_first_item_dim,
            synced_padded_stream,
        )
        from flinkml_tpu.parallel import DeviceMesh
        from flinkml_tpu.parallel.dispatch import DispatchGuard

        mesh = self.mesh or DeviceMesh()
        local_devs = mesh.axis_size() // jax.process_count()
        row_tile = local_devs * 8
        fcol = self.get(_OnlineLogisticRegressionParams.FEATURES_COL)
        lcol = self.get(_OnlineLogisticRegressionParams.LABEL_COL)
        wcol = self.get(_OnlineLogisticRegressionParams.WEIGHT_COL)

        def extract(t):
            x, y, w = labeled_data(t, fcol, lcol, wcol)
            return (
                np.asarray(x, np.float32),
                np.asarray(y, np.float32),
                np.asarray(w, np.float32),
            )

        d_seen = [None]

        def check(item):
            x, y, w = item
            if x.ndim != 2 or x.shape[0] == 0:
                raise ValueError(
                    f"stream batches must be non-empty [n, d], got {x.shape}"
                )
            if d_seen[0] is None:
                d_seen[0] = x.shape[1]
            elif x.shape[1] != d_seen[0]:
                raise ValueError(
                    f"batch feature dim {x.shape[1]} != first batch's "
                    f"{d_seen[0]}"
                )

        # First-item dim agreement: an exhausted rank adopts the agreed
        # dim so its zero-weight dummies are shaped; iterator raises are
        # held for the same agreement.
        first, it, dim = agree_first_item_dim(
            (extract(t) for t in batches), check,
            lambda item: item[0].shape[1], mesh,
        )
        d_seen[0] = dim

        # Replicated FTRL state, warm start as the single-process path.
        if self._initial_coefficient is None:
            coef = jnp.zeros(dim, jnp.float32)
            z = jnp.zeros(dim, jnp.float32)
        else:
            if self._initial_coefficient.shape[0] != dim:
                raise ValueError(
                    f"initial coefficient has dim "
                    f"{self._initial_coefficient.shape[0]} but the stream "
                    f"has dim {dim}"
                )
            coef = jnp.asarray(self._initial_coefficient, jnp.float32)
            z = -coef * (beta / alpha + l2) - jnp.sign(coef) * l1
            z = jnp.where(coef == 0.0, 0.0, z)
        n = jnp.zeros(dim, jnp.float32)
        a_j, b_j = jnp.float32(alpha), jnp.float32(beta)
        l1_j, l2_j = jnp.float32(l1), jnp.float32(l2)

        step_fn = _ftrl_sharded_fn(mesh.mesh, DeviceMesh.DATA_AXIS)
        guard = DispatchGuard()  # sustained dispatch needs backpressure
        stream = itertools.chain([first] if first is not None else [], it)
        version = 0
        # The zero-padded user weights ARE the validity mask (padding and
        # dummy rows carry weight 0), so the shared loop's valid_w is
        # redundant here.
        for (x_pad, y_pad, w_pad), _valid, _h in synced_padded_stream(
            stream, mesh, check=check, row_tile=row_tile,
            dummy_cols=((dim,), (), ()),
        ):
            z, n, coef, _ = step_fn(
                mesh.global_batch(x_pad), mesh.global_batch(y_pad),
                mesh.global_batch(w_pad), z, n, coef, a_j, b_j, l1_j, l2_j,
            )
            version += 1
            guard.after_dispatch(coef)
        guard.flush(coef)

        model = OnlineLogisticRegressionModel()
        model.copy_params_from(self)
        model._coefficient = np.asarray(coef, np.float64)
        model._model_version = version
        return model


class OnlineLogisticRegressionModel(_OnlineLogisticRegressionParams, Model):
    """Versioned online model; transform predicts with the latest weights
    and stamps each output with the model version (flink-ml's online model
    appends a modelVersionCol the same way)."""

    def __init__(self):
        super().__init__()
        self._coefficient: Optional[np.ndarray] = None
        self._model_version: int = 0

    def set_model_data(self, *inputs: Table) -> "OnlineLogisticRegressionModel":
        (table,) = inputs
        self._coefficient = np.asarray(
            table.column("coefficient"), dtype=np.float64
        ).reshape(-1)
        if "modelVersion" in table:
            self._model_version = int(table.column("modelVersion")[0])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [
            Table(
                {
                    "coefficient": self._coefficient[None, :],
                    "modelVersion": np.array([self._model_version]),
                }
            )
        ]

    @property
    def coefficient(self) -> np.ndarray:
        self._require_model()
        return self._coefficient

    @property
    def model_version(self) -> int:
        return self._model_version

    def _require_model(self) -> None:
        if self._coefficient is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        x = features_matrix(table, self.get(_OnlineLogisticRegressionParams.FEATURES_COL))
        dot = np.asarray(jnp.asarray(x) @ jnp.asarray(self._coefficient))
        p = 1.0 / (1.0 + np.exp(-dot))
        out = (
            table.with_column(
                self.get(_OnlineLogisticRegressionParams.PREDICTION_COL),
                (dot >= 0).astype(np.float64),
            )
            .with_column(
                self.get(_OnlineLogisticRegressionParams.RAW_PREDICTION_COL),
                np.stack([1 - p, p], axis=-1),
            )
            .with_column(
                "modelVersion", np.full(len(dot), self._model_version, dtype=np.int64)
            )
        )
        return (out,)

    def save(self, path: str) -> None:
        self._require_model()
        self._save_with_arrays(
            path,
            {"coefficient": self._coefficient},
            extra={"modelVersion": self._model_version},
        )

    @classmethod
    def load(cls, path: str) -> "OnlineLogisticRegressionModel":
        model, arrays, meta = cls._load_with_arrays(path)
        model._coefficient = arrays["coefficient"]
        model._model_version = int(meta.get("modelVersion", 0))
        return model
