"""LinearRegression — least squares via proximal SGD.

Capability target: BASELINE.json config #3. Same shared trainer as
LogisticRegression/LinearSVC with the squared loss; supports L2 ("ridge"),
L1 ("lasso") and elastic-net via the proximal step.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasReg,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from flinkml_tpu.models import _linear_sgd
from flinkml_tpu.models._coefficient import CoefficientModelMixin
from flinkml_tpu.models._data import features_matrix, sparse_features
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


class _LinearRegressionParams(
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasMaxIter,
    HasReg,
    HasElasticNet,
    HasLearningRate,
    HasGlobalBatchSize,
    HasTol,
    HasSeed,
    HasPredictionCol,
):
    pass


class LinearRegression(_LinearRegressionParams, Estimator):
    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "LinearRegressionModel":
        (table,) = inputs
        features_col = self.get(_LinearRegressionParams.FEATURES_COL)
        hyper = dict(
            loss="squared",
            mesh=self.mesh or DeviceMesh(),
            max_iter=self.get(_LinearRegressionParams.MAX_ITER),
            learning_rate=self.get(_LinearRegressionParams.LEARNING_RATE),
            global_batch_size=self.get(_LinearRegressionParams.GLOBAL_BATCH_SIZE),
            reg=self.get(_LinearRegressionParams.REG),
            elastic_net=self.get(_LinearRegressionParams.ELASTIC_NET),
            tol=self.get(_LinearRegressionParams.TOL),
            seed=self.get_seed(),
        )
        coef = _linear_sgd.train_linear_model_from_table(
            table, features_col,
            self.get(_LinearRegressionParams.LABEL_COL),
            self.get(_LinearRegressionParams.WEIGHT_COL),
            **hyper,
        )
        model = LinearRegressionModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"coefficient": coef[None, :]}))
        return model


class LinearRegressionModel(CoefficientModelMixin, _LinearRegressionParams, Model):
    def __init__(self):
        super().__init__()
        self._coefficient: Optional[np.ndarray] = None

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        features_col = self.get(_LinearRegressionParams.FEATURES_COL)
        sparse_col = sparse_features(table, features_col)
        if sparse_col is not None:
            from flinkml_tpu.ops.sparse import sparse_margins

            pred = sparse_margins(sparse_col, self._coefficient).astype(
                np.float64
            )
        else:
            x = features_matrix(table, features_col)
            pred = np.asarray(jnp.asarray(x) @ jnp.asarray(self._coefficient))
        return (
            table.with_column(self.get(_LinearRegressionParams.PREDICTION_COL), pred),
        )
