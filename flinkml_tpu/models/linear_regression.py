"""LinearRegression — least squares via proximal SGD or exact normal
equations.

Capability target: BASELINE.json config #3. ``solver='sgd'`` (default)
uses the shared trainer (LogisticRegression/LinearSVC substrate) with
the squared loss; L2 ("ridge"), L1 ("lasso") and elastic-net via the
proximal step. ``solver='normal'`` computes the exact (weighted,
optionally ridge) OLS solution: the ``[d, d]`` normal matrix ``XᵀWX``
accumulates as ONE sharded MXU gram pass (the same reduction PCA uses)
and a tiny host f64 linear solve finishes it — no learning rate, no
iteration count. elasticNet > 0 requires
the SGD solver (L1 has no closed form).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasReg,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from flinkml_tpu.models import _linear_sgd
from flinkml_tpu.models._coefficient import CoefficientModelMixin
from flinkml_tpu.models._data import features_matrix, sparse_features
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.params import ParamValidators, StringParam
from flinkml_tpu.table import Table


class _LinearRegressionParams(
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasMaxIter,
    HasReg,
    HasElasticNet,
    HasLearningRate,
    HasGlobalBatchSize,
    HasTol,
    HasSeed,
    HasPredictionCol,
):
    SOLVER = StringParam(
        "solver",
        "'sgd' (proximal minibatch SGD) or 'normal' (exact weighted "
        "ridge OLS via one sharded gram pass + host f64 solve).",
        "sgd", ParamValidators.in_array(["sgd", "normal"]),
    )


@functools.lru_cache(maxsize=16)
def _normal_eq_gram_fn(mesh, axis: str):
    """One sharded MXU pass: A = XᵀWX, b = XᵀWy, s = Σw (psum-combined)."""
    import jax
    from jax.sharding import PartitionSpec as P

    def local(xl, wl, yl):
        xw = xl * wl[:, None]
        a = jax.lax.psum(xl.T @ xw, axis)
        b = jax.lax.psum(xw.T @ yl, axis)
        return a, b

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
            out_specs=(P(), P()),
        )
    )


def _fit_normal_equations(table, features_col, label_col, weight_col,
                          mesh: DeviceMesh, reg: float) -> np.ndarray:
    """Exact weighted ridge OLS, solving the SGD solver's fixed point:
    the trainer's gradient is ``XᵀW·err + 2·reg·c`` (the L2 term is NOT
    scaled by Σw — ``_linear_sgd`` adds ``2·reg·coef`` to the summed
    gradient), so both solvers solve ``(XᵀWX + 2·reg·I) c = XᵀWy`` and
    ``reg`` means the same thing in both (sklearn Ridge: α = 2·reg)."""
    from flinkml_tpu.models._data import labeled_data
    from flinkml_tpu.parallel import pad_to_multiple

    x, y, w = labeled_data(table, features_col, label_col, weight_col)
    p = mesh.axis_size()
    x_pad, _ = pad_to_multiple(x.astype(np.float32), p)
    y_pad, _ = pad_to_multiple(y.astype(np.float32), p)
    w_pad, _ = pad_to_multiple(w.astype(np.float32), p)
    a, b = _normal_eq_gram_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(
        mesh.shard_batch(x_pad), mesh.shard_batch(w_pad),
        mesh.shard_batch(y_pad),
    )
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    if reg > 0:
        # SPD by construction: direct solve.
        a64 += 2.0 * reg * np.eye(a64.shape[0])
        return np.linalg.solve(a64, b64)
    # reg == 0: rank-deficient (collinear) grams must yield the stable
    # min-norm solution, matching sklearn's lstsq — a jittered direct
    # solve would silently split weight arbitrarily between collinear
    # columns. (pinv(XᵀWX)·XᵀWy is the min-norm weighted OLS solution.)
    coef, _, _, _ = np.linalg.lstsq(a64, b64, rcond=None)
    return coef


class LinearRegression(StreamingEstimatorMixin, _LinearRegressionParams, Estimator):
    """``fit`` also accepts an iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the streamed
    out-of-core path (squared loss through the shared linear stream
    trainer, ``solver='sgd'`` only; ``ReplayOperator.java:62-250``
    parity), checkpointable via ``checkpoint_manager``/
    ``checkpoint_interval``/``resume``."""

    _SHARDING_PLAN_AWARE = True  # sgd dense path threads a ShardingPlan
    _PRECISION_AWARE = True  # ... and the FML6xx-gated precision policy

    def _make_model(self, coef) -> "LinearRegressionModel":
        model = LinearRegressionModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"coefficient": coef[None, :]}))
        return model

    def fit(self, *inputs) -> "LinearRegressionModel":
        (table,) = inputs
        features_col = self.get(_LinearRegressionParams.FEATURES_COL)
        if not isinstance(table, Table):
            if self.get(self.SOLVER) == "normal":
                raise ValueError(
                    "solver='normal' does not support streamed fits (the "
                    "closed form needs the full gram); use solver='sgd'"
                )
            if self.sharding_plan is not None:
                raise ValueError(
                    "sharding_plan supports in-RAM Table fits only; "
                    "streamed fits keep their replicated carry"
                )
            if self.precision is not None:
                raise ValueError(
                    "precision supports in-RAM Table fits only; the "
                    "streamed trainer is not yet policy-gated"
                )
            coef = _linear_sgd.streamed_linear_fit(
                table,
                features_col=features_col,
                label_col=self.get(_LinearRegressionParams.LABEL_COL),
                weight_col=self.get(_LinearRegressionParams.WEIGHT_COL),
                loss="squared",
                mesh=self.mesh or DeviceMesh(),
                max_iter=self.get(_LinearRegressionParams.MAX_ITER),
                learning_rate=self.get(
                    _LinearRegressionParams.LEARNING_RATE
                ),
                reg=self.get(_LinearRegressionParams.REG),
                elastic_net=self.get(_LinearRegressionParams.ELASTIC_NET),
                tol=self.get(_LinearRegressionParams.TOL),
                cache_dir=self.cache_dir,
                memory_budget_bytes=self.cache_memory_budget_bytes,
                **self._checkpoint_kwargs(),
            )
            return self._make_model(coef)
        if self.get(self.SOLVER) == "normal":
            if self.checkpoint_manager is not None or self.resume:
                raise ValueError(
                    "solver='normal' is a one-shot closed form; "
                    "checkpointing applies to solver='sgd'"
                )
            if self.sharding_plan is not None:
                raise ValueError(
                    "solver='normal' does not thread a sharding_plan "
                    "(the closed form materializes the replicated "
                    "[d, d] gram); use solver='sgd'"
                )
            if self.precision is not None:
                raise ValueError(
                    "solver='normal' does not thread a precision policy "
                    "(the closed form is a one-shot f32 solve); use "
                    "solver='sgd'"
                )
            if self.get(self.ELASTIC_NET) > 0:
                raise ValueError(
                    "solver='normal' has no closed form for elasticNet > 0; "
                    "use solver='sgd'"
                )
            if sparse_features(table, features_col) is not None:
                raise ValueError(
                    "solver='normal' requires dense features (the [d, d] "
                    "normal matrix is dense); use solver='sgd' for the "
                    "sparse path"
                )
            coef = _fit_normal_equations(
                table, features_col,
                self.get(_LinearRegressionParams.LABEL_COL),
                self.get(_LinearRegressionParams.WEIGHT_COL),
                self.mesh or DeviceMesh(), self.get(self.REG),
            )
            return self._make_model(coef)
        hyper = dict(
            loss="squared",
            mesh=self.mesh or DeviceMesh(),
            max_iter=self.get(_LinearRegressionParams.MAX_ITER),
            learning_rate=self.get(_LinearRegressionParams.LEARNING_RATE),
            global_batch_size=self.get(_LinearRegressionParams.GLOBAL_BATCH_SIZE),
            reg=self.get(_LinearRegressionParams.REG),
            elastic_net=self.get(_LinearRegressionParams.ELASTIC_NET),
            tol=self.get(_LinearRegressionParams.TOL),
            seed=self.get_seed(),
        )
        coef = _linear_sgd.train_linear_model_from_table(
            table, features_col,
            self.get(_LinearRegressionParams.LABEL_COL),
            self.get(_LinearRegressionParams.WEIGHT_COL),
            sharding_plan=self.sharding_plan,
            precision=self.precision,
            **self._checkpoint_kwargs(),
            **hyper,
        )
        return self._make_model(coef)


class LinearRegressionModel(CoefficientModelMixin, _LinearRegressionParams, Model):
    def __init__(self):
        super().__init__()
        self._coefficient: Optional[np.ndarray] = None

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        features_col = self.get(_LinearRegressionParams.FEATURES_COL)
        sparse_col = sparse_features(table, features_col)
        if sparse_col is not None:
            from flinkml_tpu.ops.sparse import sparse_margins

            pred = sparse_margins(sparse_col, self._coefficient).astype(
                np.float64
            )
        else:
            x = features_matrix(table, features_col)
            pred = np.asarray(jnp.asarray(x) @ jnp.asarray(self._coefficient))
        return (
            table.with_column(self.get(_LinearRegressionParams.PREDICTION_COL), pred),
        )
