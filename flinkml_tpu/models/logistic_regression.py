"""LogisticRegression — binomial logistic regression, mini-batch SGD, L2.

Capability parity with
``flink-ml-lib/.../classification/logisticregression/LogisticRegression.java:76-454``
(+ ``LogisticGradient.java:34-97``, ``LogisticRegressionModel.java:100-170``),
rebuilt TPU-first:

  - The reference's per-epoch machinery — ``CacheDataAndDoTrain`` caching
    partitions in ListState, per-task mini-batch sampling, a ``double[dim+2]``
    feedback buffer (gradient ‖ weightSum ‖ lossSum) AllReduce'd via 3-hop
    network shuffles, coefficient update on the next epoch's watermark —
    becomes ONE jitted SPMD step: per-device batch sampling, batched
    gradient on the MXU, ``psum`` over ICI, coefficient update, all fused
    into a single XLA program per epoch.
  - Loss/gradient match ``LogisticGradient.java:50-96``:
    ``loss = Σ wᵢ·log(1+exp(-ŷᵢ·(2yᵢ-1)))``,
    ``grad = Σ wᵢ·(-(2yᵢ-1)·σ(-ŷᵢ·(2yᵢ-1)))·xᵢ``; update
    ``coef -= lr/weightSum · grad`` (``LogisticRegression.java:354-358``).
    Divergence (intentional): the reference adds the L2 term once *per
    task* before its AllReduce, so regularization scales with parallelism;
    here it is applied once, globally (the mathematically standard form).
  - Termination: ``TerminateOnMaxIterOrTol(maxIter, tol)`` on the epoch's
    weighted-mean loss (``LogisticRegression.java:267-275``).
  - Prediction (``LogisticRegressionModel.java:158-170``): label =
    ``dot >= 0``, raw prediction = ``[1-p, p]`` with ``p = σ(dot)``.
"""

from __future__ import annotations


import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasMultiClass,
    HasPredictionCol,
    HasRawPredictionCol,
    HasReg,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from flinkml_tpu.iteration import IterationConfig, TerminateOnMaxIterOrTol, iterate
from flinkml_tpu.models import _linear_sgd
from flinkml_tpu.models._coefficient import CoefficientModelMixin
from flinkml_tpu.models._data import (
    check_binary_labels,
    features_matrix,
    labeled_data,
    labeled_sparse_data,
    sparse_features,
)
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _LogisticRegressionParams(
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasMaxIter,
    HasReg,
    HasLearningRate,
    HasGlobalBatchSize,
    HasTol,
    HasSeed,
    HasMultiClass,
    HasPredictionCol,
    HasRawPredictionCol,
):
    """Params shared by estimator and model (reference:
    LogisticRegressionParams / LogisticRegressionModelParams)."""


class LogisticRegression(StreamingEstimatorMixin, _LogisticRegressionParams, Estimator):
    """Fits binomial LR by epoch-synchronized distributed SGD.

    ``fit`` accepts, besides a single in-RAM :class:`Table`:

      - an **iterable of batch Tables** (one global mini-batch each) — the
        out-of-core path: epoch 0 caches the stream (spilling to
        ``cache_dir`` beyond ``cache_memory_budget_bytes``) while training,
        later epochs replay the cache through a prefetching device feed
        (reference: ``ReplayOperator.java:62-250``);
      - a sealed :class:`~flinkml_tpu.iteration.datacache.DataCache` whose
        batches carry this estimator's features/label(/weight) columns —
        replayed every epoch, no caching pass needed.
    """

    _SHARDING_PLAN_AWARE = True  # dense binomial path threads a plan
    _PRECISION_AWARE = True  # ... and the FML6xx-gated precision policy

    def fit(self, *inputs) -> "LogisticRegressionModel":
        (table,) = inputs
        multi_class = self.get(_LogisticRegressionParams.MULTI_CLASS)
        features_col = self.get(_LogisticRegressionParams.FEATURES_COL)
        if not isinstance(table, Table):
            return self._fit_stream(table)
        hyper = dict(
            mesh=self.mesh or DeviceMesh(),
            max_iter=self.get(_LogisticRegressionParams.MAX_ITER),
            learning_rate=self.get(_LogisticRegressionParams.LEARNING_RATE),
            global_batch_size=self.get(_LogisticRegressionParams.GLOBAL_BATCH_SIZE),
            reg=self.get(_LogisticRegressionParams.REG),
            tol=self.get(_LogisticRegressionParams.TOL),
            seed=self.get_seed(),
            **self._checkpoint_kwargs(),
        )

        if sparse_features(table, features_col) is not None:
            # Criteo-scale path (BASELINE.json config #5): nnz-bucketed ELL
            # blocks (ops.sparse.pack_ell_buckets — padded cells ≈ total
            # nnz even under skew), gather forward + one fused segment-sum
            # gradient scatter; the dense [dim] model stays replicated.
            # Host-side packing: the trainer shards from host, so the full
            # dataset never stages through a single device's HBM.
            if self.sharding_plan is not None:
                raise ValueError(
                    "sharding_plan supports the dense binomial path "
                    "only; the sparse trainer keeps its replicated "
                    "[dim] model (shard it via ROADMAP item 5's "
                    "embedding-table path instead)"
                )
            if self.precision is not None:
                raise ValueError(
                    "precision supports the dense binomial path only; "
                    "the sparse trainer's gather/segment-sum kernels "
                    "are not yet policy-gated"
                )
            indptr, indices, values, dim, y, w = labeled_sparse_data(
                table, features_col,
                self.get(_LogisticRegressionParams.LABEL_COL),
                self.get(_LogisticRegressionParams.WEIGHT_COL),
            )
            if _resolve_multi_class(multi_class, y) == "multinomial":
                raise ValueError(
                    "multinomial logistic regression supports dense "
                    "features only; one-hot/sparse inputs train one "
                    "binomial model per concept"
                )
            _check_binomial_labels(y)
            coef = _linear_sgd.train_linear_model_sparse_csr(
                indptr, indices, values, dim,
                y, w, loss="logistic", elastic_net=0.0, **hyper,
            )
        else:
            x, y, w = labeled_data(
                table,
                features_col,
                self.get(_LogisticRegressionParams.LABEL_COL),
                self.get(_LogisticRegressionParams.WEIGHT_COL),
            )
            if x.shape[0] == 0:
                raise ValueError("training table is empty")
            if _resolve_multi_class(multi_class, y) == "multinomial":
                # Softmax cross-entropy over integer classes 0..k-1:
                # coefficient is [k, d] (beyond the reference snapshot,
                # which rejects multinomial outright).
                if self.sharding_plan is not None:
                    raise ValueError(
                        "sharding_plan supports the dense binomial "
                        "path only (the softmax trainer is not yet "
                        "plan-aware)"
                    )
                if self.precision is not None:
                    raise ValueError(
                        "precision supports the dense binomial path "
                        "only (the softmax trainer is not yet "
                        "policy-gated)"
                    )
                num_classes = _check_multinomial_labels(y)
                coef = _linear_sgd.train_softmax_model(
                    x, y, w, num_classes=num_classes, elastic_net=0.0,
                    **hyper,
                )
            else:
                _check_binomial_labels(y)
                coef = train_logistic_regression(
                    x, y, w, sharding_plan=self.sharding_plan,
                    precision=self.precision, **hyper,
                )

        model = LogisticRegressionModel(mesh=self.mesh)
        model.copy_params_from(self)
        model.set_model_data(Table({"coefficient": coef[None, ...]}))
        return model

    def _fit_stream(self, source) -> "LogisticRegressionModel":
        """Out-of-core fit from an iterable of batch Tables or a DataCache
        (see class docstring; ReplayOperator.java:62-250 parity)."""
        if self.get(_LogisticRegressionParams.MULTI_CLASS) == "multinomial":
            raise ValueError(
                "multinomial logistic regression does not support "
                "streamed fits; materialize the data as a Table"
            )
        if self.sharding_plan is not None:
            raise ValueError(
                "sharding_plan supports in-RAM Table fits only; streamed "
                "fits keep their replicated carry"
            )
        if self.precision is not None:
            raise ValueError(
                "precision supports in-RAM Table fits only; the streamed "
                "trainer is not yet policy-gated"
            )

        features_col = self.get(_LogisticRegressionParams.FEATURES_COL)
        label_col = self.get(_LogisticRegressionParams.LABEL_COL)
        weight_col = self.get(_LogisticRegressionParams.WEIGHT_COL)
        coef = _linear_sgd.streamed_linear_fit(
            source,
            features_col=features_col,
            label_col=label_col,
            weight_col=weight_col,
            label_check=_check_stream_labels,
            loss="logistic",
            mesh=self.mesh or DeviceMesh(),
            max_iter=self.get(_LogisticRegressionParams.MAX_ITER),
            learning_rate=self.get(_LogisticRegressionParams.LEARNING_RATE),
            reg=self.get(_LogisticRegressionParams.REG),
            elastic_net=0.0,
            tol=self.get(_LogisticRegressionParams.TOL),
            cache_dir=self.cache_dir,
            memory_budget_bytes=self.cache_memory_budget_bytes,
            **self._checkpoint_kwargs(),
        )

        model = LogisticRegressionModel(mesh=self.mesh)
        model.copy_params_from(self)
        model.set_model_data(Table({"coefficient": coef[None, :]}))
        return model


class LogisticRegressionModel(CoefficientModelMixin, _LogisticRegressionParams, Model):
    """Broadcast-model batch inference (reference:
    ``LogisticRegressionModel.java:100-170`` — broadcast the coefficient,
    map each row; here: replicate the coefficient, one batched matmul)."""

    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh
        self._coefficient: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "LogisticRegressionModel":
        (table,) = inputs
        c = np.asarray(table.column("coefficient"), dtype=np.float64)
        # [1, d] (binomial vector) or [1, k, d] (multinomial matrix).
        self._coefficient = c[0] if c.ndim >= 2 else c.reshape(-1)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"coefficient": self._coefficient[None, ...]})]

    # -- inference ---------------------------------------------------------
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        multinomial = self._coefficient.ndim == 2
        features_col = self.get(_LogisticRegressionParams.FEATURES_COL)
        sparse_col = sparse_features(table, features_col)
        if sparse_col is not None:
            # Sparse inference: nnz-bucketed gather dots — O(nnz) memory
            # even under skewed nnz (same layout the trainer uses), never
            # densifying rows.
            from flinkml_tpu.ops.sparse import sparse_margins

            # Margins arrive on host; the elementwise tail stays on host
            # (no device round-trip for a sigmoid/softmax on [n] values).
            dot = sparse_margins(sparse_col, self._coefficient)
            if multinomial:
                pred, raw = _softmax_from_logits(dot.astype(np.float64))
            else:
                p = 1.0 / (1.0 + np.exp(-dot.astype(np.float64)))
                pred = (dot >= 0).astype(dot.dtype)
                raw = np.stack([1.0 - p, p], axis=-1)
            out = table.with_column(
                self.get(_LogisticRegressionParams.PREDICTION_COL), pred
            ).with_column(
                self.get(_LogisticRegressionParams.RAW_PREDICTION_COL), raw
            )
            return (out,)
        x = features_matrix(table, self.get(_LogisticRegressionParams.FEATURES_COL))
        predict = _predict_multinomial if multinomial else _predict
        if self.mesh is not None and self.mesh.num_devices > 1:
            # Sharded batch inference: rows split over the data axis, the
            # coefficient replicated (the broadcast-model pattern).
            x_pad, n_valid = pad_to_multiple(x, self.mesh.axis_size())
            xd = self.mesh.shard_batch(x_pad)
            coef = self.mesh.replicate(jnp.asarray(self._coefficient, xd.dtype))
            pred, raw = predict(xd, coef)
            # to_host: data-sharded outputs span non-addressable devices
            # on a multi-process mesh; every rank gathers the full result.
            pred = self.mesh.to_host(pred)[:n_valid]
            raw = self.mesh.to_host(raw)[:n_valid]
        else:
            pred, raw = predict(jnp.asarray(x), jnp.asarray(self._coefficient))
        out = table.with_column(
            self.get(_LogisticRegressionParams.PREDICTION_COL), np.asarray(pred)
        ).with_column(
            self.get(_LogisticRegressionParams.RAW_PREDICTION_COL), np.asarray(raw)
        )
        return (out,)

    def transform_kernel(self):
        """Dense single-device inference as a fusable kernel (the same
        math as :func:`_predict`/:func:`_predict_multinomial`). The
        per-stage path's compute dtype is whatever ``jnp.asarray`` gives
        the float64 feature matrix — float64 under the ambient x64 flag,
        float32 otherwise — so the kernel captures that flag at build
        time (the fused executor always traces under x64 for the scaler
        kernels' sake, and must not let that leak into this stage's
        dtypes). Sparse feature columns are object columns, which the
        fused executor rejects per-table — those chains fall back to the
        O(nnz) per-stage path. Multi-device meshes keep the sharded
        per-stage path (fusion is single-program, not SPMD, today)."""
        if self._coefficient is None:
            return None
        if self.mesh is not None and self.mesh.num_devices > 1:
            return None
        multinomial = self._coefficient.ndim == 2
        fcol = self.get(_LogisticRegressionParams.FEATURES_COL)
        pcol = self.get(_LogisticRegressionParams.PREDICTION_COL)
        rcol = self.get(_LogisticRegressionParams.RAW_PREDICTION_COL)
        x64 = bool(jax.config.jax_enable_x64)
        dt = jnp.float64 if x64 else jnp.float32

        from flinkml_tpu.api import ColumnKernel

        def fn(cols, consts, valid):
            # Resolved at TRACE time: the fused executor's program cache
            # keys on the active PrecisionPolicy, so a bf16 trace and an
            # f32 trace never share an executable. Under a mixed policy
            # the kernel computes at policy.compute with the matmul
            # accumulating at policy.accum (preferred_element_type)
            # instead of re-widening to the captured per-stage dtype.
            from flinkml_tpu import pipeline_fusion

            pol = pipeline_fusion.active_policy()
            # A mixed OR quantized policy declares the compute width
            # (the int8 tier runs f32 dequant-fused math — re-widening
            # to the captured f64 would silently double its bandwidth).
            declared = pol is not None and (pol.mixed or pol.quant)
            kdt = jnp.dtype(pol.compute_dtype) if declared else dt
            adt = jnp.dtype(pol.accum_dtype) if declared else None
            x = cols[fcol]
            if x.ndim == 1:
                x = x.reshape(-1, 1)
            x = x.astype(kdt)
            coef = consts["coefficient"].astype(kdt)
            if multinomial:
                logits = jnp.matmul(x, coef.T, preferred_element_type=adt)
                raw = jax.nn.softmax(logits, axis=-1)
                pred = jnp.argmax(logits, axis=-1).astype(x.dtype)
            else:
                dot = jnp.matmul(x, coef, preferred_element_type=adt)
                p = jax.nn.sigmoid(dot)
                pred = (dot >= 0).astype(x.dtype)
                raw = jnp.stack([1.0 - p, p], axis=-1)
            return {pcol: pred, rcol: raw}

        return ColumnKernel(
            input_cols=(fcol,), output_cols=(pcol, rcol), fn=fn,
            constants={"coefficient": self._coefficient},
            fingerprint=(
                "LogisticRegressionModel", fcol, pcol, rcol, multinomial,
                x64,
            ),
            # dot + sigmoid/softmax lower context-sensitively: the input
            # column must be materialized for per-stage bit parity.
            pin_inputs=True,
        )



def _check_binomial_labels(y: np.ndarray) -> None:
    check_binary_labels(y, "binomial logistic regression")


def _check_stream_labels(y: np.ndarray) -> None:
    """Streamed fits are binomial-only; >2-class data gets the actual
    limitation in the message, not a confusing binomial-labels error."""
    try:
        _check_binomial_labels(y)
    except ValueError as e:
        raise ValueError(
            f"{e}; multinomial (>2 classes) is not supported for "
            "streamed fits — materialize the data as a Table"
        ) from None


def _resolve_multi_class(multi_class: str, y: np.ndarray) -> str:
    """'auto' follows the label cardinality (≤2 → binomial), like the
    wider flink-ml family; explicit settings are honored as-is."""
    if multi_class != "auto":
        return multi_class
    return "multinomial" if np.unique(y).size > 2 else "binomial"


def _check_multinomial_labels(y: np.ndarray) -> int:
    """Labels must be exactly the integers 0..k-1 (every class present);
    returns k. Guards against phantom classes and against a single
    outlier label silently allocating a huge [maxLabel+1, d] matrix."""
    uniq = np.unique(y)
    if (
        not np.all(uniq == np.round(uniq))
        or uniq.min() < 0
        or uniq.size != int(uniq.max()) + 1
    ):
        raise ValueError(
            "multinomial logistic regression requires integer labels "
            f"covering 0..k-1 exactly, got {uniq[:6]}"
            f"{'...' if uniq.size > 6 else ''}"
        )
    return int(uniq.max()) + 1


@jax.jit
def _predict(x, coef):
    """prediction = 1[dot >= 0]; raw = [1-p, p]
    (parity: LogisticRegressionModel.predictRaw, :158-170)."""
    dot = x @ coef
    p = jax.nn.sigmoid(dot)
    pred = (dot >= 0).astype(x.dtype)
    raw = jnp.stack([1.0 - p, p], axis=-1)
    return pred, raw


@jax.jit
def _predict_multinomial(x, coef):
    """prediction = argmax class; raw = softmax probabilities [n, k]."""
    logits = x @ coef.T
    raw = jax.nn.softmax(logits, axis=-1)
    pred = jnp.argmax(logits, axis=-1).astype(x.dtype)
    return pred, raw


def _softmax_from_logits(logits: np.ndarray):
    z = logits - logits.max(axis=-1, keepdims=True)
    e = np.exp(z)
    raw = e / e.sum(axis=-1, keepdims=True)
    pred = np.argmax(logits, axis=-1).astype(np.float64)
    return pred, raw


def _shard_training_data(x, y, w, mesh: DeviceMesh):
    """Pad to the mesh and shard; padded rows carry weight 0 so they never
    contribute to any weighted sum."""
    p_size = mesh.axis_size()
    row_tile = p_size
    x_pad, _ = pad_to_multiple(x, row_tile)
    y_pad, _ = pad_to_multiple(y, row_tile)
    w_pad, _ = pad_to_multiple(w, row_tile)
    return mesh.shard_batch(x_pad), mesh.shard_batch(y_pad), mesh.shard_batch(w_pad)


# The shared linear-SGD kernels live in _linear_sgd. Mini-batch selection
# divergence from the reference (intentional, HBM-friendly): the reference
# samples WITH replacement per task (LogisticRegression.java:345-352 —
# random row gathers); random gathers waste HBM bandwidth on TPU, so each
# epoch takes a contiguous rotating window of the host-shuffled shard —
# shuffled SGD with full-bandwidth streaming reads.
def _device_trainer(mesh, local_bs: int, axis: str):
    """Whole-training-run XLA program for logistic loss (cached)."""
    return _linear_sgd._dense_trainer(mesh, "logistic", local_bs, axis)


def train_logistic_regression(
    x: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    global_batch_size: int,
    reg: float,
    tol: float,
    seed: int,
    dtype=None,
    mode: str = "device",
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
    sharding_plan=None,
    precision=None,
) -> np.ndarray:
    """The distributed SGD loop; returns the fitted coefficient on host.

    Two modes:
      - ``device`` (default): the ENTIRE epoch loop — sampling, gradient,
        psum, update, termination test — compiles into one XLA program
        (``lax.while_loop`` inside ``shard_map``). One dispatch per fit;
        zero host round-trips per epoch. This is the design inversion of the
        reference's per-epoch feedback/alignment machinery (SURVEY.md §3.2):
        where Flink crosses task, network, and RPC boundaries every epoch,
        the TPU loop never leaves the chip. With a ``checkpoint_manager`` +
        ``checkpoint_interval`` K, the loop runs in K-epoch dispatches with
        a carry snapshot between dispatches (``_linear_sgd._run_chunked``)
        — the fast path is fault-tolerant, and resume is bit-exact because
        chunked and unchunked runs share one compiled executable.
        ``listeners`` fire at chunk boundaries.
      - ``host``: one jitted step per epoch driven by
        ``flinkml_tpu.iteration.iterate`` — per-epoch listener callbacks
        and checkpointing at epoch granularity, at the cost of one dispatch
        per epoch. Termination always honors ``max_iter``/``tol``.
    """
    if mode not in ("device", "host"):
        raise ValueError(f"mode must be 'device' or 'host', got {mode!r}")
    if sharding_plan is not None and mode == "host":
        raise ValueError(
            "sharding_plan is supported in mode='device' only (the host "
            "iterate loop replicates its carry)"
        )
    if precision is not None and mode == "host":
        raise ValueError(
            "precision is supported in mode='device' only (the "
            "policy-gated step lives on the plan-sharded path)"
        )
    if mode == "host" and checkpoint_manager is not None:
        # The rescale guard must compare against THIS trainer's mesh, not
        # the process-global device count (they differ on subset meshes).
        # Re-pinned on every run so a manager reused across meshes never
        # carries a stale size (CheckpointManager documents this contract).
        # (Device mode pins it inside _run_chunked.)
        checkpoint_manager.world_size = mesh.mesh.size

    if mode == "device":
        return _linear_sgd.train_linear_model(
            x, y, w, loss="logistic", mesh=mesh, max_iter=max_iter,
            learning_rate=learning_rate, global_batch_size=global_batch_size,
            reg=reg, elastic_net=0.0, tol=tol, seed=seed, dtype=dtype,
            checkpoint_manager=checkpoint_manager,
            checkpoint_interval=checkpoint_interval,
            resume=resume, listeners=listeners,
            sharding_plan=sharding_plan, precision=precision,
        )

    # host mode: per-epoch dispatch with listener/checkpoint support.
    n, dim = x.shape
    p_size = mesh.axis_size()
    if dtype is not None:
        x, y, w = x.astype(dtype), y.astype(dtype), w.astype(dtype)
    # Host-side seeded shuffle; epochs then stream contiguous windows.
    perm = np.random.default_rng(seed).permutation(n)
    x, y, w = x[perm], y[perm], w[perm]
    xd, yd, wd = _shard_training_data(x, y, w, mesh)
    n_local = xd.shape[0] // p_size

    # Reference: localBatchSize = globalBatchSize / numTasks (+1 for low
    # task ids on remainder, LogisticRegression.java:336-341). Here every
    # device takes the ceiling, tile-aligned and clamped to its shard.
    local_bs = _linear_sgd.align_local_bs(global_batch_size, p_size, n_local)
    axis = DeviceMesh.DATA_AXIS
    dt = xd.dtype

    local_step = _linear_sgd.make_dense_step("logistic", local_bs, axis)
    sharded_step = jax.shard_map(
        local_step,
        mesh=mesh.mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis), P(), P(), P()),
        out_specs=(P(), P()),
    )

    @jax.jit
    def epoch_step(state, epoch):
        coef = state
        new_coef, mean_loss = sharded_step(
            coef, jnp.asarray(epoch, jnp.int32), xd, yd, wd,
            jnp.asarray(learning_rate, dt), jnp.asarray(reg, dt),
            jnp.asarray(0.0, dt),
        )
        return new_coef, mean_loss

    config = IterationConfig(
        TerminateOnMaxIterOrTol(max_iter, tol),
        checkpoint_interval=checkpoint_interval,
        checkpoint_manager=checkpoint_manager,
    )
    init = jnp.zeros(dim, dtype=xd.dtype)
    result = iterate(
        epoch_step, init, config=config, listeners=listeners, resume=resume
    )
    return np.asarray(result.state)
