"""Shared whole-run Adam trainer: a ``lax.while_loop`` of psum'd
minibatch steps over a data-sharded mesh.

The scaffold behind MLPClassifier and the factorization machines — any
model whose parameters are a flat tuple of arrays and whose loss is a
per-row weighted sum. The differentiated function contains NO
collectives; local gradient sums are ``psum``'d explicitly and divided
by the global batch weight, which keeps cross-device semantics
unambiguous (no reliance on psum-transpose rules).

Convergence: stop when ``|loss_{t-1} - loss_t| <= tol`` or at
``max_iter`` steps. Minibatch indices come from a per-step
``fold_in``; the key is replicated, so every device samples the same
local row positions of its own (distinct) shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _make_minibatch_step(local_loss, axis: str, local_bs: int,
                         n_params: int, frozen_tail: int):
    """ONE Adam minibatch step — the single source of the optimizer math
    shared by the whole-run and chunked trainers (so the streamed fit's
    numerics can never drift from the in-RAM fit's).

    Returns ``step_fn(x, y, w, params, m, v, step, lr, key) ->
    (params, m, v, loss)`` where ``step`` is the GLOBAL 0-based step
    counter (drives both the minibatch key fold and the bias
    correction).
    """

    def step_fn(x, y, w, params, m, v, step, lr, key):
        n_local = x.shape[0]
        k = jax.random.fold_in(key, step)
        idx = jax.random.randint(k, (local_bs,), 0, n_local)
        xb, yb, wb = x[idx], y[idx], w[idx]
        loss_sum, grads = jax.value_and_grad(local_loss)(params, xb, yb, wb)
        total_w = jnp.maximum(jax.lax.psum(jnp.sum(wb), axis), 1e-12)
        loss = jax.lax.psum(loss_sum, axis) / total_w
        grads = jax.tree.map(
            lambda g: jax.lax.psum(g, axis) / total_w, grads
        )
        if frozen_tail:
            grads = tuple(grads[: n_params - frozen_tail]) + tuple(
                jnp.zeros_like(g) for g in grads[n_params - frozen_tail:]
            )
        t = (step + 1).astype(jnp.float32)
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = jax.tree.map(lambda a, g: b1 * a + (1 - b1) * g, m, grads)
        v = jax.tree.map(lambda a, g: b2 * a + (1 - b2) * g * g, v, grads)
        params = jax.tree.map(
            lambda p, mm, vv: p - lr * (mm / (1 - b1 ** t))
            / (jnp.sqrt(vv / (1 - b2 ** t)) + eps),
            params, m, v,
        )
        return params, m, v, loss

    return step_fn


@functools.lru_cache(maxsize=32)
def make_adam_trainer(mesh, axis: str, local_bs: int, loss_builder,
                      n_params: int, frozen_tail: int = 0):
    """``loss_builder`` is a HASHABLE factory (module-level function)
    returning ``loss(params_tuple, xb, yb, wb) -> local weighted sum``.
    Returns a jitted ``trainer(x, y, w, params0, lr, max_iter, tol, key)
    -> (params, steps, loss)``.

    The last ``frozen_tail`` entries of the params tuple are constants
    smuggled through the pytree (e.g. a regularization strength the loss
    reads); their gradients are zeroed so Adam never touches them.
    """
    local_loss = loss_builder()
    mb_step = _make_minibatch_step(local_loss, axis, local_bs, n_params,
                                   frozen_tail)

    def local(x, y, w, params, lr, max_iter, tol, key):
        m0 = jax.tree.map(jnp.zeros_like, params)
        v0 = jax.tree.map(jnp.zeros_like, params)

        def cond(state):
            step, _, _, _, prev, cur = state
            return (step < max_iter) & (jnp.abs(prev - cur) > tol)

        def body(state):
            step, params, m, v, _, last = state
            params, m, v, loss = mb_step(x, y, w, params, m, v, step, lr,
                                         key)
            return step + 1, params, m, v, last, loss

        inf = jnp.asarray(jnp.inf, jnp.float32)
        state = (jnp.asarray(0, jnp.int32), params, m0, v0, inf, -inf)
        step, params, _, _, _, loss = jax.lax.while_loop(cond, body, state)
        return params, step, loss

    flat_specs = tuple(P() for _ in range(n_params))
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), flat_specs,
                      P(), P(), P(), P()),
            out_specs=(flat_specs, P(), P()),
        )
    )


@functools.lru_cache(maxsize=32)
def make_adam_chunk_trainer(mesh, axis: str, local_bs: int, loss_builder,
                            n_params: int):
    """Fixed-step sibling of :func:`make_adam_trainer` for streamed
    out-of-core fits: runs ``n_steps`` Adam minibatch steps over ONE
    device-resident chunk, carrying the full optimizer state
    ``(params, m, v, global_step)`` in and out — so the trajectory spans
    every chunk of a replayed cache as one continuous Adam run, and an
    epoch-boundary snapshot of that state resumes bit-exactly.

    Minibatch keys fold the GLOBAL step counter (not a per-chunk index),
    so a resumed run draws exactly the key sequence the uninterrupted
    run would have — the bit-exact-resume requirement. (The rows a key
    selects still live in the resident chunk: minibatches sample within
    the chunk, the classic streamed/sequential-SGD discipline.)
    """
    local_loss = loss_builder()
    mb_step = _make_minibatch_step(local_loss, axis, local_bs, n_params,
                                   frozen_tail=0)

    def local(x, y, w, params, m, v, step0, lr, n_steps, key):
        def body(_, state):
            params, m, v, step, _ = state
            params, m, v, loss = mb_step(x, y, w, params, m, v, step, lr,
                                         key)
            return params, m, v, step + 1, loss

        state = (params, m, v, step0, jnp.asarray(-jnp.inf, jnp.float32))
        params, m, v, step, loss = jax.lax.fori_loop(
            0, n_steps, body, state
        )
        return params, m, v, step, loss

    flat_specs = tuple(P() for _ in range(n_params))
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), flat_specs, flat_specs,
                      flat_specs, P(), P(), P(), P()),
            out_specs=(flat_specs, flat_specs, flat_specs, P(), P()),
        )
    )
