"""Shared whole-run Adam trainer: a ``lax.while_loop`` of psum'd
minibatch steps over a data-sharded mesh.

The scaffold behind MLPClassifier and the factorization machines — any
model whose parameters are a flat tuple of arrays and whose loss is a
per-row weighted sum. The differentiated function contains NO
collectives; local gradient sums are ``psum``'d explicitly and divided
by the global batch weight, which keeps cross-device semantics
unambiguous (no reliance on psum-transpose rules).

Convergence: stop when ``|loss_{t-1} - loss_t| <= tol`` or at
``max_iter`` steps. Minibatch indices come from a per-step
``fold_in``; the key is replicated, so every device samples the same
local row positions of its own (distinct) shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _make_minibatch_step(local_loss, axis: str, local_bs: int,
                         n_params: int, frozen_tail: int):
    """ONE Adam minibatch step — the single source of the optimizer math
    shared by the whole-run and chunked trainers (so the streamed fit's
    numerics can never drift from the in-RAM fit's).

    Returns ``step_fn(x, y, w, params, m, v, step, lr, key) ->
    (params, m, v, loss)`` where ``step`` is the GLOBAL 0-based step
    counter (drives both the minibatch key fold and the bias
    correction).
    """

    def step_fn(x, y, w, params, m, v, step, lr, key):
        n_local = x.shape[0]
        k = jax.random.fold_in(key, step)
        idx = jax.random.randint(k, (local_bs,), 0, n_local)
        xb, yb, wb = x[idx], y[idx], w[idx]
        loss_sum, grads = jax.value_and_grad(local_loss)(params, xb, yb, wb)
        total_w = jnp.maximum(jax.lax.psum(jnp.sum(wb), axis), 1e-12)
        loss = jax.lax.psum(loss_sum, axis) / total_w
        grads = jax.tree.map(
            lambda g: jax.lax.psum(g, axis) / total_w, grads
        )
        if frozen_tail:
            grads = tuple(grads[: n_params - frozen_tail]) + tuple(
                jnp.zeros_like(g) for g in grads[n_params - frozen_tail:]
            )
        t = (step + 1).astype(jnp.float32)
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = jax.tree.map(lambda a, g: b1 * a + (1 - b1) * g, m, grads)
        v = jax.tree.map(lambda a, g: b2 * a + (1 - b2) * g * g, v, grads)
        params = jax.tree.map(
            lambda p, mm, vv: p - lr * (mm / (1 - b1 ** t))
            / (jnp.sqrt(vv / (1 - b2 ** t)) + eps),
            params, m, v,
        )
        return params, m, v, loss

    return step_fn


@functools.lru_cache(maxsize=32)
def make_adam_trainer(mesh, axis: str, local_bs: int, loss_builder,
                      n_params: int, frozen_tail: int = 0):
    """``loss_builder`` is a HASHABLE factory (module-level function)
    returning ``loss(params_tuple, xb, yb, wb) -> local weighted sum``.
    Returns a jitted ``trainer(x, y, w, params0, lr, max_iter, tol, key)
    -> (params, steps, loss)``.

    The last ``frozen_tail`` entries of the params tuple are constants
    smuggled through the pytree (e.g. a regularization strength the loss
    reads); their gradients are zeroed so Adam never touches them.
    """
    local_loss = loss_builder()
    mb_step = _make_minibatch_step(local_loss, axis, local_bs, n_params,
                                   frozen_tail)

    def local(x, y, w, params, lr, max_iter, tol, key):
        m0 = jax.tree.map(jnp.zeros_like, params)
        v0 = jax.tree.map(jnp.zeros_like, params)

        def cond(state):
            step, _, _, _, prev, cur = state
            return (step < max_iter) & (jnp.abs(prev - cur) > tol)

        def body(state):
            step, params, m, v, _, last = state
            params, m, v, loss = mb_step(x, y, w, params, m, v, step, lr,
                                         key)
            return step + 1, params, m, v, last, loss

        inf = jnp.asarray(jnp.inf, jnp.float32)
        state = (jnp.asarray(0, jnp.int32), params, m0, v0, inf, -inf)
        step, params, _, _, _, loss = jax.lax.while_loop(cond, body, state)
        return params, step, loss

    flat_specs = tuple(P() for _ in range(n_params))
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), flat_specs,
                      P(), P(), P(), P()),
            out_specs=(flat_specs, P(), P()),
        )
    )


@functools.lru_cache(maxsize=32)
def make_adam_chunk_trainer(mesh, axis: str, local_bs: int, loss_builder,
                            n_params: int, frozen_tail: int = 0):
    """Fixed-step sibling of :func:`make_adam_trainer` for streamed
    out-of-core fits: runs ``n_steps`` Adam minibatch steps over ONE
    device-resident chunk, carrying the full optimizer state
    ``(params, m, v, global_step)`` in and out — so the trajectory spans
    every chunk of a replayed cache as one continuous Adam run, and an
    epoch-boundary snapshot of that state resumes bit-exactly.

    Minibatch keys fold the GLOBAL step counter (not a per-chunk index),
    so a resumed run draws exactly the key sequence the uninterrupted
    run would have — the bit-exact-resume requirement. (The rows a key
    selects still live in the resident chunk: minibatches sample within
    the chunk, the classic streamed/sequential-SGD discipline.)
    """
    local_loss = loss_builder()
    mb_step = _make_minibatch_step(local_loss, axis, local_bs, n_params,
                                   frozen_tail)

    def local(x, y, w, params, m, v, step0, lr, n_steps, key):
        def body(_, state):
            params, m, v, step, _ = state
            params, m, v, loss = mb_step(x, y, w, params, m, v, step, lr,
                                         key)
            return params, m, v, step + 1, loss

        state = (params, m, v, step0, jnp.asarray(-jnp.inf, jnp.float32))
        params, m, v, step, loss = jax.lax.fori_loop(
            0, n_steps, body, state
        )
        return params, m, v, step, loss

    flat_specs = tuple(P() for _ in range(n_params))
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), flat_specs, flat_specs,
                      flat_specs, P(), P(), P(), P()),
            out_specs=(flat_specs, flat_specs, flat_specs, P(), P()),
        )
    )


def run_streamed_adam(
    source,
    *,
    what: str,
    mesh,
    cache_dir,
    cache_memory_budget_bytes,
    ingest,
    place_y,
    loss_builder,
    n_params: int,
    params0_fn,
    lr: float,
    global_bs: int,
    max_iter: int,
    tol: float,
    seed: int,
    frozen_tail: int = 0,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
):
    """The shared out-of-core Adam fit loop (MLP, FM — any
    ``make_adam_trainer`` family member): cache the stream once, then
    each epoch replays the cache chunk-by-chunk through
    :func:`make_adam_chunk_trainer`, with the optimizer state carried
    across chunks as one continuous run and snapshotted at epoch
    boundaries (``begin_resume``/``should_snapshot`` protocol; resume
    requires a durable DataCache input).

    - ``ingest(table) -> {"x", "y", "w"}``: per-batch extraction +
      validation for the caching pass (one-shot stream sources).
    - ``place_y(y_raw) -> y``: label preparation/validation applied at
      replay time (covers sealed-DataCache sources too).
    - ``params0_fn(d) -> flat params tuple``: initial parameters, given
      the feature dim discovered from the cache.

    Chunk policy (the defined contract, not an accident): each resident
    chunk contributes ``ceil(rows / global_bs)`` Adam steps per epoch,
    and chunks pad to the 8p row tile (bounding the set of compiled
    shapes) — so step counts and padded shapes are functions of the
    cache's batch sizes, identical between a fresh run and a resume.

    Returns the final flat params tuple (device arrays).

    Reference parity: ``ReplayOperator.java:62-250`` (replayed cached
    partitions); ``Checkpoints.java:43-211`` (exact-resume contract).
    """
    import numpy as np

    from flinkml_tpu.iteration.checkpoint import begin_resume, should_snapshot
    from flinkml_tpu.iteration.datacache import (
        DataCache,
        DataCacheWriter,
        PrefetchingDeviceFeed,
    )
    from flinkml_tpu.parallel import pad_to_multiple
    from flinkml_tpu.parallel.mesh import DeviceMesh

    # Multi-process: per-process stream partitions + an agreed SPMD
    # schedule. The extra agreement here (vs the linear/KMeans streamed
    # fits) is the per-chunk Adam step count: ``n_steps`` is a traced
    # operand of the chunk trainer and must be identical on every process
    # at every dispatch, so the schedule is derived from the GLOBAL row
    # count of each chunk index (gathered once; the cache is sealed).
    multi = jax.process_count() > 1
    if resume and not isinstance(source, DataCache):
        raise ValueError(
            "resume=True requires a durable DataCache input: a one-shot "
            "stream cannot be replayed from the start after a failure"
        )
    p = mesh.axis_size()
    resume_epoch = begin_resume(checkpoint_manager, resume, mesh.mesh.size)

    # -- pass 0: cache --------------------------------------------------
    from flinkml_tpu.iteration.stream_sync import DeferredValidation

    dv = DeferredValidation()

    first_dim = [None]

    def validate_ingest(t):
        """Full ingest-time validation (zero rows, ragged dims, zero
        total weight) — everything place-time validation would catch,
        because on a multi-process mesh a place-time raise is a
        rank-local abort mid-collective (the hang class
        stream_sync.DeferredValidation exists to prevent)."""
        b = ingest(t)
        x = b["x"]
        if x.shape[0] == 0:
            raise ValueError(
                "stream batch has zero rows; drop empty batches"
            )
        if first_dim[0] is None:
            first_dim[0] = x.shape[1]
        elif x.shape[1] != first_dim[0]:
            raise ValueError(
                f"batch feature dim {x.shape[1]} != first batch's "
                f"{first_dim[0]}"
            )
        if "w" in b and float(np.sum(b["w"])) == 0.0:
            raise ValueError(
                "stream batch has zero total weight (all weights 0); "
                "drop such batches before training"
            )
        return b

    if isinstance(source, DataCache):
        cache = source
    else:
        writer = DataCacheWriter(cache_dir, cache_memory_budget_bytes)

        def ingest_and_append(t):
            # The append is part of the checked step too: a rank-local
            # writer failure (e.g. disk full while spilling a segment)
            # must ride the rendezvous like any ingest failure.
            writer.append(validate_ingest(t))

        from flinkml_tpu.iteration.stream_sync import checked_ingest

        # Multi-process, iterator and ingest failures are held for the
        # post-plan rendezvous (see stream_sync.checked_ingest); a
        # partial cache is fine — the rendezvous aborts every rank
        # before it is consumed.
        for _ in checked_ingest(source, dv, ingest_and_append, multi):
            pass
        cache = writer.finish()
    if not multi and cache.num_rows == 0:
        raise ValueError("training stream is empty")
    d = 0
    if cache.num_batches:
        reader = cache.reader()
        d = np.asarray(next(iter(reader))["x"]).shape[1]
        if hasattr(reader, "close"):
            reader.close()

    plan = None
    nsteps_sched = None
    if multi:
        from flinkml_tpu.iteration.stream_sync import (
            SyncedReplayPlan,
            _entry_rows,
            agree_all_ok,
            agree_feature_dim,
            agree_max,
            gather_vectors,
        )

        # Rendezvous BEFORE planning: a held ingest error must
        # surface as itself, not as plan.create's "stream is empty
        # on every process" (skip-on-failure can leave every local
        # cache empty).
        dv.rendezvous(mesh, "stream ingest validation")
        plan = SyncedReplayPlan.create(cache, mesh, p * 8)
        d = agree_feature_dim(cache, "x", mesh, local_dim=d)
        # Global per-chunk row counts → agreed Adam step schedule.
        local_rows = np.zeros(plan.global_steps)
        for t, entry in enumerate(cache.entries):
            local_rows[t] = _entry_rows(entry)
        rows_global = gather_vectors(local_rows, mesh).sum(axis=0)
        nsteps_sched = np.maximum(
            1, -(-rows_global.astype(np.int64) // global_bs)
        )
        # Agreed label dtype: dummy chunks must dispatch the exact program
        # real chunks do, so their y placeholder needs the real dtype even
        # on a process whose local cache is empty.
        _DTYPE_CODES = {
            np.dtype(np.float32): 1, np.dtype(np.int32): 2,
            np.dtype(np.int64): 3, np.dtype(np.float64): 4,
        }
        _CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}
        local_code = 0
        if cache.num_batches:
            reader = cache.reader()
            y0 = np.asarray(next(iter(reader))["y"])
            if hasattr(reader, "close"):
                reader.close()
            if isinstance(source, DataCache):  # sealed caches: raw labels
                y0 = place_y(y0)
            local_code = _DTYPE_CODES[np.dtype(np.asarray(y0).dtype)]
        code = agree_max(local_code, mesh)
        agree_all_ok(
            not (local_code and local_code != code), mesh,
            "label-dtype agreement",
        )
        y_dtype = _CODE_DTYPES[code]

    # Labels in a cache the runner built itself were already prepared/
    # validated at ingest; re-running place_y per chunk per epoch would
    # put O(rows log rows) redundant host validation on the prefetch
    # thread. Only user-supplied sealed caches need replay-time prep.
    labels_prepared = not isinstance(source, DataCache)
    # Cached batches are immutable, so validation (zero rows/weight,
    # label prep for sealed caches) only needs the FIRST replay pass —
    # not max_iter re-scans on the prefetch thread (the linear stream
    # trainer's first_pass_done discipline).
    first_pass_done = [False]

    def place(batch):
        x = np.asarray(batch["x"], np.float32)
        validate = not first_pass_done[0]
        if validate and x.shape[0] == 0:
            raise ValueError(
                "stream batch has zero rows; drop empty batches"
            )
        if validate and x.shape[1] != d:
            raise ValueError(
                f"batch feature dim {x.shape[1]} != first batch's {d}"
            )
        # Sealed-cache labels need CONVERSION every pass (the cache is
        # re-read from disk each epoch); place_y fuses that with the
        # validation, which is cheap next to the device step.
        y = np.asarray(batch["y"])
        if not labels_prepared:
            y = place_y(y)
        w = (
            np.asarray(batch["w"], np.float32)
            if "w" in batch else np.ones(x.shape[0], np.float32)
        )
        if validate and float(w.sum()) == 0.0:
            # The step normalizes by the batch weight sum; an all-zero
            # chunk would silently train on nothing. Fail loudly (same
            # contract as the linear stream trainer).
            raise ValueError(
                "stream batch has zero total weight (empty batch or all "
                "weights 0); drop such batches before training"
            )
        # 8p row tile bounds the set of padded shapes -> compiles.
        x_pad, n_valid = pad_to_multiple(x, p * 8)
        y_pad, _ = pad_to_multiple(y, p * 8)
        w_pad = np.zeros(x_pad.shape[0], np.float32)
        w_pad[:n_valid] = w[:n_valid]
        return (
            mesh.shard_batch(x_pad), mesh.shard_batch(y_pad),
            mesh.shard_batch(w_pad), x.shape[0],
        )

    def place_multi(batch):
        """Fixed-shape multi-process placement (agreed height; dummy
        chunks are zero-weight no-op contributions to the global step)."""
        height = plan.local_height
        if "_dummy" in batch:
            x_pad = np.zeros((height, d), np.float32)
            y_pad = np.zeros(height, y_dtype)
            w_pad = np.zeros(height, np.float32)
        else:
            x = np.asarray(batch["x"], np.float32)
            if not first_pass_done[0] and x.shape[1] != d:
                raise ValueError(
                    f"batch feature dim {x.shape[1]} != global dim {d}"
                )
            y = np.asarray(batch["y"])
            if not labels_prepared:
                y = place_y(y)
            w = (
                np.asarray(batch["w"], np.float32)
                if "w" in batch else np.ones(x.shape[0], np.float32)
            )
            if not first_pass_done[0] and float(w.sum()) == 0.0:
                raise ValueError(
                    "stream batch has zero total weight (empty batch or "
                    "all weights 0); drop such batches before training"
                )
            from flinkml_tpu.iteration.stream_sync import pad_rows_to

            x_pad = pad_rows_to(x, height, np.float32)
            y_pad = pad_rows_to(np.asarray(y, y_dtype), height)
            w_pad = pad_rows_to(np.asarray(w, np.float32), height)
        return (
            mesh.global_batch(x_pad), mesh.global_batch(y_pad),
            mesh.global_batch(w_pad), 0,
        )

    local_bs = max(1, global_bs // p)
    trainer = make_adam_chunk_trainer(
        mesh.mesh, DeviceMesh.DATA_AXIS, local_bs, loss_builder, n_params,
        frozen_tail,
    )
    flat = tuple(params0_fn(d))
    m = tuple(jnp.zeros_like(t) for t in flat)
    v = tuple(jnp.zeros_like(t) for t in flat)
    step = jnp.asarray(0, jnp.int32)
    sample_key = jax.random.fold_in(jax.random.PRNGKey(seed), 123)
    lr_dev = jnp.asarray(lr, jnp.float32)

    prev_loss = np.inf
    start_epoch = 0
    terminated = False
    mgr = checkpoint_manager
    if resume_epoch is not None:
        like = (
            tuple(np.zeros(t.shape, np.float32) for t in flat),
            tuple(np.zeros(t.shape, np.float32) for t in flat),
            tuple(np.zeros(t.shape, np.float32) for t in flat),
            np.int32(0), np.float64(0.0), np.asarray(False),
        )
        from flinkml_tpu.iteration.stream_sync import agreed_restore

        (flat_h, m_h, v_h, step_h, prev_h, term), start_epoch = (
            agreed_restore(mgr, resume_epoch, like, mesh)
        )
        flat = tuple(jnp.asarray(t) for t in flat_h)
        m = tuple(jnp.asarray(t) for t in m_h)
        v = tuple(jnp.asarray(t) for t in v_h)
        step = jnp.asarray(int(step_h), jnp.int32)
        prev_loss = float(prev_h)
        terminated = bool(term)

    # max_iter counts EPOCHS (one replay pass each); within an epoch
    # every chunk contributes ceil(rows / global_bs) Adam steps.
    from flinkml_tpu.parallel.dispatch import DispatchGuard

    guard = DispatchGuard()  # multi-process backpressure (no-op single)
    for epoch in range(start_epoch, max_iter):
        if terminated:
            break
        last_loss = None
        if multi:
            src = plan.epoch_batches(cache.reader(), lambda: {"_dummy": True})
            feed = PrefetchingDeviceFeed(src, place=place_multi, depth=2)
        else:
            feed = PrefetchingDeviceFeed(cache.reader(), place=place, depth=2)
        try:
            for t, (xb, yb, wb, rows) in enumerate(feed):
                n_steps = (
                    int(nsteps_sched[t]) if multi
                    else max(1, -(-rows // global_bs))  # ceil
                )
                flat, m, v, step, loss = trainer(
                    xb, yb, wb, flat, m, v, step, lr_dev,
                    jnp.asarray(n_steps, jnp.int32), sample_key,
                )
                last_loss = loss
                step = guard.after_dispatch(step)
        finally:
            feed.close()
        guard.flush(step)
        first_pass_done[0] = True  # batches are immutable: validate once
        cur = float(last_loss)
        terminated = abs(prev_loss - cur) <= tol
        prev_loss = cur
        if should_snapshot(mgr, checkpoint_interval, epoch + 1, max_iter,
                           terminal=terminated):
            state = (
                tuple(np.asarray(t) for t in flat),
                tuple(np.asarray(t) for t in m),
                tuple(np.asarray(t) for t in v),
                np.int32(int(step)), np.float64(prev_loss),
                np.asarray(terminated),
            )
            if multi:
                from flinkml_tpu.iteration.checkpoint import save_replicated

                save_replicated(mgr, state, epoch + 1, mesh)
            else:
                mgr.save(state, epoch + 1)
        if terminated:
            break
    return flat
