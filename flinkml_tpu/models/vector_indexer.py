"""VectorIndexer — detect categorical features in a vector column and
index them (the upstream operator).

``fit`` decides per feature: ≤ ``maxCategories`` distinct values →
categorical, its sorted distinct values map to indices ``0..k-1``;
otherwise the feature is continuous and passes through unchanged.
``handleInvalid`` governs unseen categorical values at transform time:
``error`` raises, ``skip`` drops the row, ``keep`` maps to the extra
index ``k``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasHandleInvalid,
    HasInputCol,
    HasOutputCol,
)
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.table import Table


class _VectorIndexerParams(HasInputCol, HasOutputCol, HasHandleInvalid):
    MAX_CATEGORIES = IntParam(
        "maxCategories",
        "Features with at most this many distinct values are categorical.",
        20, ParamValidators.gt(1),
    )


class VectorIndexer(_VectorIndexerParams, Estimator):
    def fit(self, *inputs: Table) -> "VectorIndexerModel":
        (table,) = inputs
        x = features_matrix(table, self.get(self.INPUT_COL))
        max_cat = self.get(self.MAX_CATEGORIES)
        category_maps: Dict[int, np.ndarray] = {}
        for j in range(x.shape[1]):
            col = x[:, j]
            # NaN can never be matched by the equality lookup, so it must
            # not enter a category map — NaN rows are handled by
            # handleInvalid at transform time (same stance as
            # StringIndexer).
            uniq = np.unique(col[~np.isnan(col)])
            if 0 < len(uniq) <= max_cat:
                category_maps[j] = uniq
        model = VectorIndexerModel()
        model.copy_params_from(self)
        model._set_maps(x.shape[1], category_maps)
        return model


class VectorIndexerModel(_VectorIndexerParams, Model):
    def __init__(self):
        super().__init__()
        self._num_features: Optional[int] = None
        self._category_maps: Dict[int, np.ndarray] = {}

    def _set_maps(self, num_features: int,
                  category_maps: Dict[int, np.ndarray]) -> None:
        self._num_features = int(num_features)
        self._category_maps = {
            int(j): np.asarray(v, np.float64) for j, v in category_maps.items()
        }

    @property
    def category_maps(self) -> Dict[int, np.ndarray]:
        self._require()
        return self._category_maps

    def set_model_data(self, *inputs: Table) -> "VectorIndexerModel":
        (table,) = inputs
        num_features = int(np.asarray(table.column("numFeatures"))[0])
        idx = np.asarray(table.column("featureIndex"))
        values = table.column("categories")
        self._set_maps(
            num_features,
            {int(j): values[i] for i, j in enumerate(idx) if j >= 0},
        )  # featureIndex -1 is the no-categorical-features sentinel row
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        keys = sorted(self._category_maps)
        cats = np.empty(max(len(keys), 1), dtype=object)
        if keys:
            for i, j in enumerate(keys):
                cats[i] = self._category_maps[j]
            return [Table({
                "numFeatures": np.full(len(keys), self._num_features),
                "featureIndex": np.asarray(keys),
                "categories": cats,
            })]
        cats[0] = np.zeros(0)
        return [Table({
            "numFeatures": np.asarray([self._num_features]),
            "featureIndex": np.asarray([-1]),
            "categories": cats,
        })]

    def _require(self) -> None:
        if self._num_features is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.INPUT_COL))
        if x.shape[1] != self._num_features:
            raise ValueError(
                f"model was fit on {self._num_features} features, "
                f"got {x.shape[1]}"
            )
        handle = self.get(self.HANDLE_INVALID)
        out = x.copy()
        keep_mask = np.ones(x.shape[0], dtype=bool)
        for j, cats in self._category_maps.items():
            pos = np.searchsorted(cats, x[:, j])
            pos_c = np.minimum(pos, len(cats) - 1)
            found = cats[pos_c] == x[:, j]
            if handle == HasHandleInvalid.ERROR_INVALID:
                if not found.all():
                    raise ValueError(
                        f"Feature {j} has values not seen during fitting: "
                        f"{x[~found, j][:5]}"
                    )
            elif handle == HasHandleInvalid.SKIP_INVALID:
                keep_mask &= found
            else:
                pos_c = np.where(found, pos_c, len(cats))
            out[:, j] = pos_c
        result = table.with_column(self.get(self.OUTPUT_COL), out)
        if not keep_mask.all():
            result = result.take(np.nonzero(keep_mask)[0])
        return (result,)

    def save(self, path: str) -> None:
        self._require()
        arrays = {
            f"cats_{j}": v for j, v in self._category_maps.items()
        }
        arrays["featureIndex"] = np.asarray(sorted(self._category_maps))
        self._save_with_arrays(
            path, arrays, extra={"numFeatures": self._num_features}
        )

    @classmethod
    def load(cls, path: str) -> "VectorIndexerModel":
        model, arrays, meta = cls._load_with_arrays(path)
        idx = arrays["featureIndex"]
        model._set_maps(
            int(meta["numFeatures"]),
            {int(j): arrays[f"cats_{int(j)}"] for j in idx},
        )
        return model
