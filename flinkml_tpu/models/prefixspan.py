"""PrefixSpan — frequent sequential pattern mining (the Spark family
member; an AlgoOperator, no fitted model — mirrors the upstream API).

Pei et al.'s prefix-projected mining: recursively extend each frequent
prefix with the items that remain frequent in its projected database
(the suffixes after the prefix's first occurrence). Host combinatorial
work like FPGrowth — pointer-chasing over projections has no dense
numeric structure for an accelerator.

Patterns here are sequences of single items (each element one item —
the common case; Spark's itemset-elements generalization is not
modeled). ``minSupport`` is a fraction of sequences;
``maxPatternLength`` bounds the recursion.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.models.text import _object_column, _token_column
from flinkml_tpu.params import FloatParam, IntParam, ParamValidators, StringParam
from flinkml_tpu.table import Table


def prefixspan(sequences: List[List[str]], min_support: float,
               max_length: int):
    """Frequent sequential patterns: dict {tuple(items): count}."""
    n = len(sequences)
    min_count = max(1, int(np.ceil(min_support * n)))
    seqs = [[str(it) for it in s] for s in sequences]

    out: Dict[Tuple[str, ...], int] = {}
    # Explicit DFS stack (no Python recursion: maxPatternLength can
    # legitimately exceed the interpreter's recursion limit).
    stack: List[Tuple[Tuple[str, ...], List[Tuple[int, int]]]] = [
        ((), [(i, 0) for i in range(n)])
    ]
    while stack:
        prefix, projections = stack.pop()
        if len(prefix) >= max_length:
            continue
        # Count each candidate item once per sequence (first occurrence
        # position recorded for the next projection).
        first_pos: Dict[str, Dict[int, int]] = {}
        for si, start in projections:
            seen = set()
            seq = seqs[si]
            for pos in range(start, len(seq)):
                it = seq[pos]
                if it not in seen:
                    seen.add(it)
                    first_pos.setdefault(it, {})[si] = pos
        for it, positions in first_pos.items():
            if len(positions) < min_count:
                continue
            pattern = prefix + (it,)
            out[pattern] = len(positions)
            stack.append(
                (pattern, [(si, pos + 1) for si, pos in positions.items()])
            )
    return out


class PrefixSpan(AlgoOperator):
    SEQUENCE_COL = StringParam(
        "sequenceCol", "Sequence (token-list) column.", "sequence"
    )
    MIN_SUPPORT = FloatParam(
        "minSupport", "Minimum fraction of sequences containing a pattern.",
        0.1, ParamValidators.in_range(0.0, 1.0, lower_inclusive=False),
    )
    MAX_PATTERN_LENGTH = IntParam(
        "maxPatternLength", "Longest pattern mined.", 10,
        ParamValidators.gt(0),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        """Output: one row per frequent pattern — (sequence, freq),
        support-descending (the upstream ``findFrequentSequentialPatterns``
        layout)."""
        (table,) = inputs
        seqs = _token_column(table, self.get(self.SEQUENCE_COL))
        patterns = prefixspan(
            [list(s) for s in seqs],
            self.get(self.MIN_SUPPORT),
            self.get(self.MAX_PATTERN_LENGTH),
        )
        ordered = sorted(patterns.items(), key=lambda kv: (-kv[1], kv[0]))
        return (
            Table({
                "sequence": _object_column([list(k) for k, _ in ordered]),
                "freq": np.asarray([v for _, v in ordered], np.int64),
            }) if ordered else Table({
                "sequence": np.empty(0, dtype=object),
                "freq": np.zeros(0, np.int64),
            }),
        )
