"""Feature scaling stages: StandardScaler, MinMaxScaler, MaxAbsScaler,
RobustScaler.

Beyond the reference snapshot (whose only feature stage is OneHotEncoder,
SURVEY.md §2.3) but standard members of the wider Flink ML operator family;
fit statistics are computed by sharded passes over the mesh (per-device
partial sums/extrema + psum/pmin/pmax; variance via the two-pass centered
form so float32 never cancels). Transform applies the tiny fitted
statistics on the host in numpy — elementwise rescaling of an already
host-resident table is bandwidth-trivial, so there is nothing to ship to
the device.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import ColumnKernel, Estimator, Model
from flinkml_tpu.common_params import HasInputCol, HasOutputCol
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import BoolParam, FloatParam, ParamValidators
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _HasInputOutputCol(HasInputCol, HasOutputCol):
    """Shared single-column in/out mixin (common_params is the canonical
    home of the Has* params; this alias keeps the scaler class lists
    short)."""


def _scaler_kernel(model, name, consts, apply, extra_static=()):
    """Shared :class:`ColumnKernel` scaffold for the four scaler models.

    ``apply(x, consts)`` is the stage's elementwise math on a float
    ``[n, d]`` block — the same op sequence as the host transform, so the
    fused output is bit-identical (float elementwise ops are exactly
    rounded in both numpy and XLA). The fitted statistics travel as traced
    constants; only the flag configuration is baked into the fingerprint.

    Dtype contract (matches :func:`_scaler_compute_dtype` on the host
    path): floating inputs keep their dtype — the fitted float64
    statistics are cast down to the input dtype, NOT the input up —
    and non-float inputs promote to float64. A float32 pipeline stays
    float32 end to end instead of silently doubling its bandwidth
    (analysis rule FML106).
    """
    in_col = model.get(model.INPUT_COL)
    out_col = model.get(model.OUTPUT_COL)

    def fn(cols, c, valid):
        x = cols[in_col]
        if x.ndim == 1:
            x = x.reshape(-1, 1)
        dt = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float64
        c = {k: v.astype(dt) for k, v in c.items()}
        return {out_col: apply(x.astype(dt), c)}

    return ColumnKernel(
        input_cols=(in_col,),
        output_cols=(out_col,),
        fn=fn,
        constants=consts,
        fingerprint=(name, in_col, out_col) + tuple(extra_static),
    )


@functools.lru_cache(maxsize=32)
def _sum_fn(mesh, axis: str):
    # The mean pass is shift-centered too: summing raw values of
    # magnitude M loses ~M * eps_f32 per 2^k added terms; summing
    # (x - shift) with shift ≈ typical value keeps the accumulator small.
    def local(xl, wl, shift):
        s = jax.lax.psum(jnp.sum((xl - shift) * wl[:, None], axis=0), axis)
        n = jax.lax.psum(jnp.sum(wl), axis)
        return s, n

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(P(axis), P(axis), P()),
            out_specs=(P(), P()),
        )
    )


@functools.lru_cache(maxsize=32)
def _centered_sumsq_fn(mesh, axis: str):
    # Two-pass variance: summing (x - mean)^2 keeps float32 exact enough
    # for any mean magnitude; the one-pass E[x^2] - E[x]^2 form cancels
    # catastrophically when |mean| >> std.
    def local(xl, wl, mean):
        c = xl - mean
        return jax.lax.psum(jnp.sum(c * c * wl[:, None], axis=0), axis)

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(P(axis), P(axis), P()),
            out_specs=P(),
        )
    )


@functools.lru_cache(maxsize=32)
def _extrema_fn(mesh, axis: str):
    def local(xl, wl):
        big = jnp.asarray(np.finfo(np.float32).max, xl.dtype)
        lo = jnp.where(wl[:, None] > 0, xl, big)
        hi = jnp.where(wl[:, None] > 0, xl, -big)
        return (
            jax.lax.pmin(jnp.min(lo, axis=0), axis),
            jax.lax.pmax(jnp.max(hi, axis=0), axis),
        )

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(P(axis), P(axis)),
            out_specs=(P(), P()),
        )
    )


def _shard_with_mask(x: np.ndarray, mesh: DeviceMesh):
    p = mesh.axis_size()
    x_pad, n_valid = pad_to_multiple(x.astype(np.float32), p)
    w = np.zeros(x_pad.shape[0], dtype=np.float32)
    w[:n_valid] = 1.0
    return mesh.shard_batch(x_pad), mesh.shard_batch(w)


class StandardScaler(_HasInputOutputCol, Estimator):
    """Standardize features to zero mean / unit variance (configurable)."""

    WITH_MEAN = BoolParam("withMean", "Center features to mean zero.", True)
    WITH_STD = BoolParam("withStd", "Scale features to unit std.", True)

    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "StandardScalerModel":
        (table,) = inputs
        x = features_matrix(table, self.get(self.INPUT_COL))
        mesh = self.mesh or DeviceMesh()
        xd, wd = _shard_with_mask(x, mesh)
        shift = np.asarray(x[0], dtype=np.float32)
        s, n = _sum_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(
            xd, wd, jnp.asarray(shift)
        )
        mean = shift.astype(np.float64) + np.asarray(s, np.float64) / float(n)
        sq = _centered_sumsq_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(
            xd, wd, jnp.asarray(mean, xd.dtype)
        )
        var = np.maximum(np.asarray(sq, dtype=np.float64) / float(n), 0.0)
        model = StandardScalerModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({"mean": mean[None, :], "std": np.sqrt(var)[None, :]})
        )
        return model


class StandardScalerModel(_HasInputOutputCol, Model):
    WITH_MEAN = StandardScaler.WITH_MEAN
    WITH_STD = StandardScaler.WITH_STD

    def __init__(self):
        super().__init__()
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "StandardScalerModel":
        (table,) = inputs
        self._mean = np.asarray(table.column("mean"), dtype=np.float64)[0]
        self._std = np.asarray(table.column("std"), dtype=np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"mean": self._mean[None, :], "std": self._std[None, :]})]

    def _require(self) -> None:
        if self._mean is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        # dtype=None + casting the statistics DOWN: a float32 column stays
        # float32 (same op order as the fused kernel, so fused == host
        # bitwise at every float width).
        x = features_matrix(table, self.get(self.INPUT_COL), dtype=None)
        out = x
        if self.get(self.WITH_MEAN):
            out = out - self._mean.astype(x.dtype, copy=False)
        if self.get(self.WITH_STD):
            # Guard AFTER the downcast: a float64 std that underflows to
            # 0.0 in float32 must hit the constant-feature branch, not
            # divide by zero.
            std = self._std.astype(x.dtype, copy=False)
            out = out / np.where(std > 0, std, 1.0)
        return (table.with_column(self.get(self.OUTPUT_COL), out),)

    def transform_kernel(self):
        if self._mean is None:
            return None
        with_mean = self.get(self.WITH_MEAN)
        with_std = self.get(self.WITH_STD)

        def apply(x, c):
            out = x
            if with_mean:
                out = out - c["mean"]
            if with_std:
                # Same order as the host path: the constants arrive cast
                # to the compute dtype, THEN the zero guard applies.
                out = out / jnp.where(c["std"] > 0, c["std"], 1.0)
            return out

        return _scaler_kernel(
            self, "StandardScalerModel",
            {"mean": self._mean, "std": self._std},
            apply, (with_mean, with_std),
        )

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"mean": self._mean, "std": self._std})

    @classmethod
    def load(cls, path: str) -> "StandardScalerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._mean = arrays["mean"]
        model._std = arrays["std"]
        return model


class MinMaxScaler(_HasInputOutputCol, Estimator):
    """Rescale features into [min, max] (default [0, 1])."""

    MIN = FloatParam("min", "Lower bound of the output range.", 0.0)
    MAX = FloatParam("max", "Upper bound of the output range.", 1.0)

    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "MinMaxScalerModel":
        (table,) = inputs
        if self.get(self.MIN) >= self.get(self.MAX):
            raise ValueError(
                f"min {self.get(self.MIN)} must be < max {self.get(self.MAX)}"
            )
        x = features_matrix(table, self.get(self.INPUT_COL))
        mesh = self.mesh or DeviceMesh()
        xd, wd = _shard_with_mask(x, mesh)
        lo, hi = _extrema_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(xd, wd)
        model = MinMaxScalerModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({
                "dataMin": np.asarray(lo, np.float64)[None, :],
                "dataMax": np.asarray(hi, np.float64)[None, :],
            })
        )
        return model


class MinMaxScalerModel(_HasInputOutputCol, Model):
    MIN = MinMaxScaler.MIN
    MAX = MinMaxScaler.MAX

    def __init__(self):
        super().__init__()
        self._data_min: Optional[np.ndarray] = None
        self._data_max: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "MinMaxScalerModel":
        (table,) = inputs
        self._data_min = np.asarray(table.column("dataMin"), np.float64)[0]
        self._data_max = np.asarray(table.column("dataMax"), np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "dataMin": self._data_min[None, :],
            "dataMax": self._data_max[None, :],
        })]

    def _require(self) -> None:
        if self._data_min is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.INPUT_COL), dtype=None)
        dmin = self._data_min.astype(x.dtype, copy=False)
        span = self._data_max.astype(x.dtype, copy=False) - dmin
        # Constant features map to the middle of the output range (the
        # Flink ML / sklearn convention of avoiding division by zero).
        safe = np.where(span > 0, span, 1.0)
        unit = np.where(span > 0, (x - dmin) / safe, 0.5)
        lo, hi = self.get(self.MIN), self.get(self.MAX)
        return (
            table.with_column(self.get(self.OUTPUT_COL), unit * (hi - lo) + lo),
        )

    def transform_kernel(self):
        if self._data_min is None:
            return None
        lo, hi = self.get(self.MIN), self.get(self.MAX)

        def apply(x, c):
            span = c["dataMax"] - c["dataMin"]
            safe = jnp.where(span > 0, span, 1.0)
            unit = jnp.where(span > 0, (x - c["dataMin"]) / safe, 0.5)
            return unit * (hi - lo) + lo

        return _scaler_kernel(
            self, "MinMaxScalerModel",
            {"dataMin": self._data_min, "dataMax": self._data_max},
            apply, (lo, hi),
        )

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"dataMin": self._data_min, "dataMax": self._data_max}
        )

    @classmethod
    def load(cls, path: str) -> "MinMaxScalerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._data_min = arrays["dataMin"]
        model._data_max = arrays["dataMax"]
        return model


class MaxAbsScaler(_HasInputOutputCol, Estimator):
    """Scale each feature into [-1, 1] by its max absolute value.

    The fit statistic reuses the sharded extrema pass (per-device
    min/max + pmin/pmax over the mesh): max|x| = max(|min|, |max|).
    """

    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "MaxAbsScalerModel":
        (table,) = inputs
        x = features_matrix(table, self.get(self.INPUT_COL))
        mesh = self.mesh or DeviceMesh()
        xd, wd = _shard_with_mask(x, mesh)
        lo, hi = _extrema_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(xd, wd)
        max_abs = np.maximum(
            np.abs(np.asarray(lo, np.float64)), np.abs(np.asarray(hi, np.float64))
        )
        model = MaxAbsScalerModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"maxAbs": max_abs[None, :]}))
        return model


class MaxAbsScalerModel(_HasInputOutputCol, Model):
    def __init__(self):
        super().__init__()
        self._max_abs: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "MaxAbsScalerModel":
        (table,) = inputs
        self._max_abs = np.asarray(table.column("maxAbs"), np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"maxAbs": self._max_abs[None, :]})]

    def _require(self) -> None:
        if self._max_abs is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.INPUT_COL), dtype=None)
        # Guard after the downcast (see StandardScalerModel.transform).
        ma = self._max_abs.astype(x.dtype, copy=False)
        return (
            table.with_column(
                self.get(self.OUTPUT_COL), x / np.where(ma > 0, ma, 1.0)
            ),
        )

    def transform_kernel(self):
        if self._max_abs is None:
            return None
        return _scaler_kernel(
            self, "MaxAbsScalerModel",
            {"maxAbs": self._max_abs},
            lambda x, c: x / jnp.where(c["maxAbs"] > 0, c["maxAbs"], 1.0),
        )

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"maxAbs": self._max_abs})

    @classmethod
    def load(cls, path: str) -> "MaxAbsScalerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._max_abs = arrays["maxAbs"]
        return model


class RobustScaler(_HasInputOutputCol, Estimator):
    """Scale by quantile range (robust to outliers): optionally center by
    the median, scale by ``quantile(upper) - quantile(lower)``.

    Quantiles are exact, computed on the host: per-feature quantiles of
    an in-RAM column are one vectorized ``np.quantile`` pass — a
    distributed sketch would add error without saving a device
    round-trip (the data starts host-resident).
    """

    LOWER = FloatParam(
        "lower", "Lower quantile of the scaling range.", 0.25,
        ParamValidators.in_range(0.0, 1.0),
    )
    UPPER = FloatParam(
        "upper", "Upper quantile of the scaling range.", 0.75,
        ParamValidators.in_range(0.0, 1.0),
    )
    WITH_CENTERING = BoolParam(
        "withCentering", "Whether to subtract the median.", False
    )
    WITH_SCALING = BoolParam(
        "withScaling", "Whether to divide by the quantile range.", True
    )

    def fit(self, *inputs: Table) -> "RobustScalerModel":
        (table,) = inputs
        lower, upper = self.get(self.LOWER), self.get(self.UPPER)
        if lower >= upper:
            raise ValueError(f"lower {lower} must be < upper {upper}")
        x = features_matrix(table, self.get(self.INPUT_COL)).astype(np.float64)
        median = np.quantile(x, 0.5, axis=0)
        q_lo = np.quantile(x, lower, axis=0)
        q_hi = np.quantile(x, upper, axis=0)
        model = RobustScalerModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({"median": median[None, :], "range": (q_hi - q_lo)[None, :]})
        )
        return model


class RobustScalerModel(_HasInputOutputCol, Model):
    LOWER = RobustScaler.LOWER
    UPPER = RobustScaler.UPPER
    WITH_CENTERING = RobustScaler.WITH_CENTERING
    WITH_SCALING = RobustScaler.WITH_SCALING

    def __init__(self):
        super().__init__()
        self._median: Optional[np.ndarray] = None
        self._range: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "RobustScalerModel":
        (table,) = inputs
        self._median = np.asarray(table.column("median"), np.float64)[0]
        self._range = np.asarray(table.column("range"), np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "median": self._median[None, :], "range": self._range[None, :],
        })]

    def _require(self) -> None:
        if self._median is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.INPUT_COL), dtype=None)
        out = x
        if self.get(self.WITH_CENTERING):
            out = out - self._median.astype(x.dtype, copy=False)
        if self.get(self.WITH_SCALING):
            # Guard after the downcast (see StandardScalerModel.transform).
            rng = self._range.astype(x.dtype, copy=False)
            out = out / np.where(rng > 0, rng, 1.0)
        return (table.with_column(self.get(self.OUTPUT_COL), out),)

    def transform_kernel(self):
        if self._median is None:
            return None
        centering = self.get(self.WITH_CENTERING)
        scaling = self.get(self.WITH_SCALING)

        def apply(x, c):
            out = x
            if centering:
                out = out - c["median"]
            if scaling:
                out = out / jnp.where(c["range"] > 0, c["range"], 1.0)
            return out

        return _scaler_kernel(
            self, "RobustScalerModel",
            {"median": self._median, "range": self._range},
            apply, (centering, scaling),
        )

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"median": self._median, "range": self._range}
        )

    @classmethod
    def load(cls, path: str) -> "RobustScalerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._median = arrays["median"]
        model._range = arrays["range"]
        return model
