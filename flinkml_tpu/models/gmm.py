"""GaussianMixture — EM-fit mixture of diagonal or full-covariance
Gaussians (the Spark/Flink family member).

TPU-native EM: each iteration is ONE device program over the
data-sharded mesh —

  - E-step: all per-component log-densities as batched MXU work
    (full covariance uses precomputed Cholesky factors; solves are
    ``[k, d, d]`` batched triangular solves), responsibilities via a
    stable log-sum-exp;
  - M-step: sufficient statistics (Σr, Σr·x, Σr·x xᵀ) are per-device
    sums combined with one ``psum`` each — the keyed-aggregation
    pattern, with k "keys" dense in a leading axis;
  - the whole EM loop is a host loop around that jitted step (the
    carry is tiny: weights/means/covs), stopping on log-likelihood
    change ≤ tol.

Initialization: k-means++-style seeding from the data (seeded), shared
covariance = data variance. ``covarianceType`` ∈ {"full", "diag"}.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasSeed,
    HasTol,
)
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.models.scalers import _shard_with_mask
from flinkml_tpu.params import IntParam, ParamValidators, StringParam
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table

_REG = 1e-6  # covariance ridge, sklearn's reg_covar default


class _GMMParams(
    HasFeaturesCol, HasPredictionCol, HasRawPredictionCol, HasMaxIter,
    HasTol, HasSeed,
):
    K = IntParam("k", "Number of mixture components.", 2, ParamValidators.gt(0))
    COVARIANCE_TYPE = StringParam(
        "covarianceType", "Component covariance structure.", "full",
        ParamValidators.in_array(["full", "diag"]),
    )


def _log_prob(x, weights, means, covs, cov_type: str):
    """[n, k] log(w_j * N(x | mu_j, Sigma_j)). x: [n, d] (f32)."""
    n, d = x.shape
    diff = x[:, None, :] - means[None, :, :]            # [n, k, d]
    if cov_type == "diag":
        inv = 1.0 / covs                                # [k, d]
        maha = jnp.sum(diff * diff * inv[None], axis=2)
        logdet = jnp.sum(jnp.log(covs), axis=1)         # [k]
    else:
        chol = jnp.linalg.cholesky(covs)                # [k, d, d]
        # One triangular solve per component with ALL samples as the
        # right-hand-side batch: L_j Z_j = diff[:, j, :]ᵀ  ([d, n] RHS).
        rhs = jnp.transpose(diff, (1, 2, 0))            # [k, d, n]
        z = jax.vmap(
            lambda L, R: jax.scipy.linalg.solve_triangular(L, R, lower=True)
        )(chol, rhs)                                    # [k, d, n]
        maha = jnp.sum(z * z, axis=1).T                 # [n, k]
        logdet = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(chol, axis1=1, axis2=2)), axis=1
        )
    log_norm = -0.5 * (d * jnp.log(2.0 * jnp.pi) + logdet)
    return jnp.log(weights)[None, :] + log_norm[None, :] - 0.5 * maha


@functools.lru_cache(maxsize=16)
def _em_step_fn(mesh, axis: str, k: int, cov_type: str):
    def local(xl, wl, weights, means, covs):
        logp = _log_prob(xl, weights, means, covs, cov_type)
        logz = jax.scipy.special.logsumexp(logp, axis=1, keepdims=True)
        resp = jnp.exp(logp - logz) * wl[:, None]       # masked rows drop
        ll_local = jnp.sum(logz[:, 0] * wl)
        r_k = jax.lax.psum(jnp.sum(resp, axis=0), axis)            # [k]
        r_x = jax.lax.psum(resp.T @ xl, axis)                      # [k, d]
        if cov_type == "diag":
            r_xx = jax.lax.psum(resp.T @ (xl * xl), axis)          # [k, d]
        else:
            r_xx = jax.lax.psum(
                jnp.einsum("nk,nd,ne->kde", resp, xl, xl), axis
            )                                                      # [k, d, d]
        ll = jax.lax.psum(ll_local, axis)
        n_tot = jax.lax.psum(jnp.sum(wl), axis)
        return r_k, r_x, r_xx, ll, n_tot

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(), P(), P()),
            out_specs=(P(), P(), P(), P(), P()),
        )
    )


def _m_step(r_k, r_x, r_xx, cov_type: str):
    d = r_x.shape[1]
    safe = np.maximum(r_k, 1e-12)
    weights = r_k / r_k.sum()
    means = r_x / safe[:, None]
    if cov_type == "diag":
        covs = r_xx / safe[:, None] - means * means + _REG
        covs = np.maximum(covs, _REG)
    else:
        covs = (
            r_xx / safe[:, None, None]
            - means[:, :, None] * means[:, None, :]
            + _REG * np.eye(d)[None]
        )
    return weights, means, covs


class GaussianMixture(StreamingEstimatorMixin, _GMMParams, Estimator):
    """``fit`` accepts, besides a single in-RAM :class:`Table`, an
    iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the
    out-of-core path (round 3): each EM iteration replays the cache,
    accumulating the psum'd sufficient statistics batch-by-batch with
    bounded HBM residency (reference: ``ReplayOperator.java:62-250``)."""


    def fit(self, *inputs) -> "GaussianMixtureModel":
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        self._reject_in_ram_checkpointing()
        x = features_matrix(table, self.get(self.FEATURES_COL))
        n, d = x.shape
        k = self.get(self.K)
        if n < k:
            raise ValueError(f"n_rows={n} < k={k}")
        cov_type = self.get(self.COVARIANCE_TYPE)
        mesh = self.mesh or DeviceMesh()
        # EM runs in CENTERED space: sufficient statistics accumulate on
        # device in f32, and E[xxᵀ] − μμᵀ cancels catastrophically when
        # |mean| ≫ std (a +1e4 offset NaN-poisons the Cholesky);
        # centering once on the host makes the stats magnitude-safe and
        # is mathematically identical. The shift is added back at the end.
        shift = x.mean(axis=0)
        x = x - shift
        xd, wd = _shard_with_mask(x, mesh)
        # k-means++ seeding (the shared helper handles degenerate
        # all-duplicate data) + shared data variance.
        from flinkml_tpu.models.kmeans import _kmeans_pp_init

        rng = np.random.default_rng(self.get_seed())
        means = np.asarray(_kmeans_pp_init(x, k, rng), dtype=np.float64)
        var = np.maximum(x.var(axis=0), _REG)
        if cov_type == "diag":
            covs = np.tile(var[None, :], (k, 1))
        else:
            covs = np.tile(np.diag(var)[None], (k, 1, 1))
        weights = np.full(k, 1.0 / k)
        step = _em_step_fn(mesh.mesh, DeviceMesh.DATA_AXIS, k, cov_type)
        f32 = lambda a: jnp.asarray(a, jnp.float32)
        prev_ll = -np.inf
        for _ in range(self.get(self.MAX_ITER)):
            r_k, r_x, r_xx, ll, n_tot = step(
                xd, wd, f32(weights), f32(means), f32(covs)
            )
            weights, means, covs = _m_step(
                np.asarray(r_k, np.float64), np.asarray(r_x, np.float64),
                np.asarray(r_xx, np.float64), cov_type,
            )
            ll = float(ll) / float(n_tot)
            if not np.isfinite(ll):
                raise FloatingPointError(
                    "GaussianMixture log-likelihood became non-finite; "
                    "the data may be degenerate (try covarianceType='diag' "
                    "or fewer components)"
                )
            if abs(ll - prev_ll) <= self.get(self.TOL):
                prev_ll = ll
                break
            prev_ll = ll
        model = GaussianMixtureModel()
        model.copy_params_from(self)
        model._set(weights, means + shift[None, :], covs)
        return model

    def _fit_stream(self, source) -> "GaussianMixtureModel":
        """Out-of-core EM (see class docstring). Pass 0 caches the stream
        while accumulating mean/variance sums (for the centering shift
        and init covariances) and reservoir-sampling rows for k-means++
        seeding; each EM iteration replays the cache batch-by-batch."""
        from flinkml_tpu.iteration.datacache import (
            DataCache,
            DataCacheWriter,
            PrefetchingDeviceFeed,
        )
        from flinkml_tpu.models.kmeans import _kmeans_pp_init
        from flinkml_tpu.parallel import pad_to_multiple
        from flinkml_tpu.utils.sampling import RowReservoir

        from flinkml_tpu.iteration.checkpoint import (
            begin_resume,
            should_snapshot,
        )
        # Multi-process: per-process stream partitions + the agreed SPMD
        # replay schedule; pass-0 moments and the init reservoir are
        # combined across processes through the device fabric
        # (iteration/stream_sync.py).
        multi = jax.process_count() > 1
        if self.resume and not isinstance(source, DataCache):
            raise ValueError(
                "resume=True requires a durable DataCache input: a one-shot "
                "stream cannot be replayed from the start after a failure"
            )
        features_col = self.get(self.FEATURES_COL)
        k = self.get(self.K)
        cov_type = self.get(self.COVARIANCE_TYPE)
        mesh = self.mesh or DeviceMesh()
        row_tile = mesh.axis_size() * 8
        column = features_col if isinstance(source, DataCache) else "x"

        # Resume target decided BEFORE pass 0: pass 0 must still run (the
        # centering shift comes from its moments) but a restore skips the
        # reservoir sampling + k-means++ seeding it would discard.
        resume_epoch = begin_resume(
            self.checkpoint_manager, self.resume, mesh.mesh.size
        )

        # -- pass 0: cache + running moments + init row sample -------------
        reservoir = RowReservoir(65_536, seed=self.get_seed())
        sum_x = None
        sum_xx = None
        count = 0
        d = None

        def ingest(x):
            nonlocal sum_x, sum_xx, count, d
            if x.ndim != 2 or x.shape[0] == 0:
                raise ValueError(
                    f"stream batches must be non-empty [n, d], got {x.shape}"
                )
            if d is None:
                d = x.shape[1]
            elif x.shape[1] != d:
                raise ValueError(
                    f"batch feature dim {x.shape[1]} != first batch's {d}"
                )
            if resume_epoch is None:
                reservoir.add(x)
            s = x.astype(np.float64)
            sum_x = s.sum(0) if sum_x is None else sum_x + s.sum(0)
            sq = (s * s).sum(0)
            sum_xx = sq if sum_xx is None else sum_xx + sq
            count += x.shape[0]

        from flinkml_tpu.iteration.stream_sync import DeferredValidation

        dv = DeferredValidation()

        def extract_cached(batch):
            # Extraction is part of the checked step: a missing column or
            # ragged value must ride the rendezvous, not raise rank-local.
            x = np.asarray(batch[column], np.float32)
            ingest(x)
            return x

        def extract_table(t):
            x = features_matrix(t, features_col).astype(np.float32)
            ingest(x)
            return x

        from flinkml_tpu.iteration.stream_sync import checked_ingest

        # Multi-process, iterator and ingest failures are held for the
        # post-plan rendezvous (see stream_sync.checked_ingest).
        if isinstance(source, DataCache):
            cache = source
            for _ in checked_ingest(cache.reader(), dv, extract_cached,
                                    multi):
                pass
        else:
            writer = DataCacheWriter(
                self.cache_dir, self.cache_memory_budget_bytes
            )

            def extract_append(t):
                # The append is part of the checked step too: a rank-local
                # writer failure (e.g. disk full while spilling) must ride
                # the rendezvous like any ingest failure. A partial cache
                # is fine — the rendezvous aborts every rank first.
                x = extract_table(t)
                writer.append({column: np.array(x)})

            for _ in checked_ingest(source, dv, extract_append, multi):
                pass
            cache = writer.finish()
        plan = None
        if multi:
            from flinkml_tpu.iteration.stream_sync import (
                SyncedReplayPlan,
                agree_feature_dim,
                gather_vectors,
                pooled_sample,
            )

            # Rendezvous BEFORE planning: a held ingest error must
            # surface as itself, not as plan.create's "stream is empty
            # on every process" (skip-on-failure can leave every local
            # cache empty).
            dv.rendezvous(mesh, "stream ingest validation")
            plan = SyncedReplayPlan.create(cache, mesh, row_tile)
            d = agree_feature_dim(
                cache, column, mesh, local_dim=0 if d is None else d
            )
            # Combine pass-0 moments exactly (f64 via hi/lo f32 pairs).
            local_stats = np.concatenate([
                np.zeros(2 * d) if sum_x is None
                else np.concatenate([sum_x, sum_xx]),
                [float(count)],
            ])
            stats = gather_vectors(local_stats, mesh).sum(axis=0)
            sum_x, sum_xx = stats[:d], stats[d : 2 * d]
            local_count = count
            count = int(round(stats[2 * d]))
        if count < k:
            raise ValueError(f"n_rows={count} < k={k}")

        mean = sum_x / count
        var = np.maximum(sum_xx / count - mean * mean, _REG)
        shift = mean  # centered-space EM, as the in-RAM path (f32 safety)

        if cov_type == "diag":
            covs = np.tile(var[None, :], (k, 1))
        else:
            covs = np.tile(np.diag(var)[None], (k, 1, 1))
        weights = np.full(k, 1.0 / k)
        if resume_epoch is None:
            rng = np.random.default_rng(self.get_seed())
            sample = reservoir.sample()
            if multi:
                # pooled_sample tolerates an empty local partition.
                sample = pooled_sample(
                    sample.astype(np.float32), local_count,
                    65_536, self.get_seed(), mesh,
                )
            sample = sample.astype(np.float64) - shift[None, :]
            means = np.asarray(_kmeans_pp_init(sample, k, rng), np.float64)
        else:
            means = np.zeros((k, d))  # placeholder; restored below

        step = _em_step_fn(mesh.mesh, DeviceMesh.DATA_AXIS, k, cov_type)
        f32 = lambda a: jnp.asarray(a, jnp.float32)

        if multi:
            from flinkml_tpu.iteration.stream_sync import pad_rows_to

            height = plan.local_height

            def place(batch):
                if "_dummy" in batch:
                    return (
                        mesh.global_batch(np.zeros((height, d), np.float32)),
                        mesh.global_batch(np.zeros(height, np.float32)),
                    )
                x = np.asarray(batch[column], np.float32) - shift.astype(
                    np.float32
                )[None, :]
                x_pad = pad_rows_to(x, height)
                wl = pad_rows_to(np.ones(x.shape[0], np.float32), height)
                return mesh.global_batch(x_pad), mesh.global_batch(wl)

        else:

            def place(batch):
                x = np.asarray(batch[column], np.float32) - shift.astype(
                    np.float32
                )[None, :]
                x_pad, n_valid = pad_to_multiple(x, row_tile)
                wl = np.zeros(x_pad.shape[0], np.float32)
                wl[:n_valid] = 1.0
                return mesh.shard_batch(x_pad), mesh.shard_batch(wl)

        # -- checkpoint/resume: state = (weights, means, covs, prev_ll,
        # terminated) -- each EM epoch is a pure function of (state, cache),
        # so restoring the latest snapshot and continuing is bit-exact with
        # the uninterrupted run (Checkpoints.java:43-211 contract).
        mgr = self.checkpoint_manager
        prev_ll = -np.inf
        start_epoch = 0
        terminated = False
        if resume_epoch is not None:
            like = (weights, means, covs, np.float64(0.0), np.asarray(False))
            from flinkml_tpu.iteration.stream_sync import agreed_restore

            (weights, means, covs, prev_ll, term), start_epoch = (
                agreed_restore(mgr, resume_epoch, like, mesh)
            )
            prev_ll = float(prev_ll)
            terminated = bool(term)

        def snapshot(epoch):
            state = (weights, means, covs, np.float64(prev_ll),
                     np.asarray(terminated))
            if multi:
                from flinkml_tpu.iteration.checkpoint import save_replicated

                save_replicated(mgr, state, epoch, mesh)
                return
            mgr.save(
                state,
                epoch,
            )

        from flinkml_tpu.parallel.dispatch import DispatchGuard

        guard = DispatchGuard()  # multi-process backpressure (no-op single)
        max_iter = self.get(self.MAX_ITER)
        for epoch in range(start_epoch, max_iter):
            if terminated:
                break  # restored from a tol-terminated run: no-op resume
            acc = None
            src = (
                plan.epoch_batches(cache.reader(), lambda: {"_dummy": True})
                if multi else cache.reader()
            )
            feed = PrefetchingDeviceFeed(src, place=place, depth=2)
            try:
                for xb, wl in feed:
                    out = step(xb, wl, f32(weights), f32(means), f32(covs))
                    acc = (
                        out if acc is None
                        else tuple(a + b for a, b in zip(acc, out))
                    )
                    guard.after_dispatch(acc[0])
            finally:
                feed.close()
            guard.flush(acc[0])
            r_k, r_x, r_xx, ll, n_tot = acc
            weights, means, covs = _m_step(
                np.asarray(r_k, np.float64), np.asarray(r_x, np.float64),
                np.asarray(r_xx, np.float64), cov_type,
            )
            ll = float(ll) / float(n_tot)
            if not np.isfinite(ll):
                raise FloatingPointError(
                    "GaussianMixture log-likelihood became non-finite; "
                    "the data may be degenerate (try covarianceType='diag' "
                    "or fewer components)"
                )
            terminated = abs(ll - prev_ll) <= self.get(self.TOL)
            prev_ll = ll
            if should_snapshot(mgr, self.checkpoint_interval, epoch + 1,
                               max_iter, terminal=terminated):
                snapshot(epoch + 1)
            if terminated:
                break
        model = GaussianMixtureModel()
        model.copy_params_from(self)
        model._set(weights, means + shift[None, :], covs)
        return model


class GaussianMixtureModel(_GMMParams, Model):
    def __init__(self):
        super().__init__()
        self._weights: Optional[np.ndarray] = None
        self._means: Optional[np.ndarray] = None
        self._covs: Optional[np.ndarray] = None

    def _set(self, weights, means, covs):
        self._weights = np.asarray(weights, np.float64)
        self._means = np.asarray(means, np.float64)
        self._covs = np.asarray(covs, np.float64)

    @property
    def weights(self) -> np.ndarray:
        self._require()
        return self._weights

    @property
    def means(self) -> np.ndarray:
        self._require()
        return self._means

    @property
    def covariances(self) -> np.ndarray:
        self._require()
        return self._covs

    def set_model_data(self, *inputs: Table) -> "GaussianMixtureModel":
        (table,) = inputs
        self._set(
            np.asarray(table.column("weights"), np.float64)[0],
            np.asarray(table.column("means"), np.float64)[0],
            np.asarray(table.column("covs"), np.float64)[0],
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "weights": self._weights[None, :],
            "means": self._means[None, :, :],
            "covs": self._covs[None, ...],
        })]

    def _require(self) -> None:
        if self._weights is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.FEATURES_COL))
        logp = np.asarray(_log_prob(
            jnp.asarray(x, jnp.float32),
            jnp.asarray(self._weights, jnp.float32),
            jnp.asarray(self._means, jnp.float32),
            jnp.asarray(self._covs, jnp.float32),
            self.get(self.COVARIANCE_TYPE),
        ), dtype=np.float64)
        shifted = logp - logp.max(axis=1, keepdims=True)
        resp = np.exp(shifted)
        resp /= resp.sum(axis=1, keepdims=True)
        out = table.with_column(
            self.get(self.PREDICTION_COL),
            np.argmax(logp, axis=1).astype(np.float64),
        )
        out = out.with_column(self.get(self.RAW_PREDICTION_COL), resp)
        return (out,)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {
            "weights": self._weights, "means": self._means,
            "covs": self._covs,
        })

    @classmethod
    def load(cls, path: str) -> "GaussianMixtureModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._set(arrays["weights"], arrays["means"], arrays["covs"])
        return model
