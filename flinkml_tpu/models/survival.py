"""AFTSurvivalRegression — accelerated-failure-time survival model
(the Spark family member).

Weibull AFT with right-censoring: ``log T = β·x + σ·ε`` with ε
standard extreme-value. Per-row log-likelihood (censor = 1 for an
observed event, 0 for right-censored)::

    z  = (log t − β·x) / σ
    ll = censor · (z − log σ) − exp(z)

Training rides the shared whole-run Adam device trainer
(``_adam.make_adam_trainer``) — one program of psum'd minibatch steps
over the data-sharded mesh; ``log σ`` is the optimized scale parameter
so positivity is structural. (Spark trains L-BFGS on the JVM;
Adam-on-device is the TPU-idiomatic equivalent.) Prediction is the
median survival time ``exp(β·x) · ln(2)^σ``; ``quantileProbabilities``
adds per-row quantile columns.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
    HasTol,
)
from flinkml_tpu.models._adam import make_adam_trainer
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import BoolParam, FloatArrayParam, StringParam
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _AFTParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasMaxIter,
    HasLearningRate, HasGlobalBatchSize, HasTol, HasSeed,
):
    CENSOR_COL = StringParam(
        "censorCol", "1.0 = event observed, 0.0 = right-censored.", "censor"
    )
    FIT_INTERCEPT = BoolParam(
        "fitIntercept",
        "Whether to fit an intercept term (matches Spark AFT's "
        "fitIntercept=true default; without it, data whose log survival "
        "times have nonzero mean biases the scale/coefficients).",
        True,
    )
    QUANTILE_PROBABILITIES = FloatArrayParam(
        "quantileProbabilities",
        "Survival-time quantiles emitted by transform (empty = none).",
        [],
    )
    QUANTILES_COL = StringParam(
        "quantilesCol", "Output column for the quantile matrix.", "quantiles"
    )


def _aft_loss_builder():
    def local_loss(params, xb, yb, wb):
        # yb packs [log_t, censor] as a [bs, 2] column.
        beta, log_sigma = params[0], params[1][0]
        log_t = yb[:, 0]
        censor = yb[:, 1]
        z = (log_t - xb @ beta) / jnp.exp(log_sigma)
        ll = censor * (z - log_sigma) - jnp.exp(z)
        return jnp.sum(-ll * wb)

    return local_loss


class AFTSurvivalRegression(_AFTParams, Estimator):
    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "AFTSurvivalRegressionModel":
        (table,) = inputs
        x = features_matrix(table, self.get(self.FEATURES_COL))
        t = np.asarray(
            table.column(self.get(self.LABEL_COL)), np.float64
        ).reshape(-1)
        censor = np.asarray(
            table.column(self.get(self.CENSOR_COL)), np.float64
        ).reshape(-1)
        if (t <= 0).any():
            raise ValueError("survival times must be positive")
        if not np.isin(censor, (0.0, 1.0)).all():
            raise ValueError("censor column must be 0/1")
        if censor.sum() == 0:
            raise ValueError("all rows are censored; nothing to fit")
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        fit_intercept = self.get(self.FIT_INTERCEPT)
        if fit_intercept:
            # Intercept as an appended constant feature: the optimized β
            # gains one entry, split back out after training.
            x = np.concatenate([x, np.ones((x.shape[0], 1), x.dtype)], axis=1)
        x_pad, n_valid = pad_to_multiple(x.astype(np.float32), p)
        y = np.stack([np.log(t), censor], axis=1).astype(np.float32)
        y_pad, _ = pad_to_multiple(y, p)
        w_pad = np.zeros(x_pad.shape[0], np.float32)
        w_pad[:n_valid] = 1.0
        local_bs = max(1, self.get(self.GLOBAL_BATCH_SIZE) // p)
        trainer = make_adam_trainer(
            mesh.mesh, DeviceMesh.DATA_AXIS, local_bs, _aft_loss_builder, 2
        )
        params0 = (
            jnp.zeros(x.shape[1], jnp.float32),
            jnp.zeros(1, jnp.float32),          # log sigma = 0 → sigma = 1
        )
        f32 = lambda v: jnp.asarray(v, jnp.float32)
        (beta, log_sigma), steps, loss = trainer(
            mesh.shard_batch(x_pad), mesh.shard_batch(y_pad),
            mesh.shard_batch(w_pad), params0,
            f32(self.get(self.LEARNING_RATE)),
            jnp.asarray(self.get(self.MAX_ITER), jnp.int32),
            f32(self.get(self.TOL)),
            jax.random.PRNGKey(self.get_seed()),
        )
        model = AFTSurvivalRegressionModel()
        model.copy_params_from(self)
        beta = np.asarray(beta, np.float64)
        intercept = float(beta[-1]) if fit_intercept else 0.0
        if fit_intercept:
            beta = beta[:-1]
        model._set(beta, float(np.exp(np.asarray(log_sigma)[0])), intercept)
        return model


class AFTSurvivalRegressionModel(_AFTParams, Model):
    def __init__(self):
        super().__init__()
        self._beta: Optional[np.ndarray] = None
        self._sigma: float = 1.0
        self._intercept: float = 0.0

    def _set(self, beta: np.ndarray, sigma: float,
             intercept: float = 0.0) -> None:
        self._beta = np.asarray(beta, np.float64)
        self._sigma = float(sigma)
        self._intercept = float(intercept)

    @property
    def coefficients(self) -> np.ndarray:
        self._require()
        return self._beta

    @property
    def scale(self) -> float:
        self._require()
        return self._sigma

    @property
    def intercept(self) -> float:
        self._require()
        return self._intercept

    def set_model_data(self, *inputs: Table) -> "AFTSurvivalRegressionModel":
        (table,) = inputs
        intercept = (
            float(np.asarray(table.column("intercept"))[0])
            if "intercept" in table.column_names else 0.0
        )
        self._set(
            np.asarray(table.column("beta"), np.float64)[0],
            float(np.asarray(table.column("sigma"))[0]),
            intercept,
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "beta": self._beta[None, :], "sigma": np.asarray([self._sigma]),
            "intercept": np.asarray([self._intercept]),
        })]

    def _require(self) -> None:
        if self._beta is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.FEATURES_COL))
        eta = x @ self._beta + self._intercept
        # Weibull median: exp(eta) * ln(2)^sigma.
        median = np.exp(eta) * np.log(2.0) ** self._sigma
        out = table.with_column(self.get(self.PREDICTION_COL), median)
        qs = self.get(self.QUANTILE_PROBABILITIES)
        if qs:
            q = np.asarray(qs, np.float64)
            if (q <= 0).any() or (q >= 1).any():
                raise ValueError(
                    f"quantileProbabilities must lie in (0, 1), got {qs}"
                )
            # T_q = exp(eta) * (-ln(1-q))^sigma.
            mat = np.exp(eta)[:, None] * (
                (-np.log1p(-q))[None, :] ** self._sigma
            )
            out = out.with_column(self.get(self.QUANTILES_COL), mat)
        return (out,)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"beta": self._beta, "sigma": np.asarray(self._sigma),
                   "intercept": np.asarray(self._intercept)},
        )

    @classmethod
    def load(cls, path: str) -> "AFTSurvivalRegressionModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._set(arrays["beta"], float(arrays["sigma"]),
                   float(arrays.get("intercept", 0.0)))
        return model
