"""FPGrowth — frequent-itemset mining + association rules (the
Spark/Flink family member).

Classic FP-tree mining on the host: itemset mining is pointer-chasing
over a prefix tree — no dense numeric structure for an accelerator to
exploit (the genuinely combinatorial corner of the library, like
Swing's set intersections). ``minSupport`` is a fraction of baskets;
rules are single-consequent (the Spark convention) with confidence and
lift; ``transform`` predicts, per basket, the union of consequents of
applicable rules minus items already present.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models.text import _object_column, _token_column
from flinkml_tpu.params import FloatParam, ParamValidators, StringParam
from flinkml_tpu.table import Table


class _FPNode:
    __slots__ = ("item", "count", "parent", "children")

    def __init__(self, item, parent):
        self.item = item
        self.count = 0
        self.parent = parent
        self.children: Dict[str, "_FPNode"] = {}


def _build_tree(transactions, counts, min_count):
    """Build an FP-tree over support-ordered, filtered transactions.
    Returns (root, header: item -> list of nodes)."""
    order = {
        it: (-c, it) for it, c in counts.items() if c >= min_count
    }
    root = _FPNode(None, None)
    header: Dict[str, List[_FPNode]] = {}
    for basket, mult in transactions:
        items = sorted(
            (it for it in basket if it in order), key=lambda it: order[it]
        )
        node = root
        for it in items:
            child = node.children.get(it)
            if child is None:
                child = _FPNode(it, node)
                node.children[it] = child
                header.setdefault(it, []).append(child)
            child.count += mult
            node = child
    return root, header


def _mine(transactions, counts, min_count, suffix, out):
    root, header = _build_tree(transactions, counts, min_count)
    # Items ascending by support: standard FP-growth order.
    items = sorted(
        header, key=lambda it: (counts[it], it)
    )
    for it in items:
        support = sum(n.count for n in header[it])
        itemset = tuple(sorted(suffix + (it,)))
        out[itemset] = support
        # Conditional pattern base: prefix paths above each node.
        cond_trans = []
        cond_counts: Dict[str, int] = {}
        for node in header[it]:
            path = []
            p = node.parent
            while p is not None and p.item is not None:
                path.append(p.item)
                p = p.parent
            if path:
                cond_trans.append((path, node.count))
                for pi in path:
                    cond_counts[pi] = cond_counts.get(pi, 0) + node.count
        if cond_trans:
            _mine(cond_trans, cond_counts, min_count, suffix + (it,), out)


def fpgrowth(baskets: List[List[str]], min_support: float):
    """Frequent itemsets: dict {tuple(sorted items): count}."""
    n = len(baskets)
    min_count = max(1, int(np.ceil(min_support * n)))
    counts: Dict[str, int] = {}
    dedup = []
    for b in baskets:
        items = set(map(str, b))
        dedup.append((items, 1))
        for it in items:
            counts[it] = counts.get(it, 0) + 1
    out: Dict[Tuple[str, ...], int] = {}
    _mine(dedup, counts, min_count, (), out)
    return out


class FPGrowth(Estimator):
    ITEMS_COL = StringParam("itemsCol", "Basket (token-list) column.", "items")
    MIN_SUPPORT = FloatParam(
        "minSupport", "Minimum fraction of baskets an itemset appears in.",
        0.3, ParamValidators.in_range(0.0, 1.0, lower_inclusive=False),
    )
    MIN_CONFIDENCE = FloatParam(
        "minConfidence", "Minimum confidence for association rules.", 0.8,
        ParamValidators.in_range(0.0, 1.0),
    )
    PREDICTION_COL = StringParam(
        "predictionCol", "Output column of predicted items.", "prediction"
    )

    def fit(self, *inputs: Table) -> "FPGrowthModel":
        (table,) = inputs
        baskets = _token_column(table, self.get(self.ITEMS_COL))
        itemsets = fpgrowth(
            [list(b) for b in baskets], self.get(self.MIN_SUPPORT)
        )
        model = FPGrowthModel()
        model.copy_params_from(self)
        model._set(itemsets, len(baskets))
        return model


class FPGrowthModel(Model):
    ITEMS_COL = FPGrowth.ITEMS_COL
    MIN_SUPPORT = FPGrowth.MIN_SUPPORT
    MIN_CONFIDENCE = FPGrowth.MIN_CONFIDENCE
    PREDICTION_COL = FPGrowth.PREDICTION_COL

    def __init__(self):
        super().__init__()
        self._itemsets: Optional[Dict[Tuple[str, ...], int]] = None
        self._n_baskets: int = 0
        self._rule_cache = None

    def _set(self, itemsets, n_baskets: int) -> None:
        self._itemsets = dict(itemsets)
        self._n_baskets = int(n_baskets)
        self._rule_cache = None   # (minConfidence, rules); lazy

    def _require(self) -> None:
        if self._itemsets is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    # -- outputs -------------------------------------------------------------
    def freq_itemsets(self) -> Table:
        """One row per frequent itemset: (items, freq), support-desc."""
        self._require()
        ordered = sorted(
            self._itemsets.items(), key=lambda kv: (-kv[1], kv[0])
        )
        items = _object_column([list(k) for k, _ in ordered])
        return Table({
            "items": items,
            "freq": np.asarray([v for _, v in ordered], np.int64),
        })

    def association_rules(self) -> Table:
        """Single-consequent rules with confidence ≥ minConfidence:
        (antecedent, consequent, confidence, lift, support)."""
        self._require()
        min_conf = self.get(self.MIN_CONFIDENCE)
        n = max(self._n_baskets, 1)
        ante, cons, confs, lifts, supps = [], [], [], [], []
        for itemset, count in self._itemsets.items():
            if len(itemset) < 2:
                continue
            for i, c in enumerate(itemset):
                a = itemset[:i] + itemset[i + 1:]
                a_count = self._itemsets.get(a)
                if not a_count:
                    continue
                conf = count / a_count
                if conf < min_conf:
                    continue
                c_count = self._itemsets.get((c,), 0)
                ante.append(list(a))
                cons.append(c)
                confs.append(conf)
                lifts.append(conf / (c_count / n) if c_count else np.nan)
                supps.append(count / n)
        return Table({
            "antecedent": _object_column(ante),
            "consequent": np.asarray(cons, dtype=str),
            "confidence": np.asarray(confs),
            "lift": np.asarray(lifts),
            "support": np.asarray(supps),
        })

    def _rules_for_transform(self):
        conf = self.get(self.MIN_CONFIDENCE)
        if self._rule_cache is None or self._rule_cache[0] != conf:
            rules = self.association_rules()
            self._rule_cache = (conf, [
                (frozenset(a), c)
                for a, c in zip(rules["antecedent"], rules["consequent"])
                if len(a)
            ])
        return self._rule_cache[1]

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        """Per basket: consequents of rules whose antecedent ⊆ basket,
        minus items already present (the Spark transform)."""
        (table,) = inputs
        self._require()
        rule_list = self._rules_for_transform()
        baskets = _token_column(table, self.get(self.ITEMS_COL))
        preds = []
        for b in baskets:
            bs = set(map(str, b))
            hit = {c for a, c in rule_list if a <= bs and c not in bs}
            preds.append(sorted(hit))
        return (
            table.with_column(
                self.get(self.PREDICTION_COL), _object_column(preds)
            ),
        )

    # -- persistence ---------------------------------------------------------
    def set_model_data(self, *inputs: Table) -> "FPGrowthModel":
        (table,) = inputs
        items = table.column("items")
        freqs = np.asarray(table.column("freq"), np.int64)
        # numBaskets rides per row, with a freq=-1 sentinel row so an
        # EMPTY model (nothing frequent) still carries it.
        n = int(np.asarray(table.column("numBaskets"))[0])
        real = freqs >= 0
        self._set(
            {
                tuple(sorted(map(str, it))): int(f)
                for it, f, keep in zip(items, freqs, real) if keep
            },
            n,
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        t = self.freq_itemsets()
        items = np.empty(t.num_rows + 1, dtype=object)
        items[0] = []          # sentinel row: freq -1, carries numBaskets
        for i in range(t.num_rows):
            items[i + 1] = t.column("items")[i]
        freqs = np.concatenate([[-1], np.asarray(t.column("freq"), np.int64)])
        return [Table({
            "items": items,
            "freq": freqs,
            "numBaskets": np.full(t.num_rows + 1, self._n_baskets),
        })]

    def save(self, path: str) -> None:
        self._require()
        # Itemsets serialize as NUL-joined strings; a NUL inside an item
        # would silently change itemset arity on load, so reject it.
        if any("\x00" in it for k in self._itemsets for it in k):
            raise ValueError(
                "item strings must not contain NUL characters to be saved"
            )
        keys = ["\x00".join(k) for k in self._itemsets]
        self._save_with_arrays(
            path,
            {
                "itemsets": np.asarray(keys, dtype=str),
                "freq": np.asarray(list(self._itemsets.values()), np.int64),
            },
            extra={"numBaskets": self._n_baskets},
        )

    @classmethod
    def load(cls, path: str) -> "FPGrowthModel":
        model, arrays, meta = cls._load_with_arrays(path)
        itemsets = {
            tuple(k.split("\x00")): int(f)
            for k, f in zip(arrays["itemsets"].astype(str), arrays["freq"])
        }
        model._set(itemsets, int(meta["numBaskets"]))
        return model
