"""OnlineStandardScaler — standardization statistics over an unbounded
stream.

Member of the wider Flink ML family (upstream ``OnlineStandardScaler``:
continuously-updated mean/std emitted as versioned models — online
feature engineering is Flink ML's signature capability). Third user of
the unbounded-iteration mode after OnlineLogisticRegression /
OnlineKMeans.

Statistics merge exactly per batch via Chan's parallel
mean/M2 combination (no accumulation drift regardless of stream
length); each consumed batch bumps ``model_version``, mirroring the
other online models. The fitted model transforms exactly like
``StandardScalerModel`` (``withMean``/``withStd``).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator
from flinkml_tpu.common_params import HasGlobalBatchSize
from flinkml_tpu.iteration import (
    IterationConfig,
    Iterations,
    TerminateOnMaxIter,
    iterate,
)
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.models.scalers import StandardScalerModel, _HasInputOutputCol
from flinkml_tpu.table import Table


class OnlineStandardScaler(
    _HasInputOutputCol, HasGlobalBatchSize, Estimator
):
    WITH_MEAN = StandardScalerModel.WITH_MEAN
    WITH_STD = StandardScalerModel.WITH_STD

    def __init__(self, mesh=None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "OnlineStandardScalerModel":
        """Consume the table as a stream of globalBatchSize mini-batches."""
        (table,) = inputs
        return self.fit_stream(
            table.batches(self.get(self.GLOBAL_BATCH_SIZE))
        )

    def fit_stream(
        self,
        batches: Iterable[Table],
        *,
        checkpoint_manager=None,
        checkpoint_interval: int = 0,
        resume: bool = False,
        stream_resume: str = "replay",
        sentinel=None,
        recovery=None,
    ) -> "OnlineStandardScalerModel":
        """One exact Chan-merge per arriving batch.

        Self-healing (ISSUE 9): ``sentinel``/``recovery`` thread the
        numerics sentinel + rollback-and-quarantine policy of
        :mod:`flinkml_tpu.recovery` through the loop (see the
        OnlineLogisticRegression docstring and
        ``fault_tolerance.md``, "Self-healing").

        Crash safety (ISSUE 4, single-process): ``checkpoint_manager`` +
        ``checkpoint_interval`` snapshot the moment carry (n, mean, M2,
        model version) every N consumed batches; ``resume=True``
        continues bit-exactly from the newest valid snapshot (corrupt
        ones are verified and skipped); ``stream_resume`` picks the
        resumed-stream cursor contract ('replay' skips the consumed
        prefix of a restartable source, 'continue' reads a live stream
        from the front).

        Multi-process (round 4): moment merging is associative and
        exact, so each process consumes its OWN stream partition
        independently (no per-step lockstep needed) and the per-rank
        ``(n, mean, M2)`` triples merge once at stream end through the
        device fabric's f64-exact transport — in rank order, so every
        host computes the identical model. A rank-local failure is held
        and agreed before the merge (no stranded peers)."""
        input_col = self.get(self.INPUT_COL)

        def step(carry, batch_table, epoch):
            x = features_matrix(batch_table, input_col).astype(np.float64)
            nb = float(x.shape[0])
            if nb == 0:
                return carry, None
            mb = x.mean(axis=0)
            m2b = ((x - mb) ** 2).sum(axis=0)
            if carry["mean"] is None:
                carry["mean"] = mb
                carry["m2"] = m2b
                carry["n"] = nb
            else:
                # Chan et al. pairwise merge: exact for any batch split
                # (and bitwise-exact from the zero-initialized carry of
                # the single-process path: na=0 gives mean = mb exactly
                # and a zero correction term).
                na = carry["n"]
                delta = mb - carry["mean"]
                n = na + nb
                carry["mean"] = carry["mean"] + delta * (nb / n)
                carry["m2"] = (
                    carry["m2"] + m2b + delta * delta * (na * nb / n)
                )
                carry["n"] = n
            carry["version"] = int(carry["version"]) + 1
            return carry, None

        import jax

        multi = jax.process_count() > 1
        if multi:
            if (checkpoint_manager is not None or resume
                    or sentinel is not None or recovery is not None):
                raise NotImplementedError(
                    "checkpoint/resume and sentinel/recovery for the "
                    "multi-process online stream path are not wired yet; "
                    "run the checkpointing/self-healing fit single-process"
                )
            # The local pass's failures are HELD: a rank-local raise would
            # strand the peers in the final merge collective.
            state = {"n": 0.0, "mean": None, "m2": None, "version": 0}
            final = state
            err = None
            try:
                final = Iterations.iterate_unbounded_streams(
                    step, state, batches,
                    IterationConfig(TerminateOnMaxIter(2**31 - 1)),
                ).state
            except Exception as e:  # noqa: BLE001 — agreed below
                err = e
            from flinkml_tpu.iteration.stream_sync import DeferredValidation

            dv = DeferredValidation()
            dv.err = err
            dv.rendezvous(self.mesh, "online scaler stream")
            final = self._merge_across_processes(final, self.mesh)
            if final["mean"] is None:
                raise ValueError("training stream is empty on every process")
        else:
            from flinkml_tpu.iteration.checkpoint import begin_resume
            from flinkml_tpu.models._streaming import feed_world_size

            # The rescale guard pins the FEED's world (Dataset shard
            # count / ElasticFeed world; 1 for plain iterables); the
            # moment carry is replicated, so a rescale="reshard"
            # manager resumes it at any world bit-exactly.
            restore_epoch = begin_resume(
                checkpoint_manager, resume,
                world_size=feed_world_size(batches)
            )
            # Peek the first batch to fix the feature dim: the carry is a
            # full array pytree from epoch 0 (the checkpointable
            # structure); zero-initialized moments Chan-merge exactly. A
            # flinkml_tpu.data.Dataset goes to iterate() whole (cursor
            # checkpoint/resume belongs to the runtime).
            from flinkml_tpu.models._streaming import peek_stream

            first, stream = peek_stream(batches)
            if first is None:
                if restore_epoch is not None:
                    # Resume-as-noop on an already-exhausted stream: the
                    # checkpointed moments ARE the model (`like` leaf
                    # values are irrelevant — only the structure).
                    final, _ = checkpoint_manager.restore_latest(
                        like={"n": 0, "mean": 0, "m2": 0, "version": 0}
                    )
                    return self._model_from_final(final)
                raise ValueError("training stream is empty")
            d = features_matrix(first, input_col).shape[1]
            state = {
                "n": 0.0,
                "mean": np.zeros(d),
                "m2": np.zeros(d),
                "version": 0,
            }
            result = iterate(
                step, state, stream,
                IterationConfig(
                    TerminateOnMaxIter(2**31 - 1),
                    checkpoint_interval=checkpoint_interval,
                    checkpoint_manager=checkpoint_manager,
                    stream_resume=stream_resume,
                    sentinel=sentinel,
                    recovery=recovery,
                ),
                resume=resume,
            )
            final = result.state
            if float(final["n"]) == 0.0:
                raise ValueError("training stream is empty")
            model = self._model_from_final(final)
            # Self-healing record of the fit (None without a policy).
            model.recovery_summary = result.recovery
            return model
        return self._model_from_final(final)

    def _model_from_final(self, final) -> "OnlineStandardScalerModel":
        model = OnlineStandardScalerModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "mean": np.asarray(final["mean"])[None, :],
            "std": np.sqrt(np.asarray(final["m2"]) / float(final["n"]))[None, :],
        }))
        model._model_version = int(final["version"])
        return model

    @staticmethod
    def _merge_across_processes(final, mesh=None):
        """Chan-merge the per-rank (n, mean, M2, version) in rank order —
        identical on every host (see :meth:`fit_stream`)."""
        from flinkml_tpu.iteration.stream_sync import (
            agree_all_ok,
            agree_max,
            gather_vectors,
        )

        local_d = 0 if final["mean"] is None else final["mean"].shape[0]
        d = agree_max(local_d, mesh)
        # Rank-SYMMETRIC mismatch abort: the max-dim rank always matches
        # the agreed d, so a bare local raise would strand it in the
        # gather below — every rank must pass through this agreement.
        agree_all_ok(
            not (local_d and local_d != d), mesh,
            f"feature-dim agreement (local {local_d}, global {d})",
        )
        if d == 0:
            return {"n": 0.0, "mean": None, "m2": None, "version": 0}
        vec = np.zeros(2 + 2 * d)
        vec[0] = final["n"]
        vec[1] = float(final["version"])
        if final["mean"] is not None:
            vec[2 : 2 + d] = final["mean"]
            vec[2 + d :] = final["m2"]
        rows = gather_vectors(vec, mesh)
        n = 0.0
        mean = np.zeros(d)
        m2 = np.zeros(d)
        version = 0
        for row in rows:  # rank order: identical merge on every host
            nb = float(row[0])
            version += int(round(row[1]))
            if nb == 0.0:
                continue
            mb, m2b = row[2 : 2 + d], row[2 + d :]
            if n == 0.0:
                n, mean, m2 = nb, mb.copy(), m2b.copy()
                continue
            delta = mb - mean
            tot = n + nb
            mean = mean + delta * (nb / tot)
            m2 = m2 + m2b + delta * delta * (n * nb / tot)
            n = tot
        if n == 0.0:
            return {"n": 0.0, "mean": None, "m2": None, "version": version}
        return {"n": n, "mean": mean, "m2": m2, "version": version}


class OnlineStandardScalerModel(StandardScalerModel):
    """StandardScalerModel + the online model-version counter (persisted,
    like the other online models')."""

    def __init__(self):
        super().__init__()
        self._model_version = 0

    @property
    def model_version(self) -> int:
        return self._model_version

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"mean": self._mean, "std": self._std},
            extra={"modelVersion": self._model_version},
        )

    @classmethod
    def load(cls, path: str) -> "OnlineStandardScalerModel":
        model, arrays, meta = cls._load_with_arrays(path)
        model._mean = arrays["mean"]
        model._std = arrays["std"]
        model._model_version = int(meta.get("modelVersion", 0))
        return model
