"""OnlineStandardScaler — standardization statistics over an unbounded
stream.

Member of the wider Flink ML family (upstream ``OnlineStandardScaler``:
continuously-updated mean/std emitted as versioned models — online
feature engineering is Flink ML's signature capability). Third user of
the unbounded-iteration mode after OnlineLogisticRegression /
OnlineKMeans.

Statistics merge exactly per batch via Chan's parallel
mean/M2 combination (no accumulation drift regardless of stream
length); each consumed batch bumps ``model_version``, mirroring the
other online models. The fitted model transforms exactly like
``StandardScalerModel`` (``withMean``/``withStd``).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator
from flinkml_tpu.common_params import HasGlobalBatchSize
from flinkml_tpu.iteration import (
    IterationConfig,
    Iterations,
    TerminateOnMaxIter,
)
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.models.scalers import StandardScalerModel, _HasInputOutputCol
from flinkml_tpu.table import Table


class OnlineStandardScaler(
    _HasInputOutputCol, HasGlobalBatchSize, Estimator
):
    WITH_MEAN = StandardScalerModel.WITH_MEAN
    WITH_STD = StandardScalerModel.WITH_STD

    def fit(self, *inputs: Table) -> "OnlineStandardScalerModel":
        """Consume the table as a stream of globalBatchSize mini-batches."""
        (table,) = inputs
        return self.fit_stream(
            table.batches(self.get(self.GLOBAL_BATCH_SIZE))
        )

    def fit_stream(self, batches: Iterable[Table]) -> "OnlineStandardScalerModel":
        input_col = self.get(self.INPUT_COL)

        state = {"n": 0.0, "mean": None, "m2": None, "version": 0}

        def step(carry, batch_table, epoch):
            x = features_matrix(batch_table, input_col).astype(np.float64)
            nb = float(x.shape[0])
            if nb == 0:
                return carry, None
            mb = x.mean(axis=0)
            m2b = ((x - mb) ** 2).sum(axis=0)
            if carry["mean"] is None:
                carry["mean"] = mb
                carry["m2"] = m2b
                carry["n"] = nb
            else:
                # Chan et al. pairwise merge: exact for any batch split.
                na = carry["n"]
                delta = mb - carry["mean"]
                n = na + nb
                carry["mean"] = carry["mean"] + delta * (nb / n)
                carry["m2"] = (
                    carry["m2"] + m2b + delta * delta * (na * nb / n)
                )
                carry["n"] = n
            carry["version"] += 1
            return carry, None

        result = Iterations.iterate_unbounded_streams(
            step, state, batches, IterationConfig(TerminateOnMaxIter(2**31 - 1))
        )
        final = result.state
        if final["mean"] is None:
            raise ValueError("training stream is empty")
        model = OnlineStandardScalerModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "mean": final["mean"][None, :],
            "std": np.sqrt(final["m2"] / final["n"])[None, :],
        }))
        model._model_version = final["version"]
        return model


class OnlineStandardScalerModel(StandardScalerModel):
    """StandardScalerModel + the online model-version counter (persisted,
    like the other online models')."""

    def __init__(self):
        super().__init__()
        self._model_version = 0

    @property
    def model_version(self) -> int:
        return self._model_version

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"mean": self._mean, "std": self._std},
            extra={"modelVersion": self._model_version},
        )

    @classmethod
    def load(cls, path: str) -> "OnlineStandardScalerModel":
        model, arrays, meta = cls._load_with_arrays(path)
        model._mean = arrays["mean"]
        model._std = arrays["std"]
        model._model_version = int(meta.get("modelVersion", 0))
        return model
