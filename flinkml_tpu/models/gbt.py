"""Gradient-boosted trees (histogram-based): GBTClassifier, GBTRegressor.

A major model family beyond the reference snapshot, designed TPU-first
rather than translated from CPU tree libraries:

  - **Quantile binning** (host, once): each feature → int32 bin ids in
    ``[0, maxBins)`` via per-feature quantile edges — the LightGBM/
    HistGradientBoosting layout. Raw thresholds are recovered from the
    edges so inference needs no binning.
  - **Level-wise growth with static shapes**: every tree is a complete
    binary tree of depth ``maxDepth`` (heap layout). Each level computes
    ALL (node, feature, bin) gradient/hessian histograms as ONE
    ``segment_sum`` over ``n·d`` keys, cumulative-sums over bins, and
    picks every node's best split with one argmax — no per-node
    recursion, no data-dependent shapes, XLA-friendly end to end.
  - **Whole-boosting-run on device**: trees are built inside a single
    ``lax.scan`` (predictions are the carry; per-tree parameters are the
    stacked outputs), sharded over the data axis with ``psum``-combined
    histograms — every device decides identical splits, SPMD-style.
  - Second-order (XGBoost) gains: ``gain = GL²/(HL+λ) + GR²/(HR+λ) −
    G²/(H+λ)``; leaf value ``−G/(H+λ)``; logistic loss for the
    classifier (base score = train log-odds), squared loss for the
    regressor (base = weighted mean). Per-tree row subsampling.
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasLabelCol,
    HasLearningRate,
    HasPredictionCol,
    HasRawPredictionCol,
    HasSeed,
    HasWeightCol,
)
from flinkml_tpu.models._data import (
    check_binary_labels,
    hashed_feature_matrix,
    labeled_data,
    sparse_features,
)
from flinkml_tpu.params import FloatParam, IntParam, ParamValidators
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _GBTParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasWeightCol,
    HasLearningRate, HasSeed,
):
    NUM_TREES = IntParam(
        "numTrees", "Number of boosting rounds.", 50, ParamValidators.gt(0)
    )
    MAX_DEPTH = IntParam(
        "maxDepth", "Depth of every (complete) tree.", 5,
        ParamValidators.in_range(1, 12),
    )
    MAX_BINS = IntParam(
        "maxBins", "Histogram bins per feature.", 64,
        ParamValidators.in_range(2, 256),
    )
    REG_LAMBDA = FloatParam(
        "regLambda", "L2 regularization on leaf values.", 1.0,
        ParamValidators.gt_eq(0.0),
    )
    SUBSAMPLE = FloatParam(
        "subsample", "Per-tree row sampling fraction.", 1.0,
        ParamValidators.in_range(0.0, 1.0, lower_inclusive=False),
    )
    VALIDATION_FRACTION = FloatParam(
        "validationFraction",
        "Held-out fraction for early stopping: the forest is truncated "
        "to the prefix with the best holdout loss (0 = off; boosted "
        "estimators only).",
        0.0, ParamValidators.in_range(0.0, 0.9),
    )
    NUM_HASH_FEATURES = IntParam(
        "numHashFeatures",
        "Bundle width for SparseVector feature columns: sparse inputs "
        "(one-hot / hashed text) are hash-bundled into this many dense "
        "features before binning, so trees train in O(n x numHashFeatures) "
        "memory regardless of the sparse dimensionality. Dense inputs "
        "ignore it.",
        256, ParamValidators.in_range(2, 1 << 16),
    )


# -- binning ------------------------------------------------------------------

def quantile_bin_edges(x: np.ndarray, max_bins: int) -> np.ndarray:
    """Per-feature interior quantile edges, padded with +inf to a fixed
    ``[d, max_bins - 1]`` (duplicate quantiles collapse, so features with
    few distinct values just use fewer real edges)."""
    n, d = x.shape
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    edges = np.full((d, max_bins - 1), np.inf)
    for j in range(d):
        e = np.unique(np.quantile(x[:, j], qs))
        e = e[np.isfinite(e)]
        edges[j, : len(e)] = e
    return edges


def bin_features(x: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """bin = #{edges < x} per feature; ``bin <= b  ⟺  x <= edges[b]``."""
    n, d = x.shape
    out = np.empty((n, d), dtype=np.int32)
    for j in range(d):
        out[:, j] = np.searchsorted(edges[j], x[:, j], side="left")
    return out


# -- device forest builder ----------------------------------------------------


def _hist_layout() -> str:
    """Measured-default gate for the per-level histogram reduction.

    ``segment`` (default): one ``segment_sum`` over ``n·d`` cells per
    level — XLA's sort-based lowering re-sorts every cell at every level
    of every tree (measured 0.22% of the streaming bound, BASELINE.md
    "rooflines": the same sort class as sparse LR). ``cumsum``: the
    (feature, bin) half of the key is STATIC per fit, so cells are
    sorted once at pack time (:func:`gbt_hist_tables`) and each level
    reduces ``2^level``-wide one-hot-expanded (grad, hess) columns with
    :func:`~flinkml_tpu.ops.sparse.chunked_run_totals` — streaming
    passes, no per-level sort. ``FLINKML_TPU_GBT_HISTOGRAM`` selects;
    the device A/B (``tools/gbt_hist_probe.py``) decides the default."""
    layout = os.environ.get("FLINKML_TPU_GBT_HISTOGRAM")
    if layout is None:
        # Measured default for this mesh (autotune tuning table), else
        # the historical "segment".
        from flinkml_tpu.autotune import tuned_default

        return tuned_default("gbt_histogram", "segment",
                             allowed=("segment", "cumsum"))
    if layout not in ("segment", "cumsum"):
        raise ValueError(
            f"FLINKML_TPU_GBT_HISTOGRAM={layout!r}: expected "
            "'segment' or 'cumsum'"
        )
    return layout


def gbt_hist_tables(b_pad: np.ndarray, p_size: int, n_bins: int):
    """Pack-time tables for the ``cumsum`` histogram layout.

    Per device shard of the padded binned matrix ``[n, d]``: flatten the
    ``n_local·d`` cells row-major, sort ONCE by the static key
    ``feat·n_bins + bin``, and record

    - ``srow [p·cells] int32`` — row-in-shard of each sorted cell (the
      level body gathers grad/hess/node through it);
    - ``ends [p·max_runs] int32`` — inclusive end of each (feat, bin)
      run, padded by repeating the last end (differences to exactly 0);
    - ``cols [p·max_runs] int32`` — the run's static key, ascending.
    """
    from flinkml_tpu.ops.sparse import run_boundary_tables

    n, d = b_pad.shape
    n_local = n // p_size
    cells = n_local * d
    srow = np.empty((p_size, cells), np.int32)
    skeys = np.empty((p_size, cells), np.int64)
    for dev in range(p_size):
        shard = b_pad[dev * n_local:(dev + 1) * n_local]
        key = (np.arange(d, dtype=np.int64)[None, :] * n_bins
               + shard).reshape(-1)
        order = np.argsort(key, kind="stable")
        srow[dev] = (order // d).astype(np.int32)
        skeys[dev] = key[order]
    ends, cols = run_boundary_tables(skeys)
    return srow.reshape(-1), ends.reshape(-1), cols.reshape(-1)


def sharded_hist_args(b_pad: np.ndarray, mesh, n_bins: int,
                      hist_layout: str) -> tuple:
    """The extra sharded builder args for ``hist_layout`` — ONE
    definition shared by the product fit path, the bench GBT stage, and
    ``tools/gbt_hist_probe.py``, so every consumer passes the builder
    the identical table layout. Empty for ``segment``."""
    if hist_layout != "cumsum":
        return ()
    srow, ends, cols = gbt_hist_tables(b_pad, mesh.axis_size(), n_bins)
    return (
        mesh.shard_batch(srow), mesh.shard_batch(ends),
        mesh.shard_batch(cols),
    )


@functools.lru_cache(maxsize=16)
def _forest_builder(mesh, axis: str, n_feat: int, n_bins: int, depth: int,
                    num_trees: int, logistic: bool, boosting: bool = True,
                    feat_subset: int = 0, hist_layout: str = "segment"):
    """One compiled program that builds the whole forest.

    Static config in the cache key; runtime inputs are the sharded
    binned matrix / labels / weights and scalar hyperparams.

    ``boosting=False`` turns the scan into BAGGING (random forest):
    every tree fits the same base-score residual independently (the
    prediction carry is not updated), row weights become Poisson
    bootstrap multiplicities (diversity even at subsample=1.0), and
    ``feat_subset > 0`` draws exactly that many features per tree (a
    permutation prefix — never empty), masking the rest's gains to -inf
    so an excluded feature can never win the argmax even when every
    in-subset gain is negative.
    """
    n_leaves = 1 << depth
    n_inner = n_leaves - 1          # heap: level L starts at 2^L - 1
    seg = n_leaves * n_feat * n_bins  # uniform segment space per level

    def grad_hess(pred, y, w):
        if logistic:
            p = jax.nn.sigmoid(pred)
            return (p - y) * w, jnp.maximum(p * (1 - p), 1e-6) * w
        return (pred - y) * w, w

    def local(binned, y, w, base, lr, lam, subsample, key, *hist_tables):
        n_local = binned.shape[0]
        feat_ids = jnp.arange(n_feat, dtype=jnp.int32)[None, :]
        if hist_layout == "cumsum":
            srow, ends, cols = hist_tables

        def level_hists_cumsum(g, h, node, level):
            """Sort-free per-level histograms: gather by the pack-time
            cell order, expand by a 2^level-wide node one-hot, reduce
            grad and hess columns in ONE fused run-totals pass at the
            static (feat, bin) boundaries."""
            from flinkml_tpu.ops.sparse import chunked_run_totals

            width = 1 << level
            oh = jax.nn.one_hot(node[srow], width, dtype=g.dtype)
            both = jnp.concatenate(
                [g[srow][:, None] * oh, h[srow][:, None] * oh], axis=1
            )
            t2 = chunked_run_totals(both, ends)    # [runs, 2*width]
            out = []
            for t in (t2[:, :width], t2[:, width:]):
                fb = jnp.zeros((n_feat * n_bins, width), g.dtype) \
                    .at[cols].add(t)
                full = jnp.zeros((n_leaves, n_feat, n_bins), g.dtype) \
                    .at[:width].set(
                        jnp.moveaxis(
                            fb.reshape(n_feat, n_bins, width), -1, 0
                        )
                    )
                out.append(full)
            return out[0], out[1]

        def build_tree(g, h, fmask):
            node = jnp.zeros(n_local, jnp.int32)   # index within level
            feat_arr = jnp.zeros(n_inner, jnp.int32)
            bin_arr = jnp.zeros(n_inner, jnp.int32)
            gain_arr = jnp.zeros(n_inner, jnp.float32)
            for level in range(depth):
                if hist_layout == "cumsum":
                    hg, hh = level_hists_cumsum(g, h, node, level)
                    hg = jax.lax.psum(hg, axis)
                    hh = jax.lax.psum(hh, axis)
                else:
                    ids = ((node[:, None] * n_feat + feat_ids) * n_bins
                           + binned).reshape(-1)
                    hg = jax.lax.psum(jax.ops.segment_sum(
                        jnp.repeat(g, n_feat), ids, num_segments=seg), axis)
                    hh = jax.lax.psum(jax.ops.segment_sum(
                        jnp.repeat(h, n_feat), ids, num_segments=seg), axis)
                    hg = hg.reshape(n_leaves, n_feat, n_bins)
                    hh = hh.reshape(n_leaves, n_feat, n_bins)
                gl = jnp.cumsum(hg, axis=2)
                hl = jnp.cumsum(hh, axis=2)
                gt = gl[:, :, -1:]
                ht = hl[:, :, -1:]
                gr = gt - gl
                hr = ht - hl
                gain = (
                    gl * gl / (hl + lam) + gr * gr / (hr + lam)
                    - gt * gt / (ht + lam)
                )
                # Splits with an empty side are not real splits — and with
                # lam == 0 their 0/0 gain would be NaN, which argmax treats
                # as the maximum (silently training a useless forest).
                gain = jnp.where((hl > 0) & (hr > 0), gain, 0.0)
                # The last bin's "split" sends everything left: force its
                # gain to 0 so argmax prefers real splits.
                gain = gain.at[:, :, -1].set(0.0)
                # Per-tree feature subset (bagging): -inf, NOT a zero
                # multiply — zeroed gains would still beat negative
                # in-subset gains (possible under regLambda) and leak
                # excluded features into the forest.
                gain = jnp.where(
                    fmask[None, :, None] > 0, gain, -jnp.inf
                )
                flat_gain = gain.reshape(n_leaves, n_feat * n_bins)
                best = jnp.argmax(flat_gain, axis=1)
                best_gain = jnp.maximum(jnp.max(flat_gain, axis=1), 0.0)
                bf = (best // n_bins).astype(jnp.int32)     # [n_leaves]
                bb = (best % n_bins).astype(jnp.int32)
                start = (1 << level) - 1
                idx = start + jnp.arange(1 << level)
                feat_arr = feat_arr.at[idx].set(bf[: 1 << level])
                bin_arr = bin_arr.at[idx].set(bb[: 1 << level])
                gain_arr = gain_arr.at[idx].set(best_gain[: 1 << level])
                sample_bin = jnp.take_along_axis(
                    binned, bf[node][:, None], axis=1
                )[:, 0]
                node = node * 2 + (sample_bin > bb[node])
            lg = jax.lax.psum(jax.ops.segment_sum(
                g, node, num_segments=n_leaves), axis)
            lh = jax.lax.psum(jax.ops.segment_sum(
                h, node, num_segments=n_leaves), axis)
            # Empty leaves have lh == 0; with lam == 0 the division would
            # be 0/0 — floor the denominator so they get value 0.
            leaf = -lg / jnp.maximum(lh + lam, 1e-12)
            return feat_arr, bin_arr, gain_arr, leaf, node

        def tree_step(carry, tree_key):
            pred = carry
            g, h = grad_hess(pred, y, w)
            k_rows, k_feats = jax.random.split(tree_key)
            if boosting:
                mask = (
                    jax.random.uniform(k_rows, (n_local,)) < subsample
                ).astype(g.dtype)
            else:
                # Poisson bootstrap: multiplicity weights give the
                # classic with-replacement resample (diverse trees even
                # at subsample = 1.0, where a Bernoulli mask would make
                # every tree identical).
                mask = jax.random.poisson(
                    k_rows, subsample, (n_local,)
                ).astype(g.dtype)
            if feat_subset:
                perm = jax.random.permutation(k_feats, n_feat)
                fmask = jnp.zeros(n_feat, jnp.float32).at[
                    perm[:feat_subset]
                ].set(1.0)
            else:
                fmask = jnp.ones(n_feat, jnp.float32)
            feat_arr, bin_arr, gain_arr, leaf, node = build_tree(
                g * mask, h * mask, fmask
            )
            if boosting:
                pred = (pred + lr * leaf[node]).astype(jnp.float32)
            return pred, (feat_arr, bin_arr, gain_arr, leaf)

        keys = jax.random.split(key, num_trees)
        # Derive the initial carry from a sharded input so it is marked
        # varying over the mesh axis (a replicated-scalar broadcast is
        # "unvarying" and shard_map rejects the scan carry).
        pred0 = (jnp.zeros_like(y) + base).astype(jnp.float32)
        _, trees = jax.lax.scan(tree_step, pred0, keys)
        return trees

    hist_specs = (P(axis),) * 3 if hist_layout == "cumsum" else ()
    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(), P(), P(), P(), P())
            + hist_specs,
            out_specs=(P(), P(), P(), P()),
        )
    )


def _walk_forest_per_tree(x: np.ndarray, feats, thrs, leaves,
                          depth: int) -> np.ndarray:
    """[T, n] per-tree leaf values for raw features (host numpy)."""
    n = x.shape[0]
    out = np.empty((feats.shape[0], n))
    for t in range(feats.shape[0]):
        node = np.zeros(n, dtype=np.int64)   # index within level
        for level in range(depth):
            start = (1 << level) - 1
            f = feats[t, start + node]
            thr = thrs[t, start + node]
            node = node * 2 + (x[np.arange(n), f] > thr)
        out[t] = leaves[t, node]
    return out


def _walk_forest(x: np.ndarray, feats, thrs, leaves, depth: int) -> np.ndarray:
    """Sum of leaf values over all trees (host numpy). Streams one tree
    at a time — an O(n) accumulator, NOT the [T, n] matrix the
    early-stopping path materializes (that would be gigabytes for big
    forests scoring big batches)."""
    n = x.shape[0]
    total = np.zeros(n)
    for t in range(feats.shape[0]):
        node = np.zeros(n, dtype=np.int64)
        for level in range(depth):
            start = (1 << level) - 1
            f = feats[t, start + node]
            thr = thrs[t, start + node]
            node = node * 2 + (x[np.arange(n), f] > thr)
        total += leaves[t, node]
    return total


class _GBTBase(StreamingEstimatorMixin, _GBTParams, Estimator):
    """``fit`` accepts, besides a single in-RAM :class:`Table`:

      - an **iterable of batch Tables** — the out-of-core path: the
        stream is cached once (spilling to ``cache_dir`` beyond
        ``cache_memory_budget_bytes``), bin edges come from a seeded
        reservoir row sample, and every tree level accumulates its
        histograms by replaying the binned cache with bounded HBM
        residency (see :mod:`flinkml_tpu.models._gbt_stream`);
      - a sealed :class:`~flinkml_tpu.iteration.datacache.DataCache`
        whose batches carry this estimator's features/label(/weight)
        columns.

    Streamed mode is boosting-only and excludes ``validationFraction``.
    """

    _LOGISTIC = True
    _BOOSTING = True

    def __init__(self, mesh=None, *, stream_reservoir_capacity: int = 65_536,
                 **knobs):
        super().__init__(mesh=mesh, **knobs)
        # Streamed-fit bin-edge sample size (see _gbt_stream: edges come
        # from a seeded uniform row reservoir; capacity >= n gives exact
        # edges, smaller capacities trade accuracy for a bounded sample —
        # envelope quantified in tests/test_gbt_reservoir.py).
        self.stream_reservoir_capacity = stream_reservoir_capacity

    def _feat_fraction(self, d: int) -> float:
        return 1.0

    def _labeled_maybe_hashed(self, table: Table):
        """(x, y, w, hash_features): SparseVector feature columns are
        hash-bundled to ``numHashFeatures`` dense columns (0 = dense
        input) so one-hot/text pipelines feed trees without densifying
        to the full sparse dimensionality."""
        features_col = self.get(self.FEATURES_COL)
        sp = sparse_features(table, features_col)
        if sp is None:
            x, y, w = labeled_data(
                table, features_col, self.get(self.LABEL_COL),
                self.get(self.WEIGHT_COL),
            )
            return x, y, w, 0
        n_hash = self.get(self.NUM_HASH_FEATURES)
        x = hashed_feature_matrix(sp, n_hash).astype(np.float64)
        y = np.asarray(
            table.column(self.get(self.LABEL_COL)), np.float64
        ).reshape(-1)
        if y.shape[0] != x.shape[0]:
            raise ValueError(
                f"label column has {y.shape[0]} rows, features have "
                f"{x.shape[0]}"
            )
        weight_col = self.get(self.WEIGHT_COL)
        w = (
            np.asarray(table.column(weight_col), np.float64).reshape(-1)
            if weight_col is not None
            else np.ones(x.shape[0], np.float64)
        )
        return x, y, w, n_hash

    def _fit_forest(self, table: Table):
        x, y, w, hash_features = self._labeled_maybe_hashed(table)
        if self._LOGISTIC:
            # Validate on the FULL label column, before any holdout split
            # (an invalid label permuted into the holdout would silently
            # corrupt the early-stopping loss instead of raising).
            check_binary_labels(y, type(self).__name__)
        vf = self.get(self.VALIDATION_FRACTION)
        holdout = None
        if vf > 0:
            if not self._BOOSTING:
                raise ValueError(
                    "validationFraction applies to boosted estimators only "
                    "(bagged forests don't overfit with more trees)"
                )
            rng = np.random.default_rng(self.get_seed())
            perm = rng.permutation(x.shape[0])
            n_hold = max(1, int(round(vf * x.shape[0])))
            if n_hold >= x.shape[0]:
                raise ValueError("validationFraction leaves no training rows")
            hold_idx, train_idx = perm[:n_hold], perm[n_hold:]
            holdout = (x[hold_idx], y[hold_idx], w[hold_idx])
            x, y, w = x[train_idx], y[train_idx], w[train_idx]
        if self._LOGISTIC:
            pos = float(np.sum(w * y))
            neg = float(np.sum(w * (1 - y)))
            base = float(np.log(max(pos, 1e-12) / max(neg, 1e-12)))
        else:
            base = float(np.sum(w * y) / np.sum(w))
        max_bins = self.get(self.MAX_BINS)
        depth = self.get(self.MAX_DEPTH)
        edges = quantile_bin_edges(x, max_bins)
        binned = bin_features(x, edges)
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        b_pad, n_valid = pad_to_multiple(binned, p)
        y_pad, _ = pad_to_multiple(y.astype(np.float32), p)
        w_pad = np.zeros(b_pad.shape[0], np.float32)
        w_pad[:n_valid] = w[:n_valid].astype(np.float32)
        f = self._feat_fraction(x.shape[1])
        feat_subset = (
            0 if f >= 1.0 else max(1, int(round(f * x.shape[1])))
        )
        hist_layout = _hist_layout()
        builder = _forest_builder(
            mesh.mesh, DeviceMesh.DATA_AXIS, x.shape[1], max_bins, depth,
            self.get(self.NUM_TREES), self._LOGISTIC,
            boosting=self._BOOSTING, feat_subset=feat_subset,
            hist_layout=hist_layout,
        )
        hist_args = sharded_hist_args(b_pad, mesh, max_bins, hist_layout)
        f32 = lambda v: jnp.asarray(v, jnp.float32)
        feats, bins, gains, leaves = builder(
            mesh.shard_batch(b_pad), mesh.shard_batch(y_pad),
            mesh.shard_batch(w_pad),
            f32(base), f32(self.get(self.LEARNING_RATE)),
            f32(self.get(self.REG_LAMBDA)), f32(self.get(self.SUBSAMPLE)),
            jax.random.PRNGKey(self.get_seed()), *hist_args,
        )
        feats = np.asarray(feats)
        bins = np.asarray(bins)
        # Raw thresholds: split "bin <= b" ⟺ "x <= edges[f, b]" (the last
        # bin has threshold +inf: everything goes left).
        edges_inf = np.concatenate(
            [edges, np.full((edges.shape[0], 1), np.inf)], axis=1
        )
        thrs = edges_inf[feats, np.minimum(bins, edges_inf.shape[1] - 1)]
        gains = np.asarray(gains)
        leaves = np.asarray(leaves)
        if holdout is not None:
            feats, thrs, gains, leaves = self._truncate_to_best_prefix(
                holdout, feats, thrs, gains, leaves, base, depth,
            )
        return (feats, thrs, gains, leaves, base, depth, x.shape[1],
                hash_features)

    def _truncate_to_best_prefix(self, holdout, feats, thrs, gains, leaves,
                                 base, depth):
        """Early stopping: keep the tree prefix with the best holdout
        loss (cumulative per-tree margins on the held-out rows)."""
        hx, hy, hw = holdout
        lr = self.get(self.LEARNING_RATE)
        contribs = _walk_forest_per_tree(hx, feats, thrs, leaves, depth)
        margins = base + lr * np.cumsum(contribs, axis=0)   # [T, n_hold]
        if self._LOGISTIC:
            # NLL = log(1 + e^m) - y*m, computed stably.
            losses = (
                np.logaddexp(0.0, margins) - hy[None, :] * margins
            )
        else:
            losses = 0.5 * (margins - hy[None, :]) ** 2
        per_prefix = (losses * hw[None, :]).sum(axis=1)
        best = int(np.argmin(per_prefix)) + 1
        return feats[:best], thrs[:best], gains[:best], leaves[:best]

    def _fit_stream_forest(self, source):
        """Out-of-core forest build (see class docstring;
        ``ReplayOperator.java:62-250`` parity)."""
        from flinkml_tpu.iteration.datacache import DataCache, cache_stream
        from flinkml_tpu.models._gbt_stream import train_gbt_stream

        if not self._BOOSTING:
            raise ValueError(
                "streamed fits support boosted estimators only; random "
                "forests need the in-RAM path (independent bagged trees)"
            )
        if self.get(self.VALIDATION_FRACTION) > 0:
            raise ValueError(
                "validationFraction is not supported in streamed fits "
                "(a holdout needs a second materialized stream)"
            )
        if self.resume and not isinstance(source, DataCache):
            raise ValueError(
                "resume=True requires a durable DataCache input: a one-shot "
                "stream cannot be replayed from the start after a failure"
            )
        features_col = self.get(self.FEATURES_COL)
        label_col = self.get(self.LABEL_COL)
        weight_col = self.get(self.WEIGHT_COL)
        if isinstance(source, DataCache):
            cache = source
            columns = (features_col, label_col, weight_col)
        else:
            hash_seen = [None]  # None until first batch decides the mode

            def batches():
                for t in source:
                    # The hashing is stateless (pure function of column
                    # id), so per-batch bundling is consistent across the
                    # stream — but the mode must not flip mid-stream.
                    x, y, w, nh = self._labeled_maybe_hashed(t)
                    if hash_seen[0] is None:
                        hash_seen[0] = nh
                    elif hash_seen[0] != nh:
                        raise ValueError(
                            "stream mixes sparse and dense feature "
                            "batches; use one representation throughout"
                        )
                    yield {"x": x.astype(np.float32),
                           "y": y.astype(np.float32),
                           "w": w.astype(np.float32)}

            cache = cache_stream(
                batches(), self.cache_dir, self.cache_memory_budget_bytes
            )
            columns = ("x", "y", "w")
        label_check = (
            (lambda y: check_binary_labels(y, type(self).__name__))
            if self._LOGISTIC else None
        )
        max_bins = self.get(self.MAX_BINS)
        depth = self.get(self.MAX_DEPTH)
        feats, bins, gains, leaves, base, edges = train_gbt_stream(
            cache,
            mesh=self.mesh or DeviceMesh(),
            logistic=self._LOGISTIC,
            num_trees=self.get(self.NUM_TREES),
            depth=depth,
            max_bins=max_bins,
            learning_rate=self.get(self.LEARNING_RATE),
            reg_lambda=self.get(self.REG_LAMBDA),
            subsample=self.get(self.SUBSAMPLE),
            seed=self.get_seed(),
            columns=columns,
            label_check=label_check,
            reservoir_capacity=self.stream_reservoir_capacity,
            **self._checkpoint_kwargs(),
        )
        edges_inf = np.concatenate(
            [edges, np.full((edges.shape[0], 1), np.inf)], axis=1
        )
        thrs = edges_inf[feats, np.minimum(bins, edges_inf.shape[1] - 1)]
        hash_features = (
            0 if isinstance(source, DataCache) else (hash_seen[0] or 0)
        )
        return (feats, thrs, gains, leaves, base, depth, edges.shape[0],
                hash_features)

    _MODEL_CLS = None   # set per concrete estimator

    def fit(self, *inputs):
        (table,) = inputs
        if isinstance(table, Table):
            self._reject_in_ram_checkpointing(
                "the in-RAM fit builds the whole forest in one device "
                "program"
            )
            forest = self._fit_forest(table)
        else:
            forest = self._fit_stream_forest(table)
        (feats, thrs, gains, leaves, base, depth, n_features,
         hash_features) = forest
        model = self._MODEL_CLS()
        model.copy_params_from(self)
        # Bagged forests predict the MEAN of tree outputs (lr = 1/T);
        # boosted forests scale each tree by the learning rate.
        lr = (
            self.get(self.LEARNING_RATE) if self._BOOSTING
            else 1.0 / feats.shape[0]
        )
        model._set_forest(feats, thrs, leaves, base, depth, lr,
                          gains, n_features, hash_features)
        return model


class _GBTModelBase(_GBTParams, Model):
    _LOGISTIC = True

    def __init__(self):
        super().__init__()
        self._feats: Optional[np.ndarray] = None
        self._thrs: Optional[np.ndarray] = None
        self._leaves: Optional[np.ndarray] = None
        self._base: float = 0.0
        self._depth: int = 0
        self._lr: float = 0.1
        self._gains: Optional[np.ndarray] = None
        self._n_features: int = 0
        self._hash_features: int = 0

    def _set_forest(self, feats, thrs, leaves, base, depth, lr,
                    gains=None, n_features=None, hash_features=0):
        self._feats = np.asarray(feats, np.int64)
        self._thrs = np.asarray(thrs, np.float64)
        self._leaves = np.asarray(leaves, np.float64)
        self._base = float(base)
        self._depth = int(depth)
        self._lr = float(lr)
        self._gains = (
            np.asarray(gains, np.float64) if gains is not None
            else np.ones_like(self._feats, dtype=np.float64)
        )
        self._n_features = (
            int(n_features) if n_features is not None
            else int(self._feats.max()) + 1
        )
        # > 0 when the forest was trained on hash-bundled sparse input:
        # transform must apply the same stateless bundling.
        self._hash_features = int(hash_features)

    def set_model_data(self, *inputs: Table):
        (table,) = inputs
        self._set_forest(
            table.column("feat"), table.column("threshold"),
            table.column("leaf"),
            float(table.column("base")[0]),
            int(table.column("depth")[0]),
            float(table.column("learningRate")[0]),
            gains=table.column("gain") if "gain" in table else None,
            n_features=(
                int(table.column("numFeatures")[0])
                if "numFeatures" in table else None
            ),
            hash_features=(
                int(table.column("hashFeatures")[0])
                if "hashFeatures" in table else 0
            ),
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        t = self._feats.shape[0]
        return [Table({
            "feat": self._feats, "threshold": self._thrs,
            "gain": self._gains, "leaf": self._leaves,
            "base": np.full(t, self._base),
            "depth": np.full(t, self._depth),
            "learningRate": np.full(t, self._lr),
            "numFeatures": np.full(t, self._n_features),
            "hashFeatures": np.full(t, self._hash_features),
        })]

    def _require(self) -> None:
        if self._feats is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def feature_importances(self, num_features: Optional[int] = None) -> np.ndarray:
        """Gain importance (the XGBoost convention): each feature's share
        of the total split gain across the forest, normalized to sum
        to 1. Degenerate nodes (empty/pure — zero gain) contribute
        nothing, so deep complete trees don't inflate feature 0.
        Default length = the training feature count."""
        self._require()
        d = self._n_features if num_features is None else int(num_features)
        max_feat = int(self._feats.max())
        if d <= max_feat:
            raise ValueError(
                f"num_features={d} but the forest splits on feature "
                f"{max_feat}"
            )
        imp = np.bincount(
            self._feats.reshape(-1),
            weights=self._gains.reshape(-1),
            minlength=d,
        )
        total = imp.sum()
        return imp / total if total > 0 else imp

    def _margin(self, table: Table) -> np.ndarray:
        col = table.column(self.get(self.FEATURES_COL))
        if self._hash_features and col.dtype == object:
            # Hash-trained forest scoring sparse input: apply the same
            # stateless bundling the estimator used.
            x = hashed_feature_matrix(
                col, self._hash_features
            ).astype(np.float64)
        else:
            x = np.asarray(col, dtype=np.float64)
        if x.ndim != 2:
            raise ValueError(f"features must be [n, d], got {x.shape}")
        if self._feats.size and self._feats.max() >= x.shape[1]:
            raise ValueError(
                f"model uses feature {self._feats.max()}, features have "
                f"dim {x.shape[1]}"
            )
        return self._base + self._lr * _walk_forest(
            x, self._feats, self._thrs, self._leaves, self._depth
        )

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {
            "feat": self._feats, "threshold": self._thrs,
            "gain": self._gains, "leaf": self._leaves,
            "base": np.asarray(self._base),
            "depth": np.asarray(self._depth),
            "learningRate": np.asarray(self._lr),
            "numFeatures": np.asarray(self._n_features),
            "hashFeatures": np.asarray(self._hash_features),
        })

    @classmethod
    def load(cls, path: str):
        model, arrays, _ = cls._load_with_arrays(path)
        model._set_forest(
            arrays["feat"], arrays["threshold"], arrays["leaf"],
            float(arrays["base"]), int(arrays["depth"]),
            float(arrays["learningRate"]),
            gains=arrays.get("gain"),
            n_features=(
                int(arrays["numFeatures"]) if "numFeatures" in arrays else None
            ),
            hash_features=int(arrays.get("hashFeatures", 0)),
        )
        return model


class GBTClassifier(_GBTBase):
    """Binary gradient-boosted tree classifier (logistic loss)."""

    _LOGISTIC = True


class GBTClassifierModel(_GBTModelBase):
    _LOGISTIC = True

    RAW_PREDICTION_COL = HasRawPredictionCol.RAW_PREDICTION_COL

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        margin = self._margin(table)
        prob = 1.0 / (1.0 + np.exp(-margin))
        out = table.with_column(
            self.get(self.PREDICTION_COL), (margin >= 0).astype(np.float64)
        )
        out = out.with_column(
            self.get(self.RAW_PREDICTION_COL),
            np.stack([1.0 - prob, prob], axis=1),
        )
        return (out,)


class GBTRegressor(_GBTBase):
    """Gradient-boosted tree regressor (squared loss)."""

    _LOGISTIC = False


class GBTRegressorModel(_GBTModelBase):
    _LOGISTIC = False

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        return (
            table.with_column(self.get(self.PREDICTION_COL), self._margin(table)),
        )


class _RandomForestParams(_GBTParams):
    FEATURE_SUBSET_FRACTION = FloatParam(
        "featureSubsetFraction",
        "Fraction of features drawn per tree (None = sqrt(d)/d for the "
        "classifier, all features for the regressor — the sklearn "
        "conventions).",
        None, lambda v: v is None or 0 < v <= 1,
    )


class _RFBase(_RandomForestParams, _GBTBase):
    """Random forest = the same device forest builder in BAGGING mode:
    every tree fits the base-score residual independently on a row
    subsample and a per-tree feature subset; prediction averages the
    tree outputs (Newton-step leaves at the constant base score)."""

    _BOOSTING = False

    def _feat_fraction(self, d: int) -> float:
        f = self.get(self.FEATURE_SUBSET_FRACTION)
        return float(f) if f is not None else min(1.0, np.sqrt(d) / d)


class RandomForestClassifier(_RFBase):
    """Bagged binary classifier (defaults: subsample 1.0 — set e.g. 0.7
    for extra diversity; feature subset sqrt(d))."""

    _LOGISTIC = True


class RandomForestClassifierModel(_RandomForestParams, GBTClassifierModel):
    pass


class RandomForestRegressor(_RFBase):
    _LOGISTIC = False

    def _feat_fraction(self, d: int) -> float:
        # Regression forests default to ALL features per tree (the
        # sklearn convention; sqrt is the classification default).
        f = self.get(self.FEATURE_SUBSET_FRACTION)
        return float(f) if f is not None else 1.0


class RandomForestRegressorModel(_RandomForestParams, GBTRegressorModel):
    pass


# Estimator -> model wiring (assigned after all classes exist).
GBTClassifier._MODEL_CLS = GBTClassifierModel
GBTRegressor._MODEL_CLS = GBTRegressorModel
RandomForestClassifier._MODEL_CLS = RandomForestClassifierModel
RandomForestRegressor._MODEL_CLS = RandomForestRegressorModel
