"""OneVsRest — multiclass meta-classifier over any binary Estimator
(the Spark/Flink family member).

One binary model per class (label = 1 for the class, 0 for the rest);
prediction takes the argmax of the per-class positive scores (the
``rawPrediction`` probability column when the inner model emits one,
else the 0/1 prediction). The inner estimator is refit per class
sequentially — each fit IS the framework's device program, the same
stance as the tuning loops.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasRawPredictionCol,
)
from flinkml_tpu.io import read_write
from flinkml_tpu.table import Table


class _OneVsRestParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasRawPredictionCol
):
    pass


class OneVsRest(_OneVsRestParams, Estimator):
    def __init__(self, classifier: Optional[Estimator] = None):
        super().__init__()
        self.classifier = classifier

    def fit(self, *inputs: Table) -> "OneVsRestModel":
        (table,) = inputs
        if self.classifier is None:
            raise ValueError("OneVsRest requires a binary classifier")
        label_col = self.get(self.LABEL_COL)
        y = np.asarray(table.column(label_col), np.float64).reshape(-1)
        classes = np.unique(y)
        if len(classes) < 2:
            raise ValueError(f"need >= 2 classes, got {classes}")
        if not np.all(classes == np.round(classes)):
            raise ValueError(f"labels must be integral class ids, got {classes}")
        # The binary 0/1 view must land in the column the INNER
        # estimator reads (it may differ from OneVsRest's labelCol —
        # writing only our own column would silently train every
        # per-class model on the raw multiclass ids).
        inner_label_param = self.classifier.get_param("labelCol")
        inner_label_col = (
            self.classifier.get(inner_label_param)
            if inner_label_param is not None else label_col
        )
        models = []
        for c in classes:
            binary = table.with_column(
                inner_label_col, (y == c).astype(np.float64)
            )
            if inner_label_col != label_col:
                binary = binary.with_column(
                    label_col, (y == c).astype(np.float64)
                )
            models.append(self.classifier.fit(binary))
        out = OneVsRestModel()
        out.copy_params_from(self)
        out._set(classes, models)
        return out


class OneVsRestModel(_OneVsRestParams, Model):
    def __init__(self):
        super().__init__()
        self._classes: Optional[np.ndarray] = None
        self._models: Optional[List[Model]] = None

    def _set(self, classes: np.ndarray, models: List[Model]) -> None:
        self._classes = np.asarray(classes, np.float64)
        self._models = list(models)

    @property
    def classes(self) -> np.ndarray:
        self._require()
        return self._classes

    @property
    def models(self) -> List[Model]:
        self._require()
        return self._models

    def _require(self) -> None:
        if self._models is None:
            raise ValueError("Model data is not set; fit first or load")

    @staticmethod
    def _inner_col(model: Model, param_name: str, fallback: str) -> str:
        """The column the INNER model writes (its own configured param,
        not OneVsRest's — mirroring fit's labelCol resolution)."""
        p = model.get_param(param_name)
        return model.get(p) if p is not None else fallback

    def _class_score(self, model: Model, table: Table) -> np.ndarray:
        (scored,) = model.transform(table)
        raw_col = self._inner_col(
            model, "rawPredictionCol", self.get(self.RAW_PREDICTION_COL)
        )
        if raw_col in scored.column_names:
            raw = np.asarray(scored.column(raw_col), np.float64)
            if raw.ndim == 2 and raw.shape[1] == 2:
                return raw[:, 1]           # probability pair: P(class)
            if raw.ndim == 1:
                return raw                 # margin (LinearSVC's layout)
        pred_col = self._inner_col(
            model, "predictionCol", self.get(self.PREDICTION_COL)
        )
        return np.asarray(scored.column(pred_col), np.float64)

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        scores = np.stack(
            [self._class_score(m, table) for m in self._models], axis=1
        )
        pred = self._classes[np.argmax(scores, axis=1)]
        out = table.with_column(self.get(self.PREDICTION_COL), pred)
        out = out.with_column(self.get(self.RAW_PREDICTION_COL), scores)
        return (out,)

    # -- persistence: one subdirectory per class model ----------------------
    def save(self, path: str) -> None:
        self._require()
        read_write.save_metadata(self, path, extra={
            "classes": [float(c) for c in self._classes],
        })
        for i, m in enumerate(self._models):
            m.save(read_write.stage_path(path, i))

    @classmethod
    def load(cls, path: str) -> "OneVsRestModel":
        meta = read_write.load_metadata(
            path, expected_class_name=f"{cls.__module__}.{cls.__qualname__}"
        )
        model = cls()
        model.load_param_map_json(meta["paramMap"])
        classes = np.asarray(meta["classes"], np.float64)
        models = [
            read_write.load_stage(read_write.stage_path(path, i))
            for i in range(len(classes))
        ]
        model._set(classes, models)
        return model
