"""Feature statistics + selection: ChiSqTest,
VarianceThresholdSelector, UnivariateFeatureSelector.

Members of the wider Flink ML family (``ChiSqTest``,
``VarianceThresholdSelector``, ``UnivariateFeatureSelector`` in the
upstream operator set; the reference snapshot has none).

TPU stance: variance uses the same sharded shift-centered passes as the
scalers; chi-square contingency tables are weighted ``bincount``s over
(feature-category, label) pairs — a keyed aggregation that is one
``segment_sum`` per feature on device, but since the tables involved are
tiny (categories × classes) the host ``bincount`` is already exact and
instant, and the heavy part (the selector's transform) is a column
slice.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator, Estimator, Model
from flinkml_tpu.common_params import HasFeaturesCol, HasLabelCol, HasOutputCol
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.models.scalers import (
    _centered_sumsq_fn,
    _shard_with_mask,
    _sum_fn,
)
from flinkml_tpu.params import (
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


# -- chi-square ---------------------------------------------------------------

def _chi2_sf(x: float, df: int) -> float:
    """Survival function of the chi-square distribution via the
    regularized upper incomplete gamma Q(df/2, x/2) (no scipy needed)."""
    if x <= 0:
        return 1.0
    a, half_x = df / 2.0, x / 2.0
    # Series for P when x < a+1, continued fraction for Q otherwise
    # (Numerical Recipes 6.2).
    if half_x < a + 1.0:
        term = 1.0 / a
        total = term
        n = a
        for _ in range(500):
            n += 1.0
            term *= half_x / n
            total += term
            if abs(term) < abs(total) * 1e-15:
                break
        p = total * math.exp(-half_x + a * math.log(half_x) - math.lgamma(a))
        return max(0.0, min(1.0, 1.0 - p))
    b = half_x + 1.0 - a
    c = 1e300
    d = 1.0 / b
    h = d
    for i in range(1, 500):
        an = -i * (i - a)
        b += 2.0
        d = an * d + b
        d = 1.0 / d if abs(d) > 1e-300 else 1e300
        c = b + an / c
        if abs(c) < 1e-300:
            c = 1e-300
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < 1e-15:
            break
    q = h * math.exp(-half_x + a * math.log(half_x) - math.lgamma(a))
    return max(0.0, min(1.0, q))


def chi_square_test(x: np.ndarray, y: np.ndarray):
    """Pearson chi-square independence test of each categorical feature
    column against the label. Returns (statistics, p_values, dof) arrays.
    """
    x = np.asarray(x, dtype=np.float64)
    if x.ndim == 1:
        x = x[:, None]
    y = np.asarray(y).reshape(-1)
    if y.shape[0] != x.shape[0]:
        raise ValueError("label rows != feature rows")
    _, yi = np.unique(y, return_inverse=True)
    n_classes = yi.max() + 1
    stats, pvals, dofs = [], [], []
    for j in range(x.shape[1]):
        cats, ci = np.unique(x[:, j], return_inverse=True)
        k = len(cats)
        observed = np.bincount(
            ci * n_classes + yi, minlength=k * n_classes
        ).reshape(k, n_classes).astype(np.float64)
        row = observed.sum(axis=1, keepdims=True)
        col = observed.sum(axis=0, keepdims=True)
        expected = row @ col / observed.sum()
        with np.errstate(divide="ignore", invalid="ignore"):
            contrib = np.where(
                expected > 0, (observed - expected) ** 2 / expected, 0.0
            )
        stat = float(contrib.sum())
        dof = (k - 1) * (n_classes - 1)
        stats.append(stat)
        dofs.append(dof)
        pvals.append(_chi2_sf(stat, dof) if dof > 0 else 1.0)
    return np.asarray(stats), np.asarray(pvals), np.asarray(dofs)


class ChiSqTest(HasFeaturesCol, HasLabelCol, AlgoOperator):
    """Per-feature chi-square independence test against the label.

    Output table: one row per feature with ``featureIndex``, ``pValue``,
    ``statistic``, ``degreesOfFreedom`` (the upstream ChiSqTest layout).
    """

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        x = features_matrix(table, self.get(self.FEATURES_COL))
        y = table.column(self.get(self.LABEL_COL))
        stats, pvals, dofs = chi_square_test(x, y)
        return (
            Table({
                "featureIndex": np.arange(x.shape[1]),
                "pValue": pvals,
                "statistic": stats,
                "degreesOfFreedom": dofs,
            }),
        )


# -- f-test (one-way ANOVA) ---------------------------------------------------

def _f_sf(f: float, d1: int, d2: int) -> float:
    """Survival function of the F distribution via the regularized
    incomplete beta function (continued fraction, NR 6.4)."""
    if f <= 0:
        return 1.0
    x = d2 / (d2 + d1 * f)   # P(F > f) = I_x(d2/2, d1/2)
    a, b = d2 / 2.0, d1 / 2.0

    def betacf(a, b, x):
        qab, qap, qam = a + b, a + 1.0, a - 1.0
        c = 1.0
        d = 1.0 - qab * x / qap
        if abs(d) < 1e-300:
            d = 1e-300
        d = 1.0 / d
        h = d
        for m in range(1, 300):
            m2 = 2 * m
            aa = m * (b - m) * x / ((qam + m2) * (a + m2))
            d = 1.0 + aa * d
            if abs(d) < 1e-300:
                d = 1e-300
            c = 1.0 + aa / c
            if abs(c) < 1e-300:
                c = 1e-300
            d = 1.0 / d
            h *= d * c
            aa = -(a + m) * (qab + m) * x / ((a + m2) * (qap + m2))
            d = 1.0 + aa * d
            if abs(d) < 1e-300:
                d = 1e-300
            c = 1.0 + aa / c
            if abs(c) < 1e-300:
                c = 1e-300
            d = 1.0 / d
            delta = d * c
            h *= delta
            if abs(delta - 1.0) < 1e-14:
                break
        return h

    ln_front = (
        math.lgamma(a + b) - math.lgamma(a) - math.lgamma(b)
        + a * math.log(x) + b * math.log(1.0 - x)
    )
    if x < (a + 1.0) / (a + b + 2.0):
        val = math.exp(ln_front) * betacf(a, b, x) / a
    else:
        val = 1.0 - math.exp(
            math.lgamma(a + b) - math.lgamma(b) - math.lgamma(a)
            + b * math.log(1.0 - x) + a * math.log(x)
        ) * betacf(b, a, 1.0 - x) / b
    return max(0.0, min(1.0, val))


def f_classif_test(x: np.ndarray, y: np.ndarray):
    """One-way ANOVA F-test per feature (sklearn ``f_classif``)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y).reshape(-1)
    classes, yi = np.unique(y, return_inverse=True)
    k, n = len(classes), x.shape[0]
    if k < 2:
        raise ValueError("f-test requires at least 2 classes")
    overall = x.mean(axis=0)
    ss_between = np.zeros(x.shape[1])
    ss_within = np.zeros(x.shape[1])
    for c in range(k):
        xc = x[yi == c]
        mc = xc.mean(axis=0)
        ss_between += len(xc) * (mc - overall) ** 2
        ss_within += ((xc - mc) ** 2).sum(axis=0)
    d1, d2 = k - 1, n - k
    with np.errstate(divide="ignore", invalid="ignore"):
        f = (ss_between / d1) / (ss_within / d2)
    # ss_within == 0: a perfectly discriminative feature scores F = inf
    # (p = 0), matching sklearn; 0/0 (constant feature) scores 0.
    f = np.where(ss_within > 0, f,
                 np.where(ss_between > 0, np.inf, 0.0))
    p = np.asarray([
        0.0 if np.isinf(v) else _f_sf(float(v), d1, d2) for v in f
    ])
    return f, p


# -- selectors ----------------------------------------------------------------

class _SelectorModelBase(HasFeaturesCol, HasOutputCol, Model):
    """Shared transform/persistence for index-keeping selector models."""

    def __init__(self):
        super().__init__()
        self._indices: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table):
        (table,) = inputs
        self._indices = np.asarray(table.column("selected"), dtype=np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"selected": self._indices.copy()})]

    @property
    def selected_indices(self) -> np.ndarray:
        self._require()
        return self._indices

    def _require(self) -> None:
        if self._indices is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.FEATURES_COL))
        if self._indices.size and self._indices.max() >= x.shape[1]:
            raise ValueError(
                f"model selects index {self._indices.max()} but features "
                f"have dim {x.shape[1]}"
            )
        return (
            table.with_column(self.get(self.OUTPUT_COL), x[:, self._indices]),
        )

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"selected": self._indices})

    @classmethod
    def load(cls, path: str):
        model, arrays, _ = cls._load_with_arrays(path)
        model._indices = arrays["selected"].astype(np.int64)
        return model


class VarianceThresholdSelector(HasFeaturesCol, HasOutputCol, Estimator):
    """Keep features whose (population) variance exceeds
    ``varianceThreshold`` (default 0: drop constants). Variance comes
    from the same sharded two-pass mesh reduction as StandardScaler."""

    VARIANCE_THRESHOLD = FloatParam(
        "varianceThreshold", "Features with variance <= this are dropped.",
        0.0, ParamValidators.gt_eq(0.0),
    )

    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "VarianceThresholdSelectorModel":
        import jax.numpy as jnp

        (table,) = inputs
        x = features_matrix(table, self.get(self.FEATURES_COL))
        mesh = self.mesh or DeviceMesh()
        xd, wd = _shard_with_mask(x, mesh)
        shift = np.asarray(x[0], dtype=np.float32)
        s, n = _sum_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(xd, wd, jnp.asarray(shift))
        mean = shift.astype(np.float64) + np.asarray(s, np.float64) / float(n)
        sq = _centered_sumsq_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(
            xd, wd, jnp.asarray(mean, xd.dtype)
        )
        var = np.maximum(np.asarray(sq, np.float64) / float(n), 0.0)
        keep = np.nonzero(var > self.get(self.VARIANCE_THRESHOLD))[0]
        model = VarianceThresholdSelectorModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"selected": keep}))
        return model


class VarianceThresholdSelectorModel(_SelectorModelBase):
    VARIANCE_THRESHOLD = VarianceThresholdSelector.VARIANCE_THRESHOLD


class _UnivariateParams(HasFeaturesCol, HasLabelCol, HasOutputCol):
    SCORE_FUNCTION = StringParam(
        "scoreFunction", "Scoring test.", "chi2",
        ParamValidators.in_array(["chi2", "fClassif"]),
    )
    SELECTION_MODE = StringParam(
        "selectionMode", "How to pick features.", "numTopFeatures",
        ParamValidators.in_array(["numTopFeatures", "percentile", "fpr"]),
    )
    SELECTION_THRESHOLD = FloatParam(
        "selectionThreshold",
        "numTopFeatures: count; percentile: fraction in (0,1]; fpr: "
        "p-value bound.",
        None,
    )


class UnivariateFeatureSelector(_UnivariateParams, Estimator):
    """Select features by a univariate statistical test against the
    label — ``chi2`` (categorical features) or ``fClassif`` (ANOVA,
    continuous features)."""

    def fit(self, *inputs: Table) -> "UnivariateFeatureSelectorModel":
        (table,) = inputs
        x = features_matrix(table, self.get(self.FEATURES_COL))
        y = table.column(self.get(self.LABEL_COL))
        if self.get(self.SCORE_FUNCTION) == "chi2":
            stats, pvals, _ = chi_square_test(x, y)
        else:
            stats, pvals = f_classif_test(x, y)
        mode = self.get(self.SELECTION_MODE)
        threshold = self.get(self.SELECTION_THRESHOLD)
        if threshold is None:
            threshold = {"numTopFeatures": 50, "percentile": 0.1, "fpr": 0.05}[mode]
        if mode == "numTopFeatures":
            if threshold < 1:
                raise ValueError(
                    f"numTopFeatures needs selectionThreshold >= 1, got {threshold}"
                )
        elif not 0.0 < threshold <= 1.0:
            raise ValueError(
                f"{mode} needs selectionThreshold in (0, 1], got {threshold}"
            )
        d = x.shape[1]
        if mode == "numTopFeatures":
            k = min(int(threshold), d)
            keep = np.sort(np.argsort(pvals, kind="stable")[:k])
        elif mode == "percentile":
            k = max(1, int(d * float(threshold)))
            keep = np.sort(np.argsort(pvals, kind="stable")[:k])
        else:  # fpr
            keep = np.nonzero(pvals < float(threshold))[0]
        model = UnivariateFeatureSelectorModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"selected": keep}))
        return model


class UnivariateFeatureSelectorModel(_SelectorModelBase):
    SCORE_FUNCTION = UnivariateFeatureSelector.SCORE_FUNCTION
    SELECTION_MODE = UnivariateFeatureSelector.SELECTION_MODE
    SELECTION_THRESHOLD = UnivariateFeatureSelector.SELECTION_THRESHOLD


def f_regression_test(x: np.ndarray, y: np.ndarray):
    """Univariate linear F-test per feature against a CONTINUOUS label
    (sklearn ``f_regression``): F = r²/(1−r²)·(n−2), p from F(1, n−2)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64).reshape(-1)
    n = x.shape[0]
    if n < 3:
        raise ValueError("f_regression requires at least 3 rows")
    xc = x - x.mean(axis=0)
    yc = y - y.mean()
    denom = np.sqrt((xc * xc).sum(axis=0) * (yc * yc).sum())
    with np.errstate(divide="ignore", invalid="ignore"):
        r = np.where(denom > 0, xc.T @ yc / denom, 0.0)
    r2 = np.clip(r * r, 0.0, 1.0)
    d2 = n - 2
    with np.errstate(divide="ignore"):
        f = r2 / np.maximum(1.0 - r2, 0.0) * d2
    f = np.where(r2 >= 1.0, np.inf, f)
    p = np.asarray([
        0.0 if np.isinf(v) else _f_sf(float(v), 1, d2) for v in f
    ])
    return f, p


class _UnivariateTestBase(HasFeaturesCol, HasLabelCol, AlgoOperator):
    """Shared output layout for the per-feature test operators
    (featureIndex, pValue, statistic — the upstream ANOVATest/FValueTest
    shape)."""

    def _run(self, x, y):
        raise NotImplementedError

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        x = features_matrix(table, self.get(self.FEATURES_COL))
        y = table.column(self.get(self.LABEL_COL))
        stats, pvals = self._run(x, y)
        return (
            Table({
                "featureIndex": np.arange(x.shape[1]),
                "pValue": pvals,
                "statistic": stats,
            }),
        )


class ANOVATest(_UnivariateTestBase):
    """One-way ANOVA F-test of continuous features against a categorical
    label (upstream ``ANOVATest``)."""

    def _run(self, x, y):
        return f_classif_test(x, y)


class FValueTest(_UnivariateTestBase):
    """Univariate linear F-test of continuous features against a
    continuous label (upstream ``FValueTest``)."""

    def _run(self, x, y):
        return f_regression_test(x, y)
