"""OneHotEncoder — integer category columns → one-hot vectors.

Capability parity with
``flink-ml-lib/.../feature/onehotencoder/OneHotEncoder.java:51-147`` and
``OneHotEncoderModel.java:56-190``:

  - ``fit`` finds the max category index per input column (the reference's
    keyed mapPartition; here a column max).
  - Model data = (columnIndex, maxIndex) pairs; vector size =
    ``maxIndex + (0 if dropLast else 1)``; encoding value v yields a vector
    with 1.0 at v, and the LAST category (v == size) encodes as the empty
    vector when dropLast (``OneHotEncoderModel.java:160-183``).
  - ``handleInvalid`` supports "error" (reject v > max or non-integral —
    the reference's only supported mode, ``OneHotEncoderModel.java:71``),
    plus "keep" (clamp into an extra catch-all category) and "skip" is
    rejected explicitly.

Output layout is selected by ``outputFormat``:

  - ``"dense"`` (default): ``[n, size]`` one-hot matrices — batched,
    MXU-ready, the TPU-first layout for moderate cardinality.
  - ``"sparse"``: one ``SparseVector(size, [v], [1.0])`` per row, exactly
    the reference's encoding (``OneHotEncoderModel.java:160-183``) — the
    only viable layout at high cardinality (dense is O(n·cardinality)),
    and directly consumable by the sparse LogisticRegression path
    (nnz-bucketed ELL training).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import ColumnKernel, Estimator, Model
from flinkml_tpu.common_params import HasHandleInvalid, HasInputCols, HasOutputCols
from flinkml_tpu.linalg import SparseVector
from flinkml_tpu.params import BoolParam, ParamValidators, StringParam
from flinkml_tpu.table import Table


# Shared, frozen 1.0 buffer for the sparse rows (each SparseVector holds a
# read-only view; freezing removes any cross-row mutation hazard).
_ONE = np.ones(1)
_ONE.setflags(write=False)


class _OneHotEncoderParams(HasInputCols, HasOutputCols, HasHandleInvalid):
    DROP_LAST = BoolParam("dropLast", "Whether to drop the last category.", True)
    OUTPUT_FORMAT = StringParam(
        "outputFormat",
        "Encoding layout: 'dense' ([n, size] matrices) or 'sparse' "
        "(per-row SparseVector, the reference's encoding — required at "
        "high cardinality).",
        "dense",
        ParamValidators.in_array(["dense", "sparse"]),
    )


class OneHotEncoder(_OneHotEncoderParams, Estimator):
    def __init__(self):
        super().__init__()

    def fit(self, *inputs: Table) -> "OneHotEncoderModel":
        (table,) = inputs
        input_cols = self.get(_OneHotEncoderParams.INPUT_COLS)
        if not input_cols:
            raise ValueError("inputCols must be set")
        max_indices = []
        for col in input_cols:
            values = np.asarray(table.column(col), dtype=np.float64)
            _check_indexed(values, col)
            if (values < 0).any():
                raise ValueError(f"Column {col!r} contains negative category values")
            max_indices.append(int(values.max()))
        model = OneHotEncoderModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table(
                {
                    "columnIndex": np.arange(len(input_cols)),
                    "maxIndex": np.asarray(max_indices),
                }
            )
        )
        return model


class OneHotEncoderModel(_OneHotEncoderParams, Model):
    def __init__(self):
        super().__init__()
        self._max_indices: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "OneHotEncoderModel":
        (table,) = inputs
        order = np.argsort(np.asarray(table.column("columnIndex")))
        self._max_indices = np.asarray(table.column("maxIndex"))[order].astype(int)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [
            Table(
                {
                    "columnIndex": np.arange(len(self._max_indices)),
                    "maxIndex": self._max_indices.copy(),
                }
            )
        ]

    def _require_model(self) -> None:
        if self._max_indices is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        input_cols = self.get(_OneHotEncoderParams.INPUT_COLS)
        output_cols = self.get(_OneHotEncoderParams.OUTPUT_COLS)
        handle_invalid = self.get(_OneHotEncoderParams.HANDLE_INVALID)
        if handle_invalid == HasHandleInvalid.SKIP_INVALID:
            raise ValueError(
                "handleInvalid='skip' is not supported (parity with the "
                "reference, which supports 'error' only)"
            )
        if len(input_cols) != len(output_cols):
            raise ValueError(
                f"{len(input_cols)} input columns vs {len(output_cols)} output columns"
            )
        if len(input_cols) != len(self._max_indices):
            raise ValueError(
                f"model was fit on {len(self._max_indices)} columns, got {len(input_cols)}"
            )
        drop_last = self.get(_OneHotEncoderParams.DROP_LAST)
        sparse_format = (
            self.get(_OneHotEncoderParams.OUTPUT_FORMAT) == "sparse"
        )
        out = table
        for col, out_col, max_idx in zip(input_cols, output_cols, self._max_indices):
            values = np.asarray(table.column(col), dtype=np.float64)
            _check_indexed(values, col)
            idx = values.astype(int)
            # Valid categories are [0, maxIndex] regardless of dropLast;
            # with dropLast the LAST category (idx == maxIndex) encodes
            # as the all-zero vector (OneHotEncoderModel.java:176-183).
            max_valid = int(max_idx)
            base_size = max_valid + (0 if drop_last else 1)
            invalid = (idx < 0) | (idx > max_valid)
            keep = handle_invalid == HasHandleInvalid.KEEP_INVALID
            if keep:
                # Invalids go to an extra catch-all slot appended AFTER
                # base_size, keeping every valid encoding (including the
                # all-zero dropped-last one) unchanged and distinguishable.
                size = base_size + 1
                hot = np.where(invalid, base_size, idx)
                zero_row = ~invalid & drop_last & (idx == max_valid)
            else:
                if invalid.any():
                    raise ValueError(
                        f"Column {col!r} contains categories outside "
                        f"[0, {max_valid}]: {idx[invalid][:5]}"
                    )
                size = base_size
                hot = idx
                zero_row = drop_last & (idx == max_valid)
            if sparse_format:
                # Reference encoding (OneHotEncoderModel.java:160-183):
                # SparseVector(size, [v], [1.0]); the dropped-last value
                # encodes as the empty vector. O(n) memory regardless of
                # cardinality. Trusted construction (single known-valid
                # index per row) — full validation would dominate at
                # Criteo-scale row counts.
                empty_i = np.zeros(0, dtype=np.int64)
                empty_v = np.zeros(0)
                hot64 = hot.astype(np.int64)
                hot64.setflags(write=False)
                onehot = np.empty(len(idx), dtype=object)
                for i in range(len(idx)):
                    onehot[i] = (
                        SparseVector._from_sorted(size, empty_i, empty_v)
                        if zero_row[i]
                        else SparseVector._from_sorted(
                            size, hot64[i : i + 1], _ONE
                        )
                    )
            else:
                onehot = np.zeros((len(idx), size), dtype=np.float64)
                rows = np.nonzero(~zero_row)[0]
                onehot[rows, hot[rows]] = 1.0
            out = out.with_column(out_col, onehot)
        return (out,)

    def transform_kernel(self):
        """Fusable only for ``outputFormat='dense'`` with
        ``handleInvalid='keep'``: sparse output is a per-row object column
        (no device representation), and ``error`` raises on out-of-range /
        non-integral values, which a pure device function cannot. In keep
        mode invalids clamp to the catch-all slot exactly as the host path
        does; note the host path's non-integral-value check does not run
        on device (non-integral values truncate toward zero, the same cast
        the host applies after its check)."""
        if self._max_indices is None:
            return None
        if self.get(_OneHotEncoderParams.OUTPUT_FORMAT) != "dense":
            return None
        if self.get(_OneHotEncoderParams.HANDLE_INVALID) != HasHandleInvalid.KEEP_INVALID:
            return None
        input_cols = self.get(_OneHotEncoderParams.INPUT_COLS)
        output_cols = self.get(_OneHotEncoderParams.OUTPUT_COLS)
        if (
            not input_cols
            or not output_cols
            or len(input_cols) != len(output_cols)
            or len(input_cols) != len(self._max_indices)
        ):
            return None
        input_cols = tuple(input_cols)
        output_cols = tuple(output_cols)
        drop_last = self.get(_OneHotEncoderParams.DROP_LAST)
        max_idx = tuple(int(m) for m in self._max_indices)

        def fn(cols, consts, valid):
            import jax
            import jax.numpy as jnp

            outs = {}
            for col, out_col, mv in zip(input_cols, output_cols, max_idx):
                idx = cols[col].astype(jnp.int32)
                base_size = mv + (0 if drop_last else 1)
                invalid = (idx < 0) | (idx > mv)
                # keep semantics: catch-all slot appended after base_size.
                hot = jnp.where(invalid, base_size, idx)
                oh = jax.nn.one_hot(hot, base_size + 1, dtype=jnp.float64)
                if drop_last:
                    zero_row = (~invalid) & (idx == mv)
                    oh = jnp.where(zero_row[:, None], 0.0, oh)
                outs[out_col] = oh
            return outs

        return ColumnKernel(
            input_cols=input_cols, output_cols=output_cols, fn=fn,
            fingerprint=(
                "OneHotEncoderModel", input_cols, output_cols, drop_last,
                max_idx,
            ),
        )

    def save(self, path: str) -> None:
        self._require_model()
        self._save_with_arrays(path, {"maxIndex": self._max_indices})

    @classmethod
    def load(cls, path: str) -> "OneHotEncoderModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._max_indices = arrays["maxIndex"].astype(int)
        return model


def _check_indexed(values: np.ndarray, col: str) -> None:
    if not np.all(values == np.round(values)):
        raise ValueError(
            f"Value in column {col!r} cannot be parsed as indexed integer."
        )
