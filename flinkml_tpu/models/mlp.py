"""MLPClassifier — multilayer perceptron (the Spark/Flink
``MultilayerPerceptronClassifier`` family member), TPU-native.

The natural fit for this framework's design stance: the WHOLE training
run is one device program — a ``lax.while_loop`` of Adam steps (with
tol-based early stopping) over a data-sharded mesh, gradients
``psum``-combined per step, every layer a batched MXU matmul. (The upstream operator trains with L-BFGS on the
JVM; Adam-on-device is the TPU-idiomatic equivalent and is documented
as such rather than imitated.)

Architecture: ``layers = [d_in, h_1, ..., h_k, n_classes]``, tanh hidden
activations (the upstream convention), softmax output, cross-entropy
loss, He-scaled Gaussian init. Labels are class ids ``0..n_classes-1``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.models._adam import make_adam_trainer
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasSeed,
    HasTol,
)
from flinkml_tpu.models._data import features_matrix, labeled_data
from flinkml_tpu.params import IntArrayParam, ParamValidators
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _MLPParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol,
    HasMaxIter, HasLearningRate, HasGlobalBatchSize, HasTol, HasSeed,
):
    LAYERS = IntArrayParam(
        "layers",
        "Sizes of every layer, input first, output last.",
        None, ParamValidators.non_empty_array(),
    )


class _MLPClassifierParams(_MLPParams, HasRawPredictionCol):
    """Only the classifier emits a rawPrediction column; the regressor
    must not carry the dead param."""


def _init_params(layers: List[int], key) -> List:
    params = []
    for i in range(len(layers) - 1):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / layers[i])
        params.append((
            jax.random.normal(sub, (layers[i], layers[i + 1]),
                              jnp.float32) * scale,
            jnp.zeros(layers[i + 1], jnp.float32),
        ))
    return params


def _forward(params, x):
    """params: flat tuple (w0, b0, w1, b1, ...); returns logits."""
    n_layers = len(params) // 2
    h = x
    for i in range(n_layers - 1):
        h = jnp.tanh(h @ params[2 * i] + params[2 * i + 1])
    return h @ params[-2] + params[-1]


def _mlp_loss_builder():
    def local_loss(params, xb, yb, wb):
        logits = _forward(params, xb)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, yb[:, None], axis=1)[:, 0]
        return jnp.sum(nll * wb)

    return local_loss


def _mlp_squared_loss_builder():
    def local_loss(params, xb, yb, wb):
        pred = _forward(params, xb)[:, 0]
        err = pred - yb
        return 0.5 * jnp.sum(err * err * wb)

    return local_loss


class _MLPBase(StreamingEstimatorMixin, _MLPParams, Estimator):
    """Shared fit scaffold: the subclasses differ only in label
    preparation/validation and the loss builder (same pairing pattern as
    ``fm._FMBase``).

    ``fit`` also accepts an iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the
    out-of-core path (reference replay parity:
    ``ReplayOperator.java:62-250``): the stream is cached once, then
    each epoch replays the cache chunk-by-chunk, running Adam minibatch
    steps within the resident chunk with the optimizer state carried
    across chunks as one continuous run. ``checkpoint_manager`` +
    ``checkpoint_interval`` snapshot the full Adam state every N epochs;
    ``resume=True`` (durable DataCache input required) continues
    bit-exactly.
    """

    _MODEL_CLS = None
    _LOSS_BUILDER = None


    def _prepare_labels(self, y: np.ndarray, layers) -> np.ndarray:
        raise NotImplementedError

    def _check_layers(self):
        layers = self.get(self.LAYERS)
        if layers is None or len(layers) < 2:
            raise ValueError("layers must list at least [inputDim, outputDim]")
        return layers

    def fit(self, *inputs):
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        self._reject_in_ram_checkpointing()
        layers = self._check_layers()
        x, y, w = labeled_data(
            table, self.get(self.FEATURES_COL), self.get(self.LABEL_COL)
        )
        if x.shape[1] != layers[0]:
            raise ValueError(
                f"layers[0]={layers[0]} != feature dim {x.shape[1]}"
            )
        y_dev = self._prepare_labels(y, layers)
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        x_pad, n_valid = pad_to_multiple(x.astype(np.float32), p)
        y_pad, _ = pad_to_multiple(y_dev, p)
        w_pad = np.zeros(x_pad.shape[0], np.float32)
        w_pad[:n_valid] = w[:n_valid].astype(np.float32)
        local_bs = max(1, self.get(self.GLOBAL_BATCH_SIZE) // p)
        trainer = make_adam_trainer(
            mesh.mesh, DeviceMesh.DATA_AXIS, local_bs,
            type(self)._LOSS_BUILDER, 2 * (len(layers) - 1),
        )
        key = jax.random.PRNGKey(self.get_seed())
        init = _init_params(list(layers), key)
        flat0 = tuple(t for wb in init for t in wb)
        f32 = lambda v: jnp.asarray(v, jnp.float32)
        flat, _steps, _loss = trainer(
            mesh.shard_batch(x_pad), mesh.shard_batch(y_pad),
            mesh.shard_batch(w_pad), flat0,
            f32(self.get(self.LEARNING_RATE)),
            jnp.asarray(self.get(self.MAX_ITER), jnp.int32),
            f32(self.get(self.TOL)),
            jax.random.fold_in(key, 123),
        )
        model = self._MODEL_CLS()
        model.copy_params_from(self)
        model._weights = [np.asarray(t, np.float64) for t in flat]
        return model

    def _fit_stream(self, source):
        """Out-of-core Adam via the shared runner
        (:func:`flinkml_tpu.models._adam.run_streamed_adam`): the
        optimizer state rides across the replayed chunks as one
        continuous run, snapshotted at epoch boundaries."""
        from flinkml_tpu.models._adam import run_streamed_adam

        layers = self._check_layers()
        features_col = self.get(self.FEATURES_COL)
        label_col = self.get(self.LABEL_COL)
        mesh = self.mesh or DeviceMesh()

        def ingest(t):
            x, y, w = labeled_data(t, features_col, label_col)
            if x.shape[1] != layers[0]:
                raise ValueError(
                    f"layers[0]={layers[0]} != feature dim {x.shape[1]}"
                )
            return {
                "x": x.astype(np.float32),
                "y": self._prepare_labels(y, layers),
                "w": w.astype(np.float32),
            }

        def params0_fn(d):
            if d != layers[0]:
                raise ValueError(
                    f"layers[0]={layers[0]} != feature dim {d}"
                )
            init = _init_params(
                list(layers), jax.random.PRNGKey(self.get_seed())
            )
            return tuple(t for wb in init for t in wb)

        flat = run_streamed_adam(
            source,
            what="MLP streamed fit",
            mesh=mesh,
            cache_dir=self.cache_dir,
            cache_memory_budget_bytes=self.cache_memory_budget_bytes,
            ingest=ingest,
            place_y=lambda y: self._prepare_labels(y, layers),
            loss_builder=type(self)._LOSS_BUILDER,
            n_params=2 * (len(layers) - 1),
            params0_fn=params0_fn,
            lr=self.get(self.LEARNING_RATE),
            global_bs=self.get(self.GLOBAL_BATCH_SIZE),
            max_iter=self.get(self.MAX_ITER),
            tol=self.get(self.TOL),
            seed=self.get_seed(),
            **self._checkpoint_kwargs(),
        )
        model = self._MODEL_CLS()
        model.copy_params_from(self)
        model._weights = [np.asarray(t, np.float64) for t in flat]
        return model


class MLPClassifier(_MLPClassifierParams, _MLPBase):
    def _prepare_labels(self, y: np.ndarray, layers) -> np.ndarray:
        n_classes = layers[-1]
        yi = y.astype(np.int64)
        if not np.all(y == yi) or yi.min() < 0 or yi.max() >= n_classes:
            raise ValueError(
                f"labels must be class ids in [0, {n_classes}), got "
                f"[{y.min()}, {y.max()}]"
            )
        return yi.astype(np.int32)


class _MLPModelBase(_MLPParams, Model):
    """Weight storage, forward pass, and persistence shared by the
    sibling classifier/regressor models."""

    def __init__(self):
        super().__init__()
        self._weights: Optional[List[np.ndarray]] = None

    def set_model_data(self, *inputs: Table) -> "MLPClassifierModel":
        (table,) = inputs
        n = int(np.asarray(table.column("numArrays"))[0])
        self._weights = [
            np.asarray(table.column(f"arr{i}"), np.float64)[0]
            for i in range(n)
        ]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        cols = {"numArrays": np.asarray([len(self._weights)])}
        for i, a in enumerate(self._weights):
            cols[f"arr{i}"] = a[None, ...]
        return [Table(cols)]

    def _require(self) -> None:
        if self._weights is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def _logits(self, table: Table) -> np.ndarray:
        x = features_matrix(table, self.get(self.FEATURES_COL))
        n_layers = len(self._weights) // 2
        h = x
        for i in range(n_layers - 1):
            h = np.tanh(h @ self._weights[2 * i] + self._weights[2 * i + 1])
        return h @ self._weights[-2] + self._weights[-1]

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path,
            {f"arr{i}": a for i, a in enumerate(self._weights)},
            extra={"numArrays": len(self._weights)},
        )

    @classmethod
    def load(cls, path: str):
        model, arrays, meta = cls._load_with_arrays(path)
        n = int(meta["numArrays"])
        model._weights = [arrays[f"arr{i}"] for i in range(n)]
        return model


class MLPClassifierModel(_MLPClassifierParams, _MLPModelBase):
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        logits = self._logits(table)
        shifted = logits - logits.max(axis=1, keepdims=True)
        probs = np.exp(shifted)
        probs /= probs.sum(axis=1, keepdims=True)
        out = table.with_column(
            self.get(self.PREDICTION_COL),
            np.argmax(logits, axis=1).astype(np.float64),
        )
        out = out.with_column(self.get(self.RAW_PREDICTION_COL), probs)
        return (out,)


class MLPRegressor(_MLPBase):
    """Multilayer perceptron regressor: ``layers = [d_in, h..., 1]``,
    tanh hidden activations, linear output, squared loss — the same
    whole-run Adam device trainer as the classifier."""

    def _prepare_labels(self, y: np.ndarray, layers) -> np.ndarray:
        if layers[-1] != 1:
            raise ValueError(
                "layers must be [inputDim, hidden..., 1] for regression"
            )
        return y.astype(np.float32)


class MLPRegressorModel(_MLPModelBase):
    """Sibling of the classifier model (not a subclass of it): the
    transform emits the linear output directly."""

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        pred = self._logits(table)[:, 0]
        return (
            table.with_column(self.get(self.PREDICTION_COL), pred),
        )


MLPClassifier._MODEL_CLS = MLPClassifierModel
MLPClassifier._LOSS_BUILDER = staticmethod(_mlp_loss_builder)
MLPRegressor._MODEL_CLS = MLPRegressorModel
MLPRegressor._LOSS_BUILDER = staticmethod(_mlp_squared_loss_builder)
