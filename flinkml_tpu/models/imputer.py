"""Imputer — replace missing values in scalar columns with a fitted
surrogate (mean / median / most frequent).

Beyond the reference snapshot but a standard member of the wider Flink ML
operator family. Missing = ``missingValue`` (default NaN; NaN always
counts as missing). Surrogates are per-column host statistics: the
columns are host-resident and the statistic is one vectorized pass, so
there is no device work to ship. ``mostFrequent`` ties break by smallest
value (deterministic).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import HasInputCols, HasOutputCols
from flinkml_tpu.params import FloatParam, ParamValidators, StringParam
from flinkml_tpu.table import Table

MEAN = "mean"
MEDIAN = "median"
MOST_FREQUENT = "mostFrequent"


class _ImputerParams(HasInputCols, HasOutputCols):
    STRATEGY = StringParam(
        "strategy", "Imputation strategy.", MEAN,
        ParamValidators.in_array([MEAN, MEDIAN, MOST_FREQUENT]),
    )
    MISSING_VALUE = FloatParam(
        "missingValue",
        "The placeholder that marks a value as missing (NaN always does).",
        float("nan"),
    )


def _missing_mask(values: np.ndarray, missing_value: float) -> np.ndarray:
    mask = np.isnan(values)
    if not np.isnan(missing_value):
        mask |= values == missing_value
    return mask


class Imputer(_ImputerParams, Estimator):
    def fit(self, *inputs: Table) -> "ImputerModel":
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        if not input_cols:
            raise ValueError("inputCols must be set")
        strategy = self.get(self.STRATEGY)
        missing_value = self.get(self.MISSING_VALUE)
        surrogates = []
        for col in input_cols:
            values = np.asarray(table.column(col), dtype=np.float64)
            if values.ndim != 1:
                raise ValueError(
                    f"Column {col!r} must be scalar, has shape {values.shape}"
                )
            present = values[~_missing_mask(values, missing_value)]
            if present.size == 0:
                raise ValueError(
                    f"Column {col!r} has no non-missing values to fit from"
                )
            if strategy == MEAN:
                surrogates.append(float(present.mean()))
            elif strategy == MEDIAN:
                surrogates.append(float(np.median(present)))
            else:  # mostFrequent; np.unique is ascending -> smallest wins ties
                uniq, counts = np.unique(present, return_counts=True)
                surrogates.append(float(uniq[np.argmax(counts)]))
        model = ImputerModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({"surrogate": np.asarray(surrogates)[None, :]})
        )
        return model


class ImputerModel(_ImputerParams, Model):
    def __init__(self):
        super().__init__()
        self._surrogates: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "ImputerModel":
        (table,) = inputs
        self._surrogates = np.asarray(table.column("surrogate"), np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"surrogate": self._surrogates[None, :]})]

    def _require(self) -> None:
        if self._surrogates is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        input_cols = self.get(self.INPUT_COLS)
        output_cols = self.get(self.OUTPUT_COLS)
        if len(input_cols) != len(output_cols):
            raise ValueError(
                f"{len(input_cols)} input columns vs {len(output_cols)} output columns"
            )
        if len(input_cols) != len(self._surrogates):
            raise ValueError(
                f"model was fit on {len(self._surrogates)} columns, "
                f"got {len(input_cols)}"
            )
        missing_value = self.get(self.MISSING_VALUE)
        out = table
        for col, out_col, surrogate in zip(
            input_cols, output_cols, self._surrogates
        ):
            values = np.asarray(table.column(col), dtype=np.float64)
            mask = _missing_mask(values, missing_value)
            out = out.with_column(out_col, np.where(mask, surrogate, values))
        return (out,)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"surrogate": self._surrogates})

    @classmethod
    def load(cls, path: str) -> "ImputerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._surrogates = arrays["surrogate"]
        return model
