"""Imputer — replace missing values in scalar or vector columns with
fitted surrogates (mean / median / most frequent, per dimension).

Beyond the reference snapshot but a standard member of the wider Flink ML
operator family. Missing = ``missingValue`` (default NaN; NaN always
counts as missing). Surrogates are per-column host statistics: the
columns are host-resident and the statistic is one vectorized pass, so
there is no device work to ship. ``mostFrequent`` ties break by smallest
value (deterministic).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import HasInputCols, HasOutputCols
from flinkml_tpu.params import FloatParam, ParamValidators, StringParam
from flinkml_tpu.table import Table

MEAN = "mean"
MEDIAN = "median"
MOST_FREQUENT = "mostFrequent"


class _ImputerParams(HasInputCols, HasOutputCols):
    STRATEGY = StringParam(
        "strategy", "Imputation strategy.", MEAN,
        ParamValidators.in_array([MEAN, MEDIAN, MOST_FREQUENT]),
    )
    MISSING_VALUE = FloatParam(
        "missingValue",
        "The placeholder that marks a value as missing (NaN always does).",
        float("nan"),
    )


def _missing_mask(values: np.ndarray, missing_value: float) -> np.ndarray:
    mask = np.isnan(values)
    if not np.isnan(missing_value):
        mask |= values == missing_value
    return mask


def _column_surrogates(values: np.ndarray, col: str, strategy: str,
                       missing_value: float) -> list:
    """Per-dimension surrogates for a scalar ([n]) or vector ([n, d])
    column."""
    mat = values if values.ndim == 2 else values[:, None]
    out = []
    for j in range(mat.shape[1]):
        v = mat[:, j]
        present = v[~_missing_mask(v, missing_value)]
        if present.size == 0:
            raise ValueError(
                f"Column {col!r} (dim {j}) has no non-missing values "
                "to fit from"
            )
        if strategy == MEAN:
            out.append(float(present.mean()))
        elif strategy == MEDIAN:
            out.append(float(np.median(present)))
        else:  # mostFrequent; np.unique is ascending -> smallest wins ties
            uniq, counts = np.unique(present, return_counts=True)
            out.append(float(uniq[np.argmax(counts)]))
    return out


class Imputer(_ImputerParams, Estimator):
    def fit(self, *inputs: Table) -> "ImputerModel":
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        if not input_cols:
            raise ValueError("inputCols must be set")
        strategy = self.get(self.STRATEGY)
        missing_value = self.get(self.MISSING_VALUE)
        surrogates = []       # flat; per-column widths recorded alongside
        widths = []
        for col in input_cols:
            values = np.asarray(table.column(col), dtype=np.float64)
            if values.ndim > 2 or (values.ndim == 2 and values.shape[1] == 0):
                raise ValueError(
                    f"Column {col!r} must be scalar or [n, d] with d >= 1, "
                    f"has shape {values.shape}"
                )
            subs = _column_surrogates(values, col, strategy, missing_value)
            widths.append(0 if values.ndim == 1 else len(subs))
            surrogates.extend(subs)
        model = ImputerModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({
                "surrogate": np.asarray(surrogates)[None, :],
                "width": np.asarray(widths)[None, :],
            })
        )
        return model


class ImputerModel(_ImputerParams, Model):
    def __init__(self):
        super().__init__()
        self._surrogates: Optional[np.ndarray] = None
        # Per input column: 0 = scalar, d = vector width (flat offsets
        # into _surrogates).
        self._widths: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "ImputerModel":
        (table,) = inputs
        self._surrogates = np.asarray(table.column("surrogate"), np.float64)[0]
        if "width" in table:
            self._widths = np.asarray(table.column("width"), np.int64)[0]
        else:   # pre-vector-support model data: all scalar columns
            self._widths = np.zeros(len(self._surrogates), np.int64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "surrogate": self._surrogates[None, :],
            "width": self._widths[None, :],
        })]

    def _require(self) -> None:
        if self._surrogates is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        input_cols = self.get(self.INPUT_COLS)
        output_cols = self.get(self.OUTPUT_COLS)
        if len(input_cols) != len(output_cols):
            raise ValueError(
                f"{len(input_cols)} input columns vs {len(output_cols)} output columns"
            )
        if len(input_cols) != len(self._widths):
            raise ValueError(
                f"model was fit on {len(self._widths)} columns, "
                f"got {len(input_cols)}"
            )
        missing_value = self.get(self.MISSING_VALUE)
        out = table
        offset = 0
        for col, out_col, width in zip(
            input_cols, output_cols, self._widths
        ):
            values = np.asarray(table.column(col), dtype=np.float64)
            if width == 0:
                if values.ndim != 1:
                    raise ValueError(
                        f"Column {col!r} was fit as scalar, got {values.shape}"
                    )
                surrogate = self._surrogates[offset]
                offset += 1
                mask = _missing_mask(values, missing_value)
                filled = np.where(mask, surrogate, values)
            else:
                if values.ndim != 2 or values.shape[1] != width:
                    raise ValueError(
                        f"Column {col!r} was fit as [n, {width}], got "
                        f"{values.shape}"
                    )
                surrogate = self._surrogates[offset: offset + width]
                offset += width
                mask = _missing_mask(values, missing_value)
                filled = np.where(mask, surrogate[None, :], values)
            out = out.with_column(out_col, filled)
        return (out,)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"surrogate": self._surrogates, "width": self._widths}
        )

    @classmethod
    def load(cls, path: str) -> "ImputerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._surrogates = arrays["surrogate"]
        model._widths = (
            arrays["width"].astype(np.int64) if "width" in arrays
            else np.zeros(len(arrays["surrogate"]), np.int64)
        )
        return model
