"""Knn — brute-force k-nearest-neighbors classifier.

Capability parity with ``flink-ml-lib/.../classification/knn/Knn.java:52-140``
and ``KnnModel.java:51-197``, rebuilt TPU-first:

  - ``fit`` materializes the train set as the model (the reference packs
    per-partition column-major ``DenseMatrix`` blocks + norms,
    ``Knn.java:87-140``); here the model is simply the [n, d] matrix +
    labels.
  - Prediction: the reference broadcasts the whole model and, per query row,
    runs gemv-style distances + a top-k priority queue
    (``KnnModel.java:72-197``). Here the query batch hits the model in ONE
    [nq, d] @ [d, n] MXU matmul via the ‖x‖²-2xy+‖y‖² expansion, then
    a bucketed top-k and a one-hot vote — no per-row loop anywhere. The
    top-k lowers through the kernel-backend gate
    (:mod:`flinkml_tpu.kernels`): ``lax.top_k`` by default, the Pallas
    masked-pass kernel when the gate selects it; the resolved backend is
    a jit STATIC argument, so a gate flip re-keys the program.
  - Queries are processed in fixed-size chunks so the [chunk, n] distance
    matrix stays HBM-resident at any train-set size.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasK,
    HasLabelCol,
    HasPredictionCol,
)
from flinkml_tpu.models._data import features_matrix, labeled_data
from flinkml_tpu.ops import blas
from flinkml_tpu.table import Table


class _KnnParams(HasFeaturesCol, HasLabelCol, HasPredictionCol, HasK):
    pass


class Knn(_KnnParams, Estimator):
    def __init__(self):
        super().__init__()

    def fit(self, *inputs: Table) -> "KnnModel":
        (table,) = inputs
        x, y, _ = labeled_data(
            table,
            self.get(_KnnParams.FEATURES_COL),
            self.get(_KnnParams.LABEL_COL),
        )
        model = KnnModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"features": x, "labels": y}))
        return model


class KnnModel(_KnnParams, Model):
    CHUNK = 4096  # query rows per distance-matrix block

    def __init__(self):
        super().__init__()
        self._features: Optional[np.ndarray] = None
        self._labels: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "KnnModel":
        (table,) = inputs
        self._features = np.asarray(table.column("features"), dtype=np.float64)
        self._labels = np.asarray(table.column("labels"), dtype=np.float64)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"features": self._features, "labels": self._labels})]

    def _require_model(self) -> None:
        if self._features is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        k = self.get(_KnnParams.K)
        n_train = self._features.shape[0]
        if n_train == 0:
            raise ValueError("Knn model has no training points")
        # Reference parity: KnnModel's top-k priority queue simply holds
        # all n points when k > n — vote among everything, don't raise.
        k = min(k, n_train)
        x = features_matrix(table, self.get(_KnnParams.FEATURES_COL))

        # Map labels to dense class ids for the one-hot vote.
        classes, label_ids = np.unique(self._labels, return_inverse=True)
        xt = jnp.asarray(self._features)
        ids = jnp.asarray(label_ids, dtype=jnp.int32)

        from flinkml_tpu import kernels

        topk_backend = kernels.topk_backend()
        preds = []
        for start in range(0, x.shape[0], self.CHUNK):
            chunk = jnp.asarray(x[start : start + self.CHUNK])
            votes = _knn_vote(chunk, xt, ids, k, len(classes),
                              topk_backend)
            preds.append(np.asarray(votes))
        pred_ids = np.concatenate(preds) if preds else np.zeros(0, dtype=np.int32)
        pred = classes[pred_ids]
        return (table.with_column(self.get(_KnnParams.PREDICTION_COL), pred),)

    def save(self, path: str) -> None:
        self._require_model()
        self._save_with_arrays(
            path, {"features": self._features, "labels": self._labels}
        )

    @classmethod
    def load(cls, path: str) -> "KnnModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._features = arrays["features"]
        model._labels = arrays["labels"]
        return model


@functools.partial(
    jax.jit, static_argnames=("k", "num_classes", "topk_backend")
)
def _knn_vote(queries, train_x, train_label_ids, k: int, num_classes: int,
              topk_backend: str = "xla"):
    """Top-k nearest by squared distance, then majority vote.

    Ties break toward the smaller class id (deterministic), matching the
    reference's priority-queue + map iteration determinism in spirit.
    ``topk_backend`` is static (part of the jit key — the lru-keyed gate
    idiom); both backends break distance ties toward the lower train
    index, so the vote is backend-invariant.
    """
    from flinkml_tpu import kernels

    d2 = blas.squared_distances(queries, train_x)
    _, idx = kernels.top_k(-d2, k, backend=topk_backend)
    votes = train_label_ids[idx]  # [nq, k]
    counts = jnp.sum(jax.nn.one_hot(votes, num_classes), axis=1)
    return jnp.argmax(counts, axis=-1).astype(jnp.int32)
