"""Column-extraction helpers shared by algorithms.

The analog of the reference's row→POJO maps (e.g.
``LogisticRegression.java:111-130`` mapping rows to
``LabeledPointWithWeight``): tables are already columnar, so "extraction" is
densifying a features column to ``[n, d]`` and reading label/weight columns.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from flinkml_tpu.linalg import SparseVector, Vector, stack_vectors
from flinkml_tpu.table import Table


def features_matrix(
    table: Table, features_col: str, dtype=np.float64
) -> np.ndarray:
    """Densify a features column to float [n, d].

    Accepts 2-D numeric columns (native layout) or object columns of
    ``Vector`` / array-likes (row-wise user data).

    ``dtype=None`` preserves a floating input dtype (float32 stays
    float32 — elementwise stages then move half the bytes on the CPU
    fallback path; flagged as FML106 by ``flinkml_tpu.analysis`` when
    promoted silently) and promotes non-float inputs to float64.
    """
    col = table.column(features_col)
    if col.dtype == object:
        return stack_vectors(col)
    if dtype is None:
        dtype = col.dtype if col.dtype.kind == "f" else np.float64
    if col.ndim == 1:
        return col.astype(dtype).reshape(-1, 1)
    return np.ascontiguousarray(col, dtype=dtype)


def labeled_data(
    table: Table,
    features_col: str,
    label_col: str,
    weight_col: Optional[str] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Extract (X [n,d], y [n], w [n]); weight defaults to 1.0 per row."""
    x = features_matrix(table, features_col)
    y = np.asarray(table.column(label_col), dtype=np.float64).reshape(-1)
    if y.shape[0] != x.shape[0]:
        raise ValueError(
            f"label column {label_col!r} has {y.shape[0]} rows, features have {x.shape[0]}"
        )
    if weight_col is not None:
        w = np.asarray(table.column(weight_col), dtype=np.float64).reshape(-1)
    else:
        w = np.ones(x.shape[0], dtype=np.float64)
    return x, y, w


def sparse_features(table: Table, features_col: str):
    """The features column if EVERY row is a SparseVector, else None —
    the dispatch every linear model uses to pick the O(nnz) sparse path
    over densification. A mixed Sparse/Dense vector column returns None
    and takes the densifying path (which handles any Vector)."""
    col = table.column(features_col)
    if (
        col.dtype == object
        and col.size
        and isinstance(col[0], SparseVector)
        and all(isinstance(v, SparseVector) for v in col)
    ):
        return col
    return None


_HASH_MIX = np.uint64(0x9E3779B97F4A7C15)  # golden-ratio multiplicative mix


def hashed_feature_matrix(
    sparse_col: np.ndarray, num_buckets: int, dtype=np.float32
) -> np.ndarray:
    """Hash-bundle a SparseVector column into a dense ``[n, num_buckets]``
    matrix: bucket ``mix(col_id) % num_buckets`` accumulates the sum of
    that row's values whose column hashes there.

    The tree-model route for high-cardinality sparse inputs (one-hot /
    hashed text): histogram GBT needs a bounded dense feature space, and
    one-hot columns are individually uninformative 0/1s — bundling by a
    mixing hash (LightGBM's EFB instinct, sklearn's hashing-trick
    mechanics) keeps memory at ``n x num_buckets`` regardless of the
    original dimensionality. Collisions merge features; num_buckets
    trades memory for collision rate.
    """
    from flinkml_tpu.ops.sparse import csr_from_sparse_vectors

    indptr, indices, values, _dim = csr_from_sparse_vectors(
        sparse_col, dtype=dtype
    )
    n = indptr.size - 1
    mixed = indices.astype(np.uint64) * _HASH_MIX
    buckets = ((mixed >> np.uint64(32)) % np.uint64(num_buckets)).astype(
        np.int64
    )
    rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
    # bincount over flat (row, bucket) keys: orders of magnitude faster
    # than np.add.at's unbuffered per-element scatter at Criteo-scale nnz.
    flat = np.bincount(
        rows * num_buckets + buckets, weights=values,
        minlength=n * num_buckets,
    )
    return flat.reshape(n, num_buckets).astype(dtype)


def check_binary_labels(y: np.ndarray, model_name: str) -> None:
    """Validate labels ∈ {0, 1} (shared by the binomial classifiers)."""
    labels = np.unique(y)
    if not np.all(np.isin(labels, (0.0, 1.0))):
        raise ValueError(
            f"{model_name} requires labels in {{0, 1}}, got {labels}"
        )


def labeled_sparse_data(
    table: Table,
    features_col: str,
    label_col: str,
    weight_col: Optional[str] = None,
    dtype=np.float32,
):
    """Sparse analog of :func:`labeled_data`: host CSR arrays + labels.

    Returns ``(indptr, indices, values, dim, y, w)``.
    """
    from flinkml_tpu.ops.sparse import csr_from_sparse_vectors

    col = table.column(features_col)
    indptr, indices, values, dim = csr_from_sparse_vectors(col, dtype=dtype)
    y = np.asarray(table.column(label_col), dtype=dtype).reshape(-1)
    if y.shape[0] != indptr.size - 1:
        raise ValueError(
            f"label column {label_col!r} has {y.shape[0]} rows, features "
            f"have {indptr.size - 1}"
        )
    if weight_col is not None:
        w = np.asarray(table.column(weight_col), dtype=dtype).reshape(-1)
    else:
        w = np.ones(y.shape[0], dtype=dtype)
    return indptr, indices, values, dim, y, w
