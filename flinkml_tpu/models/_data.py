"""Column-extraction helpers shared by algorithms.

The analog of the reference's row→POJO maps (e.g.
``LogisticRegression.java:111-130`` mapping rows to
``LabeledPointWithWeight``): tables are already columnar, so "extraction" is
densifying a features column to ``[n, d]`` and reading label/weight columns.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from flinkml_tpu.linalg import Vector, stack_vectors
from flinkml_tpu.table import Table


def features_matrix(table: Table, features_col: str) -> np.ndarray:
    """Densify a features column to float [n, d].

    Accepts 2-D numeric columns (native layout) or object columns of
    ``Vector`` / array-likes (row-wise user data).
    """
    col = table.column(features_col)
    if col.dtype == object:
        return stack_vectors(col)
    if col.ndim == 1:
        return col.astype(np.float64).reshape(-1, 1)
    return np.ascontiguousarray(col, dtype=np.float64)


def labeled_data(
    table: Table,
    features_col: str,
    label_col: str,
    weight_col: Optional[str] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Extract (X [n,d], y [n], w [n]); weight defaults to 1.0 per row."""
    x = features_matrix(table, features_col)
    y = np.asarray(table.column(label_col), dtype=np.float64).reshape(-1)
    if y.shape[0] != x.shape[0]:
        raise ValueError(
            f"label column {label_col!r} has {y.shape[0]} rows, features have {x.shape[0]}"
        )
    if weight_col is not None:
        w = np.asarray(table.column(weight_col), dtype=np.float64).reshape(-1)
    else:
        w = np.ones(x.shape[0], dtype=np.float64)
    return x, y, w
