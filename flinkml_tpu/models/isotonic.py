"""IsotonicRegression — weighted monotone regression via pool-adjacent-
violators (the Spark/Flink family member).

PAV is an inherently sequential O(n) stack algorithm over sorted rows —
host code by nature (there is nothing for the MXU in it; the sort
dominates and numpy's is fine). Prediction interpolates linearly
between fitted boundary points and clamps outside the fitted range, the
upstream convention.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasWeightCol,
)
from flinkml_tpu.params import BoolParam
from flinkml_tpu.table import Table


def _feature_column(table: Table, col: str) -> np.ndarray:
    """The single scalar feature as [n] f64 — accepts a 1-D column or the
    repo's standard [n, 1] / Vector object layouts (via features_matrix)."""
    from flinkml_tpu.models._data import features_matrix

    x = features_matrix(table, col)
    if x.shape[1] != 1:
        raise ValueError(
            f"IsotonicRegression takes a single feature, got dim {x.shape[1]}"
        )
    return x[:, 0]


def pav(x: np.ndarray, y: np.ndarray, w: np.ndarray,
        increasing: bool = True):
    """Weighted PAV. Returns (boundaries, values): the stepwise-fit knots
    (x deduplicated by weighted mean within ties, then pooled)."""
    # Zero-weight rows carry no information and would poison the pooled
    # means (sklearn drops them too).
    keep = w > 0
    if not keep.any():
        raise ValueError("all weights are zero")
    x, y, w = x[keep], y[keep], w[keep]
    order = np.argsort(x, kind="stable")
    xs, ys, ws = x[order], y[order], w[order]
    if not increasing:
        ys = -ys
    # Merge duplicate x first (weighted mean), as sklearn/Spark do.
    uniq, start = np.unique(xs, return_index=True)
    stop = np.append(start[1:], len(xs))
    xm, ym, wm = [], [], []
    for s, e in zip(start, stop):
        wt = ws[s:e].sum()
        xm.append(xs[s])
        ym.append(float((ys[s:e] * ws[s:e]).sum() / wt))
        wm.append(float(wt))
    # PAV stack: pool adjacent violators into weighted-mean blocks.
    # Each block is [start_idx, end_idx, mean, weight] over xm indices.
    blocks: List[List[float]] = []
    for i, (yi, wi) in enumerate(zip(ym, wm)):
        blocks.append([i, i, yi, wi])
        while len(blocks) > 1 and blocks[-2][2] >= blocks[-1][2]:
            s2, e2, y2, w2 = blocks.pop()
            s1, e1, y1, w1 = blocks.pop()
            tot = w1 + w2
            blocks.append([s1, e2, (y1 * w1 + y2 * w2) / tot, tot])
    # Emit (start_x, v) and (end_x, v) knots per block: interpolation is
    # flat within blocks and linear between them (the Spark boundary
    # convention).
    boundaries: List[float] = []
    values: List[float] = []
    for s, e, v, _ in blocks:
        boundaries.append(xm[int(s)])
        values.append(v)
        if e > s:
            boundaries.append(xm[int(e)])
            values.append(v)
    bnd = np.asarray(boundaries)
    val = np.asarray(values)
    if not increasing:
        val = -val
    return bnd, val


class _IsotonicParams(
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasWeightCol
):
    ISOTONIC = BoolParam(
        "isotonic", "Fit increasing (true) or decreasing (false).", True
    )


class IsotonicRegression(_IsotonicParams, Estimator):
    def fit(self, *inputs: Table) -> "IsotonicRegressionModel":
        (table,) = inputs
        x = _feature_column(table, self.get(self.FEATURES_COL))
        y = np.asarray(
            table.column(self.get(self.LABEL_COL)), dtype=np.float64
        ).reshape(-1)
        weight_col = self.get(self.WEIGHT_COL)
        w = (
            np.asarray(table.column(weight_col), dtype=np.float64).reshape(-1)
            if weight_col else np.ones_like(y)
        )
        if not (x.shape == y.shape == w.shape):
            raise ValueError("features/label/weight lengths differ")
        bnd, val = pav(x, y, w, self.get(self.ISOTONIC))
        model = IsotonicRegressionModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({"boundaries": bnd[None, :], "values": val[None, :]})
        )
        return model


class IsotonicRegressionModel(_IsotonicParams, Model):
    def __init__(self):
        super().__init__()
        self._boundaries: Optional[np.ndarray] = None
        self._values: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "IsotonicRegressionModel":
        (table,) = inputs
        self._boundaries = np.asarray(
            table.column("boundaries"), np.float64
        )[0]
        self._values = np.asarray(table.column("values"), np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "boundaries": self._boundaries[None, :],
            "values": self._values[None, :],
        })]

    @property
    def boundaries(self) -> np.ndarray:
        self._require()
        return self._boundaries

    @property
    def values(self) -> np.ndarray:
        self._require()
        return self._values

    def _require(self) -> None:
        if self._boundaries is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = _feature_column(table, self.get(self.FEATURES_COL))
        pred = np.interp(x, self._boundaries, self._values)
        return (table.with_column(self.get(self.PREDICTION_COL), pred),)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {
            "boundaries": self._boundaries, "values": self._values,
        })

    @classmethod
    def load(cls, path: str) -> "IsotonicRegressionModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._boundaries = arrays["boundaries"]
        model._values = arrays["values"]
        return model
