"""Word2Vec — skip-gram with negative sampling (the Spark/Flink family
member), TPU-native.

Host prep (strings never touch the device): frequency vocabulary with
``minCount`` pruning, (center, context) pair generation over
``windowSize``, and a unigram^0.75 negative-sampling pool materialized
as a flat int array (sampling a negative = one uniform integer into the
pool — no alias tables on device).

Device training: the WHOLE run is one program — a ``lax.while_loop``
of minibatch SGNS steps over the pair list sharded across the mesh.
Each step gathers the batch's embedding rows, computes
``log σ(u_ctx·v_w) + Σ_neg log σ(−u_neg·v_w)`` gradients, scatter-adds
them back with ``.at[].add``, ``psum``s the dense embedding gradients
and steps by the GLOBAL-batch mean (device-count invariant; below
``_shard_vocab_threshold`` a dense psum per step beats bespoke sparse
collectives). ABOVE the threshold the in-RAM fit AND the
single-process streamed fit switch to ``_sgns_trainer_sharded``:
embedding tables shard over the mesh and
batch-sized payloads ride a ``ppermute`` ring, so per-step traffic is
independent of vocab. Spark trains hierarchical softmax on the JVM —
SGNS is the TPU-idiomatic equivalent and is documented as such, not
imitated.

The fitted model maps token-list documents to the MEAN of their word
vectors (the upstream convention) and offers ``find_synonyms`` via
cosine top-k (one gemm + top_k).
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional, Tuple

import flinkml_tpu._jax_compat  # noqa: F401  (jax version shims; install before first jax use)
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasInputCol,
    HasLearningRate,
    HasMaxIter,
    HasOutputCol,
    HasSeed,
)
from flinkml_tpu.models.text import _token_column
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table

_NEG_POOL = 1 << 18   # negative-sampling pool entries


class _Word2VecParams(HasInputCol, HasOutputCol, HasMaxIter,
                      HasLearningRate, HasSeed):
    VECTOR_SIZE = IntParam(
        "vectorSize", "Embedding dimensionality.", 100, ParamValidators.gt(0)
    )
    WINDOW_SIZE = IntParam(
        "windowSize", "Max distance between center and context.", 5,
        ParamValidators.gt(0),
    )
    MIN_COUNT = IntParam(
        "minCount", "Tokens rarer than this are dropped.", 5,
        ParamValidators.gt(0),
    )
    NUM_NEGATIVES = IntParam(
        "numNegatives", "Negative samples per (center, context) pair.", 5,
        ParamValidators.gt(0),
    )
    BATCH_SIZE = IntParam(
        "batchSize", "Global pairs per SGNS step.", 1024,
        ParamValidators.gt(0),
    )


def _build_pairs(docs, vocab_index: Dict[str, int], window: int,
                 rng: np.random.Generator):
    centers, contexts = [], []
    for toks in docs:
        ids = [vocab_index[t] for t in map(str, toks) if t in vocab_index]
        for i, c in enumerate(ids):
            w = int(rng.integers(1, window + 1))   # word2vec's window jitter
            for j in range(max(0, i - w), min(len(ids), i + w + 1)):
                if j != i:
                    centers.append(c)
                    contexts.append(ids[j])
    return (np.asarray(centers, np.int32), np.asarray(contexts, np.int32))


def _agree_token_counts(tokens, counts, mesh) -> "Dict[str, int]":
    """Union the per-process (token, count) maps through the device
    fabric: each token rides as UTF-8 bytes (values 0-255 — exact on
    the f64 hi/lo transport of ``stream_sync.gather_vectors``) with its
    count, padded to the agreed (max tokens, max byte length); every
    host decodes the gathered rows in rank order and sums counts per
    token, so the merged map is identical everywhere. An empty local
    vocabulary is legal. Transport cost is
    ``P x max_tokens x (max_len + 2) x 8`` bytes through device memory
    — sized for real vocabularies (1e5 tokens x 32 bytes ≈ 27 MB/rank),
    not for unbounded cardinality."""
    from flinkml_tpu.iteration.stream_sync import agree_max, gather_vectors

    enc = [str(t).encode("utf-8") for t in tokens]
    t_max = agree_max(len(enc), mesh)
    if t_max == 0:
        return {}
    l_max = agree_max(max((len(b) for b in enc), default=0), mesh)
    stride = 2 + l_max
    vec = np.zeros(1 + t_max * stride)
    vec[0] = len(enc)
    for j, b in enumerate(enc):
        off = 1 + j * stride
        vec[off] = len(b)
        vec[off + 1] = counts[j]
        vec[off + 2 : off + 2 + len(b)] = np.frombuffer(b, np.uint8)
    rows = gather_vectors(vec, mesh)
    merged: Dict[str, int] = {}
    for row in rows:  # rank order: identical merge on every host
        for j in range(int(round(row[0]))):
            off = 1 + j * stride
            blen = int(round(row[off]))
            tok = (
                np.asarray(row[off + 2 : off + 2 + blen])
                .astype(np.uint8).tobytes().decode("utf-8")
            )
            merged[tok] = merged.get(tok, 0) + int(round(row[off + 1]))
    return merged


def _w2v_accum() -> str:
    """Embedding-gradient accumulation layout of the dense SGNS trainer
    (the roofline audit's sort-class gap: XLA lowers the per-step row
    scatters into ``[vocab, dim]`` through a sort, pinning the stage at
    ~5% of its ~40M pairs/s bound — VERDICT Missing #3, probed by
    ``tools/w2v_scatter_probe.py``). ``FLINKML_TPU_W2V_ACCUM`` selects,
    mirroring the sparse-LR/GBT/ALS cumsum gates:

    - ``scatter`` (default): ``.at[ids].add(rows)`` — the original
      formulation;
    - ``onehot``: ``one_hot(ids)^T @ rows`` as a fused einsum — a true
      matrix-matrix product on the MXU IF XLA fuses the iota-compare
      into the dot operand (the probe's question; flip the default only
      on a measured win).

    Numerics: both accumulate the same per-pair gradients; they differ
    only in f32 summation order (pinned in ``tests/test_word2vec.py::
    test_onehot_accum_matches_scatter``)."""
    layout = os.environ.get("FLINKML_TPU_W2V_ACCUM")
    if layout is None:
        # Measured default for this mesh (autotune tuning table), else
        # the historical "scatter".
        from flinkml_tpu.autotune import tuned_default

        return tuned_default("w2v_accum", "scatter",
                             allowed=("scatter", "onehot"))
    if layout not in ("scatter", "onehot"):
        raise ValueError(
            f"FLINKML_TPU_W2V_ACCUM={layout!r}: expected 'scatter' or "
            "'onehot'"
        )
    return layout


def _kernels_segsum_backend() -> str:
    """The kernel-backend gate for the embedding-gradient scatter
    (:mod:`flinkml_tpu.kernels`, site ``segment_sum``) — resolved at
    fit time and threaded through the trainer's lru key, mirroring
    :func:`_w2v_accum`."""
    from flinkml_tpu import kernels

    return kernels.segsum_backend()


def _sgns_pair_grads(vc, uc, un, wb):
    """SGNS pair gradients from the gathered embedding rows — the ONE
    definition of the loss math, shared by the dense and vocab-sharded
    trainers (their numerics-parity contract,
    ``tests/test_word2vec.py::test_sharded_trainer_matches_dense``,
    depends on it). Returns ``(grad_vc, grad_uc, grad_un)``."""
    pos_score = jnp.sum(vc * uc, axis=1)
    neg_score = jnp.einsum("bd,bnd->bn", vc, un)
    g_pos = (jax.nn.sigmoid(pos_score) - 1.0) * wb   # [bs]
    g_neg = jax.nn.sigmoid(neg_score) * wb[:, None]  # [bs, neg]
    grad_vc = g_pos[:, None] * uc + jnp.einsum("bn,bnd->bd", g_neg, un)
    grad_uc = g_pos[:, None] * vc
    grad_un = g_neg[..., None] * vc[:, None, :]
    return grad_vc, grad_uc, grad_un


@functools.lru_cache(maxsize=8)
def _sgns_trainer(mesh, axis: str, local_bs: int, n_neg: int,
                  accum: str = "scatter", segsum_backend: str = "xla"):
    from flinkml_tpu import kernels

    def local(centers, contexts, wl, pool, v0, u0, lr, n_steps, key):
        n_local = centers.shape[0]

        def scatter_rows(table_like, ids, rows):
            """The ``scatter`` accumulation under the kernel-backend
            gate: ``.at[ids].add`` (XLA) or the Pallas row-payload
            segment-sum — ``segsum_backend`` is lru-key material, so a
            gate flip re-keys the jitted trainer."""
            if segsum_backend == "pallas":
                return kernels.segment_sum(
                    rows.reshape(-1, rows.shape[-1]), ids.reshape(-1),
                    table_like.shape[0], backend="pallas",
                )
            return jnp.zeros_like(table_like).at[ids.reshape(-1)].add(
                rows.reshape(-1, rows.shape[-1])
            )

        def onehot_sum(table_like, ids, rows):
            """``one_hot(ids)^T @ rows`` — the gated scatter-free
            accumulation (:func:`_w2v_accum`); ``ids`` may be [bs] or
            [bs, neg]."""
            flat_ids = ids.reshape(-1)
            flat_rows = rows.reshape(-1, rows.shape[-1])
            oh = jax.nn.one_hot(
                flat_ids, table_like.shape[0], dtype=flat_rows.dtype
            )
            return jnp.einsum("bv,bd->vd", oh, flat_rows)

        def body(state):
            step, v, u = state
            k = jax.random.fold_in(key, step)
            k1, k2 = jax.random.split(k)
            idx = jax.random.randint(k1, (local_bs,), 0, n_local)
            c = centers[idx]
            ctx = contexts[idx]
            wb = wl[idx]                   # [bs]; 0 on dummy chunks
            neg = pool[jax.random.randint(
                k2, (local_bs, n_neg), 0, pool.shape[0]
            )]
            vc = v[c]                      # [bs, d]
            uc = u[ctx]                    # [bs, d]
            un = u[neg]                    # [bs, neg, d]
            grad_vc, grad_uc, grad_un = _sgns_pair_grads(vc, uc, un, wb)
            if accum == "onehot":
                dv = onehot_sum(v, c, grad_vc)
                du = onehot_sum(u, ctx, grad_uc) + onehot_sum(
                    u, neg, grad_un
                )
            elif segsum_backend == "pallas":
                # Two independent scatters summed (instead of one
                # chained scatter) — same gradients, f32 order differs
                # only on ctx/neg id collisions; the kernel parity test
                # pins each scatter bitwise against its XLA twin.
                dv = scatter_rows(v, c, grad_vc)
                du = scatter_rows(u, ctx, grad_uc) + scatter_rows(
                    u, neg, grad_un
                )
            else:
                dv = jnp.zeros_like(v).at[c].add(grad_vc)
                du = (
                    jnp.zeros_like(u).at[ctx].add(grad_uc)
                    .at[neg.reshape(-1)].add(
                        grad_un.reshape(-1, grad_un.shape[-1])
                    )
                )
            # Device-invariant normalization: psum the per-device sums
            # and divide by the GLOBAL selected weight, so learningRate
            # means "step on the mean pair gradient" regardless of mesh
            # size (pmean of sums would shrink the step by the device
            # count). All-ones weights make this exactly the global
            # batch size (f32 sums of ones are exact at these sizes);
            # zero-weight rows (multi-process dummy chunks) drop out of
            # both the gradient and the normalizer.
            tw = jnp.maximum(jax.lax.psum(jnp.sum(wb), axis), 1e-12)
            scale = lr / tw
            dv = jax.lax.psum(dv, axis)
            du = jax.lax.psum(du, axis)
            return step + 1, v - scale * dv, u - scale * du

        def cond(state):
            return state[0] < n_steps

        _, v, u = jax.lax.while_loop(cond, body, (jnp.asarray(0, jnp.int32),
                                                  v0, u0))
        return v, u

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(), P(), P(), P(), P(), P()),
            out_specs=(P(), P()),
        )
    )


@functools.lru_cache(maxsize=8)
def _sgns_trainer_sharded(mesh, axis: str, local_bs: int, n_neg: int,
                          shard_rows: int, strategy: str = "ring",
                          segsum_backend: str = "xla"):
    """Vocab-sharded SGNS trainer: the scale path above the embedding
    dense-psum threshold (VERDICT r4 weak #6 — the dense trainer psums
    a full ``[vocab, dim]`` gradient every step, quadratically painful
    at the 1M+ vocabs the Spark-family operator serves).

    Re-expressed on the :mod:`flinkml_tpu.embeddings.exchange`
    primitives (this trainer is where they were born — the ring loops
    moved there verbatim, so the ``ring`` strategy is bit-identical to
    the pre-subsystem trainer): both embedding tables shard over the
    mesh axis (``shard_rows`` rows per device); per-step communication
    is the BATCH's activation and gradient rows riding the
    strategy-gated exchange, never a vocab-sized array:

      1. ONE exchange gather — each device's minibatch ids for BOTH
         tables (center ids against v; context + negative ids against
         u) resolve to complete rows (``ppermute`` ring hops, or one
         ``all_to_all`` under the gated strategy).
      2. local pair math — :func:`_sgns_pair_grads`, shared with the
         dense trainer.
      3. ONE exchange scatter — the scaled gradient rows for both
         tables route home; the ``all_to_all`` scatter rides the PR 12
         padded-ELL ``segment_sum`` kernel gate (``segsum_backend`` is
         lru-key material, like the dense trainer's).

    Per step, per device: ``2·(2 + n_neg)·global_bs·dim`` floats total
    regardless of strategy — independent of vocab AND of P. Numerics
    match the dense trainer up to f32 summation order; the strategies
    match each other bitwise on the gather and up to summation order on
    the scatter (both pinned in ``tests/test_word2vec.py`` /
    ``tests/test_embeddings.py``)."""
    from flinkml_tpu.embeddings import exchange

    p = dict(mesh.shape)[axis]

    def local(centers, contexts, wl, pool, v_shard, u_shard, lr, n_steps,
              key):
        n_local = centers.shape[0]

        def body(state):
            step, v, u = state
            k = jax.random.fold_in(key, step)
            k1, k2 = jax.random.split(k)
            idx = jax.random.randint(k1, (local_bs,), 0, n_local)
            c = centers[idx]
            ctx = contexts[idx]
            wb = wl[idx]
            neg = pool[jax.random.randint(
                k2, (local_bs, n_neg), 0, pool.shape[0]
            )]
            vc, uc, un = exchange.gather(
                ((v, c), (u, ctx), (u, neg)),
                axes=axis, n_shards=p, shard_rows=shard_rows,
                strategy=strategy,
            )
            grad_vc, grad_uc, grad_un = _sgns_pair_grads(vc, uc, un, wb)
            tw = jnp.maximum(jax.lax.psum(jnp.sum(wb), axis), 1e-12)
            scale = lr / tw
            v, u = exchange.scatter_add(
                (v, u),
                (
                    (0, c, -scale * grad_vc),
                    (1, ctx, -scale * grad_uc),
                    (1, neg, -scale * grad_un),
                ),
                axes=axis, n_shards=p, shard_rows=shard_rows,
                strategy=strategy, segsum_backend=segsum_backend,
            )
            return step + 1, v, u

        def cond(state):
            return state[0] < n_steps

        _, v, u = jax.lax.while_loop(
            cond, body, (jnp.asarray(0, jnp.int32), v_shard, u_shard)
        )
        return v, u

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(), P(axis), P(axis),
                      P(), P(), P()),
            out_specs=(P(axis), P(axis)),
        )
    )


def _shard_vocab_threshold() -> int:
    """Vocab size above which the in-RAM fit switches to the
    vocab-sharded exchange trainer on a multi-device mesh (the dense
    trainer's per-step [vocab, dim] gradient psum stops scaling there).
    Now the embedding subsystem's ONE dense-psum threshold
    (:func:`flinkml_tpu.embeddings.dense_vocab_threshold`), which
    honors ``FLINKML_W2V_SHARD_VOCAB`` as a back-compat alias (0 forces
    sharding — the test hook)."""
    from flinkml_tpu.embeddings import dense_vocab_threshold

    return dense_vocab_threshold()


def _exchange_strategy() -> str:
    """The sharded exchange algorithm for this fit — resolved once at
    fit time (env > autotune ``embedding_exchange`` > ring) and threaded
    through the trainer's lru key, mirroring :func:`_w2v_accum`."""
    from flinkml_tpu.embeddings import exchange_strategy

    return exchange_strategy()


class Word2Vec(StreamingEstimatorMixin, _Word2VecParams, Estimator):
    """``fit`` accepts, besides a single in-RAM :class:`Table`, an
    **iterable of batch Tables** — the out-of-core path: pass A encodes
    the token stream to an int-coded doc cache (strings never spill; the
    vocabulary dictionary is model-sized host state), pass B replays it
    into a (center, context) pair cache, and each training epoch replays
    the pair cache chunk-by-chunk — SGNS minibatches sample within the
    resident chunk, the classic word2vec sequential-corpus discipline
    (reference replay parity: ``ReplayOperator.java:62-250``).
    ``checkpoint_manager`` + ``checkpoint_interval`` snapshot both
    embedding matrices every N epochs; ``resume=True`` continues
    bit-exactly PROVIDED the caller re-feeds the complete identical
    stream — Word2Vec cannot take a sealed DataCache (no string
    vocabulary), so the durable-input guard the other streamed fits
    enforce cannot apply here; passes A/B re-run deterministically from
    the same seed over the re-fed stream."""


    def fit(self, *inputs) -> "Word2VecModel":
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        self._reject_in_ram_checkpointing()
        docs = _token_column(table, self.get(self.INPUT_COL))
        min_count = self.get(self.MIN_COUNT)
        counts: Dict[str, int] = {}
        for toks in docs:
            for t in toks:
                t = str(t)
                counts[t] = counts.get(t, 0) + 1
        vocab = [t for t, c in counts.items() if c >= min_count]
        vocab.sort(key=lambda t: (-counts[t], t))
        if not vocab:
            raise ValueError(
                f"no token reaches minCount={min_count}; vocabulary is empty"
            )
        vocab_index = {t: i for i, t in enumerate(vocab)}
        rng = np.random.default_rng(self.get_seed())
        centers, contexts = _build_pairs(
            docs, vocab_index, self.get(self.WINDOW_SIZE), rng
        )
        if centers.size == 0:
            raise ValueError("no (center, context) pairs; documents too short")
        # unigram^0.75 negative pool.
        freq = np.asarray([counts[t] for t in vocab], np.float64) ** 0.75
        pool = rng.choice(
            len(vocab), size=_NEG_POOL, p=freq / freq.sum()
        ).astype(np.int32)

        dim = self.get(self.VECTOR_SIZE)
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        # Shuffle, then pad by REPEATING real pairs: a zero-filled pad
        # would be a genuine (0, 0) positive pair self-training the most
        # frequent word; cycling real pairs only mildly over-weights a
        # few of them.
        perm = rng.permutation(len(centers))
        centers, contexts = centers[perm], contexts[perm]
        pad = (-len(centers)) % p
        centers_p = np.concatenate([centers, centers[:pad]])
        contexts_p = np.concatenate([contexts, contexts[:pad]])

        local_bs = max(1, self.get(self.BATCH_SIZE) // p)
        n_pairs = len(centers)
        steps_per_epoch = max(1, n_pairs // self.get(self.BATCH_SIZE))
        n_steps = steps_per_epoch * self.get(self.MAX_ITER)

        v0 = (rng.random((len(vocab), dim)) - 0.5).astype(np.float32) / dim
        u0 = np.zeros((len(vocab), dim), np.float32)
        if p > 1 and len(vocab) > _shard_vocab_threshold():
            # Scale path: both embedding tables shard over the mesh; the
            # per-step ring traffic is batch-sized, never vocab-sized.
            shard_rows = -(-len(vocab) // p)
            row_pad = shard_rows * p - len(vocab)
            v0p = np.concatenate([v0, np.zeros((row_pad, dim), np.float32)])
            u0p = np.concatenate([u0, np.zeros((row_pad, dim), np.float32)])
            trainer = _sgns_trainer_sharded(
                mesh.mesh, DeviceMesh.DATA_AXIS, local_bs,
                self.get(self.NUM_NEGATIVES), shard_rows,
                _exchange_strategy(), _kernels_segsum_backend(),
            )
            v, _u = trainer(
                mesh.shard_batch(centers_p), mesh.shard_batch(contexts_p),
                mesh.shard_batch(np.ones(len(centers_p), np.float32)),
                jnp.asarray(pool), mesh.shard_batch(v0p),
                mesh.shard_batch(u0p),
                jnp.asarray(self.get(self.LEARNING_RATE), jnp.float32),
                jnp.asarray(n_steps, jnp.int32),
                jax.random.PRNGKey(self.get_seed()),
            )
            v = np.asarray(v)[: len(vocab)]
        else:
            trainer = _sgns_trainer(
                mesh.mesh, DeviceMesh.DATA_AXIS, local_bs,
                self.get(self.NUM_NEGATIVES), _w2v_accum(),
                _kernels_segsum_backend(),
            )
            v, _u = trainer(
                mesh.shard_batch(centers_p), mesh.shard_batch(contexts_p),
                mesh.shard_batch(np.ones(len(centers_p), np.float32)),
                jnp.asarray(pool), jnp.asarray(v0), jnp.asarray(u0),
                jnp.asarray(self.get(self.LEARNING_RATE), jnp.float32),
                jnp.asarray(n_steps, jnp.int32),
                jax.random.PRNGKey(self.get_seed()),
            )
        model = Word2VecModel()
        model.copy_params_from(self)
        model._set(np.asarray(vocab, dtype=str), np.asarray(v, np.float64))
        return model

    # Pair-chunk row tile: bounds the set of padded chunk shapes (and so
    # trainer recompiles) while keeping chunks MXU-sized.
    _PAIR_TILE = 2048

    def _fit_stream(self, source) -> "Word2VecModel":
        """Out-of-core SGNS (see class docstring).

        Multi-process (round 4): each process feeds its OWN document
        partition. The string vocabulary unions through the device
        fabric — tokens ride as UTF-8 bytes on the f64-exact transport
        (:func:`_agree_token_counts`) — so every rank holds the
        identical (token, count) map; pair building then stays
        rank-local (per-rank deterministic window RNG), and each
        training dispatch is one agreed-step SGNS run over every rank's
        resident chunk with psum'd gradients (drained ranks feed
        zero-weight dummy chunks). The negative pool and embedding init
        draw from a fresh seed-only RNG so they are identical on every
        rank; the fitted vectors are identical on every rank."""
        import os
        import shutil
        import tempfile

        from flinkml_tpu.iteration.checkpoint import (
            begin_resume,
            should_snapshot,
        )
        from flinkml_tpu.iteration.datacache import (
            DataCache,
            DataCacheWriter,
        )

        if isinstance(source, DataCache):
            raise ValueError(
                "Word2Vec streamed fit takes an iterable of batch Tables "
                "(token documents are encoded internally; a raw DataCache "
                "carries no string vocabulary)"
            )
        multi = jax.process_count() > 1
        input_col = self.get(self.INPUT_COL)
        min_count = self.get(self.MIN_COUNT)
        window = self.get(self.WINDOW_SIZE)
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        resume_epoch = begin_resume(
            self.checkpoint_manager, self.resume, mesh.mesh.size
        )

        # -- pass A: count tokens + cache int-coded docs -------------------
        # The doc cache is transient (consumed once by pass B), so it
        # lives in a private temp dir; the pair cache — replayed every
        # epoch — goes to the user's cache_dir.
        if self.cache_dir is not None:
            os.makedirs(self.cache_dir, exist_ok=True)
        doc_dir = tempfile.mkdtemp(prefix="flinkml-w2v-docs-",
                                   dir=self.cache_dir)
        pid: Dict[str, int] = {}
        counts_list: List[int] = []
        try:
            doc_writer = DataCacheWriter(
                doc_dir, self.cache_memory_budget_bytes
            )

            def ingest_docs(t):
                docs = _token_column(t, input_col)
                codes: List[int] = []
                lengths: List[int] = []
                for toks in docs:
                    start = len(codes)
                    for tok in map(str, toks):
                        i = pid.get(tok)
                        if i is None:
                            i = pid[tok] = len(counts_list)
                            counts_list.append(0)
                        counts_list[i] += 1
                        codes.append(i)
                    lengths.append(len(codes) - start)
                if lengths:
                    # Flat single-column record (columns of a cached batch
                    # must agree on row count): [n_docs, *lengths, *codes].
                    doc_writer.append({
                        "rec": np.concatenate([
                            [len(lengths)], lengths, codes
                        ]).astype(np.int32),
                    })

            from flinkml_tpu.iteration.stream_sync import (
                DeferredValidation,
                checked_ingest,
            )

            dv = DeferredValidation()
            for _ in checked_ingest(source, dv, ingest_docs, multi):
                pass
            doc_cache = doc_writer.finish()

            tokens = np.empty(len(pid), dtype=object)
            for tok, i in pid.items():
                tokens[i] = tok
            if multi:
                # Rendezvous BEFORE the vocab union: a held ingest error
                # must surface as itself on every rank.
                dv.rendezvous(mesh, "stream ingest validation")
                merged = _agree_token_counts(
                    list(tokens), counts_list, mesh
                )
                if not merged:
                    raise ValueError(
                        "training stream is empty on every process"
                    )
                vocab = [t for t, c in merged.items() if c >= min_count]
                vocab.sort(key=lambda t: (-merged[t], t))
                if not vocab:  # merged is identical: symmetric raise
                    raise ValueError(
                        f"no token reaches minCount={min_count}; "
                        "vocabulary is empty"
                    )
                final_of_token = {t: f for f, t in enumerate(vocab)}
                final_of_pid = np.full(len(counts_list), -1, np.int32)
                for i in range(len(counts_list)):
                    final_of_pid[i] = final_of_token.get(str(tokens[i]), -1)
                vocab_counts = np.asarray(
                    [merged[t] for t in vocab], np.int64
                )
            else:
                counts_arr = np.asarray(counts_list, np.int64)
                kept = [i for i in range(len(counts_list))
                        if counts_arr[i] >= min_count]
                kept.sort(key=lambda i: (-counts_arr[i], tokens[i]))
                if not kept:
                    raise ValueError(
                        f"no token reaches minCount={min_count}; vocabulary "
                        "is empty"
                    )
                vocab = [tokens[i] for i in kept]
                final_of_pid = np.full(len(counts_list), -1, np.int32)
                for f, i in enumerate(kept):
                    final_of_pid[i] = f
                vocab_counts = counts_arr[kept]

            # Scale guard BEFORE pass B: the vocabulary is final here,
            # and failing now costs seconds — after pass B it would cost
            # a full doc-cache replay and a pair cache on disk first.
            # Single-process multi-device streams switch to the
            # vocab-sharded ring trainer below instead; only the
            # multi-PROCESS stream (whose per-rank pair partitions the
            # ring trainer does not yet route) rejects.
            if multi and len(vocab) > _shard_vocab_threshold():
                raise ValueError(
                    f"multi-process streamed Word2Vec fit: vocabulary "
                    f"({len(vocab)} tokens) exceeds the dense-gradient "
                    f"scale ceiling ({_shard_vocab_threshold()}): every "
                    "SGNS step would psum a full [vocab, dim] gradient "
                    "across processes. Use the in-RAM fit or a "
                    "single-process mesh (both switch to the "
                    "vocab-sharded ring trainer above this threshold), "
                    "raise minCount to prune the vocabulary, or override "
                    "via FLINKML_W2V_SHARD_VOCAB."
                )

            # -- pass B: replay doc cache into the pair cache --------------
            # Multi-process: per-rank deterministic window RNG (pairs are
            # rank-local); the pool/init RNG below is then seed-only so
            # those draws are identical on every rank.
            if multi:
                rng = np.random.default_rng(
                    [self.get_seed(), 1 + jax.process_index()]
                )
            else:
                rng = np.random.default_rng(self.get_seed())
            pair_writer = DataCacheWriter(
                self.cache_dir, self.cache_memory_budget_bytes
            )
            n_pairs = 0
            for batch in doc_cache.reader():
                rec = batch["rec"]
                n_docs = int(rec[0])
                lengths_b = rec[1:1 + n_docs]
                fids = final_of_pid[rec[1 + n_docs:]]
                centers: List[int] = []
                contexts: List[int] = []
                off = 0
                for length in lengths_b:
                    ids = [int(c) for c in fids[off:off + length] if c >= 0]
                    off += int(length)
                    for i, c in enumerate(ids):
                        w = int(rng.integers(1, window + 1))
                        for j in range(max(0, i - w),
                                       min(len(ids), i + w + 1)):
                            if j != i:
                                centers.append(c)
                                contexts.append(ids[j])
                if centers:
                    pair_writer.append({
                        "c": np.asarray(centers, np.int32),
                        "x": np.asarray(contexts, np.int32),
                    })
                    n_pairs += len(centers)
            pair_cache = pair_writer.finish()
        finally:
            shutil.rmtree(doc_dir, ignore_errors=True)
        if multi:
            from flinkml_tpu.iteration.stream_sync import gather_vectors

            total_pairs = int(round(gather_vectors(
                np.asarray([float(n_pairs)]), mesh
            ).sum()))
            if total_pairs == 0:
                raise ValueError(
                    "no (center, context) pairs on any process; documents "
                    "too short"
                )
        elif n_pairs == 0:
            raise ValueError("no (center, context) pairs; documents too short")

        # unigram^0.75 negative pool over the FINAL vocab (seed-only RNG
        # under multi-process — identical pool/init on every rank).
        rng_global = (
            np.random.default_rng(self.get_seed()) if multi else rng
        )
        freq = vocab_counts.astype(np.float64) ** 0.75
        pool = rng_global.choice(
            len(vocab), size=_NEG_POOL, p=freq / freq.sum()
        ).astype(np.int32)
        pool_dev = jnp.asarray(pool)

        dim = self.get(self.VECTOR_SIZE)
        batch_size = self.get(self.BATCH_SIZE)
        local_bs = max(1, batch_size // p)
        # Above the vocab threshold on a single-process multi-device
        # mesh, the streamed fit uses the same vocab-sharded ring
        # trainer as the in-RAM fit (the multi-PROCESS case was
        # rejected with guidance right after the vocabulary was final).
        use_sharded = p > 1 and len(vocab) > _shard_vocab_threshold()
        if use_sharded:
            shard_rows = -(-len(vocab) // p)
            vocab_pad = shard_rows * p
            trainer = _sgns_trainer_sharded(
                mesh.mesh, DeviceMesh.DATA_AXIS, local_bs,
                self.get(self.NUM_NEGATIVES), shard_rows,
                _exchange_strategy(), _kernels_segsum_backend(),
            )
        else:
            trainer = _sgns_trainer(
                mesh.mesh, DeviceMesh.DATA_AXIS, local_bs,
                self.get(self.NUM_NEGATIVES), _w2v_accum(),
                _kernels_segsum_backend(),
            )
        lr = jnp.asarray(self.get(self.LEARNING_RATE), jnp.float32)
        base_key = jax.random.PRNGKey(self.get_seed())
        tile = p * self._PAIR_TILE

        def place_vu(v_h, u_h):
            """Device placement of the embedding pair: replicated for the
            dense trainer, row-sharded (padded) for the ring trainer."""
            if not use_sharded:
                return jnp.asarray(v_h), jnp.asarray(u_h)
            pad = vocab_pad - len(vocab)
            z = np.zeros((pad, dim), np.float32)
            return (
                mesh.shard_batch(np.concatenate([v_h, z])),
                mesh.shard_batch(np.concatenate([u_h, z])),
            )

        u_h0 = np.zeros((len(vocab), dim), np.float32)
        start_epoch = 0
        if resume_epoch is None:
            v_h0 = (
                (rng_global.random((len(vocab), dim)) - 0.5)
                .astype(np.float32) / dim
            )
        else:
            like = (np.zeros((len(vocab), dim), np.float32),) * 2
            from flinkml_tpu.iteration.stream_sync import agreed_restore

            (v_h0, u_h0), start_epoch = agreed_restore(
                self.checkpoint_manager, resume_epoch, like, mesh
            )
        v, u = place_vu(v_h0, u_h0)

        from flinkml_tpu.parallel.dispatch import DispatchGuard

        guard = DispatchGuard()  # multi-process backpressure (no-op single)
        local_tile = (p // jax.process_count()) * self._PAIR_TILE
        max_iter = self.get(self.MAX_ITER)
        for epoch in range(start_epoch, max_iter):
            if multi:
                from flinkml_tpu.iteration.stream_sync import (
                    agree_max,
                    synced_stream,
                )

                # Data-proportional training intensity: distribute the
                # single-process per-epoch step budget (global pairs /
                # batch_size) evenly over the agreed dispatch count, so
                # dummy padding on skewed or drained ranks never
                # inflates the SGD step count over the real pairs.
                n_dispatch = max(1, agree_max(pair_cache.num_batches, mesh))
                steps = max(1, total_pairs // (batch_size * n_dispatch))
                # Agreed per-dispatch height (tiles ride the step
                # agreement), so every rank runs the same collectives;
                # drained ranks feed zero-weight dummy chunks.
                height_of = lambda b: -(-max(len(b["c"]), 1) // local_tile)
                for ci, (b, tiles) in enumerate(synced_stream(
                    pair_cache.reader(), mesh, payload=height_of
                )):
                    h = tiles * local_tile
                    if b is None:
                        c_p = np.zeros(h, np.int32)
                        x_p = np.zeros(h, np.int32)
                        w_p = np.zeros(h, np.float32)
                    else:
                        # Pad by CYCLING real pairs (a zero pad would be
                        # a genuine (0, 0) positive pair).
                        c_p, x_p = np.resize(b["c"], h), np.resize(b["x"], h)
                        w_p = np.ones(h, np.float32)
                    v, u = trainer(
                        mesh.global_batch(c_p), mesh.global_batch(x_p),
                        mesh.global_batch(w_p),
                        pool_dev, v, u, lr,
                        jnp.asarray(steps, jnp.int32),
                        jax.random.fold_in(
                            jax.random.fold_in(base_key, epoch), ci
                        ),
                    )
                    guard.after_dispatch(v)
            else:
                for ci, batch in enumerate(pair_cache.reader()):
                    c, x = batch["c"], batch["x"]
                    rows = max(tile, -(-len(c) // tile) * tile)
                    # Pad by CYCLING real pairs (a zero pad would be a
                    # genuine (0, 0) positive pair — see the in-RAM
                    # path's rationale).
                    c_p, x_p = np.resize(c, rows), np.resize(x, rows)
                    steps = max(1, len(c) // batch_size)
                    v, u = trainer(
                        mesh.shard_batch(c_p), mesh.shard_batch(x_p),
                        mesh.shard_batch(np.ones(rows, np.float32)),
                        pool_dev, v, u, lr, jnp.asarray(steps, jnp.int32),
                        jax.random.fold_in(
                            jax.random.fold_in(base_key, epoch), ci
                        ),
                    )
            if should_snapshot(self.checkpoint_manager,
                               self.checkpoint_interval, epoch + 1,
                               max_iter):
                # Slice off the shard padding rows (no-op unsharded) so
                # checkpoints are layout-independent.
                state = (
                    np.asarray(v)[: len(vocab)],
                    np.asarray(u)[: len(vocab)],
                )
                if multi:
                    from flinkml_tpu.iteration.checkpoint import (
                        save_replicated,
                    )

                    save_replicated(
                        self.checkpoint_manager, state, epoch + 1, mesh
                    )
                else:
                    self.checkpoint_manager.save(state, epoch + 1)
        guard.flush(v)

        model = Word2VecModel()
        model.copy_params_from(self)
        model._set(
            np.asarray(vocab, dtype=str),
            np.asarray(v, np.float64)[: len(vocab)],
        )
        return model


class Word2VecModel(_Word2VecParams, Model):
    def __init__(self):
        super().__init__()
        self._vocab: Optional[np.ndarray] = None
        self._vectors: Optional[np.ndarray] = None
        self._index: Dict[str, int] = {}

    def _set(self, vocab: np.ndarray, vectors: np.ndarray) -> None:
        self._vocab = vocab
        self._vectors = vectors
        self._index = {str(t): i for i, t in enumerate(vocab)}

    @property
    def vocabulary(self) -> np.ndarray:
        self._require()
        return self._vocab

    @property
    def vectors(self) -> np.ndarray:
        self._require()
        return self._vectors

    def set_model_data(self, *inputs: Table) -> "Word2VecModel":
        (table,) = inputs
        self._set(
            np.asarray(table.column("word"), dtype=str),
            np.asarray(table.column("vector"), np.float64),
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"word": self._vocab, "vector": self._vectors})]

    def _require(self) -> None:
        if self._vocab is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        """Document vector = mean of its in-vocabulary word vectors
        (zero vector when none are in vocabulary) — the upstream layout."""
        (table,) = inputs
        self._require()
        docs = _token_column(table, self.get(self.INPUT_COL))
        dim = self._vectors.shape[1]
        out = np.zeros((len(docs), dim))
        for i, toks in enumerate(docs):
            ids = [self._index[t] for t in map(str, toks) if t in self._index]
            if ids:
                out[i] = self._vectors[ids].mean(axis=0)
        return (table.with_column(self.get(self.OUTPUT_COL), out),)

    def find_synonyms(self, word: str, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k cosine-similar vocabulary words (one gemm + top_k)."""
        self._require()
        i = self._index.get(str(word))
        if i is None:
            raise ValueError(f"word {word!r} is not in the vocabulary")
        vecs = jnp.asarray(self._vectors, jnp.float32)
        norms = jnp.linalg.norm(vecs, axis=1) + 1e-12
        sims = (vecs @ vecs[i]) / (norms * norms[i])
        sims = sims.at[i].set(-jnp.inf)      # exclude the word itself
        vals, idx = jax.lax.top_k(sims, min(k, len(self._vocab) - 1))
        return self._vocab[np.asarray(idx)], np.asarray(vals)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"word": self._vocab, "vector": self._vectors}
        )

    @classmethod
    def load(cls, path: str) -> "Word2VecModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._set(arrays["word"].astype(str), arrays["vector"])
        return model
