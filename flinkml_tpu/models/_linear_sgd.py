"""Generic distributed SGD for linear models (dense and sparse).

One trainer serves LogisticRegression, LinearSVC, and LinearRegression: the
models differ only in ``d loss/d margin``, so the loss enters as a static
key selecting a margin-gradient function, and everything else — window
slicing, MXU matvec, ``psum``, proximal update, ``lax.while_loop``
termination — is shared. This is the TPU inversion of the reference's
``CacheDataAndDoTrain`` machinery (``LogisticRegression.java:334-397``);
see ``logistic_regression.py`` for the full mapping.

Losses (margins use labels y ∈ {0,1} mapped to ys = 2y-1 where relevant):
  - ``logistic``: loss = w·log(1+exp(-dot·ys)); matches
    ``LogisticGradient.java:50-96``.
  - ``hinge`` (LinearSVC): loss = w·max(0, 1 - dot·ys).
  - ``squared`` (LinearRegression): loss = w·(dot - y)²/2.

Regularization: L2 enters the gradient; L1 (elastic net) is applied as a
proximal soft-threshold after the gradient step — the "proximal SGD step"
of BASELINE.json config #3.

The sparse path consumes padded ELL batches (``flinkml_tpu.ops.sparse``):
forward = gather+row-sum, gradient = flat segment-sum scatter — the
Criteo-scale path (config #5).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.ops.losses import margin_terms as _margin_grad
from flinkml_tpu.ops.sparse import chunked_run_totals
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple

_LOSS_KEYS = ("logistic", "hinge", "squared")


_SPARSE_LAYOUTS = ("unsorted", "sorted", "cumsum")


def _sparse_layout() -> str:
    """Measured-default gate for the sparse gradient layout.

    Three candidates for the Criteo-scale gradient reduction (the step's
    dominant cost at dim ~1e6 — BASELINE.md "Sparse roofline"):

    - ``unsorted`` (default): one fused ``segment_sum`` per step. Round-4
      device A/B: 69.1 ms/step — the measured winner of the first two.
    - ``sorted`` (round-3 layout): pack-time per-window sort +
      ``indices_are_sorted=True``, at the cost of a per-step O(cells)
      random gather of the contributions. Round-4 device A/B: 90.9
      ms/step (0.76x) — the permutation gather costs more than the sort
      it removes. Kept for A/B repeatability.
    - ``cumsum`` (round-5 layout): cells pre-sorted by column at pack
      time WITH their values, so the step never touches a cells-sized
      random permutation: contributions = sorted values x a gather of
      ``mult`` from the [local_bs]-sized (VMEM-resident) table, segment
      totals = one associative scan + a gather at precomputed static run
      boundaries, and the only scatter left is ``<= distinct columns per
      window`` sorted unique adds into [dim] — O(cells) streaming passes
      instead of the per-step bitonic sort over every cell.

    ``FLINKML_TPU_SPARSE_LAYOUT`` selects; the legacy
    ``FLINKML_TPU_SORTED_SCATTER=1`` gate maps to ``sorted``. Numerics
    across layouts are pinned by ``tests/test_sparse_scale.py``
    (bit-exact for sorted/unsorted; allclose for cumsum, whose
    running-sum-difference changes f32 summation order)."""
    layout = os.environ.get("FLINKML_TPU_SPARSE_LAYOUT")
    if layout is not None:
        if layout not in _SPARSE_LAYOUTS:
            raise ValueError(
                f"FLINKML_TPU_SPARSE_LAYOUT={layout!r}: "
                f"expected one of {_SPARSE_LAYOUTS}"
            )
        return layout
    if os.environ.get("FLINKML_TPU_SORTED_SCATTER", "0") == "1":
        return "sorted"
    # No explicit gate: the measured default for this mesh (committed by
    # the autotune search; docs/development/compile_cache.md), falling
    # back to the historical "unsorted".
    from flinkml_tpu.autotune import tuned_default

    return tuned_default("sparse_layout", "unsorted",
                         allowed=_SPARSE_LAYOUTS)


def _segsum_backend() -> str:
    """The kernel-backend gate for the gradient scatter-accumulate
    (:mod:`flinkml_tpu.kernels`, site ``segment_sum``): env var >
    autotune table > ``"xla"``. Resolved at FIT time like
    :func:`_sparse_layout` and threaded through the trainer factories'
    lru keys, so flipping the gate re-keys the jitted trainer."""
    from flinkml_tpu import kernels

    return kernels.segsum_backend()


def _spmv_backend() -> str:
    """The kernel-backend gate for the forward ELL matvec
    (:mod:`flinkml_tpu.kernels`, site ``spmv``) — same fit-time
    resolution and lru-key threading as :func:`_segsum_backend`."""
    from flinkml_tpu import kernels

    return kernels.spmv_backend()


def _soft_threshold(x, t):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def _acc_dt(dt):
    """Reduction dtype: sub-f32 data accumulates in f32. A stepwise bf16
    sum saturates absurdly early (32768 unit weights sum to 256), which
    would corrupt ``step_size = lr / wsum`` and the loss criterion."""
    return jnp.float32 if jnp.dtype(dt).itemsize < 4 else jnp.dtype(dt)


def align_local_bs(global_batch_size: int, p_size: int, n_local: int) -> int:
    """Per-device batch: ceil(global/p), clamped to the shard — the
    requested batch is honored exactly, no silent inflation."""
    return min(max(1, math.ceil(global_batch_size / p_size)), n_local)


def _window(arr, epoch, local_bs):
    """Contiguous rotating window with ceil coverage (tail included via
    dynamic_slice clamping)."""
    n_windows = max(-(-arr.shape[0] // local_bs), 1)
    start = (jnp.asarray(epoch, jnp.int32) % n_windows) * local_bs
    zero = jnp.zeros((), dtype=start.dtype)
    if arr.ndim == 1:
        return jax.lax.dynamic_slice(arr, (start,), (local_bs,))
    return jax.lax.dynamic_slice(arr, (start, zero), (local_bs, arr.shape[1]))


def make_dense_step(loss: str, local_bs: int, axis: str):
    """Per-device epoch: window → margin grad on MXU → psum → prox update.

    A hand-fused Pallas version of this step was measured LOSING to this
    plain lowering at every shape (0.70-0.82x; BASELINE.md "Kernel-path
    verdict") and was removed — XLA's forward + back-product pair is the
    fast path on current TPU generations."""

    def step(coef, epoch, xl, yl, wl, learning_rate, reg_l2, reg_l1):
        xb = _window(xl, epoch, local_bs)
        yb = _window(yl, epoch, local_bs)
        wb = _window(wl, epoch, local_bs)
        acc = _acc_dt(xb.dtype)
        dot = xb @ coef
        mult, per_ex = _margin_grad(loss, dot, yb, wb)
        grad_l = xb.T @ mult
        loss_l = jnp.sum(per_ex.astype(acc))
        wsum_l = jnp.sum(wb.astype(acc))
        grad = jax.lax.psum(grad_l, axis)
        loss_sum = jax.lax.psum(loss_l, axis)
        wsum = jax.lax.psum(wsum_l, axis)
        grad = grad + 2.0 * reg_l2 * coef
        loss_sum = loss_sum + reg_l2 * jnp.sum(jnp.square(coef.astype(acc)))
        step_size = learning_rate.astype(acc) / wsum
        new_coef = _soft_threshold(
            coef - step_size.astype(coef.dtype) * grad,
            step_size.astype(coef.dtype) * reg_l1,
        )
        return new_coef, (loss_sum / wsum).astype(coef.dtype)

    return step


def make_sparse_step(loss: str, local_bs: int, axis: str, dim: int,
                     segsum_backend: str = "xla",
                     spmv_backend: str = "xla"):
    """Sparse (padded-ELL) variant: gather forward, segment-sum gradient.

    ``segsum_backend`` selects the scatter-accumulate lowering and
    ``spmv_backend`` the forward matvec lowering (XLA or the Pallas
    kernels, :mod:`flinkml_tpu.kernels`); each resolved ONCE at fit
    time and threaded through the trainer factories' lru keys so a
    gate flip re-keys the jitted step."""
    from flinkml_tpu import kernels

    def step(coef, epoch, idxl, vall, yl, wl, learning_rate, reg_l2, reg_l1):
        ib = _window(idxl, epoch, local_bs)
        vb = _window(vall, epoch, local_bs)
        yb = _window(yl, epoch, local_bs)
        wb = _window(wl, epoch, local_bs)
        acc = _acc_dt(vb.dtype)
        dot = kernels.spmv(ib, vb, coef, backend=spmv_backend)
        mult, per_ex = _margin_grad(loss, dot, yb, wb)
        contrib = (vb * mult[:, None]).reshape(-1)
        grad_local = kernels.segment_sum(
            contrib, ib.reshape(-1), dim, backend=segsum_backend
        )
        grad = jax.lax.psum(grad_local, axis)
        loss_sum = jax.lax.psum(jnp.sum(per_ex.astype(acc)), axis)
        wsum = jax.lax.psum(jnp.sum(wb.astype(acc)), axis)
        grad = grad + 2.0 * reg_l2 * coef
        loss_sum = loss_sum + reg_l2 * jnp.sum(jnp.square(coef.astype(acc)))
        step_size = learning_rate.astype(acc) / wsum
        new_coef = _soft_threshold(
            coef - step_size.astype(coef.dtype) * grad,
            step_size.astype(coef.dtype) * reg_l1,
        )
        return new_coef, (loss_sum / wsum).astype(coef.dtype)

    return step


_SPARSE_ARGS_PER_BUCKET = {"unsorted": 4, "sorted": 6, "cumsum": 8}


def make_sparse_step_bucketed(loss: str, local_bss: Tuple[int, ...],
                              axis: str, dim: int,
                              layout: str = "unsorted",
                              segsum_backend: str = "xla",
                              spmv_backend: str = "xla"):
    """nnz-bucketed sparse step: one window per bucket, fused scatters.

    The batch is stratified across the nnz buckets (``ops.sparse.
    pack_ell_buckets``): each bucket contributes a window sized
    proportionally to its row count, so every step sees a representative
    nnz mix and every epoch covers every bucket's rows.

    ``layout`` selects the gradient reduction (measured A/B history in
    :func:`_sparse_layout`):

    - ``unsorted``: one fused ``segment_sum`` over every bucket's cells —
      XLA's lowering pays a per-step bitonic sort over all cells.
    - ``sorted`` (round-3): pack-time per-window sort + ``indices_are_
      sorted=True``; the step pays an O(cells) random permutation gather
      of the contributions instead (round-4 device A/B: the gather costs
      MORE than the sort it removes — 0.76x).
    - ``cumsum`` (round-5): the pack step stores each window's cells
      column-sorted WITH their values and row indices
      (:func:`_window_cumsum_tables`), so the step is sort-free AND
      cells-sized-gather-free: contributions come from ``svals * mult[
      srows]`` (``mult`` is a [local_bs] table — VMEM-resident), segment
      totals from one running sum differenced at the precomputed run
      boundaries, and the only scatter is ``<= max_d`` ascending unique
      column adds. Every cells-sized op is a streaming pass.
    """

    from flinkml_tpu import kernels

    def step(coef, epoch, blocks, learning_rate, reg_l2, reg_l1):
        acc = _acc_dt(coef.dtype)
        per_bucket = _SPARSE_ARGS_PER_BUCKET[layout]

        def window_of(table2d, ep):
            n_windows, width = table2d.shape
            wnum = jnp.asarray(ep, jnp.int32) % n_windows
            return jax.lax.dynamic_slice(
                table2d, (wnum, jnp.zeros((), jnp.int32)), (1, width)
            ).reshape(-1)

        contribs, flat_idx = [], []
        grad_local = jnp.zeros((dim,), coef.dtype)
        loss_l = jnp.zeros((), acc)
        wsum_l = jnp.zeros((), acc)
        for b, local_bs in enumerate(local_bss):
            block = blocks[per_bucket * b : per_bucket * (b + 1)]
            idxl, vall, yl, wl = block[:4]
            ib = _window(idxl, epoch, local_bs)
            vb = _window(vall, epoch, local_bs)
            yb = _window(yl, epoch, local_bs)
            wb = _window(wl, epoch, local_bs)
            dot = kernels.spmv(ib, vb, coef, backend=spmv_backend)
            mult, per_ex = _margin_grad(loss, dot, yb, wb)
            if layout == "sorted":
                contrib = (vb * mult[:, None]).reshape(-1)
                perm_w = window_of(block[4], epoch)
                sids_w = window_of(block[5], epoch)
                grad_local = grad_local + kernels.segment_sum(
                    jnp.take(contrib, perm_w), sids_w, dim,
                    indices_are_sorted=True, backend=segsum_backend,
                )
            elif layout == "cumsum":
                srowsl, svalsl, endsl, colsl = block[4:]
                srows_w = window_of(srowsl, epoch)
                svals_w = window_of(svalsl, epoch)
                ends_w = window_of(endsl, epoch)
                cols_w = window_of(colsl, epoch)
                contrib = svals_w * jnp.take(mult, srows_w)
                seg = chunked_run_totals(contrib.astype(acc), ends_w)
                grad_local = grad_local.at[cols_w].add(
                    seg.astype(coef.dtype), indices_are_sorted=True,
                )
            else:
                contrib = (vb * mult[:, None]).reshape(-1)
                contribs.append(contrib)
                flat_idx.append(ib.reshape(-1))
            loss_l = loss_l + jnp.sum(per_ex.astype(acc))
            wsum_l = wsum_l + jnp.sum(wb.astype(acc))
        if layout == "unsorted":
            grad_local = kernels.segment_sum(
                jnp.concatenate(contribs), jnp.concatenate(flat_idx),
                dim, backend=segsum_backend,
            )
        grad = jax.lax.psum(grad_local, axis)
        loss_sum = jax.lax.psum(loss_l, axis)
        wsum = jax.lax.psum(wsum_l, axis)
        grad = grad + 2.0 * reg_l2 * coef
        loss_sum = loss_sum + reg_l2 * jnp.sum(jnp.square(coef.astype(acc)))
        step_size = learning_rate.astype(acc) / wsum
        new_coef = _soft_threshold(
            coef - step_size.astype(coef.dtype) * grad,
            step_size.astype(coef.dtype) * reg_l1,
        )
        return new_coef, (loss_sum / wsum).astype(coef.dtype)

    return step


@functools.lru_cache(maxsize=128)
def _sparse_trainer_bucketed(mesh, loss: str, local_bss: Tuple[int, ...],
                             axis: str, dim: int,
                             layout: str = "unsorted",
                             segsum_backend: str = "xla",
                             spmv_backend: str = "xla"):
    """Bucketed counterpart of :func:`_sparse_trainer` — same carry-style
    contract; the data args are ``k·len(local_bss)`` sharded arrays where
    ``k = _SPARSE_ARGS_PER_BUCKET[layout]`` (indices, values, y, w, plus
    the layout's pack-time tables). ``segsum_backend`` and
    ``spmv_backend`` are lru-key material: an XLA-kernel trainer and a
    Pallas-kernel trainer never alias one jitted program."""
    local_step = make_sparse_step_bucketed(
        loss, local_bss, axis, dim, layout, segsum_backend, spmv_backend
    )
    n_args = _SPARSE_ARGS_PER_BUCKET[layout] * len(local_bss)

    def per_device(coef, epoch, cur_loss, *rest):
        blocks = rest[:n_args]
        learning_rate, reg_l2, reg_l1, tol, epoch_end = rest[n_args:]

        def cond(carry):
            _, ep, cur = carry
            return jnp.logical_and(ep < epoch_end, cur > tol)

        def body(carry):
            c, ep, _ = carry
            new_coef, mean_loss = local_step(
                c, ep, blocks, learning_rate, reg_l2, reg_l1
            )
            return new_coef, ep + 1, mean_loss

        return jax.lax.while_loop(cond, body, (coef, epoch, cur_loss))

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(), P()) + (P(axis),) * n_args + (P(),) * 5,
            out_specs=(P(), P(), P()),
        )
    )


@functools.lru_cache(maxsize=128)
def _dense_trainer(mesh, loss: str, local_bs: int, axis: str):
    """Carry-style whole-loop trainer: runs epochs from ``epoch`` up to
    ``epoch_end`` (or until ``loss <= tol``) entirely on device and returns
    the full carry ``(coef, epoch, loss)``.

    Because the carry and ``epoch_end`` are runtime values, the SAME
    compiled executable serves both the one-dispatch fit (epoch_end =
    max_iter) and the chunked fault-tolerant fit (K epochs per dispatch,
    carry snapshot between dispatches) — so a chunked/resumed run is
    bit-identical to the uninterrupted run by construction. This is the
    TPU-native answer to the reference's always-on mid-iteration
    checkpointing (``Checkpoints.java:43-211``): the unit of recovery is
    the dispatch, and the only state is the carry."""
    local_step = make_dense_step(loss, local_bs, axis)

    def per_device(coef, epoch, cur_loss, xl, yl, wl,
                   learning_rate, reg_l2, reg_l1, tol, epoch_end):
        def cond(carry):
            _, ep, cur = carry
            return jnp.logical_and(ep < epoch_end, cur > tol)

        def body(carry):
            c, ep, _ = carry
            new_coef, mean_loss = local_step(
                c, ep, xl, yl, wl, learning_rate, reg_l2, reg_l1
            )
            return new_coef, ep + 1, mean_loss

        return jax.lax.while_loop(cond, body, (coef, epoch, cur_loss))

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(axis), P(axis), P(axis),
                      P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P()),
        )
    )


@functools.lru_cache(maxsize=128)
def _sparse_trainer(mesh, loss: str, local_bs: int, axis: str, dim: int,
                    segsum_backend: str = "xla",
                    spmv_backend: str = "xla"):
    """Sparse counterpart of :func:`_dense_trainer` — same carry-style
    contract (see there for the chunked-checkpointing rationale).
    ``segsum_backend``/``spmv_backend`` are lru-key material (kernel
    gate idiom)."""
    local_step = make_sparse_step(loss, local_bs, axis, dim,
                                  segsum_backend, spmv_backend)

    def per_device(coef, epoch, cur_loss, idxl, vall, yl, wl,
                   learning_rate, reg_l2, reg_l1, tol, epoch_end):
        def cond(carry):
            _, ep, cur = carry
            return jnp.logical_and(ep < epoch_end, cur > tol)

        def body(carry):
            c, ep, _ = carry
            new_coef, mean_loss = local_step(
                c, ep, idxl, vall, yl, wl, learning_rate, reg_l2, reg_l1
            )
            return new_coef, ep + 1, mean_loss

        return jax.lax.while_loop(cond, body, (coef, epoch, cur_loss))

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(axis), P(axis), P(axis), P(axis),
                      P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P()),
        )
    )


def _restore_carry(checkpoint_manager, dim: int, dtype, mesh=None):
    """Restore the latest ``(coef, loss)`` carry; returns
    ``(coef_host, epoch, loss)`` or None. One definition shared by the
    dense chunked path and the stream path so the checkpoint payload shape
    can never silently diverge between them.

    Restores through :func:`stream_sync.agreed_restore_latest` so a
    rank-local failure aborts every rank instead of stranding the peers
    in the training collectives; a ``None`` return means genuinely no
    checkpoint."""
    from flinkml_tpu.iteration.stream_sync import agreed_restore_latest

    like = (np.zeros(dim, dtype=np.dtype(dtype)), np.float64(0.0))
    restored = agreed_restore_latest(
        checkpoint_manager, like, mesh, "checkpoint restore (latest carry)"
    )
    if restored is None:
        return None
    (coef_h, loss_h), epoch = restored
    return coef_h, int(epoch), float(loss_h)


def _run_chunked(
    trainer,
    data_args: Tuple,
    dim: int,
    dt,
    learning_rate: float,
    reg_l2: float,
    reg_l1: float,
    tol: float,
    max_iter: int,
    mesh: DeviceMesh,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
) -> np.ndarray:
    """Drive a carry-style trainer in K-epoch dispatches with carry
    snapshots between dispatches.

    - No checkpoint manager (or interval 0): ONE dispatch runs the whole
      loop — the fastest path, unchanged.
    - With a manager + interval K: each dispatch runs K epochs, then the
      carry ``(coef, loss)`` is snapshotted at its epoch. Failure loses at
      most one chunk; ``resume=True`` restores the carry and re-enters the
      same executable, so the resumed trajectory is exactly the
      uninterrupted one (reference contract: ``Checkpoints.java:43-211``
      exactly-once feedback logging → here, bit-exact carry replay).
    - ``listeners`` fire at chunk boundaries (epoch granularity requires
      the host loop in ``iterate``; the device loop surfaces only chunk
      boundaries to the host).
    """
    from flinkml_tpu.iteration.checkpoint import begin_resume

    resume_epoch = begin_resume(checkpoint_manager, resume, mesh.mesh.size)
    coef = jnp.zeros(dim, dtype=dt)
    epoch = 0
    cur_loss = float("inf")
    if resume_epoch is not None:
        coef_h, epoch, cur_loss = _restore_carry(
            checkpoint_manager, dim, dt, mesh
        )
        coef = jnp.asarray(coef_h, dt)

    chunk = (
        checkpoint_interval
        if checkpoint_manager is not None and checkpoint_interval > 0
        else max_iter
    )
    hy = (
        jnp.asarray(learning_rate, dt),
        jnp.asarray(reg_l2, dt),
        jnp.asarray(reg_l1, dt),
        jnp.asarray(tol, dt),
    )
    while epoch < max_iter and cur_loss > tol:
        epoch_end = min(epoch + chunk, max_iter)
        coef, ep_dev, loss_dev = trainer(
            coef, jnp.asarray(epoch, jnp.int32), jnp.asarray(cur_loss, dt),
            *data_args, *hy, jnp.asarray(epoch_end, jnp.int32),
        )
        epoch = int(ep_dev)
        cur_loss = float(loss_dev)
        coef_host = np.asarray(coef)
        if checkpoint_manager is not None:
            checkpoint_manager.save((coef_host, np.float64(cur_loss)), epoch)
        for listener in listeners:
            listener.on_epoch_watermark_incremented(epoch - 1, coef_host)
    result = np.asarray(coef)
    if checkpoint_manager is not None:
        # Drain any in-flight async write so a failed final snapshot
        # surfaces here, not silently at interpreter exit.
        checkpoint_manager.wait()
    for listener in listeners:
        listener.on_iteration_terminated(result)
    return result


def train_linear_model(
    x: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    global_batch_size: int,
    reg: float,
    elastic_net: float,
    tol: float,
    seed: int,
    dtype=None,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
    sharding_plan=None,
    precision=None,
) -> np.ndarray:
    """Dense distributed training; returns the coefficient on host.

    ``reg``/``elastic_net`` follow the sklearn/Spark convention:
    l1 = reg * elastic_net, l2 = reg * (1 - elastic_net).

    With ``checkpoint_manager`` + ``checkpoint_interval`` K, training runs
    in K-epoch device dispatches with a carry snapshot after each — the
    fast whole-loop-on-device path IS the fault-tolerant path (see
    :func:`_run_chunked`). ``resume=True`` continues exactly from the
    latest snapshot.

    ``sharding_plan`` (a :class:`~flinkml_tpu.sharding.plan.
    ShardingPlan`) routes the fit through the plan-sharded trainer
    (:func:`flinkml_tpu.sharding.apply.train_linear_plan`): parameters
    and optimizer state shard per the plan (FSDP-style), batches along
    the plan's batch axes, checkpoints carry plan-derived layout tags.
    The plan path trains with momentum SGD over the same seeded row
    order — convergence-equivalent to (not bit-identical with) the
    replicated trainer. A mesh lacking the plan's axes is re-shaped
    over the same devices via :meth:`DeviceMesh.for_plan`.

    ``precision`` (a :class:`~flinkml_tpu.precision.PrecisionPolicy`,
    preset name, or policy JSON dict) declares the mixed-precision
    contract and routes the fit through the policy-gated plan trainer
    (under the ``replicated`` plan when no ``sharding_plan`` is given):
    the step's jaxpr is validated against the policy BEFORE any compile
    by the FML6xx precision-flow pass — see
    ``docs/development/precision.md``.
    """
    if loss not in _LOSS_KEYS:
        raise ValueError(f"loss must be one of {_LOSS_KEYS}, got {loss!r}")
    n = x.shape[0]
    if n == 0:
        raise ValueError("training table is empty")
    if precision is not None and sharding_plan is None:
        # The policy-gated step lives on the plan path; REPLICATED is
        # the plan-shaped spelling of "no sharding".
        from flinkml_tpu.sharding.plan import REPLICATED

        sharding_plan = REPLICATED
    if sharding_plan is not None:
        from flinkml_tpu.sharding.apply import train_linear_plan

        if listeners:
            raise ValueError(
                "listeners are not supported on the plan-sharded path"
            )
        if any(a not in mesh.mesh.shape
               for a in sharding_plan.required_axes()):
            mesh = DeviceMesh.for_plan(
                sharding_plan,
                devices=list(mesh.mesh.devices.reshape(-1)),
            )
        perm = np.random.default_rng(seed).permutation(n)
        return train_linear_plan(
            x[perm], y[perm], w[perm], sharding_plan, mesh, loss=loss,
            max_iter=max_iter, learning_rate=learning_rate,
            global_batch_size=global_batch_size, reg=reg,
            elastic_net=elastic_net, tol=tol, dtype=dtype,
            precision=precision,
            checkpoint_manager=checkpoint_manager,
            checkpoint_interval=checkpoint_interval, resume=resume,
        )
    p_size = mesh.axis_size()
    if dtype is not None:
        x, y, w = x.astype(dtype), y.astype(dtype), w.astype(dtype)
    perm = np.random.default_rng(seed).permutation(n)
    x, y, w = x[perm], y[perm], w[perm]
    row_tile = p_size  # pad exactly to the mesh: identical windows always
    x_pad, _ = pad_to_multiple(x, row_tile)
    y_pad, _ = pad_to_multiple(y, row_tile)
    w_pad, _ = pad_to_multiple(w, row_tile)
    xd = mesh.shard_batch(x_pad)
    yd = mesh.shard_batch(y_pad)
    wd = mesh.shard_batch(w_pad)
    n_local = xd.shape[0] // p_size
    local_bs = align_local_bs(global_batch_size, p_size, n_local)
    trainer = _dense_trainer(mesh.mesh, loss, local_bs, DeviceMesh.DATA_AXIS)
    return _run_chunked(
        trainer, (xd, yd, wd), x.shape[1], xd.dtype,
        learning_rate, reg * (1.0 - elastic_net), reg * elastic_net,
        tol, max_iter, mesh,
        checkpoint_manager=checkpoint_manager,
        checkpoint_interval=checkpoint_interval,
        resume=resume, listeners=listeners,
    )


def train_linear_model_sparse(
    indices: np.ndarray,
    values: np.ndarray,
    dim: int,
    y: np.ndarray,
    w: np.ndarray,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    global_batch_size: int,
    reg: float,
    elastic_net: float,
    tol: float,
    seed: int,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
) -> np.ndarray:
    """Sparse (padded-ELL rows) distributed training — the Criteo-scale
    path: per-step cost scales with nnz, the model stays a dense [dim]
    array updated by segment-sum scatter-adds. Chunked checkpointing as in
    :func:`train_linear_model`."""
    if loss not in _LOSS_KEYS:
        raise ValueError(f"loss must be one of {_LOSS_KEYS}, got {loss!r}")
    n = indices.shape[0]
    if n == 0:
        raise ValueError("training table is empty")
    p_size = mesh.axis_size()
    perm = np.random.default_rng(seed).permutation(n)
    indices, values, y, w = indices[perm], values[perm], y[perm], w[perm]
    idx_pad, _ = pad_to_multiple(indices, p_size)
    val_pad, _ = pad_to_multiple(values, p_size)
    y_pad, _ = pad_to_multiple(y, p_size)
    w_pad, _ = pad_to_multiple(w, p_size)
    idxd = mesh.shard_batch(idx_pad)
    vald = mesh.shard_batch(val_pad)
    yd = mesh.shard_batch(y_pad)
    wd = mesh.shard_batch(w_pad)
    n_local = idxd.shape[0] // p_size
    local_bs = min(max(1, math.ceil(global_batch_size / p_size)), n_local)
    trainer = _sparse_trainer(
        mesh.mesh, loss, local_bs, DeviceMesh.DATA_AXIS, int(dim),
        _segsum_backend(), _spmv_backend(),
    )
    return _run_chunked(
        trainer, (idxd, vald, yd, wd), int(dim), vald.dtype,
        learning_rate, reg * (1.0 - elastic_net), reg * elastic_net,
        tol, max_iter, mesh,
        checkpoint_manager=checkpoint_manager,
        checkpoint_interval=checkpoint_interval,
        resume=resume, listeners=listeners,
    )


def _window_sort_tables(
    idx_pad: np.ndarray, p_size: int, local_bs: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-device, per-window scatter sort tables for the sorted-scatter
    layout: ``(perm, sorted_ids)``, each ``[p * n_windows, local_bs *
    width]``, sharded so device d sees its own ``[n_windows, cells]``.

    Window w on a device covers local rows ``min(w·bs, n_local−bs) ..
    +bs`` — exactly :func:`_window`'s clamped rotating tile — and its
    flattened cells are argsorted by column id once here, so the step's
    ``segment_sum`` can assert ``indices_are_sorted``.
    """
    n_total, width = idx_pad.shape
    n_local = n_total // p_size
    n_windows = max(-(-n_local // local_bs), 1)
    cells = local_bs * width
    perm = np.empty((p_size * n_windows, cells), np.int32)
    sids = np.empty((p_size * n_windows, cells), np.int32)
    for d in range(p_size):
        shard = idx_pad[d * n_local:(d + 1) * n_local]
        for wnum in range(n_windows):
            start = min(wnum * local_bs, max(n_local - local_bs, 0))
            flat = shard[start:start + local_bs].reshape(-1)
            order = np.argsort(flat, kind="stable").astype(np.int32)
            row = d * n_windows + wnum
            perm[row] = order
            sids[row] = flat[order]
    return perm, sids


def _window_cumsum_tables(
    idx_pad: np.ndarray, val_pad: np.ndarray, p_size: int, local_bs: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Per-device, per-window tables for the ``cumsum`` sparse layout:
    ``(srows, svals, ends, cols)``.

    Window w on a device covers local rows ``min(w·bs, n_local−bs) ..
    +bs`` (exactly :func:`_window`'s clamped rotating tile). Its
    flattened cells are sorted by column id ONCE here, and the step
    consumes them without any cells-sized permutation:

    - ``srows [p·n_windows, cells] int32``: the within-window ROW of each
      sorted cell — the step gathers ``mult`` (a [local_bs] table) by it.
    - ``svals [p·n_windows, cells] f32``: the cell values, pre-sorted.
    - ``ends [p·n_windows, max_d] int32``: inclusive cell index of each
      column run's last cell, padded by repeating ``cells−1`` (the
      running-sum difference of a repeated boundary is 0).
    - ``cols [p·n_windows, max_d] int32``: the column id of each run,
      ascending; padding repeats the last real column id, whose repeated
      boundary contributes exactly 0.

    ``max_d`` is the max distinct-column count over every (device,
    window) so the stacked array is rectangular.
    """
    n_total, width = idx_pad.shape
    n_local = n_total // p_size
    n_windows = max(-(-n_local // local_bs), 1)
    cells = local_bs * width
    srows = np.empty((p_size * n_windows, cells), np.int32)
    svals = np.empty((p_size * n_windows, cells), val_pad.dtype)
    per_window = []
    for d in range(p_size):
        ishard = idx_pad[d * n_local:(d + 1) * n_local]
        vshard = val_pad[d * n_local:(d + 1) * n_local]
        for wnum in range(n_windows):
            start = min(wnum * local_bs, max(n_local - local_bs, 0))
            flat_i = ishard[start:start + local_bs].reshape(-1)
            flat_v = vshard[start:start + local_bs].reshape(-1)
            order = np.argsort(flat_i, kind="stable")
            sids = flat_i[order]
            row = d * n_windows + wnum
            srows[row] = (order // width).astype(np.int32)
            svals[row] = flat_v[order]
            # Inclusive run ends: positions where the sorted id changes.
            is_end = np.empty(cells, np.bool_)
            is_end[:-1] = sids[:-1] != sids[1:]
            is_end[-1] = True
            e = np.nonzero(is_end)[0].astype(np.int32)
            per_window.append((row, e, sids[e]))
    max_d = max(e.size for _, e, _ in per_window)
    ends = np.full((p_size * n_windows, max_d), cells - 1, np.int32)
    cols = np.empty((p_size * n_windows, max_d), np.int32)
    for row, e, c in per_window:
        ends[row, : e.size] = e
        cols[row, : e.size] = c
        # Pad runs repeat the LAST real run's end (difference 0) and dump
        # their zero contribution onto the last real column id — harmless
        # (adds 0) and keeps the ids ascending for the sorted scatter.
        cols[row, e.size:] = c[-1] if c.size else 0
    return srows, svals, ends, cols


def prepare_sparse_buckets(
    indptr, indices, values, dim: int, y, w, mesh: DeviceMesh,
    global_batch_size: int, max_buckets: int = 4, dtype=np.float32,
    seed: Optional[int] = None, layout: str = "unsorted",
) -> Tuple[Tuple, Tuple[int, ...]]:
    """Pack, shuffle, pad, and shard CSR data for the bucketed trainer.

    Returns ``(data_args, local_bss)``: the flat per-bucket sharded arrays
    (indices, values, y, w[, window-sort perm, sorted ids] per bucket) and
    each bucket's per-device window size (proportional share of
    ``global_batch_size``, ≥ 1). The single source of the batching policy
    — the bench measures exactly what the product trains with.

    ``seed`` shuffles rows *within* each bucket (bucket membership depends
    only on nnz, so this is the reference's partition shuffle applied
    post-bucketing — no re-gather of the full CSR needed).
    ``layout`` selects the gradient-reduction layout (see
    :func:`_sparse_layout`): ``sorted`` adds the per-window sort tables
    (+8 B/cell of HBM), ``cumsum`` the sorted-cell value/row tables and
    run boundaries (+12 B/cell) that remove the per-step cells-sized
    sort AND permutation gather (see ``make_sparse_step_bucketed``).
    """
    from flinkml_tpu.ops.sparse import pack_ell_buckets

    indptr = np.asarray(indptr, dtype=np.int64)
    n = indptr.size - 1
    y = np.asarray(y, dtype=dtype)
    w = np.asarray(w, dtype=dtype)
    p_size = mesh.axis_size()
    buckets, row_ids = pack_ell_buckets(
        indptr, indices, values, dim, max_buckets=max_buckets, dtype=dtype,
    )
    rng = np.random.default_rng(seed) if seed is not None else None
    data_args: list = []
    local_bss: list = []
    for bucket, rows in zip(buckets, row_ids):
        bi, bv = bucket["indices"], bucket["values"]
        if rng is not None:
            order = rng.permutation(rows.size)
            bi, bv, rows = bi[order], bv[order], rows[order]
        idx_pad, _ = pad_to_multiple(bi, p_size)
        val_pad, _ = pad_to_multiple(bv, p_size)
        yb_pad, _ = pad_to_multiple(y[rows], p_size)
        wb_pad, _ = pad_to_multiple(w[rows], p_size)
        data_args += [
            mesh.shard_batch(idx_pad), mesh.shard_batch(val_pad),
            mesh.shard_batch(yb_pad), mesh.shard_batch(wb_pad),
        ]
        n_local = idx_pad.shape[0] // p_size
        share = max(1, math.ceil(global_batch_size * rows.size / (n * p_size)))
        local_bs = min(share, n_local)
        local_bss.append(local_bs)
        if layout == "sorted":
            perm, sids = _window_sort_tables(idx_pad, p_size, local_bs)
            data_args += [mesh.shard_batch(perm), mesh.shard_batch(sids)]
        elif layout == "cumsum":
            srows, svals, ends, cols = _window_cumsum_tables(
                idx_pad, val_pad, p_size, local_bs
            )
            data_args += [
                mesh.shard_batch(srows), mesh.shard_batch(svals),
                mesh.shard_batch(ends), mesh.shard_batch(cols),
            ]
    return tuple(data_args), tuple(local_bss)


def train_linear_model_sparse_csr(
    indptr: np.ndarray,
    indices: np.ndarray,
    values: np.ndarray,
    dim: int,
    y: np.ndarray,
    w: np.ndarray,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    global_batch_size: int,
    reg: float,
    elastic_net: float,
    tol: float,
    seed: int,
    max_buckets: int = 4,
    dtype=np.float32,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
) -> np.ndarray:
    """Skew-proof sparse training from host CSR arrays.

    Replaces the uniform padded-ELL layout (pad every row to the dataset
    max nnz — pathological under skewed nnz, round-1 VERDICT "weak" #3)
    with nnz-bucketed ELL blocks (``ops.sparse.pack_ell_buckets``): total
    padded cells ≈ total nnz, so HBM cost scales with the data, not with
    the worst row. Each step takes a proportional window from every
    bucket (stratified batch); with batch ≥ n this is exactly the
    full-dataset gradient, so results match the uniform path bit-for-bit
    up to summation order.
    """
    if loss not in _LOSS_KEYS:
        raise ValueError(f"loss must be one of {_LOSS_KEYS}, got {loss!r}")
    n = np.asarray(indptr).size - 1
    if n == 0:
        raise ValueError("training table is empty")
    layout = _sparse_layout()
    data_args, local_bss = prepare_sparse_buckets(
        indptr, indices, values, dim, y, w, mesh, global_batch_size,
        max_buckets=max_buckets, dtype=dtype, seed=seed,
        layout=layout,
    )
    trainer = _sparse_trainer_bucketed(
        mesh.mesh, loss, tuple(local_bss), DeviceMesh.DATA_AXIS, int(dim),
        layout, _segsum_backend(), _spmv_backend(),
    )
    return _run_chunked(
        trainer, tuple(data_args), int(dim), jnp.dtype(dtype),
        learning_rate, reg * (1.0 - elastic_net), reg * elastic_net,
        tol, max_iter, mesh,
        checkpoint_manager=checkpoint_manager,
        checkpoint_interval=checkpoint_interval,
        resume=resume, listeners=listeners,
    )


def make_softmax_step(num_classes: int, local_bs: int, axis: str):
    """Multinomial (softmax) step: logits on the MXU, cross-entropy on
    the VPU, gradient ``(p - onehot)ᵀ·x`` back on the MXU. The model is a
    ``[k, d]`` matrix; same update rule as the binomial trainer
    (``coef -= lr/weightSum · grad``)."""

    def step(coef, epoch, xl, yl, wl, learning_rate, reg_l2, reg_l1):
        xb = _window(xl, epoch, local_bs)
        yb = _window(yl, epoch, local_bs)
        wb = _window(wl, epoch, local_bs)
        acc = _acc_dt(xb.dtype)
        logits = xb @ coef.T                             # [bs, k]
        logp = jax.nn.log_softmax(logits, axis=-1)
        onehot = jax.nn.one_hot(
            yb.astype(jnp.int32), num_classes, dtype=xb.dtype
        )
        per_ex = -jnp.sum(onehot * logp, axis=-1) * wb
        mult = (jnp.exp(logp) - onehot) * wb[:, None]    # [bs, k]
        grad_l = mult.T @ xb                             # [k, d]
        grad = jax.lax.psum(grad_l, axis)
        loss_sum = jax.lax.psum(jnp.sum(per_ex.astype(acc)), axis)
        wsum = jax.lax.psum(jnp.sum(wb.astype(acc)), axis)
        grad = grad + 2.0 * reg_l2 * coef
        loss_sum = loss_sum + reg_l2 * jnp.sum(jnp.square(coef.astype(acc)))
        step_size = learning_rate.astype(acc) / wsum
        new_coef = _soft_threshold(
            coef - step_size.astype(coef.dtype) * grad,
            step_size.astype(coef.dtype) * reg_l1,
        )
        return new_coef, (loss_sum / wsum).astype(coef.dtype)

    return step


@functools.lru_cache(maxsize=128)
def _softmax_trainer(mesh, num_classes: int, local_bs: int, axis: str):
    """Carry-style whole-loop softmax trainer — same contract as
    :func:`_dense_trainer` (chunked checkpointing included)."""
    local_step = make_softmax_step(num_classes, local_bs, axis)

    def per_device(coef, epoch, cur_loss, xl, yl, wl,
                   learning_rate, reg_l2, reg_l1, tol, epoch_end):
        def cond(carry):
            _, ep, cur = carry
            return jnp.logical_and(ep < epoch_end, cur > tol)

        def body(carry):
            c, ep, _ = carry
            new_coef, mean_loss = local_step(
                c, ep, xl, yl, wl, learning_rate, reg_l2, reg_l1
            )
            return new_coef, ep + 1, mean_loss

        return jax.lax.while_loop(cond, body, (coef, epoch, cur_loss))

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(axis), P(axis), P(axis),
                      P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P()),
        )
    )


def train_softmax_model(
    x: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    num_classes: int,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    global_batch_size: int,
    reg: float,
    elastic_net: float,
    tol: float,
    seed: int,
    dtype=None,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
) -> np.ndarray:
    """Multinomial logistic regression: returns coefficient ``[k, d]``.

    Same distributed machinery as :func:`train_linear_model` (windowed
    batches, psum, proximal elastic-net, chunked checkpointing); the loss
    is weighted softmax cross-entropy over integer labels ``0..k-1``.
    """
    n = x.shape[0]
    if n == 0:
        raise ValueError("training table is empty")
    p_size = mesh.axis_size()
    if dtype is not None:
        x = x.astype(dtype)
    w = np.asarray(w, dtype=x.dtype)
    y = np.asarray(y, dtype=x.dtype)
    perm = np.random.default_rng(seed).permutation(n)
    x, y, w = x[perm], y[perm], w[perm]
    x_pad, _ = pad_to_multiple(x, p_size)
    y_pad, _ = pad_to_multiple(y, p_size)
    w_pad, _ = pad_to_multiple(w, p_size)
    xd = mesh.shard_batch(x_pad)
    yd = mesh.shard_batch(y_pad)
    wd = mesh.shard_batch(w_pad)
    n_local = xd.shape[0] // p_size
    local_bs = min(max(1, math.ceil(global_batch_size / p_size)), n_local)
    trainer = _softmax_trainer(
        mesh.mesh, int(num_classes), local_bs, DeviceMesh.DATA_AXIS
    )
    return _run_chunked(
        trainer, (xd, yd, wd), (int(num_classes), x.shape[1]), xd.dtype,
        learning_rate, reg * (1.0 - elastic_net), reg * elastic_net,
        tol, max_iter, mesh,
        checkpoint_manager=checkpoint_manager,
        checkpoint_interval=checkpoint_interval,
        resume=resume, listeners=listeners,
    )


def _run_multiprocess_stream_epochs(
    cache, plan, place, stepper, dim, hy, dt, criterion,
    checkpoint_manager, checkpoint_interval, listeners, prefetch_depth,
    mesh, coef, epoch, cur_loss, after_first_epoch=None,
):
    """The shared multi-process epoch driver for the dense and sparse
    stream trainers: agreed-schedule replay through the prefetching
    feed, bounded in-flight dispatch, watermark listeners, rank-0 +
    barrier checkpoint commits, and the termination epilogue (async
    checkpoint ``wait`` — which also surfaces a failed final write —
    plus ``on_iteration_terminated``). ONE definition so the two paths
    cannot drift (they already had once: the sparse copy dropped the
    epilogue)."""
    from flinkml_tpu.iteration.checkpoint import save_replicated
    from flinkml_tpu.iteration.datacache import PrefetchingDeviceFeed
    from flinkml_tpu.parallel.dispatch import DispatchGuard

    guard = DispatchGuard()

    def run_epoch(coef):
        loss_acc = jnp.zeros((), dt)
        wsum_acc = jnp.zeros((), dt)
        feed = PrefetchingDeviceFeed(
            plan.epoch_batches(cache.reader(), lambda: _DUMMY_BATCH),
            place=place,
            depth=prefetch_depth,
        )
        try:
            for tensors in feed:
                if coef is None:
                    coef = jnp.zeros(dim, dt)
                coef, ls, ws = stepper(coef, *tensors, *hy)
                loss_acc = loss_acc + ls
                wsum_acc = wsum_acc + ws
                coef = guard.after_dispatch(coef)
        finally:
            feed.close()
        coef = guard.flush(coef)
        return coef, float(loss_acc) / float(wsum_acc)

    while not (epoch > 0 and criterion.should_terminate(epoch - 1, cur_loss)):
        coef, cur_loss = run_epoch(coef)
        epoch += 1
        if after_first_epoch is not None:
            after_first_epoch()
        coef_host = np.asarray(coef)
        for listener in listeners:
            listener.on_epoch_watermark_incremented(epoch - 1, coef_host)
        terminated = criterion.should_terminate(epoch - 1, cur_loss)
        if checkpoint_manager is not None and (
            terminated
            or (checkpoint_interval > 0 and epoch % checkpoint_interval == 0)
        ):
            save_replicated(
                checkpoint_manager,
                (coef_host, np.float64(cur_loss)),
                epoch,
                mesh,
            )

    result = np.asarray(coef)
    if checkpoint_manager is not None:
        checkpoint_manager.wait()  # surface a failed final async write
    for listener in listeners:
        listener.on_iteration_terminated(result)
    return result


def _train_linear_sparse_stream_multiprocess(
    batches,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    reg: float,
    elastic_net: float,
    tol: float,
    cache_dir: Optional[str],
    memory_budget_bytes: Optional[int],
    checkpoint_manager,
    checkpoint_interval: int,
    resume: bool,
    listeners,
    prefetch_depth: int,
    dtype,
    validate,
    sparse_dim: int,
) -> np.ndarray:
    """Multi-process body of the sparse-native stream (the pod-scale
    Criteo path): each process feeds its OWN partition of flat CSR
    batches. SPMD invariants mirror
    :func:`_train_linear_stream_multiprocess`, with ONE extra agreed
    quantity — a single global ELL width (the max quantized per-batch
    width across every rank's stream), so every collective dispatch has
    one fixed ``[height, width]`` shape. Ingest failures, including
    dim-mismatched or ragged CSR components, ride the held-error
    rendezvous; short ranks feed zero-weight dummy blocks (exact
    no-ops). O(nnz) cache and HBM cost at any ``dim``, per rank."""
    from flinkml_tpu.iteration.checkpoint import begin_resume
    from flinkml_tpu.iteration.datacache import DataCache, DataCacheWriter
    from flinkml_tpu.iteration.runtime import TerminateOnMaxIterOrTol
    from flinkml_tpu.iteration.stream_sync import (
        DeferredValidation,
        SyncedReplayPlan,
        agree_all_ok,
        agree_max,
        checked_ingest,
        pad_rows_to,
    )

    is_cache = isinstance(batches, DataCache)
    resume_epoch = begin_resume(checkpoint_manager, resume, mesh.mesh.size)

    p_size = mesh.axis_size()
    row_tile = p_size * 8
    axis = DeviceMesh.DATA_AXIS
    stepper = _sparse_stream_stepper(mesh.mesh, loss, axis, int(sparse_dim),
                                 _segsum_backend(), _spmv_backend())
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net

    # -- pass 0: cache + local (rows, width) maxima; everything a
    # place-time raise could hit is validated HERE (a feed-thread raise
    # is rank-local mid-collective — the hang class).
    dv = DeferredValidation()
    local_max = [0, 0]  # rows, quantized width

    def check_and_stats(b):
        indptr = np.asarray(b["indptr"])[0]
        n = indptr.size - 1
        d = int(np.asarray(b["dim"]).reshape(-1)[0])
        if d != sparse_dim:
            raise ValueError(
                f"CSR stream batch has dim {d}, expected {sparse_dim}"
            )
        indices = np.asarray(b["indices"])[0]
        values = np.asarray(b["values"])[0]
        if indices.shape != values.shape or indices.size != int(indptr[-1]):
            raise ValueError(
                "ragged CSR batch: indices/values/indptr disagree"
            )
        nnz = _check_csr_structure(indptr, indices, sparse_dim)
        y = np.asarray(b["y"])[0]
        w = (np.asarray(b["w"])[0] if "w" in b
             else np.ones(n, dtype=dtype))
        if y.shape[0] != n or w.shape[0] != n:
            raise ValueError("ragged CSR batch: y/w rows != indptr rows")
        if validate is not None:
            validate(b)
        if n == 0 or float(w.sum()) == 0.0:
            raise ValueError(
                "stream batch has zero total weight (empty batch or all "
                "weights 0); drop such batches before training"
            )
        local_max[0] = max(local_max[0], n)
        local_max[1] = max(
            local_max[1], _ell_width_for(np.max(nnz, initial=1))
        )

    if is_cache:
        cache = batches
        for _ in checked_ingest(
            cache.reader(), dv, check_and_stats, multi=True
        ):
            pass
    else:
        writer = DataCacheWriter(cache_dir, memory_budget_bytes)

        def checked_append(b):
            check_and_stats(b)
            writer.append({k: np.array(v) for k, v in b.items()})

        for _ in checked_ingest(batches, dv, checked_append, multi=True):
            pass
        cache = writer.finish()

    dv.rendezvous(mesh, "sparse stream ingest validation")
    # Agree the feature dimension itself (the dense path's
    # agree_feature_dim role): per-rank validation above only checks
    # batches against the RANK-LOCAL sparse_dim — two ranks fed
    # partitions from different feature spaces would otherwise compile
    # different [dim] coefficient shapes and diverge inside the
    # collectives (the exact hang class pass 0 exists to prevent).
    agree_all_ok(
        agree_max(int(sparse_dim), mesh) == int(sparse_dim), mesh,
        "sparse stream feature-dimension agreement",
    )
    steps = agree_max(cache.num_batches, mesh)
    if steps == 0:
        raise ValueError("training stream is empty on every process")
    height = agree_max(
        -(-max(local_max[0], 1) // row_tile) * row_tile, mesh
    )
    width = agree_max(max(local_max[1], 1), mesh)
    plan = SyncedReplayPlan(
        global_steps=steps, local_height=height, mesh=mesh
    )

    def place(batch):
        if "_dummy" in batch:
            bi = np.zeros((height, width), np.int32)
            bv = np.zeros((height, width), dtype)
            y = np.zeros(height, dtype)
            w = np.zeros(height, dtype)
        else:
            indptr = np.asarray(batch["indptr"])[0]
            n = indptr.size - 1
            bi, bv = _pack_uniform_ell(
                indptr, np.asarray(batch["indices"])[0],
                np.asarray(batch["values"])[0], dtype, width=width,
            )
            bi = pad_rows_to(bi, height)
            bv = pad_rows_to(bv, height)
            y = pad_rows_to(
                np.asarray(batch["y"])[0].astype(dtype), height
            )
            w = pad_rows_to(
                (np.asarray(batch["w"])[0].astype(dtype)
                 if "w" in batch else np.ones(n, dtype=dtype)),
                height,
            )
        return (
            mesh.global_batch(bi), mesh.global_batch(bv),
            mesh.global_batch(y), mesh.global_batch(w),
        )

    dt = jnp.dtype(dtype)
    hy = (
        jnp.asarray(learning_rate, dt),
        jnp.asarray(l2, dt),
        jnp.asarray(l1, dt),
    )
    criterion = TerminateOnMaxIterOrTol(max_iter, tol)

    coef = None
    epoch = 0
    cur_loss = math.inf
    if resume_epoch is not None:
        restored = _restore_carry(checkpoint_manager, sparse_dim, dtype,
                                  mesh)
        if restored is not None:
            coef_h, epoch, cur_loss = restored
            coef = jnp.asarray(coef_h, dt)

    return _run_multiprocess_stream_epochs(
        cache, plan, place, stepper, int(sparse_dim), hy, dt, criterion,
        checkpoint_manager, checkpoint_interval, listeners, prefetch_depth,
        mesh, coef, epoch, cur_loss,
    )


def streamed_linear_fit(
    source,
    *,
    features_col: str,
    label_col: str,
    weight_col: Optional[str],
    label_check=None,
    **kwargs,
) -> np.ndarray:
    """Estimator-facing wrapper over :func:`train_linear_model_stream` —
    the one streamed dispatch for every linear estimator (LR binomial,
    LinearSVC, LinearRegression): accepts an iterable of batch Tables or
    a sealed DataCache carrying the given columns, applying
    ``label_check`` on either branch. ``kwargs`` pass straight through
    (loss, mesh, cache_dir, checkpoint_manager, ...).

    SparseVector feature columns route to the sparse-native stream
    (round 5): batches are cached and trained as CSR — O(nnz) cache and
    HBM cost at any ``dim`` — instead of densifying to ``[n, dim]``
    (ruinous at the Criteo profile: a 64-row batch at dim=1e6 would
    cache 256 MB). Multi-process meshes stream per-rank CSR partitions
    through the agreement layer with one extra agreed quantity (a
    global ELL width). A sealed DataCache
    whose batches carry ``indptr/indices/values/dim`` replays through
    the same sparse stream (this is also the resume route)."""
    from flinkml_tpu.iteration.datacache import DataCache
    from flinkml_tpu.models._data import (
        labeled_data,
        labeled_sparse_data,
        sparse_features,
    )

    if isinstance(source, DataCache):
        validate = None
        mem = source.mem_batches  # property: List[Batch]
        if mem:
            first = mem[0]  # no segment read for RAM-resident caches
        else:
            try:
                first = next(iter(source.reader()))
            except StopIteration:
                raise ValueError("training stream is empty") from None
        if "indptr" in first:  # sparse-native CSR cache
            if label_check is not None:
                def validate(batch):
                    label_check(np.asarray(batch["y"])[0])

            return train_linear_model_stream(
                source, columns=("x", "y", "w"), validate=validate,
                sparse_dim=int(np.asarray(first["dim"])[0, 0]), **kwargs,
            )
        if label_check is not None:
            def validate(batch):
                label_check(np.asarray(batch[label_col]))

        return train_linear_model_stream(
            source, columns=(features_col, label_col, weight_col),
            validate=validate, **kwargs,
        )

    import itertools

    it = iter(source)
    try:
        first_t = next(it)
    except StopIteration:
        raise ValueError("training stream is empty") from None
    tables = itertools.chain([first_t], it)

    from flinkml_tpu.table import SortedSparseColumn, Table

    if (
        isinstance(first_t, Table)
        and features_col in first_t.column_names
        and isinstance(first_t._raw_column(features_col), SortedSparseColumn)
    ):
        # Device-resident sorted-layout stream (DevicePrefetcher output):
        # train directly on the pack-time-sorted tables — no host
        # round-trip, no densify, no runtime sort.
        return train_linear_model_sorted_stream(
            tables, features_col, label_col, weight_col,
            label_check=label_check, **kwargs,
        )

    if sparse_features(first_t, features_col) is not None:
        indptr0, indices0, values0, dim0, y0, w0 = labeled_sparse_data(
            first_t, features_col, label_col, weight_col
        )

        def sparse_batches():
            for i, t in enumerate(tables):
                if i == 0:
                    indptr, indices, values, d, y, w = (
                        indptr0, indices0, values0, dim0, y0, w0
                    )
                else:
                    indptr, indices, values, d, y, w = labeled_sparse_data(
                        t, features_col, label_col, weight_col
                    )
                if d != dim0:
                    raise ValueError(
                        f"stream batch feature dimension {d} != first "
                        f"batch's {dim0}"
                    )
                if label_check is not None:
                    label_check(y)
                # Each array rides as one 2-D row: the cache's columnar
                # contract wants equal row counts per batch, and CSR
                # components have different lengths by nature.
                yield {
                    "indptr": np.asarray(indptr)[None, :],
                    "indices": np.asarray(indices)[None, :],
                    "values": np.asarray(values)[None, :],
                    "y": np.asarray(y)[None, :],
                    "w": np.asarray(w)[None, :],
                    "dim": np.asarray([[d]], np.int64),
                }

        return train_linear_model_stream(
            sparse_batches(), sparse_dim=int(dim0), **kwargs
        )

    def batches():
        for t in tables:
            x, y, w = labeled_data(t, features_col, label_col, weight_col)
            if label_check is not None:
                label_check(y)
            yield {"x": x, "y": y, "w": w}

    return train_linear_model_stream(batches(), **kwargs)


def train_linear_model_from_table(
    table,
    features_col: str,
    label_col: str,
    weight_col: Optional[str],
    label_check=None,
    sharding_plan=None,
    precision=None,
    **hyper,
) -> np.ndarray:
    """One fit dispatch for every linear estimator: SparseVector columns
    take the nnz-bucketed CSR trainer, everything else densifies into the
    dense trainer. ``label_check(y)`` (optional) validates labels on
    either branch. ``hyper`` passes straight to the trainers (loss, mesh,
    max_iter, ...). ``sharding_plan`` routes the DENSE branch through
    the plan-sharded trainer (see :func:`train_linear_model`); the
    sparse trainer keeps its replicated ``[dim]`` model and refuses a
    plan loudly. ``precision`` (the FML6xx-gated mixed-precision
    policy) rides the same dense-only route and is refused just as
    loudly on the sparse branch."""
    from flinkml_tpu.models._data import (
        labeled_data,
        labeled_sparse_data,
        sparse_features,
    )

    if sparse_features(table, features_col) is not None:
        if sharding_plan is not None:
            raise ValueError(
                "sharding_plan supports the dense path only; the sparse "
                "trainer keeps its replicated [dim] model (shard it via "
                "ROADMAP item 5's embedding-table path instead)"
            )
        if precision is not None:
            raise ValueError(
                "precision supports the dense path only; the sparse "
                "trainer's gather/segment-sum kernels are not yet "
                "policy-gated"
            )
        indptr, indices, values, dim, y, w = labeled_sparse_data(
            table, features_col, label_col, weight_col
        )
        if label_check is not None:
            label_check(y)
        return train_linear_model_sparse_csr(
            indptr, indices, values, dim, y, w, **hyper
        )
    x, y, w = labeled_data(table, features_col, label_col, weight_col)
    if x.shape[0] == 0:
        raise ValueError("training table is empty")
    if label_check is not None:
        label_check(y)
    return train_linear_model(x, y, w, sharding_plan=sharding_plan,
                              precision=precision, **hyper)


# ---------------------------------------------------------------------------
# Streamed / out-of-core training (the load-bearing ReplayOperator path)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=128)
def _stream_stepper(mesh, loss: str, axis: str):
    """One global mini-batch SGD step for streamed training: the batch
    arrives sharded over ``axis``, the coefficient stays replicated.
    Returns unnormalized ``(loss_sum, wsum)`` so the host can accumulate a
    weighted epoch-mean loss across variable-size batches."""

    def per_device(coef, xb, yb, wb, learning_rate, reg_l2, reg_l1):
        acc = _acc_dt(xb.dtype)
        dot = xb @ coef
        mult, per_ex = _margin_grad(loss, dot, yb, wb)
        grad = jax.lax.psum(xb.T @ mult, axis) + 2.0 * reg_l2 * coef
        loss_sum = jax.lax.psum(jnp.sum(per_ex.astype(acc)), axis) + (
            reg_l2 * jnp.sum(jnp.square(coef.astype(acc)))
        )
        wsum = jax.lax.psum(jnp.sum(wb.astype(acc)), axis)
        step_size = learning_rate.astype(acc) / wsum
        new_coef = _soft_threshold(
            coef - step_size.astype(coef.dtype) * grad,
            step_size.astype(coef.dtype) * reg_l1,
        )
        return new_coef, loss_sum, wsum

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P(), P(), P()),
            out_specs=(P(), P(), P()),
        )
    )


@functools.lru_cache(maxsize=64)
def _sparse_stream_stepper(mesh, loss: str, axis: str, dim: int,
                           segsum_backend: str = "xla",
                           spmv_backend: str = "xla"):
    """Sparse sibling of :func:`_stream_stepper`: the batch arrives as a
    sharded padded-ELL block (indices/values), the dense ``[dim]``
    coefficient stays replicated. SpMV forward + one ``segment_sum``
    gradient scatter (the streamed path has no static windows, so the
    pack-time-sorted ``cumsum`` layout cannot apply here — each batch's
    cells are seen once per epoch in stream order). ``segsum_backend``
    and ``spmv_backend`` are lru-key material (kernel gate idiom)."""
    from flinkml_tpu import kernels

    def per_device(coef, ib, vb, yb, wb, learning_rate, reg_l2, reg_l1):
        acc = _acc_dt(vb.dtype)
        dot = kernels.spmv(ib, vb, coef, backend=spmv_backend)
        mult, per_ex = _margin_grad(loss, dot, yb, wb)
        contrib = (vb * mult[:, None]).reshape(-1)
        grad = jax.lax.psum(
            kernels.segment_sum(contrib, ib.reshape(-1), dim,
                                backend=segsum_backend),
            axis,
        ) + 2.0 * reg_l2 * coef
        loss_sum = jax.lax.psum(jnp.sum(per_ex.astype(acc)), axis) + (
            reg_l2 * jnp.sum(jnp.square(coef.astype(acc)))
        )
        wsum = jax.lax.psum(jnp.sum(wb.astype(acc)), axis)
        step_size = learning_rate.astype(acc) / wsum
        new_coef = _soft_threshold(
            coef - step_size.astype(coef.dtype) * grad,
            step_size.astype(coef.dtype) * reg_l1,
        )
        return new_coef, loss_sum, wsum

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P(axis), P(), P(),
                      P()),
            out_specs=(P(), P(), P()),
        )
    )


@functools.lru_cache(maxsize=64)
def _sorted_column_stepper(loss: str, dim: int,
                           segsum_backend: str = "xla",
                           spmv_backend: str = "xla"):
    """Step factory for :func:`train_linear_model_sorted_stream`: one
    SGD step over a prefetched :class:`~flinkml_tpu.table
    .SortedSparseColumn` batch. Pure ``jax.jit`` — the column's global
    sort tables (``perm``/``segment_ids``) index the FULL flat cell
    block, which does not shard by rows, so the replicated single-
    program step is the correct shape here (psum-free).

    The forward is the gated SpMV over the padded-ELL block; the
    gradient scatter replays the pack-time sort —
    ``segment_sum(take(contrib, perm), segment_ids,
    indices_are_sorted=True)`` — so the step contains ZERO runtime
    sorts (the argsort already ran once on the prefetch worker
    thread). Row-bucket padding is neutralized in-jit: the weight
    column is masked by the traced ``n_valid`` row count (weight 0 ⇒
    exact zero contribution to grad/loss/wsum), so batch-size jitter
    inside a bucket never retraces. Backends are lru-key material
    (kernel gate idiom)."""
    from flinkml_tpu import kernels

    def step(coef, ib, vb, perm, seg, yb, wb, n_valid, learning_rate,
             reg_l2, reg_l1):
        acc = _acc_dt(vb.dtype)
        yb = yb.astype(vb.dtype)
        wb = jnp.where(
            jnp.arange(wb.shape[0]) < n_valid,
            wb.astype(vb.dtype),
            jnp.zeros((), vb.dtype),
        )
        dot = kernels.spmv(ib, vb, coef, backend=spmv_backend)
        mult, per_ex = _margin_grad(loss, dot, yb, wb)
        contrib = (vb * mult[:, None]).reshape(-1)
        grad = kernels.segment_sum(
            jnp.take(contrib, perm), seg, dim,
            indices_are_sorted=True, backend=segsum_backend,
        ) + 2.0 * reg_l2 * coef
        loss_sum = jnp.sum(per_ex.astype(acc)) + (
            reg_l2 * jnp.sum(jnp.square(coef.astype(acc)))
        )
        wsum = jnp.sum(wb.astype(acc))
        step_size = learning_rate.astype(acc) / wsum
        new_coef = _soft_threshold(
            coef - step_size.astype(coef.dtype) * grad,
            step_size.astype(coef.dtype) * reg_l1,
        )
        return new_coef, loss_sum, wsum

    return jax.jit(step)


def train_linear_model_sorted_stream(
    tables,
    features_col: str,
    label_col: str,
    weight_col: Optional[str] = None,
    *,
    loss: str,
    max_iter: int,
    learning_rate: float,
    reg: float,
    elastic_net: float,
    tol: float,
    mesh=None,
    label_check=None,
    listeners=(),
    dtype=np.float32,
    cache_dir=None,
    memory_budget_bytes=None,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    prefetch_depth: int = 2,
    validate=None,
) -> np.ndarray:
    """Train a linear model from a stream of DEVICE-resident Tables
    whose feature column is a :class:`~flinkml_tpu.table
    .SortedSparseColumn` (the :class:`~flinkml_tpu.data.prefetch
    .DevicePrefetcher` output format): the sorted-by-design fast path —
    the fit never densifies to ``[n, dim]`` and never sorts at step
    time; the pack-time tables carry ``indices_are_sorted=True``
    straight into the gradient scatter.

    Epoch 0 trains batch-by-batch while collecting the device Tables
    into a list; later epochs replay that list — the batches are
    ALREADY in HBM (O(nnz) per batch), so the replay cache is the
    tables themselves and ``cache_dir`` / ``memory_budget_bytes`` /
    ``prefetch_depth`` are accepted for call-compatibility but unused.
    ``mesh`` likewise: the column's global sort tables index the full
    flat cell block and do not shard by rows, so the step is a
    replicated single-program jit (see :func:`_sorted_column_stepper`).
    Checkpoint/resume is not wired for this path yet — pass batches
    through the CSR stream (:func:`train_linear_model_stream` with
    ``sparse_dim``) if you need durable mid-fit state."""
    del mesh, cache_dir, memory_budget_bytes, prefetch_depth
    from flinkml_tpu.iteration.runtime import TerminateOnMaxIterOrTol
    from flinkml_tpu.table import SortedSparseColumn

    if loss not in _LOSS_KEYS:
        raise ValueError(f"loss must be one of {_LOSS_KEYS}, got {loss!r}")
    if checkpoint_manager is not None or resume or checkpoint_interval:
        raise ValueError(
            "checkpoint/resume is not supported on the sorted-column "
            "stream path; use the CSR stream (sparse_dim=...) for "
            "durable fits"
        )
    dt = jnp.dtype(dtype)
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net
    hy = (
        jnp.asarray(learning_rate, dt),
        jnp.asarray(l2, dt),
        jnp.asarray(l1, dt),
    )
    criterion = TerminateOnMaxIterOrTol(max_iter, tol)

    stepper = None
    coef = None
    dim = None
    ones_cache = {}  # bucket -> device ones, for weightless streams

    def step_table(t, coef, first_pass: bool):
        nonlocal stepper, dim
        col = t._raw_column(features_col)
        if not isinstance(col, SortedSparseColumn):
            raise ValueError(
                f"sorted-column stream: feature column {features_col!r} "
                "is not a SortedSparseColumn (feed the stream through "
                "data.prefetch.DevicePrefetcher)"
            )
        if dim is None:
            dim = col.dim
            stepper = _sorted_column_stepper(
                loss, dim, _segsum_backend(), _spmv_backend()
            )
            coef = jnp.zeros(dim, dt)
        elif col.dim != dim:
            raise ValueError(
                f"stream batch feature dimension {col.dim} != first "
                f"batch's {dim}"
            )
        yraw = t._raw_column(label_col)
        yb = yraw.buf if hasattr(yraw, "buf") else jnp.asarray(yraw)
        if first_pass and label_check is not None:
            label_check(np.asarray(yb)[: col.rows])
        if weight_col is not None and weight_col in t.column_names:
            wraw = t._raw_column(weight_col)
            wb = wraw.buf if hasattr(wraw, "buf") else jnp.asarray(wraw)
        else:
            bucket = col.buf.shape[0]
            wb = ones_cache.get(bucket)
            if wb is None:
                wb = ones_cache.setdefault(bucket, jnp.ones(bucket, dt))
        if first_pass:
            if validate is not None:
                validate(t)
            if col.rows == 0 or float(np.asarray(wb)[: col.rows].sum()) == 0:
                raise ValueError(
                    "stream batch has zero total weight (empty batch or "
                    "all weights 0); drop such batches before training"
                )
        n_valid = jnp.asarray(col.rows, jnp.int32)
        return stepper(coef, col.indices, col.buf, col.perm,
                       col.segment_ids, yb, wb, n_valid, *hy)

    epoch = 0
    cur_loss = math.inf
    cache = []

    def run_epoch(batch_iter, coef, first_pass):
        loss_acc = jnp.zeros((), dt)
        wsum_acc = jnp.zeros((), dt)
        n_batches = 0
        for t in batch_iter:
            if first_pass:
                cache.append(t)
            coef, ls, ws = step_table(t, coef, first_pass)
            loss_acc = loss_acc + ls
            wsum_acc = wsum_acc + ws
            n_batches += 1
        if n_batches == 0:
            raise ValueError("training stream is empty")
        return coef, float(loss_acc) / float(wsum_acc)

    def after_epoch():
        coef_host = np.asarray(coef)
        for listener in listeners:
            listener.on_epoch_watermark_incremented(epoch - 1, coef_host)

    coef, cur_loss = run_epoch(tables, coef, True)
    epoch = 1
    after_epoch()
    while not criterion.should_terminate(epoch - 1, cur_loss):
        coef, cur_loss = run_epoch(cache, coef, False)
        epoch += 1
        after_epoch()

    result = np.asarray(coef)
    for listener in listeners:
        listener.on_iteration_terminated(result)
    return result


def _ell_width_for(max_nnz: int) -> int:
    """Quantize a batch's max nnz up to the next power of two, so the
    stream's per-batch nnz variation maps to a log-bounded set of
    compiled step shapes, not one per batch."""
    return 1 << max(int(max_nnz) - 1, 0).bit_length()


def _check_csr_structure(indptr, indices, sparse_dim: int):
    """Structural CSR validation shared by both sparse stream paths;
    returns ``nnz = diff(indptr)``.

    A non-monotone indptr passes the ragged check (``indices.size ==
    indptr[-1]``) but later raises rank-locally inside the ELL fill
    (``np.repeat`` with negative counts) on the prefetch thread at place
    time — the exact mid-collective hang class pass-0 validation exists
    to prevent — so it must be rejected HERE, where the failure rides the
    held-error rendezvous like every other ingest check. Out-of-range
    column indices never raise at all: the jitted gather/scatter clamps
    them, silently misattributing gradient mass to boundary columns."""
    nnz = np.diff(indptr)
    if indptr.size == 0 or indptr[0] != 0 or np.any(nnz < 0):
        raise ValueError(
            "invalid CSR batch: indptr must start at 0 and be "
            "non-decreasing"
        )
    if indices.size and (
        int(indices.min()) < 0 or int(indices.max()) >= sparse_dim
    ):
        raise ValueError(
            "invalid CSR batch: column indices must lie in "
            f"[0, {sparse_dim}); got range "
            f"[{int(indices.min())}, {int(indices.max())}]"
        )
    return nnz


def _pack_uniform_ell(indptr, indices, values, dtype, width=None):
    """Pack one CSR batch into uniform ELL (width quantized via
    :func:`_ell_width_for` unless an agreed ``width`` is given — the
    multi-process path fixes ONE global width). Padding cells carry
    index 0 / value 0 (exact no-ops)."""
    from flinkml_tpu.ops.sparse import fill_ell

    indptr = np.asarray(indptr, dtype=np.int64)
    n = indptr.size - 1
    nnz = np.diff(indptr)
    if width is None:
        width = _ell_width_for(np.max(nnz, initial=1))
    bi = np.zeros((n, width), dtype=np.int32)
    bv = np.zeros((n, width), dtype=dtype)
    fill_ell(bi, bv, indptr[:-1], nnz, indices, values)
    return bi, bv


def _pad_rows(arr: np.ndarray, rows: int) -> np.ndarray:
    if arr.shape[0] == rows:
        return arr
    pad = [(0, rows - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


_DUMMY_BATCH = {"_dummy": True}


def _train_linear_stream_multiprocess(
    batches,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    reg: float,
    elastic_net: float,
    tol: float,
    cache_dir: Optional[str],
    memory_budget_bytes: Optional[int],
    checkpoint_manager,
    checkpoint_interval: int,
    resume: bool,
    listeners,
    prefetch_depth: int,
    dtype,
    columns: Tuple[str, str, Optional[str]],
    validate,
) -> np.ndarray:
    """The multi-process body of :func:`train_linear_model_stream`.

    Each process feeds its OWN partition of the stream (the reference's
    per-subtask stream partitions); the SPMD invariants — one agreed
    padded batch height, one agreed step count per epoch, zero-weight
    dummy steps for short processes — come from
    :class:`~flinkml_tpu.iteration.stream_sync.SyncedReplayPlan`.
    Differences from the single-process path, all forced by SPMD:

      - pass 0 caches WITHOUT training (the step count must be agreed
        before the first collective dispatch), so one extra replay pass;
      - every step has one fixed global shape (bounds compilations to 1);
      - in-flight dispatches are bounded by
        :class:`~flinkml_tpu.parallel.dispatch.DispatchGuard` (the
        multi-process backpressure policy);
      - checkpoints commit rank-0-writes + global barrier
        (:func:`~flinkml_tpu.iteration.checkpoint.save_replicated`)
        against a SHARED checkpoint directory.

    Numerics match a single-process run whose step-t batch is the
    concatenation of every process's step-t batch (up to float reduction
    order); the fitted coefficient is replicated and identical on every
    process.
    """
    from flinkml_tpu.iteration.checkpoint import begin_resume, save_replicated
    from flinkml_tpu.iteration.datacache import (
        DataCache,
        DataCacheWriter,
        PrefetchingDeviceFeed,
    )
    from flinkml_tpu.iteration.runtime import TerminateOnMaxIterOrTol
    from flinkml_tpu.iteration.stream_sync import (
        DeferredValidation,
        SyncedReplayPlan,
        agree_feature_dim,
        checked_ingest,
    )
    from flinkml_tpu.parallel.dispatch import DispatchGuard

    # loss/resume-durability already validated by the dispatching caller
    # (train_linear_model_stream).
    is_cache = isinstance(batches, DataCache)
    resume_epoch = begin_resume(checkpoint_manager, resume, mesh.mesh.size)

    p_size = mesh.axis_size()
    row_tile = p_size * 8
    axis = DeviceMesh.DATA_AXIS
    stepper = _stream_stepper(mesh.mesh, loss, axis)
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net
    x_key, y_key, w_key = columns

    # -- pass 0: cache only (step counts must be agreed before training) --
    dv = DeferredValidation()
    first_dim = [None]

    def check_ingest(b):
        """Everything place-time validation would catch — a place-time
        raise on the feed thread is rank-local mid-collective (the hang
        class DeferredValidation prevents), so iterable sources must be
        FULLY validated here: x shape/dim consistency, label-column
        presence, zero total weight, plus the estimator's hook."""
        x = np.asarray(b[x_key], dtype=dtype)
        np.asarray(b[y_key], dtype=dtype)  # missing label column raises
        if x.ndim != 2:
            raise ValueError(
                f"stream batches must be [n, d], got {x.shape}"
            )
        if first_dim[0] is None:
            first_dim[0] = x.shape[1]
        elif x.shape[1] != first_dim[0]:
            raise ValueError(
                f"batch feature dim {x.shape[1]} != first batch's "
                f"{first_dim[0]}"
            )
        if validate is not None:
            validate(b)
        w = (
            np.asarray(b[w_key], dtype=dtype)
            if w_key is not None and w_key in b
            else np.ones(x.shape[0], dtype=dtype)
        )
        if x.shape[0] == 0 or float(w.sum()) == 0.0:
            raise ValueError(
                "stream batch has zero total weight (empty batch or all "
                "weights 0); drop such batches before training"
            )

    if is_cache:
        cache = batches
    else:

        writer = DataCacheWriter(cache_dir, memory_budget_bytes)

        def checked_append(b):
            # Validation, the column copies, AND the append are one
            # checked step: a ragged value's np.array ValueError or a
            # rank-local writer failure (disk full while spilling) is
            # held for the rendezvous, never raised rank-locally.
            check_ingest(b)
            writer.append({k: np.array(v) for k, v in b.items()})

        # This trainer IS the multi-process path (dispatched on
        # process_count > 1), so iterator and ingest failures always
        # ride the rendezvous.
        for _ in checked_ingest(batches, dv, checked_append, multi=True):
            pass
        cache = writer.finish()

    # Rendezvous BEFORE planning: a held ingest error must surface as
    # itself, not as plan.create's "stream is empty on every process"
    # (skip-on-failure can leave every local cache empty).
    dv.rendezvous(mesh, "stream ingest validation")
    plan = SyncedReplayPlan.create(cache, mesh, row_tile)
    height = plan.local_height
    dim = agree_feature_dim(cache, x_key, mesh)

    # Iterable sources were fully validated at ingest (above, before the
    # rendezvous); only sealed caches still validate at first replay —
    # those raises are rank-local on the feed thread, the documented
    # residual (stream_sync.DeferredValidation).
    first_pass_done = [not is_cache]

    def place(batch):
        if "_dummy" in batch:
            x = np.zeros((height, dim), dtype)
            y = np.zeros(height, dtype)
            w = np.zeros(height, dtype)
        else:
            x = np.asarray(batch[x_key], dtype=dtype)
            y = np.asarray(batch[y_key], dtype=dtype)
            w = (
                np.asarray(batch[w_key], dtype=dtype)
                if w_key is not None and w_key in batch
                else np.ones(x.shape[0], dtype=dtype)
            )
            if not first_pass_done[0]:
                if validate is not None:
                    validate(batch)
                if x.shape[0] == 0 or float(w.sum()) == 0.0:
                    raise ValueError(
                        "stream batch has zero total weight (empty batch or "
                        "all weights 0); drop such batches before training"
                    )
            from flinkml_tpu.iteration.stream_sync import pad_rows_to

            x, y, w = (
                pad_rows_to(x, height),
                pad_rows_to(y, height),
                pad_rows_to(w, height),
            )
        return (
            mesh.global_batch(x),
            mesh.global_batch(y),
            mesh.global_batch(w),
        )

    dt = jnp.dtype(dtype)
    hy = (
        jnp.asarray(learning_rate, dt),
        jnp.asarray(l2, dt),
        jnp.asarray(l1, dt),
    )
    criterion = TerminateOnMaxIterOrTol(max_iter, tol)

    coef = None
    epoch = 0
    cur_loss = math.inf
    if resume_epoch is not None:
        restored = _restore_carry(checkpoint_manager, dim, dtype, mesh)
        if restored is not None:
            coef_h, epoch, cur_loss = restored
            coef = jnp.asarray(coef_h, dt)

    def mark_validated():
        first_pass_done[0] = True

    return _run_multiprocess_stream_epochs(
        cache, plan, place, stepper, dim, hy, dt, criterion,
        checkpoint_manager, checkpoint_interval, listeners, prefetch_depth,
        mesh, coef, epoch, cur_loss, after_first_epoch=mark_validated,
    )


def train_linear_model_stream(
    batches,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    reg: float,
    elastic_net: float,
    tol: float,
    cache_dir: Optional[str] = None,
    memory_budget_bytes: Optional[int] = None,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
    prefetch_depth: int = 2,
    dtype=np.float32,
    columns: Tuple[str, str, Optional[str]] = ("x", "y", "w"),
    validate=None,
    sparse_dim: Optional[int] = None,
) -> np.ndarray:
    """Train from a one-shot stream of batches, datasets larger than RAM
    included — the round-2 integration of the datacache subsystem into a
    product fit path (round-1 VERDICT "missing" #1).

    ``columns`` names the (features, label, weight) keys inside each batch
    dict; a ``None``/absent weight key defaults to unit weights.
    ``validate`` (optional) is called with each host batch dict before
    device placement — the hook estimators use for per-batch input checks
    (e.g. binomial labels), which must also cover batches that only exist
    inside a caller-provided :class:`DataCache`.

    ``sparse_dim`` (round 5, the Criteo-1TB-shaped gap): when set, each
    batch is a FLAT CSR dict — top-level keys ``indptr`` / ``indices`` /
    ``values`` / ``y`` / ``w`` (optional) / ``dim``, each stored as one
    2-D row so the cache's equal-row-count contract holds — cached AS
    CSR (O(nnz) disk/RAM, not O(n·dim)), packed per batch into
    power-of-two-width uniform ELL at place time, and trained through
    :func:`_sparse_stream_stepper` against the dense replicated
    ``[sparse_dim]`` coefficient. Multi-process meshes route to
    :func:`_train_linear_sparse_stream_multiprocess` (per-rank CSR
    partitions, agreed schedule + global ELL width).

    Reference parity: ``ReplayOperator.java:62-250`` — epoch 0 caches the
    data stream to ``DataCacheWriter`` segments AND forwards it to training;
    every later epoch replays the cache. Here:

      - ``batches``: an iterable of ``{"x": [n,d], "y": [n], "w": [n]}``
        numpy dicts (one global mini-batch each), OR an already-sealed
        :class:`~flinkml_tpu.iteration.datacache.DataCache` of such batches
        (then no epoch-0 caching pass is needed, and ``resume=True`` is
        allowed — the cache is durable, so a restored run replays it).
      - epoch 0 trains batch-by-batch while appending each batch to the
        cache; batches beyond ``memory_budget_bytes`` spill to segment
        files under ``cache_dir``.
      - epochs 1..: replay through
        :class:`~flinkml_tpu.iteration.datacache.PrefetchingDeviceFeed`,
        overlapping the next batch's host→HBM transfer with the current
        step (the TPU answer to the reference's credit-based network
        buffering).
      - spilled and RAM-resident replay are bit-identical (raw columnar
        segments round-trip exactly), so the memory budget is a pure
        capacity knob, never a numerics knob.

    Each batch is padded to the mesh row tile with weight-0 rows (exact:
    zero weight ⇒ zero contribution to grad/loss/wsum) and sharded over the
    data axis. Termination is ``TerminateOnMaxIterOrTol(max_iter, tol)`` on
    the weighted epoch-mean loss. ``checkpoint_interval`` K snapshots
    ``(coef, loss)`` every K epochs.
    """
    from flinkml_tpu.iteration.datacache import (
        DataCache,
        DataCacheWriter,
        PrefetchingDeviceFeed,
    )

    if loss not in _LOSS_KEYS:
        raise ValueError(f"loss must be one of {_LOSS_KEYS}, got {loss!r}")
    is_cache = isinstance(batches, DataCache)
    if resume and not is_cache:
        raise ValueError(
            "resume=True requires a durable DataCache input: a one-shot "
            "stream cannot be replayed from the start after a failure"
        )
    if jax.process_count() > 1:
        if sparse_dim is not None:
            # Per-process CSR partitions + agreed SPMD schedule with one
            # extra agreed quantity (the global ELL width).
            return _train_linear_sparse_stream_multiprocess(
                batches, loss, mesh, max_iter, learning_rate, reg,
                elastic_net, tol, cache_dir, memory_budget_bytes,
                checkpoint_manager, checkpoint_interval, resume,
                listeners, prefetch_depth, dtype, validate,
                int(sparse_dim),
            )
        # Per-process stream partitions + agreed SPMD schedule; see
        # _train_linear_stream_multiprocess for the invariants.
        return _train_linear_stream_multiprocess(
            batches, loss, mesh, max_iter, learning_rate, reg, elastic_net,
            tol, cache_dir, memory_budget_bytes, checkpoint_manager,
            checkpoint_interval, resume, listeners, prefetch_depth, dtype,
            columns, validate,
        )
    from flinkml_tpu.iteration.checkpoint import begin_resume

    begin_resume(checkpoint_manager, resume, mesh.mesh.size)

    p_size = mesh.axis_size()
    row_tile = p_size * 8  # bounds the set of padded shapes → compilations
    axis = DeviceMesh.DATA_AXIS
    stepper = (
        _sparse_stream_stepper(mesh.mesh, loss, axis, int(sparse_dim),
                               _segsum_backend(), _spmv_backend())
        if sparse_dim is not None
        else _stream_stepper(mesh.mesh, loss, axis)
    )
    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net

    x_key, y_key, w_key = columns
    # Batches are immutable once cached, so input validation only needs the
    # first pass — not max_iter re-scans on the prefetch thread.
    first_pass_done = False

    def extract_yw(batch, n):
        y = np.asarray(batch[y_key], dtype=dtype)
        w = (
            np.asarray(batch[w_key], dtype=dtype)
            if w_key is not None and w_key in batch
            else np.ones(n, dtype=dtype)
        )
        if not first_pass_done:
            if validate is not None:
                validate(batch)
            if n == 0 or float(w.sum()) == 0.0:
                # The stepper divides by the batch weight sum; an inf step
                # size would silently NaN the whole model. Fail loudly.
                raise ValueError(
                    "stream batch has zero total weight (empty batch or all "
                    "weights 0); drop such batches before training"
                )
        return y, w

    def place(batch):
        x = np.asarray(batch[x_key], dtype=dtype)
        y, w = extract_yw(batch, x.shape[0])
        rows = max(row_tile, -(-x.shape[0] // row_tile) * row_tile)
        return (
            mesh.shard_batch(_pad_rows(x, rows)),
            mesh.shard_batch(_pad_rows(y, rows)),
            mesh.shard_batch(_pad_rows(w, rows)),
        )

    def place_sparse(batch):
        # Flat CSR batch format: every component is one 2-D row (the
        # cache's columnar contract wants equal row counts per batch,
        # and CSR components have different lengths by nature).
        indptr = np.asarray(batch["indptr"])[0]
        n = indptr.size - 1
        y = np.asarray(batch["y"])[0].astype(dtype)
        w = (
            np.asarray(batch["w"])[0].astype(dtype)
            if "w" in batch else np.ones(n, dtype=dtype)
        )
        if not first_pass_done:
            d = int(np.asarray(batch["dim"]).reshape(-1)[0])
            if d != sparse_dim:
                # The stepper is compiled against sparse_dim; indices
                # from a different feature space would silently clamp/
                # drop in the gather and scatter.
                raise ValueError(
                    f"CSR stream batch has dim {d}, expected {sparse_dim}"
                )
            _check_csr_structure(
                indptr, np.asarray(batch["indices"])[0], sparse_dim
            )
            if validate is not None:
                validate(batch)
            if n == 0 or float(w.sum()) == 0.0:
                raise ValueError(
                    "stream batch has zero total weight (empty batch or "
                    "all weights 0); drop such batches before training"
                )
        bi, bv = _pack_uniform_ell(
            indptr, np.asarray(batch["indices"])[0],
            np.asarray(batch["values"])[0], dtype,
        )
        rows = max(row_tile, -(-n // row_tile) * row_tile)
        # Row padding: index 0 / value 0 / weight 0 — exact no-ops.
        return (
            mesh.shard_batch(_pad_rows(bi, rows)),
            mesh.shard_batch(_pad_rows(bv, rows)),
            mesh.shard_batch(_pad_rows(y, rows)),
            mesh.shard_batch(_pad_rows(w, rows)),
        )

    if sparse_dim is not None:
        place = place_sparse

    from flinkml_tpu.iteration.runtime import TerminateOnMaxIterOrTol

    dt = jnp.dtype(dtype)
    hy = (
        jnp.asarray(learning_rate, dt),
        jnp.asarray(l2, dt),
        jnp.asarray(l1, dt),
    )
    criterion = TerminateOnMaxIterOrTol(max_iter, tol)

    coef = None
    epoch = 0  # epochs completed
    cur_loss = math.inf

    def run_epoch(device_batches, coef):
        """One pass; returns (coef, epoch mean loss). Accumulates the loss
        on device so only the per-epoch conversion synchronizes."""
        loss_acc = jnp.zeros((), dt)
        wsum_acc = jnp.zeros((), dt)
        n_batches = 0
        for tensors in device_batches:
            if coef is None:
                d0 = (sparse_dim if sparse_dim is not None
                      else tensors[0].shape[1])
                coef = jnp.zeros(d0, dt)
            coef, ls, ws = stepper(coef, *tensors, *hy)
            loss_acc = loss_acc + ls
            wsum_acc = wsum_acc + ws
            n_batches += 1
        if n_batches == 0:
            raise ValueError("training stream is empty")
        return coef, float(loss_acc) / float(wsum_acc)

    def after_epoch(terminated: bool):
        """Shared per-epoch bookkeeping (listeners + checkpoint), run after
        `epoch` has been advanced to the completed-epoch count. With a
        manager, the terminal carry is ALWAYS saved (matching
        ``_run_chunked``), even when no interval was configured."""
        nonlocal first_pass_done
        first_pass_done = True
        coef_host = np.asarray(coef)
        for listener in listeners:
            listener.on_epoch_watermark_incremented(epoch - 1, coef_host)
        if checkpoint_manager is not None and (
            terminated
            or (checkpoint_interval > 0 and epoch % checkpoint_interval == 0)
        ):
            checkpoint_manager.save((coef_host, np.float64(cur_loss)), epoch)

    # -- epoch 0: cache + train (ReplayOperator epoch-0 semantics), unless
    # the caller handed us a sealed cache (then every epoch replays it). ---
    if is_cache:
        cache = batches
        if resume:
            if sparse_dim is not None:
                dim = int(sparse_dim)
            else:
                first = next(iter(cache.reader()))
                dim = np.asarray(first[x_key]).shape[1]
            restored = _restore_carry(checkpoint_manager, dim, dtype, mesh)
            if restored is not None:
                coef_h, epoch, cur_loss = restored
                coef = jnp.asarray(coef_h, dt)
    else:
        writer = DataCacheWriter(cache_dir, memory_budget_bytes)

        def caching_iter():
            for b in batches:
                # Copy: the writer freezes RAM-resident arrays against
                # mutation, and that must not leak onto caller-owned
                # buffers that outlive the fit.
                writer.append({k: np.array(v) for k, v in b.items()})
                yield b

        feed0 = PrefetchingDeviceFeed(caching_iter(), place=place,
                                      depth=prefetch_depth)
        try:
            coef, cur_loss = run_epoch(feed0, coef)
        finally:
            feed0.close()
        cache = writer.finish()
        epoch = 1
        after_epoch(criterion.should_terminate(0, cur_loss))

    # -- remaining epochs: replay the cache through the prefetching feed ----
    while not (epoch > 0 and criterion.should_terminate(epoch - 1, cur_loss)):
        feed = PrefetchingDeviceFeed(cache.reader(), place=place,
                                     depth=prefetch_depth)
        try:
            coef, cur_loss = run_epoch(feed, coef)
        finally:
            feed.close()
        epoch += 1
        after_epoch(criterion.should_terminate(epoch - 1, cur_loss))

    result = np.asarray(coef)
    if checkpoint_manager is not None:
        checkpoint_manager.wait()  # surface a failed final async write
    for listener in listeners:
        listener.on_iteration_terminated(result)
    return result
