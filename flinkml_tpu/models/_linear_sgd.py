"""Generic distributed SGD for linear models (dense and sparse).

One trainer serves LogisticRegression, LinearSVC, and LinearRegression: the
models differ only in ``d loss/d margin``, so the loss enters as a static
key selecting a margin-gradient function, and everything else — window
slicing, MXU matvec, ``psum``, proximal update, ``lax.while_loop``
termination — is shared. This is the TPU inversion of the reference's
``CacheDataAndDoTrain`` machinery (``LogisticRegression.java:334-397``);
see ``logistic_regression.py`` for the full mapping.

Losses (margins use labels y ∈ {0,1} mapped to ys = 2y-1 where relevant):
  - ``logistic``: loss = w·log(1+exp(-dot·ys)); matches
    ``LogisticGradient.java:50-96``.
  - ``hinge`` (LinearSVC): loss = w·max(0, 1 - dot·ys).
  - ``squared`` (LinearRegression): loss = w·(dot - y)²/2.

Regularization: L2 enters the gradient; L1 (elastic net) is applied as a
proximal soft-threshold after the gradient step — the "proximal SGD step"
of BASELINE.json config #3.

The sparse path consumes padded ELL batches (``flinkml_tpu.ops.sparse``):
forward = gather+row-sum, gradient = flat segment-sum scatter — the
Criteo-scale path (config #5).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.ops import pallas_kernels
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple

_LOSS_KEYS = ("logistic", "hinge", "squared")


# The margin-gradient math is shared verbatim with the fused Pallas kernel
# (single source of truth — the fused and unfused paths must agree exactly).
_margin_grad = pallas_kernels._margin_terms


def _soft_threshold(x, t):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def align_local_bs(global_batch_size: int, p_size: int, n_local: int) -> int:
    """Per-device batch: ceil(global/p), rounded up to the 8-row tile when
    the Pallas path is in play (so the fused kernel stays reachable at any
    requested batch size), clamped to the shard. Without Pallas the
    requested batch is honored exactly — no silent inflation."""
    bs = max(1, math.ceil(global_batch_size / p_size))
    if pallas_kernels.pallas_active("linear"):
        bs = ((bs + 7) // 8) * 8
    return min(bs, n_local)


def _window(arr, epoch, local_bs):
    """Contiguous rotating window with ceil coverage (tail included via
    dynamic_slice clamping)."""
    n_windows = max(-(-arr.shape[0] // local_bs), 1)
    start = (jnp.asarray(epoch, jnp.int32) % n_windows) * local_bs
    zero = jnp.zeros((), dtype=start.dtype)
    if arr.ndim == 1:
        return jax.lax.dynamic_slice(arr, (start,), (local_bs,))
    return jax.lax.dynamic_slice(arr, (start, zero), (local_bs, arr.shape[1]))


def make_dense_step(loss: str, local_bs: int, axis: str, use_pallas: bool = False):
    """Per-device epoch: window → margin grad on MXU → psum → prox update.

    With ``use_pallas`` (batch must be tile-aligned), the gradient uses the
    fused Pallas kernel (``ops.pallas_kernels.fused_linear_grad``) — one HBM
    pass over the batch instead of XLA's two (forward + back matmul)."""

    def step(coef, epoch, xl, yl, wl, learning_rate, reg_l2, reg_l1):
        xb = _window(xl, epoch, local_bs)
        yb = _window(yl, epoch, local_bs)
        wb = _window(wl, epoch, local_bs)
        if use_pallas:
            grad_l, loss_l, wsum_l = pallas_kernels.fused_linear_grad(
                xb, yb, wb, coef, loss=loss
            )
        else:
            dot = xb @ coef
            mult, per_ex = _margin_grad(loss, dot, yb, wb)
            grad_l = xb.T @ mult
            loss_l = jnp.sum(per_ex)
            wsum_l = jnp.sum(wb)
        grad = jax.lax.psum(grad_l, axis)
        loss_sum = jax.lax.psum(loss_l, axis)
        wsum = jax.lax.psum(wsum_l, axis)
        grad = grad + 2.0 * reg_l2 * coef
        loss_sum = loss_sum + reg_l2 * jnp.sum(coef * coef)
        step_size = learning_rate / wsum
        new_coef = _soft_threshold(coef - step_size * grad, step_size * reg_l1)
        return new_coef, loss_sum / wsum

    return step


def make_sparse_step(loss: str, local_bs: int, axis: str, dim: int):
    """Sparse (padded-ELL) variant: gather forward, segment-sum gradient."""

    def step(coef, epoch, idxl, vall, yl, wl, learning_rate, reg_l2, reg_l1):
        ib = _window(idxl, epoch, local_bs)
        vb = _window(vall, epoch, local_bs)
        yb = _window(yl, epoch, local_bs)
        wb = _window(wl, epoch, local_bs)
        dot = jnp.sum(vb * coef[ib], axis=1)
        mult, per_ex = _margin_grad(loss, dot, yb, wb)
        contrib = (vb * mult[:, None]).reshape(-1)
        grad_local = jax.ops.segment_sum(
            contrib, ib.reshape(-1), num_segments=dim
        )
        grad = jax.lax.psum(grad_local, axis)
        loss_sum = jax.lax.psum(jnp.sum(per_ex), axis)
        wsum = jax.lax.psum(jnp.sum(wb), axis)
        grad = grad + 2.0 * reg_l2 * coef
        loss_sum = loss_sum + reg_l2 * jnp.sum(coef * coef)
        step_size = learning_rate / wsum
        new_coef = _soft_threshold(coef - step_size * grad, step_size * reg_l1)
        return new_coef, loss_sum / wsum

    return step


@functools.lru_cache(maxsize=128)
def _dense_trainer(mesh, loss: str, local_bs: int, axis: str, use_pallas: bool):
    local_step = make_dense_step(loss, local_bs, axis, use_pallas)

    def per_device(xl, yl, wl, learning_rate, reg_l2, reg_l1, tol, max_iter):
        def cond(carry):
            _, epoch, cur = carry
            return jnp.logical_and(epoch < max_iter, cur > tol)

        def body(carry):
            coef, epoch, _ = carry
            new_coef, mean_loss = local_step(
                coef, epoch, xl, yl, wl, learning_rate, reg_l2, reg_l1
            )
            return new_coef, epoch + 1, mean_loss

        init = (
            jnp.zeros(xl.shape[1], dtype=xl.dtype),
            jnp.asarray(0, dtype=jnp.int32),
            jnp.asarray(jnp.inf, dtype=xl.dtype),
        )
        coef, _, _ = jax.lax.while_loop(cond, body, init)
        return coef

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(), P(), P(), P(), P()),
            out_specs=P(),
            # pallas_call out_shapes carry no vma; keep the replication
            # check whenever the plain-XLA path runs.
            check_vma=not use_pallas,
        )
    )


@functools.lru_cache(maxsize=128)
def _sparse_trainer(mesh, loss: str, local_bs: int, axis: str, dim: int):
    local_step = make_sparse_step(loss, local_bs, axis, dim)

    def per_device(idxl, vall, yl, wl, learning_rate, reg_l2, reg_l1, tol, max_iter):
        def cond(carry):
            _, epoch, cur = carry
            return jnp.logical_and(epoch < max_iter, cur > tol)

        def body(carry):
            coef, epoch, _ = carry
            new_coef, mean_loss = local_step(
                coef, epoch, idxl, vall, yl, wl, learning_rate, reg_l2, reg_l1
            )
            return new_coef, epoch + 1, mean_loss

        init = (
            jnp.zeros(dim, dtype=vall.dtype),
            jnp.asarray(0, dtype=jnp.int32),
            jnp.asarray(jnp.inf, dtype=vall.dtype),
        )
        coef, _, _ = jax.lax.while_loop(cond, body, init)
        return coef

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P(), P(), P(), P(), P()),
            out_specs=P(),
        )
    )


def train_linear_model(
    x: np.ndarray,
    y: np.ndarray,
    w: np.ndarray,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    global_batch_size: int,
    reg: float,
    elastic_net: float,
    tol: float,
    seed: int,
    dtype=None,
) -> np.ndarray:
    """Dense distributed training; returns the coefficient on host.

    ``reg``/``elastic_net`` follow the sklearn/Spark convention:
    l1 = reg * elastic_net, l2 = reg * (1 - elastic_net).
    """
    if loss not in _LOSS_KEYS:
        raise ValueError(f"loss must be one of {_LOSS_KEYS}, got {loss!r}")
    n = x.shape[0]
    if n == 0:
        raise ValueError("training table is empty")
    p_size = mesh.axis_size()
    if dtype is not None:
        x, y, w = x.astype(dtype), y.astype(dtype), w.astype(dtype)
    perm = np.random.default_rng(seed).permutation(n)
    x, y, w = x[perm], y[perm], w[perm]
    # Shards align to the 8-row tile only when the Pallas path is in play;
    # otherwise pad exactly to the mesh (identical windows to the baseline).
    row_tile = p_size * 8 if pallas_kernels.pallas_active() else p_size
    x_pad, _ = pad_to_multiple(x, row_tile)
    y_pad, _ = pad_to_multiple(y, row_tile)
    w_pad, _ = pad_to_multiple(w, row_tile)
    xd = mesh.shard_batch(x_pad)
    yd = mesh.shard_batch(y_pad)
    wd = mesh.shard_batch(w_pad)
    n_local = xd.shape[0] // p_size
    local_bs = align_local_bs(global_batch_size, p_size, n_local)
    dt = xd.dtype
    trainer = _dense_trainer(
        mesh.mesh, loss, local_bs, DeviceMesh.DATA_AXIS,
        pallas_kernels.pallas_enabled(local_bs),
    )
    coef = trainer(
        xd, yd, wd,
        jnp.asarray(learning_rate, dt),
        jnp.asarray(reg * (1.0 - elastic_net), dt),
        jnp.asarray(reg * elastic_net, dt),
        jnp.asarray(tol, dt),
        jnp.asarray(max_iter, jnp.int32),
    )
    return np.asarray(coef)


def train_linear_model_sparse(
    indices: np.ndarray,
    values: np.ndarray,
    dim: int,
    y: np.ndarray,
    w: np.ndarray,
    loss: str,
    mesh: DeviceMesh,
    max_iter: int,
    learning_rate: float,
    global_batch_size: int,
    reg: float,
    elastic_net: float,
    tol: float,
    seed: int,
) -> np.ndarray:
    """Sparse (padded-ELL rows) distributed training — the Criteo-scale
    path: per-step cost scales with nnz, the model stays a dense [dim]
    array updated by segment-sum scatter-adds."""
    if loss not in _LOSS_KEYS:
        raise ValueError(f"loss must be one of {_LOSS_KEYS}, got {loss!r}")
    n = indices.shape[0]
    if n == 0:
        raise ValueError("training table is empty")
    p_size = mesh.axis_size()
    perm = np.random.default_rng(seed).permutation(n)
    indices, values, y, w = indices[perm], values[perm], y[perm], w[perm]
    idx_pad, _ = pad_to_multiple(indices, p_size)
    val_pad, _ = pad_to_multiple(values, p_size)
    y_pad, _ = pad_to_multiple(y, p_size)
    w_pad, _ = pad_to_multiple(w, p_size)
    idxd = mesh.shard_batch(idx_pad)
    vald = mesh.shard_batch(val_pad)
    yd = mesh.shard_batch(y_pad)
    wd = mesh.shard_batch(w_pad)
    n_local = idxd.shape[0] // p_size
    local_bs = min(max(1, math.ceil(global_batch_size / p_size)), n_local)
    dt = vald.dtype
    trainer = _sparse_trainer(
        mesh.mesh, loss, local_bs, DeviceMesh.DATA_AXIS, int(dim)
    )
    coef = trainer(
        idxd, vald, yd, wd,
        jnp.asarray(learning_rate, dt),
        jnp.asarray(reg * (1.0 - elastic_net), dt),
        jnp.asarray(reg * elastic_net, dt),
        jnp.asarray(tol, dt),
        jnp.asarray(max_iter, jnp.int32),
    )
    return np.asarray(coef)
