"""VectorAssembler — concatenate numeric/vector columns into one features
column.

Beyond the reference snapshot (SURVEY.md §2.3 has only OneHotEncoder) but a
standard member of the wider Flink ML feature family. Stateless
``AlgoOperator`` (no fit): scalars contribute one slot, 2-D columns their
width. ``handleInvalid``: ``error`` rejects non-finite values, ``skip``
drops offending rows, ``keep`` passes them through (NaN/inf preserved).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator, ColumnKernel
from flinkml_tpu.common_params import HasHandleInvalid, HasInputCols
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import StringParam


class VectorAssembler(HasInputCols, HasHandleInvalid, AlgoOperator):
    OUTPUT_COL = StringParam("outputCol", "Output column name.", "features")

    def transform_kernel(self):
        """Fusable only with ``handleInvalid='keep'``: ``skip`` changes the
        row count (shapes are static under jit) and ``error`` raises on
        data values (no data-dependent control flow on device)."""
        cols = self.get(self.INPUT_COLS)
        if not cols or self.get(self.HANDLE_INVALID) != HasHandleInvalid.KEEP_INVALID:
            return None
        cols = tuple(cols)
        out_col = self.get(self.OUTPUT_COL)

        def fn(colvals, consts, valid):
            import jax.numpy as jnp

            # Floating parts keep their dtype; non-float parts promote to
            # float64. Concatenation promotes to the widest part — the
            # same result_type rule as the host path, so an all-float32
            # assembly stays float32 (analysis rule FML106).
            parts = []
            for c in cols:
                p = colvals[c]
                if p.ndim == 1:
                    p = p.reshape(-1, 1)
                if not jnp.issubdtype(p.dtype, jnp.floating):
                    p = p.astype(jnp.float64)
                parts.append(p)
            dt = jnp.result_type(*(p.dtype for p in parts))
            return {
                out_col: jnp.concatenate(
                    [p.astype(dt) for p in parts], axis=1
                )
            }

        return ColumnKernel(
            input_cols=cols, output_cols=(out_col,), fn=fn,
            fingerprint=("VectorAssembler", cols, out_col),
        )

    def transform(self, *inputs: Tuple) -> Tuple:
        (table,) = inputs
        cols = self.get(self.INPUT_COLS)
        if not cols:
            raise ValueError("inputCols must be set")
        # dtype=None: floating columns keep their dtype, non-float promote
        # to float64; concatenation promotes to the widest part (matches
        # the fused kernel, so an all-float32 assembly stays float32).
        parts: List[np.ndarray] = [
            features_matrix(table, c, dtype=None) for c in cols
        ]
        n = parts[0].shape[0]
        for c, p in zip(cols, parts):
            if p.shape[0] != n:
                raise ValueError(
                    f"column {c!r} has {p.shape[0]} rows, expected {n}"
                )
        dt = np.result_type(*(p.dtype for p in parts))
        out = np.concatenate([p.astype(dt, copy=False) for p in parts], axis=1)
        mode = self.get(self.HANDLE_INVALID)
        bad = ~np.isfinite(out).all(axis=1)
        if mode == "error":
            if bad.any():
                raise ValueError(
                    f"non-finite value in row {int(np.argmax(bad))}; "
                    "set handleInvalid to 'skip' or 'keep' to allow"
                )
        elif mode == "skip":
            if bad.any():
                keep = ~bad
                table = table.take(np.flatnonzero(keep))
                out = out[keep]
        # mode == "keep": pass through unchanged.
        return (table.with_column(self.get(self.OUTPUT_COL), out),)
