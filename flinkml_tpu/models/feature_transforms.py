"""Stateless feature transformers: Normalizer, ElementwiseProduct,
VectorSlicer, PolynomialExpansion, Binarizer, Bucketizer.

Beyond the reference snapshot (whose only feature stages are OneHotEncoder
plus what this repo adds, SURVEY.md §2.3) but standard members of the wider
Flink ML operator family. All of these are pure row-wise functions with no
fitted state, so they are ``Transformer``s (no Estimator/Model split).

TPU stance: these run as vectorized numpy on the host — they are O(n·d)
elementwise passes over host-resident columnar tables, executed once per
table; shipping them to the device would spend more on the transfer than
the math. When one of them sits in front of a trainer, the trainer's
device feed ships the *result* exactly once, which is the same number of
host↔HBM crossings the fused alternative would pay.
"""

from __future__ import annotations

from itertools import combinations_with_replacement
from typing import Tuple

import numpy as np

from flinkml_tpu.api import Transformer
from flinkml_tpu.common_params import (
    HasHandleInvalid,
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    HasOutputCols,
)
from flinkml_tpu.params import (
    FloatArrayArrayParam,
    FloatArrayParam,
    FloatParam,
    IntArrayParam,
    IntParam,
    ParamValidators,
)
from flinkml_tpu.table import Table


def _features(table: Table, col: str) -> np.ndarray:
    x = np.asarray(table.column(col), dtype=np.float64)
    if x.ndim != 2:
        raise ValueError(f"Column {col!r} must be a [rows, dim] matrix, got {x.shape}")
    return x


class Normalizer(HasInputCol, HasOutputCol, Transformer):
    """Scale each row to unit p-norm (default p=2). Zero rows stay zero."""

    P = FloatParam("p", "The p of the p-norm.", 2.0, ParamValidators.gt_eq(1.0))

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        x = _features(table, self.get(self.INPUT_COL))
        p = self.get(self.P)
        if np.isinf(p):
            norms = np.abs(x).max(axis=1)
        else:
            norms = (np.abs(x) ** p).sum(axis=1) ** (1.0 / p)
        safe = np.where(norms > 0, norms, 1.0)
        return (
            table.with_column(self.get(self.OUTPUT_COL), x / safe[:, None]),
        )


class ElementwiseProduct(HasInputCol, HasOutputCol, Transformer):
    """Hadamard product of every row with a fixed scaling vector."""

    SCALING_VEC = FloatArrayParam(
        "scalingVec", "The fixed vector to multiply each row by.", None,
        ParamValidators.non_empty_array(),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        scaling = self.get(self.SCALING_VEC)
        if scaling is None:
            raise ValueError("scalingVec must be set")
        v = np.asarray(scaling, dtype=np.float64)
        x = _features(table, self.get(self.INPUT_COL))
        if x.shape[1] != v.shape[0]:
            raise ValueError(
                f"scalingVec has {v.shape[0]} entries, features have dim {x.shape[1]}"
            )
        return (table.with_column(self.get(self.OUTPUT_COL), x * v),)


class VectorSlicer(HasInputCol, HasOutputCol, Transformer):
    """Select a subset of feature indices from each row (order preserved,
    duplicates allowed — the upstream family's semantics)."""

    INDICES = IntArrayParam(
        "indices", "Indices of the features to keep.", None,
        ParamValidators.non_empty_array(),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        indices = self.get(self.INDICES)
        if indices is None:
            raise ValueError("indices must be set")
        idx = np.asarray(indices, dtype=np.int64)
        x = _features(table, self.get(self.INPUT_COL))
        if (idx < 0).any() or (idx >= x.shape[1]).any():
            raise ValueError(
                f"indices must be within [0, {x.shape[1] - 1}], got {indices}"
            )
        return (table.with_column(self.get(self.OUTPUT_COL), x[:, idx]),)


class PolynomialExpansion(HasInputCol, HasOutputCol, Transformer):
    """Expand features into all monomials of degree 1..degree.

    Output order: combinations-with-replacement of feature indices in
    lexicographic order, grouped by ascending degree — e.g. dim 2,
    degree 2 → ``[x0, x1, x0², x0·x1, x1²]``. Output size is
    C(dim + degree, degree) − 1 (no constant term), matching the upstream
    family's expansion set (ordering documented here rather than
    bit-matching Spark's recursion).
    """

    DEGREE = IntParam(
        "degree", "The polynomial degree to expand to.", 2,
        ParamValidators.gt_eq(1),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        x = _features(table, self.get(self.INPUT_COL))
        degree = self.get(self.DEGREE)
        cols = []
        for deg in range(1, degree + 1):
            for combo in combinations_with_replacement(range(x.shape[1]), deg):
                cols.append(np.prod(x[:, combo], axis=1))
        return (
            table.with_column(
                self.get(self.OUTPUT_COL), np.stack(cols, axis=1)
            ),
        )


class Binarizer(HasInputCols, HasOutputCols, Transformer):
    """Threshold columns to {0, 1}: value > threshold → 1.0.

    Works on scalar columns and on [rows, dim] vector columns alike
    (one threshold per input column).
    """

    THRESHOLDS = FloatArrayParam(
        "thresholds", "Per-column binarization thresholds.", None,
        ParamValidators.non_empty_array(),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        output_cols = self.get(self.OUTPUT_COLS)
        thresholds = self.get(self.THRESHOLDS)
        if not input_cols or thresholds is None:
            raise ValueError("inputCols and thresholds must be set")
        if not (len(input_cols) == len(output_cols) == len(thresholds)):
            raise ValueError(
                "inputCols, outputCols, and thresholds must have equal length"
            )
        out = table
        for col, out_col, thr in zip(input_cols, output_cols, thresholds):
            values = np.asarray(table.column(col), dtype=np.float64)
            out = out.with_column(out_col, (values > thr).astype(np.float64))
        return (out,)


class Bucketizer(HasInputCols, HasOutputCols, HasHandleInvalid, Transformer):
    """Map continuous scalar columns to bucket indices via split points.

    ``splitsArray[i]`` is the strictly-increasing split vector for input
    column i (±inf sentinels allowed): bucket b covers
    ``[splits[b], splits[b+1])``, with the last bucket right-inclusive.
    ``handleInvalid``: "error" raises on NaN/out-of-range, "skip" drops
    the whole row, "keep" maps invalids to the extra bucket
    ``numBuckets``.
    """

    SPLITS_ARRAY = FloatArrayArrayParam(
        "splitsArray", "Per-column arrays of split points.", None,
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        output_cols = self.get(self.OUTPUT_COLS)
        splits_array = self.get(self.SPLITS_ARRAY)
        handle_invalid = self.get(self.HANDLE_INVALID)
        if not input_cols or splits_array is None:
            raise ValueError("inputCols and splitsArray must be set")
        if not (len(input_cols) == len(output_cols) == len(splits_array)):
            raise ValueError(
                "inputCols, outputCols, and splitsArray must have equal length"
            )
        out = table
        keep_mask = np.ones(table.num_rows, dtype=bool)
        for col, out_col, splits in zip(input_cols, output_cols, splits_array):
            s = np.asarray(splits, dtype=np.float64)
            if len(s) < 2 or not np.all(np.diff(s) > 0):
                raise ValueError(
                    f"splits for column {col!r} must be >= 2 strictly "
                    f"increasing values, got {splits}"
                )
            values = np.asarray(table.column(col), dtype=np.float64)
            n_buckets = len(s) - 1
            # searchsorted('right') puts v == splits[b] into bucket b;
            # clamp the top edge so the last bucket is right-inclusive.
            bucket = np.searchsorted(s, values, side="right") - 1
            bucket = np.where(values == s[-1], n_buckets - 1, bucket)
            invalid = (
                np.isnan(values) | (values < s[0]) | (values > s[-1])
            )
            if handle_invalid == HasHandleInvalid.ERROR_INVALID:
                if invalid.any():
                    raise ValueError(
                        f"Column {col!r} has values outside "
                        f"[{s[0]}, {s[-1]}]: {values[invalid][:5]}"
                    )
            elif handle_invalid == HasHandleInvalid.SKIP_INVALID:
                keep_mask &= ~invalid
            else:  # keep → catch-all bucket
                bucket = np.where(invalid, n_buckets, bucket)
            out = out.with_column(out_col, bucket.astype(np.float64))
        if not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return (out,)
