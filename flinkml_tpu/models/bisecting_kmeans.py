"""BisectingKMeans — top-down hierarchical k-means (the Spark/Flink
family member).

Start with all rows in one cluster; repeatedly split the cluster with
the largest within-cluster sum of squared distances using a seeded
2-means (each split is the existing whole-loop-on-device KMeans program
over that cluster's rows) until ``k`` leaf clusters exist. Degenerate
splits (a cluster of identical points) retire the cluster from further
splitting. Prediction is nearest-centroid over the leaf centroids —
the model is a :class:`KMeansModel` with bisecting-derived centroids,
so the broadcast-predict path and persistence are shared.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator
from flinkml_tpu.models.kmeans import KMeansModel, _KMeansParams, train_kmeans
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.ops import blas
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


class BisectingKMeans(_KMeansParams, Estimator):
    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "BisectingKMeansModel":
        (table,) = inputs
        if self.get(self.DISTANCE_MEASURE) != "euclidean":
            raise ValueError(
                "BisectingKMeans trains on squared-euclidean WCSS; "
                "distanceMeasure must be 'euclidean' (same constraint as "
                "KMeans.fit)"
            )
        x = features_matrix(table, self.get(self.FEATURES_COL))
        k = self.get(self.K)
        n = x.shape[0]
        if n < k:
            raise ValueError(f"n_rows={n} < k={k}")
        mesh = self.mesh or DeviceMesh()
        max_iter = self.get(self.MAX_ITER)
        init_mode = self.get(self.INIT_MODE)
        seed = self.get_seed()

        # Leaf clusters as (member_index_array, centroid, splittable).
        members = [np.arange(n)]
        centroids = [x.mean(axis=0)]
        splittable = [True]
        split_round = 0
        while len(members) < k and any(
            s and len(m) >= 2 for s, m in zip(splittable, members)
        ):
            # Pick the splittable cluster with the largest WCSS.
            wcss = [
                float(((x[m] - c) ** 2).sum()) if s and len(m) >= 2 else -1.0
                for m, c, s in zip(members, centroids, splittable)
            ]
            target = int(np.argmax(wcss))
            idx = members[target]
            sub_centroids = train_kmeans(
                x[idx], 2, mesh, max_iter, seed + split_round,
                init_mode=init_mode,
            )
            split_round += 1
            assign = np.asarray(jnp.argmin(blas.squared_distances(
                jnp.asarray(x[idx], jnp.float32),
                jnp.asarray(sub_centroids, jnp.float32),
            ), axis=1))
            left, right = idx[assign == 0], idx[assign == 1]
            if len(left) == 0 or len(right) == 0:
                # Identical points (or collapsed split): retire the leaf.
                splittable[target] = False
                continue
            members[target] = left
            centroids[target] = x[left].mean(axis=0)
            splittable[target] = True
            members.append(right)
            centroids.append(x[right].mean(axis=0))
            splittable.append(True)

        model = BisectingKMeansModel()
        model.copy_params_from(self)
        model.set_model_data(
            Table({"centroids": np.stack(centroids)[None, :, :]})
        )
        return model


class BisectingKMeansModel(KMeansModel):
    """Nearest-centroid prediction over the bisecting-derived leaf
    centroids (shares KMeansModel's predict + persistence)."""
