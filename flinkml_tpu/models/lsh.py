"""MinHashLSH — Jaccard-similarity locality-sensitive hashing (the
upstream operator).

Hash family: ``h_i(x) = min over active indices j of
((a_i·(j+1) + b_i) mod PRIME)`` with Spark's ``PRIME = 2038074743``;
``numHashTables`` independent hashes trade recall for work. The model
offers the two upstream query surfaces:

  - ``approx_nearest_neighbors(dataset, key, k)`` — candidates are rows
    sharing at least one hash value with the key; exact Jaccard
    distance ranks them.
  - ``approx_similarity_join(a, b, threshold)`` — candidate pairs
    bucket-join on (table, hash value), then exact distance filters.

Active-index extraction and bucket joins are host work (hashing is
integer arithmetic over ragged index sets — nothing for the MXU);
vectorized numpy does the per-row min-hash in one pass per table.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import HasInputCol, HasOutputCol, HasSeed
from flinkml_tpu.linalg import SparseVector
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.table import Table

PRIME = 2038074743  # Spark's MinHash prime


def _active_indices(col: np.ndarray) -> List[np.ndarray]:
    """Per-row sorted active (nonzero) index arrays from a SparseVector
    object column or a dense [n, d] 0/1 matrix."""
    if col.dtype == object:
        rows = []
        for v in col:
            if isinstance(v, SparseVector):
                rows.append(v.indices[v.values != 0])
            else:
                arr = np.asarray(v, dtype=np.float64)
                rows.append(np.nonzero(arr)[0])
        return rows
    x = np.asarray(col, dtype=np.float64)
    if x.ndim != 2:
        raise ValueError(f"LSH input must be [n, d] or SparseVectors, got {x.shape}")
    return [np.nonzero(row)[0] for row in x]


def _jaccard_distance(a: np.ndarray, b: np.ndarray) -> float:
    if len(a) == 0 and len(b) == 0:
        return 1.0
    inter = len(np.intersect1d(a, b, assume_unique=True))
    union = len(a) + len(b) - inter
    return 1.0 - inter / union


class MinHashLSH(HasInputCol, HasOutputCol, HasSeed, Estimator):
    NUM_HASH_TABLES = IntParam(
        "numHashTables", "Number of independent hash functions.", 1,
        ParamValidators.gt(0),
    )

    def fit(self, *inputs: Table) -> "MinHashLSHModel":
        (table,) = inputs  # fit only draws the hash family (data-free)
        rng = np.random.default_rng(self.get_seed())
        n_tables = self.get(self.NUM_HASH_TABLES)
        a = rng.integers(1, PRIME, size=n_tables, dtype=np.int64)
        b = rng.integers(0, PRIME, size=n_tables, dtype=np.int64)
        model = MinHashLSHModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"a": a[None, :], "b": b[None, :]}))
        return model


class MinHashLSHModel(HasInputCol, HasOutputCol, HasSeed, Model):
    NUM_HASH_TABLES = MinHashLSH.NUM_HASH_TABLES

    def __init__(self):
        super().__init__()
        self._a: Optional[np.ndarray] = None
        self._b: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "MinHashLSHModel":
        (table,) = inputs
        self._a = np.asarray(table.column("a"), np.int64)[0]
        self._b = np.asarray(table.column("b"), np.int64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"a": self._a[None, :], "b": self._b[None, :]})]

    def _require(self) -> None:
        if self._a is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def _hash_rows(self, rows: List[np.ndarray]) -> np.ndarray:
        """[n, numHashTables] min-hash values; empty rows hash to PRIME.

        One vectorized pass over the concatenated index sets:
        ``minimum.reduceat`` over row offsets replaces a per-row Python
        loop.
        """
        out = np.full((len(rows), len(self._a)), PRIME, dtype=np.int64)
        lengths = np.asarray([len(r) for r in rows])
        nonempty = np.nonzero(lengths)[0]
        if len(nonempty) == 0:
            return out
        flat = np.concatenate([rows[i] for i in nonempty]).astype(np.int64)
        h = (self._a[None, :] * (flat[:, None] + 1) + self._b[None, :]) % PRIME
        offsets = np.concatenate([[0], np.cumsum(lengths[nonempty])[:-1]])
        out[nonempty] = np.minimum.reduceat(h, offsets, axis=0)
        return out

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        rows = _active_indices(table.column(self.get(self.INPUT_COL)))
        return (
            table.with_column(
                self.get(self.OUTPUT_COL),
                self._hash_rows(rows).astype(np.float64),
            ),
        )

    # -- query surfaces ------------------------------------------------------
    def approx_nearest_neighbors(
        self, dataset: Table, key, k: int,
        dist_col: str = "distCol",
    ) -> Table:
        """Top-``k`` rows of ``dataset`` by Jaccard distance to ``key``,
        restricted to rows sharing ≥1 hash value with it.

        Candidate ranking is the device ``top_k`` idiom ``knn.py`` uses
        (through the kernel-backend gate, :mod:`flinkml_tpu.kernels`)
        rather than a per-row host ``np.argsort``: ``top_k(-dists, k)``
        under x64 ranks ascending distance with ties broken toward the
        LOWER candidate index — exactly the stable-argsort order the
        host path produced (pinned by the parity test in
        ``tests/test_kernels.py``)."""
        self._require()
        rows = _active_indices(dataset.column(self.get(self.INPUT_COL)))
        hashes = self._hash_rows(rows)
        if isinstance(key, SparseVector):
            key_idx = key.indices[key.values != 0]
        else:
            key_idx = np.nonzero(np.asarray(key, dtype=np.float64))[0]
        key_hash = self._hash_rows([key_idx])[0]
        candidates = np.nonzero((hashes == key_hash[None, :]).any(axis=1))[0]
        dists = np.asarray([
            _jaccard_distance(rows[i], key_idx) for i in candidates
        ])
        k_eff = min(int(k), dists.size)
        if k_eff == 0:
            order = np.zeros(0, dtype=np.int64)
        else:
            import jax

            from flinkml_tpu import kernels

            # x64 keeps the ranking in float64, matching the host
            # distances exactly (no f32 rounding could reorder ties).
            with jax.experimental.enable_x64(True):
                _, order = kernels.top_k(
                    jax.numpy.asarray(-dists), k_eff,
                    backend=kernels.topk_backend(),
                )
            order = np.asarray(order, dtype=np.int64)
        picked = candidates[order]
        return dataset.take(picked).with_column(dist_col, dists[order])

    def approx_similarity_join(
        self, table_a: Table, table_b: Table, threshold: float,
        dist_col: str = "distCol",
    ) -> Table:
        """Pairs (idA, idB, distance) with Jaccard distance ≤ threshold,
        restricted to pairs sharing a hash bucket."""
        self._require()
        rows_a = _active_indices(table_a.column(self.get(self.INPUT_COL)))
        rows_b = _active_indices(table_b.column(self.get(self.INPUT_COL)))
        ha = self._hash_rows(rows_a)
        hb = self._hash_rows(rows_b)
        buckets: Dict[Tuple[int, int], List[int]] = {}
        for i, row in enumerate(hb):
            for t, h in enumerate(row):
                buckets.setdefault((t, int(h)), []).append(i)
        seen: Set[Tuple[int, int]] = set()
        ids_a, ids_b, dists = [], [], []
        for i, row in enumerate(ha):
            for t, h in enumerate(row):
                for j in buckets.get((t, int(h)), ()):
                    if (i, j) in seen:
                        continue
                    seen.add((i, j))
                    d = _jaccard_distance(rows_a[i], rows_b[j])
                    if d <= threshold:
                        ids_a.append(i)
                        ids_b.append(j)
                        dists.append(d)
        return Table({
            "idA": np.asarray(ids_a, dtype=np.int64),
            "idB": np.asarray(ids_b, dtype=np.int64),
            dist_col: np.asarray(dists, dtype=np.float64),
        })

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"a": self._a, "b": self._b})

    @classmethod
    def load(cls, path: str) -> "MinHashLSHModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._a = arrays["a"]
        model._b = arrays["b"]
        return model
