"""KMeans — Lloyd's algorithm with random init.

Capability parity with ``flink-ml-lib/.../clustering/kmeans/KMeans.java:79-335``
(+ ``KMeansModel.java``, ``KMeansModelData.java``), rebuilt TPU-first:

  - ``selectRandomCentroids`` (mapPartition + shuffle at parallelism 1,
    ``KMeans.java:314-335``) → seeded host choice of k distinct rows.
  - The per-epoch machinery — broadcast centroids into a 2-input
    ``SelectNearestCentroidOperator`` caching points in ListState
    (``:239-312``), per-round keyed reduce (``CountAppender``/
    ``CentroidAccumulator``/``CentroidAverager`` + ``EndOfStreamWindows``,
    ``:174-235``) — becomes one fused XLA program: pairwise-distance argmin
    on the MXU, per-cluster sums via a one-hot matmul (k is small; a matmul
    beats scatter on TPU), ``psum`` across the data axis, centroid update —
    the whole Lloyd loop in a single ``lax.while_loop`` on device.
  - Termination: ``TerminateOnMaxIter`` (``:150-151``); the reference has no
    tol-based stop for KMeans.
  - Empty clusters keep their previous centroid (the reference's keyed
    reduce simply never emits for an empty cluster, leaving it unchanged).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasDistanceMeasure,
    HasFeaturesCol,
    HasK,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
)
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import IntParam, ParamValidators, StringParam
from flinkml_tpu.ops import blas, pallas_kernels
from flinkml_tpu.ops.distance import DistanceMeasure
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _KMeansParams(
    HasDistanceMeasure, HasFeaturesCol, HasPredictionCol, HasK, HasMaxIter, HasSeed
):
    """Reference: KMeansParams. KMeans redefines ``k`` (clusters, default 2,
    > 1 — ``KMeansModelParams`` declares gt(1)) over HasK's
    nearest-neighbors variant.

    ``initMode`` is an addition over the reference (random init only there,
    ``KMeans.java:314-335``): "k-means++" gives sklearn-quality starts.
    """

    K = IntParam(
        "k", "The number of clusters to create.", 2, ParamValidators.gt(1)
    )

    INIT_MODE = StringParam(
        "initMode", "Centroid initialization: random or k-means++.", "random",
        ParamValidators.in_array(["random", "k-means++"]),
    )


class KMeans(_KMeansParams, Estimator):
    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "KMeansModel":
        (table,) = inputs
        x = features_matrix(table, self.get(_KMeansParams.FEATURES_COL))
        k = self.get(_KMeansParams.K)
        if x.shape[0] < k:
            raise ValueError(f"k={k} exceeds number of points {x.shape[0]}")
        measure = self.get(_KMeansParams.DISTANCE_MEASURE)
        if measure != "euclidean":
            raise ValueError(
                "KMeans currently supports the euclidean distance measure "
                f"(parity with the reference), got {measure!r}"
            )
        centroids = train_kmeans(
            x,
            k=k,
            mesh=self.mesh or DeviceMesh(),
            max_iter=self.get(_KMeansParams.MAX_ITER),
            seed=self.get_seed(),
            init_mode=self.get(_KMeansParams.INIT_MODE),
        )
        model = KMeansModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"centroids": centroids[None, :, :]}))
        return model


class KMeansModel(_KMeansParams, Model):
    """Nearest-centroid prediction (broadcast-model pattern,
    ``KMeansModel.java``)."""

    def __init__(self):
        super().__init__()
        self._centroids: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "KMeansModel":
        (table,) = inputs
        c = np.asarray(table.column("centroids"), dtype=np.float64)
        self._centroids = c.reshape(c.shape[-2], c.shape[-1])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"centroids": self._centroids[None, :, :]})]

    @property
    def centroids(self) -> np.ndarray:
        self._require_model()
        return self._centroids

    def _require_model(self) -> None:
        if self._centroids is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        x = features_matrix(table, self.get(_KMeansParams.FEATURES_COL))
        measure = DistanceMeasure.get_instance(
            self.get(_KMeansParams.DISTANCE_MEASURE)
        )
        assign = np.asarray(
            measure.nearest(jnp.asarray(x), jnp.asarray(self._centroids))
        )
        return (
            table.with_column(self.get(_KMeansParams.PREDICTION_COL), assign),
        )

    def save(self, path: str) -> None:
        self._require_model()
        self._save_with_arrays(path, {"centroids": self._centroids})

    @classmethod
    def load(cls, path: str) -> "KMeansModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._centroids = arrays["centroids"]
        return model


@functools.lru_cache(maxsize=64)
def _kmeans_trainer(mesh, k: int, axis: str, use_pallas: bool):
    """Whole Lloyd loop as one XLA program, cached per (mesh, k)."""

    def per_device(xl, wl, init_centroids, max_iter):
        def body(_, centroids):
            if use_pallas:
                # Fused Pallas Lloyd pass: distances + argmin + one-hot
                # accumulation in one read of the points.
                sums_l, counts_l = pallas_kernels.fused_kmeans_step(
                    xl, wl, centroids
                )
            else:
                # Assignment: argmin over pairwise squared distances (MXU).
                d2 = blas.squared_distances(xl, centroids)
                assign = jnp.argmin(d2, axis=-1)
                # Per-cluster sums via one-hot matmul; padded rows have w=0.
                onehot = jax.nn.one_hot(assign, k, dtype=xl.dtype) * wl[:, None]
                sums_l = onehot.T @ xl
                counts_l = jnp.sum(onehot, axis=0)
            sums = jax.lax.psum(sums_l, axis)
            counts = jax.lax.psum(counts_l, axis)
            # Empty clusters keep their previous centroid.
            safe = jnp.maximum(counts, 1.0)[:, None]
            new_centroids = jnp.where(
                counts[:, None] > 0, sums / safe, centroids
            )
            return new_centroids

        return jax.lax.fori_loop(0, max_iter, body, init_centroids)

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(), P()),
            out_specs=P(),
            # pallas_call out_shapes carry no vma; keep the replication
            # check whenever the plain-XLA path runs.
            check_vma=not use_pallas,
        )
    )


def _kmeans_pp_init(x: np.ndarray, k: int, rng: np.random.Generator) -> np.ndarray:
    """k-means++ seeding: each next centroid sampled ∝ distance² to the
    nearest chosen one."""
    centroids = [x[rng.integers(x.shape[0])]]
    d2 = ((x - centroids[0]) ** 2).sum(-1)
    for _ in range(1, k):
        probs = d2 / d2.sum() if d2.sum() > 0 else np.full(len(x), 1.0 / len(x))
        nxt = x[rng.choice(x.shape[0], p=probs)]
        centroids.append(nxt)
        d2 = np.minimum(d2, ((x - nxt) ** 2).sum(-1))
    return np.stack(centroids)


def train_kmeans(
    x: np.ndarray,
    k: int,
    mesh: DeviceMesh,
    max_iter: int,
    seed: int,
    init_mode: str = "random",
) -> np.ndarray:
    """Returns centroids [k, d]; the full loop runs on device."""
    rng = np.random.default_rng(seed)
    if init_mode == "k-means++":
        init_centroids = _kmeans_pp_init(x, k, rng)
    else:
        init_idx = rng.choice(x.shape[0], size=k, replace=False)
        init_centroids = np.ascontiguousarray(x[init_idx])

    xd, wd, _, use_pallas = prepare_kmeans_data(x, mesh)
    trainer = _kmeans_trainer(mesh.mesh, k, DeviceMesh.DATA_AXIS, use_pallas)
    centroids = trainer(
        xd, wd, jnp.asarray(init_centroids), jnp.asarray(max_iter, jnp.int32)
    )
    return np.asarray(centroids)


def prepare_kmeans_data(x: np.ndarray, mesh: DeviceMesh):
    """Pad/mask/shard points for the Lloyd trainer; returns
    ``(xd, wd, n_valid, use_pallas)``. The single source of the padding
    and kernel-gating policy — the bench measures exactly what
    :func:`train_kmeans` runs."""
    p_size = mesh.axis_size()
    # Pad local shards to the Pallas row tile (8) so the fused Lloyd
    # kernel applies; zero-weight rows are exact no-ops either way.
    x_pad, n_valid = pad_to_multiple(x, p_size * 8)
    w = np.zeros(x_pad.shape[0], dtype=x.dtype)
    w[:n_valid] = 1.0  # mask: padded rows never influence centroids
    return (
        mesh.shard_batch(x_pad),
        mesh.shard_batch(w),
        n_valid,
        pallas_kernels.pallas_enabled(x_pad.shape[0] // p_size, "kmeans"),
    )
