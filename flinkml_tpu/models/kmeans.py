"""KMeans — Lloyd's algorithm with random init.

Capability parity with ``flink-ml-lib/.../clustering/kmeans/KMeans.java:79-335``
(+ ``KMeansModel.java``, ``KMeansModelData.java``), rebuilt TPU-first:

  - ``selectRandomCentroids`` (mapPartition + shuffle at parallelism 1,
    ``KMeans.java:314-335``) → seeded host choice of k distinct rows.
  - The per-epoch machinery — broadcast centroids into a 2-input
    ``SelectNearestCentroidOperator`` caching points in ListState
    (``:239-312``), per-round keyed reduce (``CountAppender``/
    ``CentroidAccumulator``/``CentroidAverager`` + ``EndOfStreamWindows``,
    ``:174-235``) — becomes one fused XLA program: pairwise-distance argmin
    on the MXU, per-cluster sums via a one-hot matmul (k is small; a matmul
    beats scatter on TPU), ``psum`` across the data axis, centroid update —
    the whole Lloyd loop in a single ``lax.while_loop`` on device.
  - Termination: ``TerminateOnMaxIter`` (``:150-151``); the reference has no
    tol-based stop for KMeans.
  - Empty clusters keep their previous centroid (the reference's keyed
    reduce simply never emits for an empty cluster, leaving it unchanged).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasDistanceMeasure,
    HasFeaturesCol,
    HasK,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
)
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import IntParam, ParamValidators, StringParam
from flinkml_tpu.ops import blas
from flinkml_tpu.ops.distance import DistanceMeasure
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table


class _KMeansParams(
    HasDistanceMeasure, HasFeaturesCol, HasPredictionCol, HasK, HasMaxIter, HasSeed
):
    """Reference: KMeansParams. KMeans redefines ``k`` (clusters, default 2,
    > 1 — ``KMeansModelParams`` declares gt(1)) over HasK's
    nearest-neighbors variant.

    ``initMode`` is an addition over the reference (random init only there,
    ``KMeans.java:314-335``): "k-means++" gives sklearn-quality starts.
    """

    K = IntParam(
        "k", "The number of clusters to create.", 2, ParamValidators.gt(1)
    )

    INIT_MODE = StringParam(
        "initMode", "Centroid initialization: random or k-means++.", "random",
        ParamValidators.in_array(["random", "k-means++"]),
    )


class KMeans(StreamingEstimatorMixin, _KMeansParams, Estimator):
    """``fit`` accepts, besides a single in-RAM :class:`Table`:

      - an **iterable of batch Tables** — the out-of-core path: epoch 0
        caches the stream (spilling to ``cache_dir`` beyond
        ``cache_memory_budget_bytes``) while reservoir-sampling init
        centroids; each Lloyd iteration then replays the cache through a
        prefetching device feed, accumulating per-cluster sums/counts
        batch-by-batch with bounded HBM residency (reference:
        ``ReplayOperator.java:62-250`` + the point-caching
        ``SelectNearestCentroidOperator``, ``KMeans.java:239-312``);
      - a sealed :class:`~flinkml_tpu.iteration.datacache.DataCache`
        whose batches carry this estimator's features column.
    """


    def fit(self, *inputs) -> "KMeansModel":
        (table,) = inputs
        k = self.get(_KMeansParams.K)
        measure = self.get(_KMeansParams.DISTANCE_MEASURE)
        if measure != "euclidean":
            raise ValueError(
                "KMeans currently supports the euclidean distance measure "
                f"(parity with the reference), got {measure!r}"
            )
        if isinstance(table, Table):
            self._reject_in_ram_checkpointing(
                "the in-RAM fit runs as one whole-loop device program"
            )
            x = features_matrix(table, self.get(_KMeansParams.FEATURES_COL))
            if x.shape[0] < k:
                raise ValueError(
                    f"k={k} exceeds number of points {x.shape[0]}"
                )
            centroids = train_kmeans(
                x,
                k=k,
                mesh=self.mesh or DeviceMesh(),
                max_iter=self.get(_KMeansParams.MAX_ITER),
                seed=self.get_seed(),
                init_mode=self.get(_KMeansParams.INIT_MODE),
            )
        else:
            centroids = self._fit_stream(table, k)
        model = KMeansModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"centroids": centroids[None, :, :]}))
        return model

    def _fit_stream(self, source, k: int) -> np.ndarray:
        from flinkml_tpu.iteration.datacache import DataCache

        features_col = self.get(_KMeansParams.FEATURES_COL)
        if isinstance(source, DataCache):
            batches = source
        else:
            def batches_gen():
                for t in source:
                    yield {
                        "x": features_matrix(t, features_col)
                        .astype(np.float32)
                    }
            batches = batches_gen()
        return train_kmeans_stream(
            batches,
            k=k,
            mesh=self.mesh or DeviceMesh(),
            max_iter=self.get(_KMeansParams.MAX_ITER),
            seed=self.get_seed(),
            init_mode=self.get(_KMeansParams.INIT_MODE),
            cache_dir=self.cache_dir,
            memory_budget_bytes=self.cache_memory_budget_bytes,
            column=(
                features_col if isinstance(source, DataCache) else "x"
            ),
            **self._checkpoint_kwargs(),
        )


class KMeansModel(_KMeansParams, Model):
    """Nearest-centroid prediction (broadcast-model pattern,
    ``KMeansModel.java``)."""

    def __init__(self):
        super().__init__()
        self._centroids: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "KMeansModel":
        (table,) = inputs
        c = np.asarray(table.column("centroids"), dtype=np.float64)
        self._centroids = c.reshape(c.shape[-2], c.shape[-1])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"centroids": self._centroids[None, :, :]})]

    @property
    def centroids(self) -> np.ndarray:
        self._require_model()
        return self._centroids

    def _require_model(self) -> None:
        if self._centroids is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        x = features_matrix(table, self.get(_KMeansParams.FEATURES_COL))
        measure = DistanceMeasure.get_instance(
            self.get(_KMeansParams.DISTANCE_MEASURE)
        )
        assign = np.asarray(
            measure.nearest(jnp.asarray(x), jnp.asarray(self._centroids))
        )
        return (
            table.with_column(self.get(_KMeansParams.PREDICTION_COL), assign),
        )

    def transform_kernel(self):
        """Nearest-centroid assignment as a fusable kernel — the same
        ``DistanceMeasure.nearest`` the per-stage path dispatches, with
        the centroids travelling as a traced constant. The per-stage
        path's dtypes follow the ambient x64 flag (``jnp.asarray`` on the
        float64 feature matrix, argmin's canonical index dtype), so the
        kernel captures that flag at build time rather than inheriting
        the fused executor's always-x64 trace context."""
        if self._centroids is None:
            return None
        if self.get(_KMeansParams.DISTANCE_MEASURE) != "euclidean":
            return None
        fcol = self.get(_KMeansParams.FEATURES_COL)
        pcol = self.get(_KMeansParams.PREDICTION_COL)
        import jax

        x64 = bool(jax.config.jax_enable_x64)
        dt = jnp.float64 if x64 else jnp.float32
        idt = jnp.int64 if x64 else jnp.int32

        from flinkml_tpu.api import ColumnKernel

        def fn(cols, consts, valid):
            # Trace-time policy resolution (the fused program cache keys
            # on the active policy). The distance math follows plain
            # dtype propagation from policy.compute — so its reduce
            # accumulates NARROW, and the FML6xx gate refuses this
            # kernel under a policy whose accum is wider than compute
            # (the strict "mixed" preset); "mixed_inference" admits it.
            from flinkml_tpu import pipeline_fusion

            pol = pipeline_fusion.active_policy()
            # Mixed OR quantized policies declare the compute width (the
            # int8 tier's distances run at its f32 compute, not the
            # captured f64).
            kdt = jnp.dtype(pol.compute_dtype) \
                if pol is not None and (pol.mixed or pol.quant) else dt
            x = cols[fcol]
            if x.ndim == 1:
                x = x.reshape(-1, 1)
            x = x.astype(kdt)
            measure = DistanceMeasure.get_instance("euclidean")
            assign = measure.nearest(x, consts["centroids"].astype(kdt))
            return {pcol: assign.astype(idt)}

        return ColumnKernel(
            input_cols=(fcol,), output_cols=(pcol,), fn=fn,
            constants={"centroids": self._centroids},
            fingerprint=("KMeansModel", fcol, pcol, "euclidean", x64),
            # Distance reductions + argmin lower context-sensitively: the
            # input column must be materialized for per-stage bit parity.
            pin_inputs=True,
        )

    def save(self, path: str) -> None:
        self._require_model()
        self._save_with_arrays(path, {"centroids": self._centroids})

    @classmethod
    def load(cls, path: str) -> "KMeansModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._centroids = arrays["centroids"]
        return model


@functools.lru_cache(maxsize=64)
def _kmeans_trainer(mesh, k: int, axis: str):
    """Whole Lloyd loop as one XLA program, cached per (mesh, k).

    Round-2 measured a hand-fused Pallas Lloyd pass losing to this plain
    lowering at every shape (0.39-0.72x; BASELINE.md "Kernel-path
    verdict"), so the argmin + one-hot-matmul form below IS the fast
    path — XLA's fusion already reads the points once per pass."""

    def per_device(xl, wl, init_centroids, max_iter):
        def body(_, centroids):
            # Assignment: argmin over pairwise squared distances (MXU).
            d2 = blas.squared_distances(xl, centroids)
            assign = jnp.argmin(d2, axis=-1)
            # Per-cluster sums via one-hot matmul; padded rows have w=0.
            onehot = jax.nn.one_hot(assign, k, dtype=xl.dtype) * wl[:, None]
            sums = jax.lax.psum(onehot.T @ xl, axis)
            counts = jax.lax.psum(jnp.sum(onehot, axis=0), axis)
            # Empty clusters keep their previous centroid.
            safe = jnp.maximum(counts, 1.0)[:, None]
            new_centroids = jnp.where(
                counts[:, None] > 0, sums / safe, centroids
            )
            return new_centroids

        return jax.lax.fori_loop(0, max_iter, body, init_centroids)

    return jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(), P()),
            out_specs=P(),
        )
    )


def _kmeans_pp_init(x: np.ndarray, k: int, rng: np.random.Generator) -> np.ndarray:
    """k-means++ seeding: each next centroid sampled ∝ distance² to the
    nearest chosen one."""
    centroids = [x[rng.integers(x.shape[0])]]
    d2 = ((x - centroids[0]) ** 2).sum(-1)
    for _ in range(1, k):
        probs = d2 / d2.sum() if d2.sum() > 0 else np.full(len(x), 1.0 / len(x))
        nxt = x[rng.choice(x.shape[0], p=probs)]
        centroids.append(nxt)
        d2 = np.minimum(d2, ((x - nxt) ** 2).sum(-1))
    return np.stack(centroids)


def train_kmeans(
    x: np.ndarray,
    k: int,
    mesh: DeviceMesh,
    max_iter: int,
    seed: int,
    init_mode: str = "random",
    initial_centroids: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Returns centroids [k, d]; the full loop runs on device.
    ``initial_centroids`` overrides the seeded init (used by tests and by
    warm restarts)."""
    rng = np.random.default_rng(seed)
    if initial_centroids is not None:
        init_centroids = np.asarray(initial_centroids, x.dtype)
    elif init_mode == "k-means++":
        init_centroids = _kmeans_pp_init(x, k, rng)
    else:
        init_idx = rng.choice(x.shape[0], size=k, replace=False)
        init_centroids = np.ascontiguousarray(x[init_idx])

    xd, wd, _ = prepare_kmeans_data(x, mesh)
    trainer = _kmeans_trainer(mesh.mesh, k, DeviceMesh.DATA_AXIS)
    centroids = trainer(
        xd, wd, jnp.asarray(init_centroids), jnp.asarray(max_iter, jnp.int32)
    )
    return np.asarray(centroids)


@functools.lru_cache(maxsize=64)
def _kmeans_partial_fn(mesh, k: int, axis: str):
    """Per-batch Lloyd partials: psum'd per-cluster (sums, counts) for one
    sharded batch against replicated centroids. The streamed trainer
    accumulates these across batches, then updates centroids once per
    epoch — identical math to :func:`_kmeans_trainer`'s body with the
    batch axis split."""

    def per_device(xb, wb, centroids):
        d2 = blas.squared_distances(xb, centroids)
        assign = jnp.argmin(d2, axis=-1)
        onehot = jax.nn.one_hot(assign, k, dtype=xb.dtype) * wb[:, None]
        return (
            jax.lax.psum(onehot.T @ xb, axis),
            jax.lax.psum(jnp.sum(onehot, axis=0), axis),
        )

    return jax.jit(
        jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(), P()),
        )
    )


def train_kmeans_stream(
    batches,
    k: int,
    mesh: DeviceMesh,
    max_iter: int,
    seed: int,
    init_mode: str = "random",
    cache_dir: Optional[str] = None,
    memory_budget_bytes: Optional[int] = None,
    prefetch_depth: int = 2,
    column: str = "x",
    init_sample_size: int = 65_536,
    initial_centroids: Optional[np.ndarray] = None,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    listeners=(),
) -> np.ndarray:
    """Out-of-core Lloyd: train from a one-shot stream of batch dicts (or
    a sealed :class:`DataCache`) with bounded HBM residency.

    Reference parity: ``ReplayOperator.java:62-250`` (epoch-0 cache +
    per-epoch replay) + ``SelectNearestCentroidOperator``'s ListState
    point cache (``KMeans.java:239-312``). Pass 0 caches the stream
    (spilling beyond ``memory_budget_bytes`` to ``cache_dir``) while
    feeding a seeded :class:`RowReservoir` for centroid init —
    ``init_mode='random'`` takes k reservoir rows (uniform over the
    stream, exactly the reference's random init); ``'k-means++'`` runs
    the seeding on a ``init_sample_size`` uniform row sample. Each Lloyd
    iteration replays the cache through a prefetching device feed,
    accumulating per-cluster sums/counts on device; centroids update once
    per epoch (empty clusters keep their previous centroid). Only one
    batch (plus prefetch depth) is device-resident at a time.

    Fault tolerance (``KMeans.java:239-312`` ListState recovery;
    ``Checkpoints.java:43-211``): ``checkpoint_manager`` +
    ``checkpoint_interval`` snapshot ``(centroids, epoch)`` every N Lloyd
    epochs; ``resume=True`` restores the latest snapshot and continues —
    bit-exact with the uninterrupted run, because each epoch is a pure
    function of (centroids, cache). Resume requires the same durable
    cache (or re-fed identical stream) the crashed run trained from.

    ``listeners`` (:class:`~flinkml_tpu.iteration.IterationListener`)
    fire at every Lloyd epoch boundary with the current centroids and at
    termination — the mid-stream model-emission hook
    (``iteration.runtime.notify_epoch_listeners``): a
    :class:`flinkml_tpu.serving.SnapshotPublisher` attached here
    publishes a consistent versioned model snapshot every N epochs into
    a registry *without stopping the stream*, matching the reference's
    unbounded ``Iterations`` per-round model emission.
    """
    from flinkml_tpu.iteration.checkpoint import begin_resume, should_snapshot
    from flinkml_tpu.iteration.datacache import (
        DataCache,
        DataCacheWriter,
        PrefetchingDeviceFeed,
    )
    from flinkml_tpu.utils.sampling import RowReservoir

    # Multi-process: each process feeds its own stream partition; the SPMD
    # schedule (fixed batch height, agreed step count, zero-weight dummy
    # steps) comes from SyncedReplayPlan, init samples are pooled across
    # processes, checkpoints commit rank-0-write + barrier. See
    # iteration/stream_sync.py and _train_linear_stream_multiprocess for
    # the invariants.
    multi = jax.process_count() > 1
    if resume and not isinstance(batches, DataCache):
        raise ValueError(
            "resume=True requires a durable DataCache input: a one-shot "
            "stream cannot be replayed from the start after a failure"
        )

    # Decide the resume target BEFORE pass 0, so a successful restore
    # skips the reservoir pass + seeding whose centroids it would discard
    # (on a spilled cache that pass re-reads the whole dataset).
    resume_epoch = begin_resume(checkpoint_manager, resume, mesh.mesh.size)

    p_size = mesh.axis_size()
    row_tile = p_size * 8
    axis = DeviceMesh.DATA_AXIS
    fn = _kmeans_partial_fn(mesh.mesh, k, axis)
    n_feat = [None]  # first-seen feature dim; every batch must match

    def check_dims(x):
        if x.ndim != 2:
            raise ValueError(f"stream batches must be [n, d], got {x.shape}")
        if x.shape[0] == 0:
            raise ValueError("stream batch has zero rows; drop empty batches")
        if n_feat[0] is None:
            n_feat[0] = x.shape[1]
        elif x.shape[1] != n_feat[0]:
            raise ValueError(
                f"batch feature dim {x.shape[1]} != first batch's {n_feat[0]}"
            )

    def place(batch):
        x = np.asarray(batch[column], dtype=np.float32)
        check_dims(x)
        x_pad, n_valid = pad_to_multiple(x, row_tile)
        w = np.zeros(x_pad.shape[0], np.float32)
        w[:n_valid] = 1.0  # padded rows never influence centroids
        return mesh.shard_batch(x_pad), mesh.shard_batch(w)

    def make_multi_place(height: int, dim: int):
        """Fixed-shape multi-process placement: every step contributes
        exactly ``height`` local rows (zero-weight padding / dummies)."""

        from flinkml_tpu.iteration.stream_sync import pad_rows_to

        def place_multi(batch):
            if "_dummy" in batch:
                x_pad = np.zeros((height, dim), np.float32)
                w = np.zeros(height, np.float32)
            else:
                x = np.asarray(batch[column], dtype=np.float32)
                check_dims(x)
                x_pad = pad_rows_to(x, height)
                w = pad_rows_to(np.ones(x.shape[0], np.float32), height)
            return mesh.global_batch(x_pad), mesh.global_batch(w)

        return place_multi

    # -- pass 0: cache (if needed) + reservoir sample for init -------------
    reservoir_cap = (
        k if init_mode == "random" else max(k, init_sample_size)
    )
    need_init = initial_centroids is None and resume_epoch is None
    reservoir = RowReservoir(reservoir_cap, seed=seed)
    from flinkml_tpu.iteration.stream_sync import DeferredValidation

    dv = DeferredValidation()

    def ingest(b):
        # Extraction is part of the checked step (a missing column or
        # ragged value raises HERE, not in the reservoir add below).
        x = np.asarray(b[column], np.float32)
        check_dims(x)
        return x

    from flinkml_tpu.iteration.stream_sync import checked_ingest

    if isinstance(batches, DataCache):
        cache = batches
        if need_init:
            # Multi-process, iterator and ingest failures are held for
            # the rendezvous below (a rank-local raise would strand the
            # peers in plan.create's collective; adding a ragged batch
            # to the fixed-width reservoir would be such a raise).
            for x in checked_ingest(cache.reader(), dv, ingest, multi):
                reservoir.add(x)
        elif multi:
            # Cached source with initial_centroids/resume: pre-validate
            # every cached batch anyway — without this, a bad cached
            # batch on one rank first raises rank-locally in
            # place_multi's check_dims on the prefetch thread at replay,
            # stranding the peers mid-collective (LDA's cached-source
            # pre-validation, mirrored).
            for _ in checked_ingest(cache.reader(), dv, ingest, multi):
                pass
    else:
        writer = DataCacheWriter(cache_dir, memory_budget_bytes)

        def ingest_append(b):
            # The append is part of the checked step too: a rank-local
            # writer failure (e.g. disk full while spilling a segment)
            # must ride the rendezvous like any ingest failure.
            x = ingest(b)
            writer.append({column: np.array(x)})
            return x

        for x in checked_ingest(batches, dv, ingest_append, multi):
            if need_init:
                reservoir.add(x)
        cache = writer.finish()
    plan = None
    dim = n_feat[0] or 0
    if multi:
        from flinkml_tpu.iteration.stream_sync import (
            SyncedReplayPlan,
            agree_feature_dim,
            gather_vectors,
            pooled_sample,
        )

        # Rendezvous BEFORE planning: a held ingest error must
        # surface as itself, not as plan.create's "stream is empty
        # on every process" (skip-on-failure can leave every local
        # cache empty).
        dv.rendezvous(mesh, "stream ingest validation")
        plan = SyncedReplayPlan.create(cache, mesh, row_tile)
        dim = agree_feature_dim(cache, column, mesh, local_dim=dim)
        # f64 transport: global row counts can exceed int32.
        total_rows = int(
            gather_vectors(np.asarray([cache.num_rows], np.float64), mesh)
            .sum()
        )
        if total_rows < k:  # replicated value: every rank raises together
            raise ValueError(f"k={k} exceeds number of points {total_rows}")
    elif cache.num_rows < k:
        raise ValueError(f"k={k} exceeds number of points {cache.num_rows}")

    rng = np.random.default_rng(seed)
    start_epoch = 0
    if resume_epoch is not None:
        if multi:
            d_feat = dim
        else:
            # Shape discovery without a full pass: one cached batch gives d.
            reader = cache.reader()
            d_feat = np.asarray(next(iter(reader))[column]).shape[1]
            if hasattr(reader, "close"):
                reader.close()
        from flinkml_tpu.iteration.stream_sync import agreed_restore

        centroids, start_epoch = agreed_restore(
            checkpoint_manager, resume_epoch,
            np.zeros((k, d_feat), np.float32), mesh,
        )
    elif initial_centroids is not None:
        centroids = np.asarray(initial_centroids, np.float32)
        if centroids.shape[0] != k:
            raise ValueError(
                f"initial_centroids has {centroids.shape[0]} rows, need {k}"
            )
    else:
        sample = reservoir.sample()
        if multi:
            # Pool the per-process uniform samples into one global sample
            # (identical on every host), then seed from it.
            sample = pooled_sample(
                sample, cache.num_rows, reservoir_cap, seed, mesh
            )
        if init_mode == "k-means++":
            centroids = _kmeans_pp_init(sample, k, rng).astype(np.float32)
        else:
            # The reservoir IS the uniform k-row sample; a fixed order
            # would bias nothing, but shuffle for parity with the
            # reference's shuffled selection (KMeans.java:314-335).
            centroids = sample[rng.permutation(sample.shape[0])[:k]]

    from flinkml_tpu.parallel import dispatch as _dispatch
    from flinkml_tpu.parallel.dispatch import DispatchGuard, local_execution_lock

    guard = DispatchGuard()  # multi-process backpressure (no-op single)
    cent_dev = jnp.asarray(centroids)
    mesh_device_ids = tuple(d.id for d in mesh.mesh.devices.flatten())
    # Serialize vs. concurrent fits from other host threads over this
    # mesh's devices: interleaved multi-device collective dispatch
    # deadlocks (see local_execution_lock; the analyzer's FML302 check
    # verifies this exact program shape via the dispatch trace below).
    # The lock scopes one EPOCH, not the whole loop: every collective
    # dispatch of an epoch (including the guard flush and the
    # checkpoint's multi-process gather) completes under the lock, and
    # the only cross-release in-flight work (the centroid update) is
    # elementwise on replicated arrays — no rendezvous to interleave.
    # Releasing at epoch boundaries keeps listener callbacks (snapshot
    # publication: disk writes, a following engine's warmup compiles)
    # from stalling concurrent fits on overlapping devices.
    epoch_lock = local_execution_lock(mesh)
    for epoch in range(start_epoch, max_iter):
        with epoch_lock:
            if _dispatch.has_dispatch_observers():
                _dispatch.record_collective_dispatch(
                    "kmeans.lloyd_epoch", mesh_device_ids
                )
            sums = None
            counts = None
            if multi:
                src = plan.epoch_batches(
                    cache.reader(), lambda: {"_dummy": True}
                )
                place_fn = make_multi_place(plan.local_height, dim)
            else:
                src = cache.reader()
                place_fn = place
            feed = PrefetchingDeviceFeed(
                src, place=place_fn, depth=prefetch_depth
            )
            try:
                for xb, wb in feed:
                    s, c = fn(xb, wb, cent_dev)
                    sums = s if sums is None else sums + s
                    counts = c if counts is None else counts + c
                    counts = guard.after_dispatch(counts)
            finally:
                feed.close()
            if sums is None:
                raise ValueError("training stream is empty")
            counts = guard.flush(counts)
            safe = jnp.maximum(counts, 1.0)[:, None]
            cent_dev = jnp.where(counts[:, None] > 0, sums / safe, cent_dev)
            if should_snapshot(checkpoint_manager, checkpoint_interval,
                               epoch + 1, max_iter):
                if multi:
                    from flinkml_tpu.iteration.checkpoint import (
                        save_replicated,
                    )

                    save_replicated(
                        checkpoint_manager, np.asarray(cent_dev), epoch + 1,
                        mesh,
                    )
                else:
                    checkpoint_manager.save(np.asarray(cent_dev), epoch + 1)
        if listeners:
            from flinkml_tpu.iteration.runtime import notify_epoch_listeners

            cent_dev = notify_epoch_listeners(listeners, epoch, cent_dev)
    jax.block_until_ready(cent_dev)
    for listener in listeners:
        listener.on_iteration_terminated(cent_dev)
    return np.asarray(cent_dev)


def prepare_kmeans_data(x: np.ndarray, mesh: DeviceMesh):
    """Pad/mask/shard points for the Lloyd trainer; returns
    ``(xd, wd, n_valid)``. The single source of the padding policy — the
    bench measures exactly what :func:`train_kmeans` runs."""
    p_size = mesh.axis_size()
    # 8-row tile: keeps local shards sublane-aligned; zero-weight rows
    # are exact no-ops.
    x_pad, n_valid = pad_to_multiple(x, p_size * 8)
    w = np.zeros(x_pad.shape[0], dtype=x.dtype)
    w[:n_valid] = 1.0  # mask: padded rows never influence centroids
    return mesh.shard_batch(x_pad), mesh.shard_batch(w), n_valid
