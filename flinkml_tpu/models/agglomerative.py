"""AgglomerativeClustering — hierarchical clustering (upstream Flink ML
``AgglomerativeClustering``; an AlgoOperator, no fitted model).

Mechanism: the O(n²) pairwise distance matrix is one host f64 BLAS
gemm (merge order is precision-sensitive — an f32 device gemm flips
near-tied merges, see ``_squared_distance_matrix``); the inherently
sequential merge loop runs vectorized Lance-Williams updates with a
nearest-neighbor array (near-O(n²) total work in the common case).
Linkages: ward (default), complete, average, single; stop by
``numClusters`` (default 2) or ``distanceThreshold``.

Like the upstream operator, output labels are cluster ids in
``[0, k)`` remapped to first-appearance order for determinism.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.common_params import HasFeaturesCol, HasPredictionCol
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import FloatParam, IntParam, ParamValidators, StringParam
from flinkml_tpu.table import Table

WARD = "ward"
COMPLETE = "complete"
AVERAGE = "average"
SINGLE = "single"


def _squared_distance_matrix(x: np.ndarray) -> np.ndarray:
    """Pairwise SQUARED euclidean distances in float64 (host BLAS gemm).

    Merge ORDER is precision-sensitive: an f32 device gemm flips merges
    between near-tied pairs (fuzzing showed ~10% of random cases diverge
    from sklearn in f32 and none in f64), so exactness beats device
    placement here — agglomerative is a moderate-n method and the host
    f64 gemm is more than fast enough at that scale.
    """
    x = np.asarray(x, dtype=np.float64)
    sq = np.einsum("ij,ij->i", x, x)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (x @ x.T)
    np.maximum(d2, 0.0, out=d2)
    np.fill_diagonal(d2, 0.0)
    return d2


def agglomerate(
    x: np.ndarray,
    linkage: str = WARD,
    num_clusters: Optional[int] = 2,
    distance_threshold: Optional[float] = None,
) -> np.ndarray:
    """Lance-Williams agglomeration; returns integer labels [n].

    The merge loop maintains a per-row nearest-neighbor array (the
    classic NN-array scheme): each merge costs one O(n) row update plus
    O(n) NN repairs in the common case, keeping total host work near
    O(n²) rather than the naive O(n³) of a full argmin per merge.
    """
    n = x.shape[0]
    if num_clusters is not None and not 1 <= num_clusters <= n:
        raise ValueError(f"numClusters must be in [1, {n}], got {num_clusters}")
    d2 = _squared_distance_matrix(x)
    # Ward works on squared distances internally (sklearn/scipy report the
    # sqrt of the Ward objective); the other linkages use plain distances.
    d = d2 if linkage == WARD else np.sqrt(d2)
    big = np.inf
    np.fill_diagonal(d, big)
    sizes = np.ones(n)
    active = np.ones(n, dtype=bool)
    labels = np.arange(n)
    # Per-row nearest active neighbor.
    nn = np.argmin(d, axis=1)
    nn_dist = d[np.arange(n), nn]
    target = 1 if num_clusters is None else num_clusters
    for _ in range(n - target):
        i = int(np.argmin(nn_dist))
        j = int(nn[i])
        if i > j:
            i, j = j, i
        merge_dist = d[i, j]
        if distance_threshold is not None:
            reported = np.sqrt(merge_dist) if linkage == WARD else merge_dist
            if reported > distance_threshold:
                break
        ni, nj = sizes[i], sizes[j]
        # Lance-Williams update of row/col i to represent i∪j.
        di, dj = d[i], d[j]
        if linkage == SINGLE:
            new = np.minimum(di, dj)
        elif linkage == COMPLETE:
            new = np.maximum(di, dj)
        elif linkage == AVERAGE:
            new = (ni * di + nj * dj) / (ni + nj)
        else:  # ward, on squared distances
            nk = sizes
            new = (
                (ni + nk) * di + (nj + nk) * dj - nk * merge_dist
            ) / (ni + nj + nk)
        new[~active] = big
        new[i] = big
        d[i] = new
        d[:, i] = new
        d[j] = big
        d[:, j] = big
        sizes[i] = ni + nj
        active[j] = False
        labels[labels == j] = i   # rows always point at their active rep
        # NN maintenance: the merged row re-scans; rows whose NN was i or
        # j re-scan (their old NN distance is stale); any other row only
        # needs the cheap "did the new i row get closer?" check.
        nn_dist[j] = big
        nn[i] = int(np.argmin(d[i]))
        nn_dist[i] = d[i, nn[i]]
        stale = active & ((nn == i) | (nn == j))
        stale[i] = False
        for k in np.nonzero(stale)[0]:
            nn[k] = int(np.argmin(d[k]))
            nn_dist[k] = d[k, nn[k]]
        improved = active & (d[:, i] < nn_dist)
        improved[i] = False
        nn[improved] = i
        nn_dist[improved] = d[improved, i]
    # Remap to first-appearance order.
    _, first_idx = np.unique(labels, return_index=True)
    order = labels[np.sort(first_idx)]
    remap = {c: k for k, c in enumerate(order)}
    return np.asarray([remap[c] for c in labels])


class AgglomerativeClustering(HasFeaturesCol, HasPredictionCol, AlgoOperator):
    LINKAGE = StringParam(
        "linkage", "Cluster-merge criterion.", WARD,
        ParamValidators.in_array([WARD, COMPLETE, AVERAGE, SINGLE]),
    )
    NUM_CLUSTERS = IntParam(
        "numClusters", "Target number of clusters.", 2, ParamValidators.gt(0)
    )
    DISTANCE_THRESHOLD = FloatParam(
        "distanceThreshold",
        "Stop merging above this linkage distance (overrides numClusters; "
        "set None to return to numClusters mode).",
        None, lambda v: v is None or v > 0.0,
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        x = features_matrix(table, self.get(self.FEATURES_COL))
        threshold = self.get(self.DISTANCE_THRESHOLD)
        num_clusters = None if threshold is not None else self.get(self.NUM_CLUSTERS)
        labels = agglomerate(
            x, self.get(self.LINKAGE), num_clusters, threshold
        )
        return (
            table.with_column(
                self.get(self.PREDICTION_COL), labels.astype(np.float64)
            ),
        )
