"""Stage persistence: JSON metadata + array model data.

Parity with ``ml/util/ReadWriteUtils.java``:
  - ``saveMetadata`` (:92-128) → ``save_metadata``: a ``metadata`` JSON file
    holding {className, timestamp, paramMap, extra} under the stage path.
  - ``loadMetadata`` (:144-176) → ``load_metadata`` with class-check.
  - reflective ``loadStage`` (:382-410) → ``load_stage`` via importlib.
  - model-data save/load (:412-438, Flink FileSink/FileSource of encoded
    streams) → numpy ``.npz`` files: on TPU model data are device arrays, and
    a single compressed columnar file replaces the record-stream encoding.

The JSON layout (one directory per stage, numbered subdirectories for
composite stages) mirrors the reference so the format feels familiar, but the
class names are Python dotted paths.
"""

from __future__ import annotations

import hashlib
import importlib
import json
import os
import time
from typing import Any, Dict, Mapping, Optional

import numpy as np

METADATA_FILE = "metadata"
MODEL_DATA_DIR = "data"
FINGERPRINT_KEY = "contentFingerprint"


class ModelIntegrityError(ValueError):
    """A stage's persisted model data does not match the content
    fingerprint recorded in its metadata — the files were tampered with,
    truncated, or mixed between saves. Raised on load; the serving
    :class:`~flinkml_tpu.serving.ModelRegistry` relies on this check to
    never hot-swap a corrupt snapshot into a live engine."""


def content_fingerprint(
    arrays: Mapping[str, Any],
    param_map_json: Optional[Mapping[str, Any]] = None,
) -> str:
    """Deterministic sha256 over named model arrays (+ optionally the
    stage's param map): names, dtypes, shapes, and raw bytes all
    contribute, so any bit flip in the persisted model changes the
    fingerprint."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    if param_map_json is not None:
        h.update(json.dumps(dict(param_map_json), sort_keys=True,
                            default=str).encode())
    return h.hexdigest()


def verify_fingerprint(path: str, meta: Optional[Mapping[str, Any]] = None) -> Optional[str]:
    """Check the stage at ``path`` against its recorded content
    fingerprint, if it has one (stages saved before fingerprinting, and
    stages without model arrays, pass trivially). Returns the verified
    fingerprint or None; raises :class:`ModelIntegrityError` on mismatch.
    """
    if meta is None:
        meta = load_metadata(path)
    recorded = meta.get(FINGERPRINT_KEY)
    if recorded is None:
        return None
    actual = content_fingerprint(load_model_arrays(path), meta.get("paramMap"))
    if actual != recorded:
        raise ModelIntegrityError(
            f"model data at {path} does not match its recorded content "
            f"fingerprint (recorded {recorded[:12]}..., actual "
            f"{actual[:12]}...): the persisted arrays or params were "
            "modified after save"
        )
    return recorded


def stage_path(parent: str, stage_idx: int) -> str:
    """Numbered per-stage subdirectory, mirroring ReadWriteUtils.java:178-217."""
    return os.path.join(parent, "stages", f"{stage_idx}")


def save_metadata(stage: Any, path: str, extra: Optional[Mapping[str, Any]] = None) -> None:
    os.makedirs(path, exist_ok=True)
    meta: Dict[str, Any] = dict(extra or {})
    cls = type(stage)
    meta["className"] = f"{cls.__module__}.{cls.__qualname__}"
    meta["timestamp"] = int(time.time() * 1000)
    meta["paramMap"] = stage.get_param_map_json()
    metadata_path = os.path.join(path, METADATA_FILE)
    if os.path.exists(metadata_path):
        raise IOError(f"File {metadata_path} already exists")
    with open(metadata_path, "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)


def load_metadata(path: str, expected_class_name: str = "") -> Dict[str, Any]:
    with open(os.path.join(path, METADATA_FILE)) as f:
        meta = json.load(f)
    if expected_class_name and meta.get("className") != expected_class_name:
        raise ValueError(
            f"Stage metadata at {path} has className {meta.get('className')}, "
            f"expected {expected_class_name}"
        )
    return meta


def load_stage(path: str) -> Any:
    """Instantiate the stage recorded at ``path``.

    If the class defines its own ``load`` (beyond the default inherited one),
    delegate to it — mirroring the reference's reflective static-``load``
    convention (ReadWriteUtils.java:346-410). Otherwise reconstruct from
    params alone.
    """
    meta = load_metadata(path)
    cls = _resolve_class(meta["className"])
    own_load = _class_defines_own_load(cls)
    if own_load is not None:
        return own_load(path)
    return instantiate_with_params(cls, meta["paramMap"])


def instantiate_with_params(cls: type, param_map_json: Mapping[str, Any]) -> Any:
    stage = cls()
    stage.load_param_map_json(dict(param_map_json))
    return stage


def _resolve_class(dotted: str) -> type:
    module_name = dotted.rpartition(".")[0]
    # The class may be nested (pkg.mod.Outer.Inner): try the longest module
    # prefix first, falling back to shorter prefixes with attribute walks.
    while module_name:
        try:
            mod = importlib.import_module(module_name)
        except ModuleNotFoundError as e:
            # Only swallow "this prefix is not a module" — a missing
            # dependency raised from *inside* the module must surface.
            if e.name and (
                module_name == e.name or module_name.startswith(e.name + ".")
            ):
                module_name = module_name.rpartition(".")[0]
                continue
            raise
        obj: Any = mod
        try:
            for part in dotted[len(module_name) + 1 :].split("."):
                obj = getattr(obj, part)
        except AttributeError:
            module_name = module_name.rpartition(".")[0]
            continue
        return obj
    raise ImportError(f"Cannot resolve stage class {dotted!r}")


def _class_defines_own_load(cls: type):
    """Return cls.load if defined below Stage in the MRO, else None."""
    from flinkml_tpu.api import Stage

    for klass in cls.__mro__:
        if klass is Stage:
            return None
        if "load" in vars(klass):
            return getattr(cls, "load")
    return None


# -- model data ------------------------------------------------------------

def save_model_arrays(path: str, arrays: Mapping[str, np.ndarray], name: str = "model") -> str:
    """Persist named device/host arrays as a compressed npz under path/data/."""
    data_dir = os.path.join(path, MODEL_DATA_DIR)
    os.makedirs(data_dir, exist_ok=True)
    out = os.path.join(data_dir, f"{name}.npz")
    np.savez_compressed(out, **{k: np.asarray(v) for k, v in arrays.items()})
    return out


def load_model_arrays(path: str, name: str = "model") -> Dict[str, np.ndarray]:
    out = os.path.join(path, MODEL_DATA_DIR, f"{name}.npz")
    with np.load(out, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}
