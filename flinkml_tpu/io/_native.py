"""Shared compile-on-demand loader for the native (C++) ingest parsers.

Each parser lives in ``flinkml_tpu/native/<name>.cpp`` with a C ABI (the
sources ship inside the wheel via package-data); the first import compiles
it with the system ``g++`` into a ``build/`` dir next to the sources — or,
when the installed package is read-only, into a per-user cache dir —
(atomic rename so concurrent processes never dlopen a half-written file)
and caches the handle. Callers fall back to pure Python when no compiler
is available — the native path is a throughput optimization, never a
functional requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Callable, Dict, Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
)


def _build_dir() -> str:
    preferred = os.path.join(_NATIVE_DIR, "build")
    try:
        os.makedirs(preferred, exist_ok=True)
        if os.access(preferred, os.W_OK):
            return preferred
    except OSError:
        pass
    fallback = os.path.join(
        os.environ.get("XDG_CACHE_HOME", tempfile.gettempdir()),
        "flinkml_tpu_native",
    )
    os.makedirs(fallback, exist_ok=True)
    return fallback

_lock = threading.Lock()
_cache: Dict[str, Optional[ctypes.CDLL]] = {}


def compile_and_load(
    name: str, declare: Callable[[ctypes.CDLL], None]
) -> Optional[ctypes.CDLL]:
    """Compile ``flinkml_tpu/native/<name>.cpp`` (if stale) and load it.

    ``declare`` sets restype/argtypes on the fresh handle. Returns None if
    compilation or loading fails (callers use their Python fallback);
    the failure is cached so we do not retry per call.
    """
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
        so = os.path.join(_build_dir(), f"{name}.so")
        try:
            if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
                os.makedirs(os.path.dirname(so), exist_ok=True)
                tmp_so = f"{so}.tmp.{os.getpid()}"
                subprocess.run(
                    [
                        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                        "-o", tmp_so, src, "-lpthread",
                    ],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp_so, so)
            lib = ctypes.CDLL(so)
            declare(lib)
            _cache[name] = lib
        except (OSError, subprocess.CalledProcessError):
            _cache[name] = None
        return _cache[name]
