from flinkml_tpu.io.read_write import (
    load_metadata,
    load_stage,
    save_metadata,
    save_model_arrays,
    load_model_arrays,
)
from flinkml_tpu.io.csv import read_csv, read_csv_table
from flinkml_tpu.io.libsvm import (
    read_libsvm,
    read_libsvm_dense,
    read_libsvm_table,
)

__all__ = [
    "load_metadata",
    "load_stage",
    "save_metadata",
    "save_model_arrays",
    "load_model_arrays",
    "read_csv",
    "read_csv_table",
    "read_libsvm",
    "read_libsvm_dense",
    "read_libsvm_table",
]
