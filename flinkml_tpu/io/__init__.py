from flinkml_tpu.io.read_write import (
    load_metadata,
    load_stage,
    save_metadata,
    save_model_arrays,
    load_model_arrays,
)

__all__ = [
    "load_metadata",
    "load_stage",
    "save_metadata",
    "save_model_arrays",
    "load_model_arrays",
]
