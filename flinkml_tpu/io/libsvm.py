"""libsvm-format ingest: native multithreaded parser with pure-Python fallback.

The native path (``flinkml_tpu/native/libsvm_parser.cpp``) is compiled on first use with
the system ``g++`` and cached next to the source; when no compiler is
available the numpy fallback parses correctly (just slower). Either way the
result is CSR arrays ready for ``BatchedCSR``/densification — vectorized
ingest so the TPU is never input-bound (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from flinkml_tpu.io._native import compile_and_load


def _declare(lib: ctypes.CDLL) -> None:
    lib.libsvm_open.restype = ctypes.c_void_p
    lib.libsvm_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.libsvm_fill.restype = ctypes.c_int32
    lib.libsvm_fill.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ]
    lib.libsvm_close.restype = None
    lib.libsvm_close.argtypes = [ctypes.c_void_p]


def _load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native parser; None if unavailable."""
    return compile_and_load("libsvm_parser", _declare)


def read_libsvm(
    path: str,
    n_features: Optional[int] = None,
    n_threads: Optional[int] = None,
    zero_based: Optional[bool] = None,
    use_native: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """Parse a libsvm file.

    Returns ``(labels [n] f64, indptr [n+1] i64, indices [nnz] i32,
    values [nnz] f32, n_features)``. ``zero_based=None`` auto-detects the
    index base (0-based if any index 0 appears, matching sklearn's 'auto').
    """
    with open(path, "rb") as f:
        data = f.read()
    if not data:
        raise ValueError(f"libsvm file {path} is empty")

    lib = _load_native() if use_native else None
    if lib is not None:
        result = _parse_native(lib, data, n_threads, zero_based)
    else:
        result = _parse_python(data, zero_based)
    labels, indptr, indices, values = result
    if indices.size and indices.min() < 0:
        raise ValueError(
            f"negative feature index after base adjustment in {path}; "
            "pass zero_based=True if the file is 0-based"
        )
    inferred = int(indices.max()) + 1 if indices.size else 0
    if n_features is None:
        n_features = inferred
    elif inferred > n_features:
        raise ValueError(
            f"file contains feature index {inferred - 1} >= n_features {n_features}"
        )
    return labels, indptr, indices, values, n_features


def _parse_native(lib, data: bytes, n_threads, zero_based):
    n_threads = n_threads or min(os.cpu_count() or 1, 16)
    rows = ctypes.c_int64()
    nnz = ctypes.c_int64()
    base = ctypes.c_int64()
    handle = lib.libsvm_open(
        data, len(data), n_threads,
        ctypes.byref(rows), ctypes.byref(nnz), ctypes.byref(base),
    )
    if not handle:
        if rows.value == -2:
            raise ValueError("malformed libsvm label")
        raise RuntimeError("native libsvm parser failed to open buffer")
    try:
        index_base = (
            base.value if zero_based is None else (0 if zero_based else 1)
        )
        labels = np.empty(rows.value, dtype=np.float64)
        indptr = np.empty(rows.value + 1, dtype=np.int64)
        indices = np.empty(nnz.value, dtype=np.int32)
        values = np.empty(nnz.value, dtype=np.float32)
        rc = lib.libsvm_fill(handle, labels, indptr, indices, values, index_base)
        if rc != 0:
            raise RuntimeError(f"native libsvm parser fill failed (rc={rc})")
    finally:
        lib.libsvm_close(handle)
    return labels, indptr, indices, values


def _parse_python(data: bytes, zero_based):
    labels, indptr, indices, values = [], [0], [], []
    min_index = None
    for line in data.splitlines():
        parts = line.split()
        if not parts or parts[0].startswith(b"#"):
            continue
        try:
            label = float(parts[0])
        except ValueError:
            raise ValueError(f"malformed libsvm label: {parts[0][:20]!r}")
        labels.append(label)
        for tok in parts[1:]:
            # Contract shared with the native parser: a '#' token starts a
            # comment; a malformed "index:value" token ends the line's
            # feature list without emitting.
            if tok.startswith(b"#"):
                break
            idx_s, sep, val_s = tok.partition(b":")
            if not sep:
                break
            try:
                idx = int(idx_s)
                val = float(val_s)
            except ValueError:
                break
            min_index = idx if min_index is None else min(min_index, idx)
            indices.append(idx)
            values.append(val)
        indptr.append(len(indices))
    if zero_based is None:
        index_base = 0 if (min_index == 0) else 1
    else:
        index_base = 0 if zero_based else 1
    indices_arr = np.asarray(indices, dtype=np.int32) - index_base
    return (
        np.asarray(labels, dtype=np.float64),
        np.asarray(indptr, dtype=np.int64),
        indices_arr,
        np.asarray(values, dtype=np.float32),
    )


def read_libsvm_dense(path: str, n_features: Optional[int] = None, **kw):
    """Parse and densify to (X [n, d] f32, y [n] f64) — the a9a path."""
    labels, indptr, indices, values, n_features = read_libsvm(
        path, n_features=n_features, **kw
    )
    n = labels.shape[0]
    x = np.zeros((n, n_features), dtype=np.float32)
    rows = np.repeat(np.arange(n), np.diff(indptr))
    x[rows, indices] = values
    return x, labels


def read_libsvm_table(
    path: str,
    n_features: Optional[int] = None,
    features_col: str = "features",
    label_col: str = "label",
    **kw,
):
    """Parse into a :class:`~flinkml_tpu.table.Table` with a SparseVector
    features column — the bridge from libsvm ingest straight into the
    O(nnz) sparse estimators (LogisticRegression / LinearSVC /
    LinearRegression fit + transform), never densifying.

    Rows are sorted by feature index on the way in (libsvm does not
    guarantee ordering); a duplicate index within a row raises, keeping
    SparseVector's sorted-unique invariant intact.
    """
    from flinkml_tpu.linalg import SparseVector
    from flinkml_tpu.table import Table

    labels, indptr, indices, values, dim = read_libsvm(
        path, n_features=n_features, **kw
    )
    n = labels.shape[0]
    rows = np.repeat(np.arange(n), np.diff(indptr))
    order = np.lexsort((indices, rows))
    if indices.size > 1:
        srows, sidx = rows[order], indices[order]
        dup = (np.diff(sidx) == 0) & (np.diff(srows) == 0)
        if dup.any():
            # Indices here are base-adjusted (0-based); say so and point
            # at the 1-based data line so the message matches the file.
            raise ValueError(
                f"duplicate feature index {int(sidx[1:][dup][0])} "
                f"(0-based) on data line {int(srows[1:][dup][0]) + 1} "
                f"of {path}"
            )
    idx64 = indices[order].astype(np.int64)
    val64 = values[order].astype(np.float64)
    idx64.setflags(write=False)
    val64.setflags(write=False)
    vecs = np.empty(n, dtype=object)
    for i in range(n):
        sl = slice(indptr[i], indptr[i + 1])
        # Trusted construction over frozen sorted views: per-row
        # validation would dominate at dataset scale.
        vecs[i] = SparseVector._from_sorted(dim, idx64[sl], val64[sl])
    return Table({features_col: vecs, label_col: labels})
