"""Numeric-CSV ingest: native multithreaded parser with numpy fallback.

Companion to :mod:`flinkml_tpu.io.libsvm` (same pattern: compile
``flinkml_tpu/native/csv_parser.cpp`` on demand, fall back to pure Python without a
compiler). The reference reads CSV through Flink's table connectors,
record-at-a-time on the JVM; here the parser splits the buffer at line
boundaries across threads and fills a column-major float64 buffer so each
column is a contiguous zero-copy numpy view.

Scope: numeric CSV — every field is a number, empty fields become NaN, no
quoting. Header row auto-detected (any non-numeric field in the first
line).
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Tuple, Union

import numpy as np

from flinkml_tpu.io._native import compile_and_load
from flinkml_tpu.table import Table


def _declare(lib: ctypes.CDLL) -> None:
    lib.csv_open.restype = ctypes.c_void_p
    lib.csv_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_char,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.csv_fill.restype = ctypes.c_int32
    lib.csv_fill.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.float64, flags="F_CONTIGUOUS"),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.csv_close.restype = None
    lib.csv_close.argtypes = [ctypes.c_void_p]


def _parse_field(token: str) -> float:
    """Shared numeric grammar for fallback + header detection: Python's
    float() minus its '_'-separator extension, matching the native
    parser's from_chars/strtod grammar."""
    if "_" in token:
        raise ValueError(f"invalid numeric field {token!r}")
    return float(token)


def _is_number(token: str) -> bool:
    token = token.strip()
    if not token:
        return True  # empty fields are valid (NaN)
    try:
        _parse_field(token)
        return True
    except ValueError:
        return False


def _split_header(data: bytes, delimiter: str, header) -> Tuple[Optional[List[str]], bytes]:
    """Pop the header line if present; returns (names or None, body)."""
    # First non-blank line decides.
    text_end = data.find(b"\n")
    first = (data if text_end < 0 else data[:text_end]).decode("utf-8", "replace")
    while first.strip() == "" and text_end >= 0:
        data = data[text_end + 1:]
        text_end = data.find(b"\n")
        first = (data if text_end < 0 else data[:text_end]).decode("utf-8", "replace")
    fields = [f.strip() for f in first.rstrip("\r").split(delimiter)]
    has_header = (
        header if isinstance(header, bool)
        else any(not _is_number(f) for f in fields)
    )
    if not has_header:
        return None, data
    body = b"" if text_end < 0 else data[text_end + 1:]
    return fields, body


def read_csv(
    source: Union[str, bytes],
    delimiter: str = ",",
    header: Union[bool, str] = "auto",
    n_threads: Optional[int] = None,
    use_native: bool = True,
) -> Tuple[Optional[List[str]], np.ndarray]:
    """Parse numeric CSV.

    Args:
        source: file path, or raw bytes of CSV content.
        header: True/False, or "auto" (non-numeric first line = header).
    Returns:
        ``(names or None, data)`` with ``data`` float64 ``[rows, cols]``,
        column-major (each ``data[:, j]`` is contiguous).
    """
    if isinstance(source, bytes):
        data = source
    else:
        with open(source, "rb") as f:
            data = f.read()
    if len(delimiter.encode()) != 1:
        raise ValueError(
            f"delimiter must be one single-byte char, got {delimiter!r}"
        )
    names, body = _split_header(data, delimiter, header)
    if not body.strip():
        cols = len(names) if names else 0
        return names, np.empty((0, cols), dtype=np.float64, order="F")

    lib = compile_and_load("csv_parser", _declare) if use_native else None
    if lib is not None:
        mat = _parse_native(lib, body, delimiter, n_threads)
    else:
        mat = _parse_python(body, delimiter)
    if names is not None and mat.shape[1] != len(names):
        raise ValueError(
            f"header has {len(names)} columns but data rows have {mat.shape[1]}"
        )
    return names, mat


def read_csv_table(
    source: Union[str, bytes],
    delimiter: str = ",",
    header: Union[bool, str] = "auto",
    n_threads: Optional[int] = None,
    use_native: bool = True,
) -> Table:
    """Parse numeric CSV straight into a :class:`Table` (zero-copy column
    views). Without a header, columns are named ``c0..c{n-1}``."""
    names, mat = read_csv(source, delimiter, header, n_threads, use_native)
    if names is None:
        names = [f"c{i}" for i in range(mat.shape[1])]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ValueError(f"duplicate header column names: {dupes}")
    return Table({name: mat[:, j] for j, name in enumerate(names)})


def _parse_native(lib, body: bytes, delimiter: str, n_threads) -> np.ndarray:
    n_threads = n_threads or min(os.cpu_count() or 1, 16)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    bad = ctypes.c_int64()
    status = ctypes.c_int32()
    handle = lib.csv_open(
        body, len(body), n_threads, delimiter.encode()[0],
        ctypes.byref(rows), ctypes.byref(cols), ctypes.byref(bad),
        ctypes.byref(status),
    )
    try:
        if status.value == 1:
            raise ValueError(
                f"CSV row {bad.value} has a different field count than row 0"
            )
        if status.value == 2 or rows.value == 0:
            return np.empty((0, max(cols.value, 0)), dtype=np.float64, order="F")
        out = np.empty((rows.value, cols.value), dtype=np.float64, order="F")
        rc = lib.csv_fill(handle, out, ctypes.byref(bad))
        if rc != 0:
            raise ValueError(f"CSV row {bad.value} has a malformed numeric field")
        return out
    finally:
        lib.csv_close(handle)


def _parse_python(body: bytes, delimiter: str) -> np.ndarray:
    """Pure-Python fallback; same contract as the native parser."""
    rows: List[List[float]] = []
    ncols = -1
    for raw in body.decode("utf-8").split("\n"):
        line = raw.rstrip("\r")
        if not line.strip():
            continue
        fields = line.split(delimiter)
        if ncols < 0:
            ncols = len(fields)
        elif len(fields) != ncols:
            raise ValueError(
                f"CSV row {len(rows)} has a different field count than row 0"
            )
        vals = []
        for f in fields:
            f = f.strip()
            if not f:
                vals.append(float("nan"))
            else:
                try:
                    vals.append(_parse_field(f))
                except ValueError:
                    raise ValueError(
                        f"CSV row {len(rows)} has a malformed numeric field"
                    ) from None
        rows.append(vals)
    if not rows:
        return np.empty((0, 0), dtype=np.float64, order="F")
    return np.asarray(rows, dtype=np.float64, order="F")
