"""Pipeline and PipelineModel — linear chains of stages.

Parity with ``ml/builder/Pipeline.java:45-107`` and
``PipelineModel.java:44-68``:
  - ``Pipeline.fit`` trains each Estimator on the running inputs and
    transforms inputs forward only while an Estimator remains downstream;
  - ``PipelineModel.transform`` chains every stage's output into the next;
  - both save as metadata + numbered per-stage subdirectories
    (``ReadWriteUtils.java:178-217``) and load reflectively.

A ``Pipeline`` is itself an Estimator and a ``PipelineModel`` a Model, so
pipelines nest.

TPU-native divergence: ``PipelineModel.transform`` does not simply chain
per-stage transforms. Runs of stages that expose a
:class:`~flinkml_tpu.api.ColumnKernel` fuse into single XLA programs with
device-resident intermediates and a shape-bucketed compile cache — see
:mod:`flinkml_tpu.pipeline_fusion` and ``docs/operators/pipeline_fusion.md``
for the protocol, the bucketing policy, and how to make a stage fusable.

Chains can be validated BEFORE any dispatch:
``flinkml_tpu.analysis.analyze_pipeline(model, schema_of(table))``
abstract-evaluates the whole chain (schema flow, kernel shape/dtype
compatibility, fusion topology, fingerprint stability) device-free — see
``docs/development/static_analysis.md``.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from flinkml_tpu.api import AlgoOperator, Estimator, Model, Stage
from flinkml_tpu.io import read_write
from flinkml_tpu.table import Table


class Pipeline(Estimator):
    """Linear chain of stages, trained front to back.

    Semantics (Pipeline.java:79-107): for each stage in order — an Estimator
    is fit on the current inputs, producing a Model; an AlgoOperator is used
    as-is; the current inputs are advanced through the stage's transform only
    if another Estimator remains after it.
    """

    def __init__(self, stages: Sequence[Stage] = ()):  # noqa: D107
        super().__init__()
        self._stages: List[Stage] = list(stages)

    @property
    def stages(self) -> List[Stage]:
        return list(self._stages)

    def append_stage(self, stage: Stage) -> "Pipeline":
        self._stages.append(stage)
        return self

    def fit(self, *inputs: Table) -> "PipelineModel":
        last_estimator_idx = -1
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                last_estimator_idx = i

        model_stages: List[AlgoOperator] = []
        last_inputs: Tuple[Table, ...] = tuple(inputs)
        for i, stage in enumerate(self._stages):
            if isinstance(stage, AlgoOperator):
                model_stage: AlgoOperator = stage
            else:
                model_stage = stage.fit(*last_inputs)  # type: ignore[union-attr]
            model_stages.append(model_stage)
            if i < last_estimator_idx:
                last_inputs = tuple(model_stage.transform(*last_inputs))
        return PipelineModel(model_stages)

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        _save_stage_chain(self, self._stages, path)

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        return cls(_load_stage_chain(path))


class PipelineModel(Model):
    """Chain of transformer stages applied sequentially.

    Parity: ``PipelineModel.java:44-68`` — with one TPU-native execution
    upgrade: instead of dispatching every stage separately (N host↔device
    round trips for N stages), ``transform`` partitions the chain into
    maximal runs of kernel-capable stages (stages exposing
    :meth:`~flinkml_tpu.api.AlgoOperator.transform_kernel`) and compiles
    each run as ONE ``jax.jit`` program via
    :mod:`flinkml_tpu.pipeline_fusion` — intermediate columns stay in
    device memory, and a shape-bucketed compile cache serves repeated
    calls at any row count without retracing. Stages without kernels (or
    whose inputs aren't dense columns) fall back to the per-stage path, so
    mixed chains keep working; fused and per-stage execution produce
    bit-identical outputs.
    """

    def __init__(self, stages: Sequence[AlgoOperator] = ()):  # noqa: D107
        super().__init__()
        self._stages: List[AlgoOperator] = list(stages)

    @property
    def stages(self) -> List[AlgoOperator]:
        return list(self._stages)

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        from flinkml_tpu import pipeline_fusion

        outputs: Tuple[Table, ...] = tuple(inputs)
        stages = self._stages
        i = 0
        while i < len(stages):
            # Fusion applies to the single-table spine of the chain; multi-
            # table stages (and disabled fusion) take the per-stage path.
            if len(outputs) == 1 and pipeline_fusion.enabled():
                kernels, end = pipeline_fusion.collect_run(
                    outputs[0], stages, i
                )
                if len(kernels) >= 2:
                    outputs = (
                        pipeline_fusion.execute_kernel_chain(
                            outputs[0], kernels
                        ),
                    )
                    i = end
                    continue
            outputs = tuple(stages[i].transform(*outputs))
            i += 1
        return outputs

    def save(self, path: str) -> None:
        _save_stage_chain(self, self._stages, path)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        return cls(_load_stage_chain(path))


def _save_stage_chain(composite: Stage, stages: Sequence[Stage], path: str) -> None:
    read_write.save_metadata(composite, path, extra={"numStages": len(stages)})
    for i, stage in enumerate(stages):
        stage.save(read_write.stage_path(path, i))


def _load_stage_chain(path: str) -> List[Stage]:
    meta = read_write.load_metadata(path)
    num_stages = int(meta["numStages"])
    return [read_write.load_stage(read_write.stage_path(path, i)) for i in range(num_stages)]
