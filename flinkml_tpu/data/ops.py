"""Composable input-pipeline transforms.

Each op rewrites an iterator of :class:`~flinkml_tpu.table.Table`
batches into another — the tf.data-shaped middle of a
:class:`~flinkml_tpu.data.Dataset` chain. Two properties carry the
subsystem's contracts:

- **determinism**: an op's output sequence is a pure function of its
  input sequence (and, for shuffle, its seed). Replaying the chain
  replays the batches bit-for-bit, which is what makes the
  skip-``emitted`` resume of :mod:`flinkml_tpu.data.state` exact.
- **skip transparency** (``skip_transparent``): ops that map input
  batches 1:1 to output batches (``map``) let a resume push its skip
  all the way down to the source (O(1) for array/synthetic sources);
  cardinality-changing ops (``filter``/``rebatch``/``window``/
  ``shuffle``) force the resume to replay the chain and drop the
  consumed prefix — still exact, just not free.

Ops are instantiated once per Dataset but applied per ITERATION: all
mutable state (rebatch remainders, window buffers, shuffle buffer +
RNG) lives inside the generator ``apply`` returns, so two concurrent
iterations of one Dataset never share state.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

import numpy as np

from flinkml_tpu.table import Table


def _concat(tables: List[Table]) -> Table:
    out = tables[0]
    for t in tables[1:]:
        out = out.concat(t)
    return out


class Op:
    """One chain stage. ``apply`` receives the upstream iterator and the
    owning DatasetIterator (``ctx``) — ops with replay-relevant state
    (shuffle) register a state probe on it for cursor snapshots."""

    #: True when this op maps input batches 1:1 to output batches, so a
    #: resume's skip can be pushed below it to the source.
    skip_transparent = False

    def apply(self, it: Iterator[Table], ctx) -> Iterator[Table]:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class MapOp(Op):
    """``fn(Table) -> Table`` per batch (1:1, so skip-transparent).
    ``fn`` must be deterministic — it re-runs on replay."""

    skip_transparent = True

    def __init__(self, fn: Callable[[Table], Table]):
        self.fn = fn

    def apply(self, it, ctx):
        fn = self.fn
        for batch in it:
            yield fn(batch)

    def describe(self):
        return f"map({getattr(self.fn, '__name__', 'fn')})"


class FilterOp(Op):
    """Row-level filter: ``pred(Table) -> bool row mask``; rows where
    the mask is False are dropped, batches left empty vanish. Not
    skip-transparent (output batch count depends on the data)."""

    def __init__(self, pred: Callable[[Table], np.ndarray]):
        self.pred = pred

    def apply(self, it, ctx):
        for batch in it:
            mask = np.asarray(self.pred(batch), dtype=bool).reshape(-1)
            if mask.shape[0] != batch.num_rows:
                raise ValueError(
                    f"filter predicate returned {mask.shape[0]} mask rows "
                    f"for a {batch.num_rows}-row batch"
                )
            if mask.all():
                yield batch
                continue
            idx = np.flatnonzero(mask)
            if idx.size:
                yield batch.take(idx)

    def describe(self):
        return f"filter({getattr(self.pred, '__name__', 'pred')})"


class RebatchOp(Op):
    """Re-slice the row stream into exactly-``batch_size``-row batches
    (the final remainder is emitted unless ``drop_remainder``). The op
    every fixed-global-batch trainer wants between an arbitrary source
    and the device."""

    def __init__(self, batch_size: int, drop_remainder: bool = False):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.batch_size = int(batch_size)
        self.drop_remainder = bool(drop_remainder)

    def apply(self, it, ctx):
        pending: List[Table] = []
        rows = 0
        for batch in it:
            pending.append(batch)
            rows += batch.num_rows
            while rows >= self.batch_size:
                block = _concat(pending)
                yield block.slice(0, self.batch_size)
                rest = block.slice(self.batch_size, block.num_rows)
                rows -= self.batch_size
                pending = [rest] if rest.num_rows else []
        if rows and not self.drop_remainder:
            yield _concat(pending)

    def describe(self):
        return f"rebatch({self.batch_size})"


class WindowOp(Op):
    """Sliding count-window over rows: emit ``size``-row batches
    advancing by ``stride`` rows (``stride == size`` is a tumbling
    window — rebatch with a dropped remainder; ``stride < size``
    overlaps). Trailing rows that never fill a window are dropped."""

    def __init__(self, size: int, stride: Optional[int] = None):
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        self.size = int(size)
        self.stride = int(stride) if stride is not None else int(size)
        if self.stride < 1:
            raise ValueError(f"stride must be >= 1, got {self.stride}")

    def apply(self, it, ctx):
        buf: Optional[Table] = None
        for batch in it:
            buf = batch if buf is None else buf.concat(batch)
            while buf.num_rows >= self.size:
                yield buf.slice(0, self.size)
                buf = buf.slice(min(self.stride, buf.num_rows), buf.num_rows)
                if buf.num_rows == 0:
                    buf = None
                    break

    def describe(self):
        return f"window({self.size}, stride={self.stride})"


class ShuffleOp(Op):
    """Deterministic seeded shuffle buffer over BATCHES (the unit of
    streaming in this data plane): fill a buffer of ``buffer_batches``,
    then for every arriving batch emit a uniformly drawn resident one
    and take its slot; drain the buffer in random order at stream end.
    Identical (sequence, seed) ⇒ identical shuffled order — the
    determinism contract the kill-and-resume parity tests pin
    (``docs/operators/data.md``, "Shuffle determinism")."""

    def __init__(self, buffer_batches: int, seed: int = 0):
        if buffer_batches < 1:
            raise ValueError(
                f"buffer_batches must be >= 1, got {buffer_batches}"
            )
        self.buffer_batches = int(buffer_batches)
        self.seed = int(seed)

    def apply(self, it, ctx):
        rng = np.random.default_rng(self.seed)
        if ctx is not None:
            ctx.register_shuffle_probe(rng)
        buf: List[Table] = []
        for batch in it:
            if len(buf) < self.buffer_batches:
                buf.append(batch)
                continue
            j = int(rng.integers(0, len(buf)))
            out, buf[j] = buf[j], batch
            yield out
        while buf:
            j = int(rng.integers(0, len(buf)))
            yield buf.pop(j)

    def describe(self):
        return f"shuffle({self.buffer_batches}, seed={self.seed})"


class HashOp(Op):
    """Seeded feature hash per batch (1:1, so skip-transparent): the
    wrapped :class:`~flinkml_tpu.features.hashing.HashedFeature` turns
    the raw-key ``input_col`` into an ``output_col`` of embedding-row
    bucket ids. The hash is process-stable (murmur-style over canonical
    key bytes, never Python ``hash()``), so a cursor-resumed replay
    re-hashes every batch to bit-identical ids — the same determinism
    contract MapOp demands of its fn, here guaranteed by construction."""

    skip_transparent = True

    def __init__(self, hashed_feature):
        self.hashed = hashed_feature

    def apply(self, it, ctx):
        hashed = self.hashed
        for batch in it:
            yield hashed(batch)

    def describe(self):
        return (
            f"hash({self.hashed.input_col}->{self.hashed.output_col}, "
            f"buckets={self.hashed.num_buckets}, seed={self.hashed.seed})"
        )
