"""Checkpointable input-pipeline cursors.

The reference's DataStream sources carry their read position in operator
state, so a restored job resumes the feed exactly where the checkpoint
cut it (``DataCacheReader.java:35-135`` keeps the same contract for the
iteration-internal cache). Here the position of a whole
:class:`~flinkml_tpu.data.Dataset` chain — source shard/offset, shuffle
RNG state, and the consumer's delivered-batch watermark — folds into one
:class:`Cursor` that rides a checkpoint two ways:

- **inside ``iterate``** (the online trainers' path): the runtime stores
  the cursor in the snapshot's ``extra`` manifest field on every
  checkpoint and re-opens the Dataset from it on resume, so a killed
  and resumed pipeline replays the exact uninterrupted batch sequence —
  shuffle order included (every stage of the chain is deterministic in
  its seed, so position + replay ⇒ identical batches);
- **standalone** (hand-rolled loops): :meth:`Cursor.to_state` returns a
  one-leaf pytree (the JSON encoding as a uint8 array) that can ride
  any :class:`~flinkml_tpu.iteration.CheckpointManager` snapshot next
  to the model state; :meth:`Cursor.from_state` decodes it back.

``emitted`` is the authoritative field — the number of output batches
the CONSUMER has received. ``source``/``shuffle``/``in_flight`` record
where the producer side stood at snapshot time (the prefetcher may have
read ahead; ``in_flight`` is that watermark) — they make a cursor
auditable and let a skip-transparent chain fast-forward at the source,
but restore correctness never depends on them: a resumed Dataset
re-derives everything from ``emitted`` plus its own seeds.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional

import numpy as np


class CursorShardMismatchError(ValueError):
    """A cursor written by one shard assignment was restored into a feed
    with an INCOMPATIBLE one. A cursor from a 4-way-sharded source would
    otherwise silently fast-forward a 2-way source to the wrong rows —
    the shard count the cursor records is authoritative, so any mismatch
    that is not a legal, reshardable world change is loud. Legal
    reshards (round-robin-dealt sources with skip-transparent chains, or
    an :class:`~flinkml_tpu.data.ElasticFeed`'s global-order cursor)
    re-derive the new shard positions instead of raising."""


@dataclasses.dataclass(frozen=True)
class Cursor:
    """Position of a :class:`~flinkml_tpu.data.Dataset` iteration.

    Fields:
      emitted: output batches already delivered to the consumer — the
        replay watermark (a restored iteration produces batch
        ``emitted`` next). For a per-shard Dataset cursor this counts
        THIS shard's batches; for an
        :class:`~flinkml_tpu.data.ElasticFeed` cursor it counts GLOBAL
        batches (``shard_index`` is None there).
      num_shards: the shard count of the feed that wrote the cursor —
        **authoritative**: restoring into a feed with a different count
        is either a validated reshard (the new positions are re-derived
        from the global watermark) or a
        :class:`CursorShardMismatchError`, never a silent misread.
      shard_index: the writing iterator's shard (None for a global-order
        ElasticFeed cursor — the discriminator between the two scopes).
      source: the source's own position record (shard index, row/batch
        offset, reads) at snapshot time; diagnostic + fast-skip aid.
      shuffle: the shuffle buffer's RNG bit-generator state at snapshot
        time (diagnostic — replay regenerates it from the seed).
      in_flight: source batches read past the delivered watermark
        (sitting in transform/prefetch stages when the snapshot cut).
    """

    emitted: int = 0
    source: Optional[Dict[str, Any]] = None
    shuffle: Optional[Dict[str, Any]] = None
    in_flight: int = 0
    num_shards: Optional[int] = None
    shard_index: Optional[int] = None
    #: The EXACT global watermark, recorded by iterators that know it
    #: (always, since the elastic reshard landed). The lockstep product
    #: below is only the fallback for cursors predating this field —
    #: after a reshard whose watermark does not divide the new world,
    #: per-shard skips are uneven and ``emitted * num_shards`` would
    #: overestimate the global position (skipping real batches on the
    #: NEXT reshard); the recorded value stays exact across any chain
    #: of reshards.
    global_watermark: Optional[int] = None

    @property
    def global_emitted(self) -> int:
        """The delivered watermark in GLOBAL batches: the recorded
        :attr:`global_watermark` when present; otherwise a global-order
        cursor (``shard_index`` None) already counts globally, and a
        per-shard cursor converts under the SPMD lockstep contract
        (every shard delivers one batch per step, so per-shard progress
        times the shard count approximates the global progress — exact
        only when the feed never resharded)."""
        if self.global_watermark is not None:
            return int(self.global_watermark)
        if self.shard_index is None or self.num_shards is None:
            return int(self.emitted)
        return int(self.emitted) * int(self.num_shards)

    # -- JSON (checkpoint ``extra`` transport) ------------------------------
    def to_json_dict(self) -> Dict[str, Any]:
        return {
            "emitted": int(self.emitted),
            "source": self.source,
            "shuffle": self.shuffle,
            "in_flight": int(self.in_flight),
            "num_shards": (None if self.num_shards is None
                           else int(self.num_shards)),
            "shard_index": (None if self.shard_index is None
                            else int(self.shard_index)),
            "global_watermark": (None if self.global_watermark is None
                                 else int(self.global_watermark)),
        }

    @staticmethod
    def from_json_dict(d: Dict[str, Any]) -> "Cursor":
        num_shards = d.get("num_shards")
        shard_index = d.get("shard_index")
        watermark = d.get("global_watermark")
        return Cursor(
            emitted=int(d.get("emitted", 0)),
            source=d.get("source"),
            shuffle=d.get("shuffle"),
            in_flight=int(d.get("in_flight", 0)),
            num_shards=None if num_shards is None else int(num_shards),
            shard_index=None if shard_index is None else int(shard_index),
            global_watermark=None if watermark is None else int(watermark),
        )

    # -- pytree leaf (standalone CheckpointManager transport) ---------------
    def to_state(self) -> Dict[str, np.ndarray]:
        """A one-leaf pytree encoding for riding a CheckpointManager
        snapshot next to model state (``{"cursor": <uint8 array>}``)."""
        payload = json.dumps(self.to_json_dict(), sort_keys=True).encode()
        return {"cursor": np.frombuffer(payload, dtype=np.uint8).copy()}

    @staticmethod
    def from_state(state: Dict[str, np.ndarray]) -> "Cursor":
        payload = np.asarray(state["cursor"], dtype=np.uint8).tobytes()
        return Cursor.from_json_dict(json.loads(payload.decode()))


def rng_state_dict(rng: np.random.Generator) -> Dict[str, Any]:
    """A JSON-safe copy of a numpy Generator's bit-generator state."""

    def clean(x):
        if isinstance(x, dict):
            return {k: clean(v) for k, v in x.items()}
        if isinstance(x, np.ndarray):
            return [int(v) for v in x.tolist()]
        if isinstance(x, (np.integer,)):
            return int(x)
        return x

    return clean(rng.bit_generator.state)
