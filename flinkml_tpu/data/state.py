"""Checkpointable input-pipeline cursors.

The reference's DataStream sources carry their read position in operator
state, so a restored job resumes the feed exactly where the checkpoint
cut it (``DataCacheReader.java:35-135`` keeps the same contract for the
iteration-internal cache). Here the position of a whole
:class:`~flinkml_tpu.data.Dataset` chain — source shard/offset, shuffle
RNG state, and the consumer's delivered-batch watermark — folds into one
:class:`Cursor` that rides a checkpoint two ways:

- **inside ``iterate``** (the online trainers' path): the runtime stores
  the cursor in the snapshot's ``extra`` manifest field on every
  checkpoint and re-opens the Dataset from it on resume, so a killed
  and resumed pipeline replays the exact uninterrupted batch sequence —
  shuffle order included (every stage of the chain is deterministic in
  its seed, so position + replay ⇒ identical batches);
- **standalone** (hand-rolled loops): :meth:`Cursor.to_state` returns a
  one-leaf pytree (the JSON encoding as a uint8 array) that can ride
  any :class:`~flinkml_tpu.iteration.CheckpointManager` snapshot next
  to the model state; :meth:`Cursor.from_state` decodes it back.

``emitted`` is the authoritative field — the number of output batches
the CONSUMER has received. ``source``/``shuffle``/``in_flight`` record
where the producer side stood at snapshot time (the prefetcher may have
read ahead; ``in_flight`` is that watermark) — they make a cursor
auditable and let a skip-transparent chain fast-forward at the source,
but restore correctness never depends on them: a resumed Dataset
re-derives everything from ``emitted`` plus its own seeds.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Cursor:
    """Position of a :class:`~flinkml_tpu.data.Dataset` iteration.

    Fields:
      emitted: output batches already delivered to the consumer — the
        replay watermark (a restored iteration produces batch
        ``emitted`` next).
      source: the source's own position record (shard index, row/batch
        offset, reads) at snapshot time; diagnostic + fast-skip aid.
      shuffle: the shuffle buffer's RNG bit-generator state at snapshot
        time (diagnostic — replay regenerates it from the seed).
      in_flight: source batches read past the delivered watermark
        (sitting in transform/prefetch stages when the snapshot cut).
    """

    emitted: int = 0
    source: Optional[Dict[str, Any]] = None
    shuffle: Optional[Dict[str, Any]] = None
    in_flight: int = 0

    # -- JSON (checkpoint ``extra`` transport) ------------------------------
    def to_json_dict(self) -> Dict[str, Any]:
        return {
            "emitted": int(self.emitted),
            "source": self.source,
            "shuffle": self.shuffle,
            "in_flight": int(self.in_flight),
        }

    @staticmethod
    def from_json_dict(d: Dict[str, Any]) -> "Cursor":
        return Cursor(
            emitted=int(d.get("emitted", 0)),
            source=d.get("source"),
            shuffle=d.get("shuffle"),
            in_flight=int(d.get("in_flight", 0)),
        )

    # -- pytree leaf (standalone CheckpointManager transport) ---------------
    def to_state(self) -> Dict[str, np.ndarray]:
        """A one-leaf pytree encoding for riding a CheckpointManager
        snapshot next to model state (``{"cursor": <uint8 array>}``)."""
        payload = json.dumps(self.to_json_dict(), sort_keys=True).encode()
        return {"cursor": np.frombuffer(payload, dtype=np.uint8).copy()}

    @staticmethod
    def from_state(state: Dict[str, np.ndarray]) -> "Cursor":
        payload = np.asarray(state["cursor"], dtype=np.uint8).tobytes()
        return Cursor.from_json_dict(json.loads(payload.decode()))


def rng_state_dict(rng: np.random.Generator) -> Dict[str, Any]:
    """A JSON-safe copy of a numpy Generator's bit-generator state."""

    def clean(x):
        if isinstance(x, dict):
            return {k: clean(v) for k, v in x.items()}
        if isinstance(x, np.ndarray):
            return [int(v) for v in x.tolist()]
        if isinstance(x, (np.integer,)):
            return int(x)
        return x

    return clean(rng.bit_generator.state)
