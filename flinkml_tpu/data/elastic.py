"""ElasticFeed: the world-parallel, global-order input feed.

The elastic-resume contract (ISSUE 6 / ROADMAP item 4) needs a feed
whose delivered batch sequence is **independent of the world size**:
"world" parallelizes the *data plane* (reading, parsing, prefetching),
while consumption stays in one canonical global order — exactly the
reference's unbounded iteration, where records arrive from P parallel
source subtasks but the online model updates once per arriving record.
That independence is what makes "kill at world 4, resume at world 2 or
world 8, bit-identical model" a theorem instead of a hope.

:class:`ElasticFeed` is that feed: ``world`` per-shard
:class:`~flinkml_tpu.data.Dataset` readers (built by a
``make_dataset(shard)`` factory, shard ``i`` of ``world``), merged
round-robin back into the canonical global sequence (batch ``g`` comes
from shard ``g % world`` — the deal every reshardable
:class:`~flinkml_tpu.data.source.Source` uses), with optional
**post-merge** ops (map/shuffle/rebatch, applied to the *global*
stream, hence world-independent by construction) and an optional
device-prefetch tail.

Cursor model: an ElasticFeed cursor counts **global** batches
(``Cursor.emitted``; ``shard_index`` is None — the global-scope
discriminator) and records the writing ``world`` in
``Cursor.num_shards``. Resume:

- **same world**: each shard reader fast-forwards to its own share of
  the watermark (``round_robin_skip``) — works for ANY source;
- **different world** (the elastic case): requires every per-shard
  chain to be reshardable (round-robin source, skip-transparent
  per-shard ops); the new readers re-split the SAME global sequence, so
  the consumer continues at exactly batch ``emitted``;
- post-merge non-transparent ops (shuffle) force a replay of the merged
  stream with the consumed prefix dropped — still exact, because the
  merged global sequence (and therefore the seeded shuffle) is
  identical at every world;
- anything else — e.g. a world change over contiguous-block
  ArraySource shards — raises
  :class:`~flinkml_tpu.data.state.CursorShardMismatchError` loudly.

An ElasticFeed drops in anywhere a Dataset does: ``fit_stream`` of the
online trio, the streamed fits, :func:`~flinkml_tpu.iteration.iterate`
(which checkpoints its cursor in every snapshot and reopens it on
resume — at the same world or a new one).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

from flinkml_tpu.data.dataset import Dataset, DatasetIterator, _TrackedIterator
from flinkml_tpu.data.ops import MapOp, Op, RebatchOp, ShuffleOp
from flinkml_tpu.data.source import round_robin_skip
from flinkml_tpu.data.state import Cursor, CursorShardMismatchError
from flinkml_tpu.table import Table
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("data.elastic")


class ElasticFeed:
    """World-parallel readers, one canonical global order. See module
    docstring. Immutable like Dataset: combinators return new feeds."""

    def __init__(self, make_dataset: Callable[[Tuple[int, int]], Dataset],
                 world: int, ops: Sequence[Op] = (),
                 prefetch_spec: Optional[dict] = None):
        if int(world) < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self._make = make_dataset
        self._world = int(world)
        self._ops: Tuple[Op, ...] = tuple(ops)
        self._prefetch = prefetch_spec

    # -- combinators (post-merge: applied to the GLOBAL stream) -------------
    def _with_op(self, op: Op) -> "ElasticFeed":
        if self._prefetch is not None:
            raise ValueError(
                "prefetch() must be the LAST stage of an ElasticFeed"
            )
        return ElasticFeed(self._make, self._world, self._ops + (op,), None)

    def map(self, fn: Callable[[Table], Table]) -> "ElasticFeed":
        return self._with_op(MapOp(fn))

    def rebatch(self, batch_size: int,
                drop_remainder: bool = False) -> "ElasticFeed":
        return self._with_op(RebatchOp(batch_size, drop_remainder))

    def shuffle(self, buffer_batches: int, seed: int = 0) -> "ElasticFeed":
        """Seeded shuffle of the GLOBAL batch sequence — because it runs
        after the merge, the shuffled order is identical at every world
        (the property that keeps shuffled elastic resume bit-exact)."""
        return self._with_op(ShuffleOp(buffer_batches, seed))

    def prefetch(self, depth: int = 2, place=None,
                 metrics_group: str = "data.prefetch") -> "ElasticFeed":
        if self._prefetch is not None:
            raise ValueError("ElasticFeed already has a prefetch stage")
        return ElasticFeed(self._make, self._world, self._ops, dict(
            depth=depth, place=place, metrics_group=metrics_group,
        ))

    # -- properties ---------------------------------------------------------
    @property
    def world(self) -> int:
        return self._world

    @property
    def num_shards(self) -> int:
        """Alias of :attr:`world` — the uniform "feed world size" surface
        the checkpoint rescale guard pins (``Dataset.num_shards`` is the
        per-shard counterpart)."""
        return self._world

    @property
    def post_merge_transparent(self) -> bool:
        """True when every post-merge op maps batches 1:1, so a resume
        can fast-forward the shard readers instead of replaying the
        merged stream."""
        return all(op.skip_transparent for op in self._ops)

    def _shard_datasets(self) -> List[Dataset]:
        out = []
        for i in range(self._world):
            ds = self._make((i, self._world))
            if not isinstance(ds, Dataset):
                raise TypeError(
                    "make_dataset must return a flinkml_tpu.data.Dataset, "
                    f"got {type(ds)!r}"
                )
            if ds.num_shards != self._world or ds.shard_index != i:
                raise ValueError(
                    "make_dataset must honor its shard argument: asked "
                    f"for shard ({i}, {self._world}), got "
                    f"({ds.shard_index}, {ds.num_shards})"
                )
            out.append(ds)
        return out

    def describe(self) -> str:
        parts = [f"elastic(world={self._world})"]
        parts += [op.describe() for op in self._ops]
        if self._prefetch is not None:
            parts.append(f"prefetch(depth={self._prefetch['depth']})")
        return " -> ".join(parts)

    # -- iteration ----------------------------------------------------------
    def iterate(self, cursor: Optional[Cursor] = None) -> "ElasticFeedIterator":
        """A fresh tracked global-order iteration, optionally restored
        to ``cursor`` — written at THIS world or any other (the elastic
        reshard; see module docstring for what must hold)."""
        return ElasticFeedIterator(self, cursor)

    def __iter__(self) -> "ElasticFeedIterator":
        return self.iterate()

    def peek(self) -> Optional[Table]:
        """The first global batch via a throwaway prefetch-free
        iteration (same contract as :meth:`Dataset.peek`)."""
        feed = (self if self._prefetch is None
                else ElasticFeed(self._make, self._world, self._ops, None))
        it = feed.iterate()
        try:
            return next(it)
        except StopIteration:
            return None
        finally:
            it.close()


class ElasticFeedIterator(_TrackedIterator):
    """One tracked global-order iteration of an :class:`ElasticFeed`.
    The assembly and iterator/lifecycle tail (ops, replay drop,
    prefetcher, delivered-batch accounting, idempotent close) is the
    shared :class:`~flinkml_tpu.data.dataset._TrackedIterator`."""

    def __init__(self, feed: ElasticFeed, cursor: Optional[Cursor] = None):
        self._feed = feed
        world = feed._world
        global_skip = 0
        if cursor is not None:
            if cursor.shard_index is not None:
                raise CursorShardMismatchError(
                    f"per-shard cursor (shard {cursor.shard_index}/"
                    f"{cursor.num_shards}) restored into a global-order "
                    f"ElasticFeed(world={world}); per-shard cursors "
                    "resume through their own Dataset"
                )
            global_skip = int(cursor.emitted)
        datasets = feed._shard_datasets()
        old_world = (cursor.num_shards if cursor is not None
                     and cursor.num_shards is not None else world)
        resharding = old_world != world
        if resharding and global_skip and not all(
            ds.reshardable for ds in datasets
        ):
            culprit = next(ds for ds in datasets if not ds.reshardable)
            raise CursorShardMismatchError(
                f"cursor was written at world {old_world} but this feed "
                f"has world {world}, and the per-shard chain "
                f"({culprit.describe()}) cannot reshard: "
                + ("its source deals are not round-robin"
                   if not culprit._source.reshardable
                   else "it has non-skip-transparent per-shard ops")
                + "; resume at the original world"
            )
        fast = feed.post_merge_transparent
        if global_skip:
            _log.info(
                "elastic resume: world %d -> %d, global watermark %d "
                "(%s) — %s", old_world, world, global_skip,
                "reader fast-forward" if fast else "merged replay",
                feed.describe(),
            )
        if fast and global_skip:
            skips = [round_robin_skip(i, world, global_skip)
                     for i in range(world)]
        else:
            skips = [0] * world
        self._shard_iters: List[DatasetIterator] = [
            ds.iterate(Cursor(emitted=skips[i]) if skips[i] else None)
            for i, ds in enumerate(datasets)
        ]
        start_g = global_skip if (fast and global_skip) else 0

        def merged(iters: List[DatasetIterator], g: int) -> Iterator[Table]:
            # Round-robin in global-index order; the sequence ends at
            # the first missing index (shard exhausted), so unequal
            # shard lengths still yield exactly the canonical prefix.
            while True:
                try:
                    batch = next(iters[g % world])
                except StopIteration:
                    return
                yield batch
                g += 1

        self._assemble(
            merged(self._shard_iters, start_g), feed._ops,
            drop=0 if fast else global_skip,
            prefetch_spec=feed._prefetch, start=global_skip,
        )

    # -- cursor -------------------------------------------------------------
    def cursor(self) -> Cursor:
        """The current GLOBAL position: ``emitted`` counts global
        batches, ``num_shards`` records the world, ``shard_index`` is
        None (the global-scope discriminator), and ``source`` carries
        the per-shard reader positions for the audit trail."""
        per_shard = [it.source_position() for it in self._shard_iters]
        reads = sum(p["batches_read"] for p in per_shard)
        return Cursor(
            emitted=self._emitted,
            source={"world": self._feed._world, "per_shard": per_shard},
            shuffle=self._shuffle_state(),
            in_flight=max(0, reads - self._emitted),
            num_shards=self._feed._world,
            shard_index=None,
            global_watermark=self._emitted,  # global scope: exact
        )

    # -- lifecycle ----------------------------------------------------------
    def _close_sources(self) -> None:
        for it in self._shard_iters:
            it.close()
