"""Async host→device prefetch into the fused executor's row buckets.

The tail of a :class:`~flinkml_tpu.data.Dataset` chain: a worker thread
pulls host Tables, zero-pads every dense column to the fused compile
cache's power-of-two row bucket (:func:`flinkml_tpu.pipeline_fusion
.row_bucket`), uploads the padded buffers (``jax.device_put``, or a
mesh-sharded ``place``), and parks up to ``depth`` device-resident
Tables in a bounded queue. With ``depth >= 2`` the next batch's
PCIe/DMA copy runs under the current step's compute — double buffering,
the whole point of the subsystem.

The emitted Tables carry :class:`~flinkml_tpu.table.PaddedDeviceColumn`
columns whose buffers are EXACTLY bucket-height, so the downstream
fused executor (``Table.device_column_padded``) hands them straight
into its cached programs: varying batch sizes within a bucket cause
zero host work, zero re-pads, and **zero retraces** — the validity
handling is the executor's traced ``n_valid`` row count, which the
padded column's logical ``rows`` supplies. Collectives see only
bucket-shaped arrays, so SPMD steps never diverge on a ragged tail
batch.

The queue/worker/lifecycle machinery — timed put that re-checks the
stop event, parked-exception propagation with the producer's original
traceback, idempotent ``close()``, context-manager semantics, and the
no-back-reference worker + GC finalizer that keeps an ABANDONED
consumer from leaking the thread — is inherited from
:class:`~flinkml_tpu.iteration.datacache.PrefetchingDeviceFeed` (one
definition of those concurrency invariants, not two); this class adds
the bucket padding, the ``data.prefetch`` fault seam, and metrics.

Metrics (``utils.metrics.default_registry()``, group
``data.prefetch``): ``queue_depth`` / ``stall_fraction`` /
``rows_per_sec`` gauges plus batch/row counters. Fault seam
``data.prefetch`` (:mod:`flinkml_tpu.faults`) fires in the worker
before each placement.
"""

from __future__ import annotations

import time
from typing import Any, Iterable, Optional

import numpy as np

from flinkml_tpu.iteration.datacache import PrefetchingDeviceFeed
from flinkml_tpu.table import PaddedDeviceColumn, Table


def pad_place_table(table: Table, place=None) -> Table:
    """Pad ``table``'s dense columns to their power-of-two row bucket
    and upload: each becomes a bucket-height
    :class:`~flinkml_tpu.table.PaddedDeviceColumn` with the logical row
    count intact (dtype preserved exactly — the fused executor's
    bit-parity contract). Object columns whose rows are all
    ``SparseVector`` become bucket-height
    :class:`~flinkml_tpu.table.SortedSparseColumn`\\ s — the padded-ELL
    CSR layout plus pack-time global sort tables, built HERE on the
    worker thread (the sort overlaps compute; downstream scatters run
    ``indices_are_sorted=True`` with no runtime sort). Other object
    (ragged) columns have no device representation and stay
    host-resident."""
    import jax

    from flinkml_tpu.linalg import SparseVector
    from flinkml_tpu.pipeline_fusion import row_bucket

    if place is None:
        place = jax.device_put
    n = table.num_rows
    bucket = row_bucket(n)
    cols = {}
    with jax.experimental.enable_x64(True):
        for name in table.column_names:
            arr = table.column(name)
            if arr.dtype == object:
                if n and all(isinstance(v, SparseVector) for v in arr):
                    from flinkml_tpu.ops.sparse import (
                        pack_sorted_sparse_column,
                    )

                    cols[name] = pack_sorted_sparse_column(
                        arr, bucket=bucket, place=place
                    )
                else:
                    cols[name] = arr
                continue
            pad = bucket - n
            if pad:
                arr = np.concatenate(
                    [arr, np.zeros((pad,) + arr.shape[1:], arr.dtype)]
                )
            cols[name] = PaddedDeviceColumn(place(arr), n)
    return Table(cols)


class DevicePrefetcher(PrefetchingDeviceFeed):
    """Double-buffered bounded-queue async host→device feed over a
    batch iterator, bucket-padding Tables for the fused executor (see
    module docstring). Iterate it; ``close()`` (or the ``with`` block,
    or GC of an abandoned handle) stops the worker."""

    def __init__(self, batches: Iterable[Any], depth: int = 2, place=None,
                 metrics_group: str = "data.prefetch"):
        from flinkml_tpu.utils.metrics import default_registry

        group = (
            default_registry().group(metrics_group) if metrics_group else None
        )
        self._group = group
        self._stalled_s = 0.0
        self._consume_t0: Optional[float] = None
        self._rows_out = 0.0
        reads = [0]

        def pad_and_place(batch):
            # Runs on the worker thread (the inherited _feed_worker
            # applies `place` per batch): fault seam, bucket pad +
            # upload, producer-side counters.
            import flinkml_tpu.faults as faults

            reads[0] += 1
            if faults.ACTIVE is not None:  # scripted-failure seam
                faults.fire("data.prefetch", read=reads[0])
            if isinstance(batch, Table):
                placed = pad_place_table(batch, place)
                if group is not None:
                    group.counter("batches_prefetched")
                    group.counter("rows_prefetched", float(batch.num_rows))
                return placed
            import jax

            if group is not None:
                group.counter("batches_prefetched")
            return (place or jax.device_put)(batch)

        super().__init__(batches, place=pad_and_place, depth=depth,
                         thread_name="data-prefetch")

    def __next__(self):
        t0 = time.perf_counter()
        if self._consume_t0 is None:
            self._consume_t0 = t0
        try:
            item = super().__next__()
        finally:
            now = time.perf_counter()
            self._stalled_s += now - t0
            if self._group is not None:
                self._group.gauge("queue_depth", self._q.qsize())
                elapsed = now - self._consume_t0
                if elapsed > 0:
                    self._group.gauge(
                        "stall_fraction", self._stalled_s / elapsed
                    )
        if self._group is not None and isinstance(item, Table):
            self._rows_out += item.num_rows
            elapsed = time.perf_counter() - self._consume_t0
            if elapsed > 0:
                self._group.gauge("rows_per_sec", self._rows_out / elapsed)
        return item

    @property
    def stall_fraction(self) -> float:
        """Fraction of consumer wall-clock spent blocked on the queue —
        the headline 'is the producer keeping up' number."""
        if self._consume_t0 is None:
            return 0.0
        elapsed = time.perf_counter() - self._consume_t0
        return self._stalled_s / elapsed if elapsed > 0 else 0.0
