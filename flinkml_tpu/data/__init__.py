"""flinkml_tpu.data — streaming input pipelines with checkpointable
cursors and async device prefetch.

The fifth subsystem (ISSUE 5): the reference's DataStream layer gives
every trainer a uniform, replayable, backpressured record feed; this
package is that feed in the tf.data mold, TPU-shaped —

    source → map/filter/rebatch/window → shuffle → prefetch-to-device

built from sharded :mod:`~flinkml_tpu.data.source` heads, composable
deterministic :mod:`~flinkml_tpu.data.ops`, a bucket-padding
:class:`DevicePrefetcher` tail that feeds the fused executor with zero
retraces, and a :class:`Cursor` that rides
:class:`~flinkml_tpu.iteration.CheckpointManager` snapshots so a killed
and resumed pipeline replays the exact uninterrupted batch sequence
(shuffle order included). See ``docs/operators/data.md``.
"""

from flinkml_tpu.data.dataset import Dataset, DatasetIterator
from flinkml_tpu.data.elastic import ElasticFeed, ElasticFeedIterator
from flinkml_tpu.data.ops import (
    FilterOp,
    HashOp,
    MapOp,
    Op,
    RebatchOp,
    ShuffleOp,
    WindowOp,
)
from flinkml_tpu.data.prefetch import DevicePrefetcher, pad_place_table
from flinkml_tpu.data.source import (
    ArraySource,
    CSVSource,
    LibSVMSource,
    Source,
    SourceIterator,
    SyntheticSource,
    resolve_shard,
    round_robin_skip,
)
from flinkml_tpu.data.state import (
    Cursor,
    CursorShardMismatchError,
    rng_state_dict,
)

__all__ = [
    "Dataset",
    "DatasetIterator",
    "ElasticFeed",
    "ElasticFeedIterator",
    "Cursor",
    "CursorShardMismatchError",
    "rng_state_dict",
    "round_robin_skip",
    "Source",
    "SourceIterator",
    "ArraySource",
    "CSVSource",
    "LibSVMSource",
    "SyntheticSource",
    "resolve_shard",
    "Op",
    "MapOp",
    "FilterOp",
    "HashOp",
    "RebatchOp",
    "WindowOp",
    "ShuffleOp",
    "DevicePrefetcher",
    "pad_place_table",
]
