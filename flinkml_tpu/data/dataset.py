"""The Dataset chain: source → transforms → shuffle → prefetch-to-device.

The reference hands every trainer a uniform, replayable, backpressured
record feed through its DataStream layer; this class is that feed,
TPU-shaped: a declarative chain over a sharded
:class:`~flinkml_tpu.data.source.Source`, composable
:mod:`~flinkml_tpu.data.ops` transforms, and an optional
:class:`~flinkml_tpu.data.prefetch.DevicePrefetcher` tail. A Dataset is
an iterable of :class:`~flinkml_tpu.table.Table` batches, so it drops
in anywhere a batch iterable is accepted today — ``fit_stream`` of the
online trio, the streamed ``fit`` families, ``iterate`` — and the
iteration runtime additionally recognizes it to checkpoint and restore
its :class:`~flinkml_tpu.data.state.Cursor` (see
``docs/operators/data.md``).

Datasets are immutable: every combinator returns a new chain sharing
the source. Iteration state lives entirely in the
:class:`DatasetIterator`, so concurrent iterations never interfere.

Resume model: every stage is deterministic, so position ``k`` ⇒ "the
batch sequence's k-th element". ``iterate(cursor)`` restores by
fast-forwarding — pushed down to the source in O(1)/O(parse) when the
chain is skip-transparent (no cardinality-changing op), or by replaying
the chain and dropping the consumed prefix otherwise (shuffle included:
the seeded buffer regenerates the identical order). Either way the
resumed consumer sees the exact uninterrupted sequence.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flinkml_tpu.data.ops import (
    FilterOp,
    MapOp,
    Op,
    RebatchOp,
    ShuffleOp,
    WindowOp,
)
from flinkml_tpu.data.source import (
    ArraySource,
    CSVSource,
    LibSVMSource,
    Source,
    SourceIterator,
    SyntheticSource,
)
from flinkml_tpu.data.state import Cursor, rng_state_dict
from flinkml_tpu.table import Table
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("data")


class Dataset:
    """An immutable source → ops → prefetch chain of Table batches."""

    def __init__(self, source: Source, ops: Sequence[Op] = (),
                 prefetch_spec: Optional[dict] = None):
        if not isinstance(source, Source):
            raise TypeError(
                f"Dataset requires a data.Source head, got {type(source)!r}"
            )
        self._source = source
        self._ops: Tuple[Op, ...] = tuple(ops)
        self._prefetch = prefetch_spec

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_source(source: Source) -> "Dataset":
        return Dataset(source)

    @staticmethod
    def from_arrays(data, batch_size: int, shard=None, mesh=None) -> "Dataset":
        """In-memory Table / column-dict source (see :class:`ArraySource`)."""
        return Dataset(ArraySource(data, batch_size, shard=shard, mesh=mesh))

    @staticmethod
    def from_csv(pattern, batch_size: int, delimiter: str = ",",
                 header="auto", shard=None, mesh=None) -> "Dataset":
        """Numeric-CSV file glob source (see :class:`CSVSource`)."""
        return Dataset(CSVSource(pattern, batch_size, delimiter=delimiter,
                                 header=header, shard=shard, mesh=mesh))

    @staticmethod
    def from_libsvm(pattern, batch_size: int, n_features: int,
                    shard=None, mesh=None, **kw) -> "Dataset":
        """LibSVM file glob source (see :class:`LibSVMSource`)."""
        return Dataset(LibSVMSource(pattern, batch_size, n_features,
                                    shard=shard, mesh=mesh, **kw))

    @staticmethod
    def synthetic(make_batch: Callable[[int, np.random.Generator], Table],
                  num_batches: int, seed: int = 0, shard=None,
                  mesh=None) -> "Dataset":
        """Seeded generator source (see :class:`SyntheticSource`)."""
        return Dataset(SyntheticSource(make_batch, num_batches, seed=seed,
                                       shard=shard, mesh=mesh))

    # -- combinators --------------------------------------------------------
    def _with_op(self, op: Op) -> "Dataset":
        if self._prefetch is not None:
            raise ValueError(
                "prefetch() must be the LAST stage of a Dataset chain "
                "(its output lives on device; host transforms cannot "
                "follow it)"
            )
        return Dataset(self._source, self._ops + (op,), None)

    def map(self, fn: Callable[[Table], Table]) -> "Dataset":
        return self._with_op(MapOp(fn))

    def filter(self, pred: Callable[[Table], np.ndarray]) -> "Dataset":
        return self._with_op(FilterOp(pred))

    def rebatch(self, batch_size: int,
                drop_remainder: bool = False) -> "Dataset":
        return self._with_op(RebatchOp(batch_size, drop_remainder))

    def window(self, size: int, stride: Optional[int] = None) -> "Dataset":
        return self._with_op(WindowOp(size, stride))

    def shuffle(self, buffer_batches: int, seed: int = 0) -> "Dataset":
        return self._with_op(ShuffleOp(buffer_batches, seed))

    def hash_column(self, input_col: str, *, seed: int, num_buckets: int,
                    output_col: str = "hashed_ids",
                    **kwargs) -> "Dataset":
        """Hash the raw string/int keys of ``input_col`` into
        ``output_col`` embedding-row ids (seeded, process-stable — see
        :mod:`flinkml_tpu.features.hashing`): the vocabulary-free front
        end that lets an unbounded stream feed ``EmbeddingTable``
        training directly. Extra kwargs reach
        :class:`~flinkml_tpu.features.hashing.HashedFeature`
        (``pad_key``, ``track_collisions``, ...)."""
        from flinkml_tpu.data.ops import HashOp
        from flinkml_tpu.features.hashing import HashedFeature

        return self._with_op(HashOp(HashedFeature(
            seed, num_buckets, input_col=input_col, output_col=output_col,
            **kwargs,
        )))

    def prefetch(self, depth: int = 2, place=None,
                 metrics_group: str = "data.prefetch") -> "Dataset":
        """Append the async host→device tail (see
        :class:`~flinkml_tpu.data.prefetch.DevicePrefetcher`): batches
        arrive as Tables of bucket-padded device-resident columns."""
        if self._prefetch is not None:
            raise ValueError("Dataset already has a prefetch stage")
        return Dataset(self._source, self._ops, dict(
            depth=depth, place=place, metrics_group=metrics_group,
        ))

    # -- properties ---------------------------------------------------------
    @property
    def skip_transparent(self) -> bool:
        """True when every op maps batches 1:1, so a resume's skip can
        be pushed down to the source instead of replaying the chain."""
        return all(op.skip_transparent for op in self._ops)

    @property
    def num_shards(self) -> int:
        """The source's shard count — the feed's world size (what the
        checkpoint rescale guard pins, and what cursors record
        authoritatively)."""
        return self._source.num_shards

    @property
    def shard_index(self) -> int:
        return self._source.shard_index

    @property
    def reshardable(self) -> bool:
        """True when a cursor written at a DIFFERENT shard count can be
        legally re-split into this chain: the source deals round-robin
        over a canonical global order AND every op is skip-transparent
        (a per-shard shuffle/rebatch entangles the output sequence with
        the shard count)."""
        return self._source.reshardable and self.skip_transparent

    def describe(self) -> str:
        parts = [type(self._source).__name__]
        parts += [op.describe() for op in self._ops]
        if self._prefetch is not None:
            parts.append(f"prefetch(depth={self._prefetch['depth']})")
        return " -> ".join(parts)

    # -- iteration ----------------------------------------------------------
    def iterate(self, cursor: Optional[Cursor] = None) -> "DatasetIterator":
        """A fresh tracked iteration, optionally restored to ``cursor``
        (the consumer's next batch is sequence element
        ``cursor.emitted``)."""
        return DatasetIterator(self, cursor)

    def iterate_from(self, emitted: int) -> "DatasetIterator":
        """Restore-by-watermark: equivalent to ``iterate(Cursor(emitted))``."""
        return DatasetIterator(self, Cursor(emitted=int(emitted)))

    def __iter__(self) -> "DatasetIterator":
        return self.iterate()

    def peek(self) -> Optional[Table]:
        """The first batch (or None for an empty pipeline), produced by
        a throwaway prefetch-free iteration — peeking must not leave a
        worker thread behind or consume the real feed."""
        ds = (self if self._prefetch is None
              else Dataset(self._source, self._ops, None))
        it = ds.iterate()
        try:
            return next(it)
        except StopIteration:
            return None
        finally:
            it.close()


def _drop(it: Iterator[Table], n: int) -> Iterator[Table]:
    for _ in range(n):
        try:
            next(it)
        except StopIteration:
            return
    for batch in it:
        yield batch


class _ChainState:
    """State shared between the chain generators and the
    DatasetIterator. A separate object on purpose: the prefetch worker
    holds the chain, so the chain must NOT reference the DatasetIterator
    (which owns the prefetcher) — that cycle would keep an abandoned
    prefetcher reachable from the worker's own stack and defeat the
    GC-finalizer thread cleanup."""

    __slots__ = ("shuffle_rng",)

    def __init__(self):
        self.shuffle_rng: Optional[np.random.Generator] = None

    def register_shuffle_probe(self, rng: np.random.Generator) -> None:
        """Called by :class:`~flinkml_tpu.data.ops.ShuffleOp` so cursor
        snapshots can record the buffer's RNG state."""
        self.shuffle_rng = rng


def _read_seam(src: "SourceIterator", shard_index: int) -> Iterator[Table]:
    """Source reads through the ``data.read`` fault seam. Module-level
    (not a DatasetIterator method) for the same no-back-reference reason
    as :class:`_ChainState`."""
    import flinkml_tpu.faults as faults

    for batch in src:
        if faults.ACTIVE is not None:  # scripted source-failure seam
            faults.fire("data.read", read=src.batches_read,
                        shard=shard_index)
        yield batch


class _TrackedIterator:
    """The assembly + iterator/lifecycle tail shared by
    :class:`DatasetIterator` and :class:`~flinkml_tpu.data.elastic
    .ElasticFeedIterator`: base iterator → ops (with a
    :class:`_ChainState` for shuffle probes) → optional dropped replay
    prefix → optional :class:`~flinkml_tpu.data.prefetch
    .DevicePrefetcher`, plus the delivered-batch accounting and the
    idempotent ``close`` the cursor machinery depends on. One
    definition, so a fix to the tail (prefetcher shutdown, in-flight
    accounting) can never diverge between the two feeds."""

    def _assemble(self, base_it: Iterator[Table], ops: Sequence[Op],
                  drop: int, prefetch_spec: Optional[dict],
                  start: int) -> None:
        self._chain_state = _ChainState()
        it = base_it
        for op in ops:
            it = op.apply(it, self._chain_state)
        if drop:
            it = _drop(it, drop)
        self._prefetcher = None
        if prefetch_spec is not None:
            from flinkml_tpu.data.prefetch import DevicePrefetcher

            self._prefetcher = DevicePrefetcher(it, **prefetch_spec)
            it = self._prefetcher
        self._it = it
        self._emitted = int(start)
        self._closed = False

    # -- iterator protocol --------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> Table:
        if self._closed:
            raise StopIteration
        try:
            batch = next(self._it)
        except StopIteration:
            self.close()
            raise
        self._emitted += 1
        return batch

    @property
    def emitted(self) -> int:
        return self._emitted

    def _shuffle_state(self) -> Optional[dict]:
        return (rng_state_dict(self._chain_state.shuffle_rng)
                if self._chain_state.shuffle_rng is not None else None)

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        """Stop the prefetch worker (if any) and end the iteration.
        Idempotent; always safe to call from a ``finally``."""
        self._closed = True
        if self._prefetcher is not None:
            self._prefetcher.close()
        self._close_sources()

    def _close_sources(self) -> None:
        """Subclass hook: release reader-side resources on close."""

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False


class DatasetIterator(_TrackedIterator):
    """One tracked iteration of a :class:`Dataset`.

    Tracks the delivered-batch watermark and the source/shuffle
    positions for :meth:`cursor` snapshots; fires the ``data.read``
    fault seam per source batch; owns (and closes) the prefetcher.
    """

    def __init__(self, dataset: Dataset, cursor: Optional[Cursor] = None):
        self._dataset = dataset
        skip = int(cursor.emitted) if cursor is not None else 0
        fast = dataset.skip_transparent
        if (
            cursor is not None
            and cursor.num_shards is not None
            and (cursor.shard_index is None
                 or cursor.num_shards != dataset.num_shards)
        ):
            # The cursor's shard count is authoritative: a different
            # count is either a LEGAL reshard (round-robin source +
            # skip-transparent chain: re-derive this shard's skip from
            # the global watermark) or a loud error — never a silent
            # fast-forward to the wrong rows. A GLOBAL-order cursor
            # (shard_index None) counts a different unit entirely, so it
            # is refused even at a matching shard count.
            from flinkml_tpu.data.state import CursorShardMismatchError

            if cursor.shard_index is None:
                raise CursorShardMismatchError(
                    f"global-order cursor (world {cursor.num_shards}) "
                    f"restored into a per-shard Dataset "
                    f"({dataset.describe()}, shard "
                    f"{dataset.shard_index}/{dataset.num_shards}); "
                    "global cursors resume through an ElasticFeed"
                )
            if not dataset.reshardable:
                raise CursorShardMismatchError(
                    f"cursor was written by a {cursor.num_shards}-way "
                    f"sharded feed but this chain is sharded "
                    f"{dataset.num_shards}-way and cannot reshard "
                    f"({dataset.describe()}: "
                    + ("source deals are not round-robin"
                       if not dataset._source.reshardable
                       else "chain has non-skip-transparent ops")
                    + "); resume at the original shard count"
                )
            skip = dataset._source.skip_for_global(cursor.global_emitted)
            fast = True  # reshardable requires skip-transparency
            _log.info(
                "dataset reshard resume: world %d -> %d, global watermark "
                "%d -> shard %d/%d skip %d — %s",
                cursor.num_shards, dataset.num_shards,
                cursor.global_emitted, dataset.shard_index,
                dataset.num_shards, skip, dataset.describe(),
            )
        elif skip:
            _log.info(
                "dataset resume: fast-forwarding %d batches (%s skip) — %s",
                skip, "source" if fast else "replay", dataset.describe(),
            )
        # The EXACT global watermark this iteration starts from: after a
        # reshard the per-shard skips are uneven, so the lockstep
        # product (emitted x num_shards) would drift — the cursor's
        # recorded watermark (or the product, for pre-elastic cursors)
        # anchors it, and every subsequent lockstep round advances it by
        # num_shards (see :meth:`cursor`).
        if cursor is None:
            self._global_base = 0
        elif cursor.num_shards is not None:
            self._global_base = cursor.global_emitted
        else:  # legacy cursor: per-shard emitted, never resharded
            self._global_base = skip * dataset.num_shards
        self._emitted_base = skip
        self._src = dataset._source.open(skip_batches=skip if fast else 0)
        self._assemble(
            _read_seam(self._src, dataset._source.shard_index),
            dataset._ops, drop=0 if fast else skip,
            prefetch_spec=dataset._prefetch, start=skip,
        )

    # -- cursor -------------------------------------------------------------
    def source_position(self) -> Dict[str, Any]:
        """The underlying source iterator's position record (public:
        an :class:`~flinkml_tpu.data.ElasticFeed`'s global cursor
        aggregates its shard readers' positions through this)."""
        return self._src.position()

    def cursor(self) -> Cursor:
        """The current position: ``emitted`` is the replay watermark;
        source/shuffle/in-flight record where the producer side stands
        (ahead of the watermark by whatever sits in transform buffers
        and the prefetch queue)."""
        # batches_read counts source batches consumed on behalf of this
        # iteration (a replay-resumed iterator's dropped prefix
        # included — those outputs were consumed too, just internally),
        # so reads minus deliveries IS the in-flight population on both
        # the fast-skip and replay paths.
        src_pos = self.source_position()
        in_flight = max(0, src_pos["batches_read"] - self._emitted)
        return Cursor(
            emitted=self._emitted,
            source=src_pos,
            shuffle=self._shuffle_state(),
            in_flight=in_flight,
            num_shards=self._dataset.num_shards,
            shard_index=self._dataset.shard_index,
            # Lockstep: each round past the resume point advanced the
            # GLOBAL sequence by one batch per shard.
            global_watermark=(
                self._global_base
                + (self._emitted - self._emitted_base)
                * self._dataset.num_shards
            ),
        )
