"""Sharded input-pipeline sources.

The heads of a :class:`~flinkml_tpu.data.Dataset` chain: each source
yields :class:`~flinkml_tpu.table.Table` batches from one replayable,
shard-assignable origin — in-memory arrays, numeric-CSV file globs,
LibSVM file globs (both through :mod:`flinkml_tpu.io`'s native parsers),
or a seeded synthetic generator. The reference gets this layer from
Flink's connector sources (per-subtask splits of a partitioned stream);
here the split is per-RANK: pass a :class:`~flinkml_tpu.parallel
.DeviceMesh` (or an explicit ``shard=(index, count)``) and each process
reads only its assignment — row blocks for array sources, files
round-robin for file sources, batch indices round-robin for synthetic
sources.

Contracts every source honors (what makes the cursor machinery work):

- **deterministic replay**: ``open()`` twice yields the identical batch
  sequence (file globs are sorted; synthetic draws are keyed by global
  batch index, not call order);
- **resumable skip**: ``open(skip_batches=k)`` starts at batch ``k`` of
  this shard's sequence without re-yielding the prefix (array/synthetic
  sources jump in O(1); file sources re-parse only as far as needed and
  cache per-file row counts so a second skip is cheap);
- **position**: the returned iterator's :meth:`SourceIterator.position`
  reports (shard, offset) for the cursor's audit trail.
"""

from __future__ import annotations

import glob as _glob
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Tuple, Union

import numpy as np

from flinkml_tpu.table import Table


def resolve_shard(shard: Optional[Tuple[int, int]], mesh=None) -> Tuple[int, int]:
    """Normalize a shard assignment: explicit ``(index, count)`` wins;
    a :class:`~flinkml_tpu.parallel.DeviceMesh` assigns per-rank
    (process index/count — the reference's per-subtask stream split);
    neither means the single unsharded feed.

    Elastic resume re-derives each NEW rank's read position from a
    restored global watermark one level up: the resolved shard's
    :meth:`Source.skip_for_global` (round-robin deals,
    :func:`round_robin_skip`) computes the fast-forward, and
    :class:`~flinkml_tpu.data.Dataset`/:class:`~flinkml_tpu.data
    .ElasticFeed` validate the shard-count change before any batch is
    misread."""
    if shard is not None:
        index, count = int(shard[0]), int(shard[1])
    elif mesh is not None:
        import jax

        index, count = jax.process_index(), jax.process_count()
    else:
        index, count = 0, 1
    if count < 1 or not (0 <= index < count):
        raise ValueError(f"invalid shard assignment ({index}, {count})")
    return index, count


def round_robin_skip(shard_index: int, num_shards: int,
                     global_batches: int) -> int:
    """How many of shard ``shard_index``'s round-robin-dealt global
    batch indices (``shard_index, shard_index + num_shards, ...``) fall
    below ``global_batches`` — the per-shard fast-forward that lands a
    resharded resume exactly at a restored global watermark."""
    g = int(global_batches)
    if g <= shard_index:
        return 0
    return (g - shard_index + num_shards - 1) // num_shards


class SourceIterator:
    """Iterator over one shard's batches with a reportable position."""

    def __init__(self, gen: Iterator[Table], source: "Source", start: int):
        self._gen = gen
        self._source = source
        self.batches_read = int(start)

    def __iter__(self) -> "SourceIterator":
        return self

    def __next__(self) -> Table:
        batch = next(self._gen)
        self.batches_read += 1
        return batch

    def position(self) -> Dict[str, Any]:
        pos = self._source._position(self.batches_read)
        pos.update(
            shard=self._source.shard_index,
            num_shards=self._source.num_shards,
            batches_read=self.batches_read,
        )
        return pos


class Source:
    """Base class: a replayable, shardable origin of Table batches."""

    #: True when the shard deal is a pure round-robin over ONE canonical
    #: global batch sequence (batch ``g`` belongs to shard ``g % n``),
    #: so a cursor written at one shard count can be re-split across
    #: another: the global order is identical at every world, only the
    #: reading is parallelized. Contiguous-block deals (ArraySource) and
    #: file-granularity deals (CSV/LibSVM globs) are NOT — their
    #: mid-stream progress is entangled with the shard count.
    reshardable = False

    def __init__(self, shard: Optional[Tuple[int, int]] = None, mesh=None):
        self.shard_index, self.num_shards = resolve_shard(shard, mesh)

    def open(self, skip_batches: int = 0) -> SourceIterator:
        """A fresh iterator over this shard's batches, starting at batch
        ``skip_batches`` of the (deterministic) sequence."""
        return SourceIterator(
            self._batches(int(skip_batches)), self, int(skip_batches)
        )

    def skip_for_global(self, global_batches: int) -> int:
        """This shard's fast-forward for a restored GLOBAL watermark:
        the number of its own batches with global index below
        ``global_batches``. Defined only for :attr:`reshardable`
        sources — anything else raises
        :class:`~flinkml_tpu.data.state.CursorShardMismatchError`
        (loudly, before any row is misread)."""
        from flinkml_tpu.data.state import CursorShardMismatchError

        raise CursorShardMismatchError(
            f"{type(self).__name__} deals shards "
            f"({self.shard_index}/{self.num_shards}) without a canonical "
            "round-robin global batch order, so a cursor cannot be "
            "re-split across a different shard count; resume at the "
            "original count, or feed through a reshardable source "
            "(SyntheticSource, or an ElasticFeed over one)"
        )

    def __iter__(self) -> SourceIterator:
        return self.open()

    # -- subclass surface ---------------------------------------------------
    def _batches(self, skip: int) -> Iterator[Table]:
        raise NotImplementedError

    def _position(self, batches_read: int) -> Dict[str, Any]:
        return {}


def _as_table(data: Union[Table, Mapping[str, Any]]) -> Table:
    return data if isinstance(data, Table) else Table(dict(data))


class ArraySource(Source):
    """In-memory arrays (a :class:`Table` or a column mapping), split
    into consecutive ``batch_size``-row batches. Sharding assigns each
    rank one contiguous row block (remainder rows go to the leading
    ranks), so every rank's feed is a slice view — zero copies until a
    transform touches the rows."""

    def __init__(self, data, batch_size: int,
                 shard: Optional[Tuple[int, int]] = None, mesh=None):
        super().__init__(shard, mesh)
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.table = _as_table(data)
        self.batch_size = int(batch_size)
        n = self.table.num_rows
        base, rem = divmod(n, self.num_shards)
        sizes = [base + (1 if i < rem else 0) for i in range(self.num_shards)]
        self._lo = sum(sizes[: self.shard_index])
        self._hi = self._lo + sizes[self.shard_index]

    @property
    def num_batches(self) -> int:
        rows = self._hi - self._lo
        return -(-rows // self.batch_size) if rows else 0

    def _batches(self, skip: int) -> Iterator[Table]:
        start = self._lo + skip * self.batch_size
        for lo in range(start, self._hi, self.batch_size):
            yield self.table.slice(lo, min(lo + self.batch_size, self._hi))

    def _position(self, batches_read: int) -> Dict[str, Any]:
        return {"row_offset": min(
            batches_read * self.batch_size, self._hi - self._lo
        )}


class SyntheticSource(Source):
    """Seeded generator source: ``make_batch(index, rng) -> Table`` is
    called with the GLOBAL batch index and a Generator keyed by
    ``(seed, index)`` — so batch ``i`` is identical no matter which rank
    draws it, in what order, or after how many skips. Sharding deals
    global indices round-robin, which ALSO makes this the reshardable
    source: the global sequence is canonical at every shard count, so an
    elastic resume re-splits a restored watermark exactly
    (:meth:`skip_for_global`)."""

    reshardable = True

    def __init__(self, make_batch: Callable[[int, np.random.Generator], Table],
                 num_batches: int, seed: int = 0,
                 shard: Optional[Tuple[int, int]] = None, mesh=None):
        super().__init__(shard, mesh)
        if num_batches < 0:
            raise ValueError(f"num_batches must be >= 0, got {num_batches}")
        self.make_batch = make_batch
        self.num_batches_global = int(num_batches)
        self.seed = int(seed)

    def _global_indices(self) -> range:
        return range(self.shard_index, self.num_batches_global,
                     self.num_shards)

    @property
    def num_batches(self) -> int:
        return len(self._global_indices())

    def skip_for_global(self, global_batches: int) -> int:
        return round_robin_skip(
            self.shard_index, self.num_shards,
            min(int(global_batches), self.num_batches_global),
        )

    def _batches(self, skip: int) -> Iterator[Table]:
        for gi in list(self._global_indices())[skip:]:
            rng = np.random.default_rng([self.seed, gi])
            yield self.make_batch(gi, rng)

    def _position(self, batches_read: int) -> Dict[str, Any]:
        idx = list(self._global_indices())
        nxt = idx[batches_read] if batches_read < len(idx) else None
        return {"next_global_batch": nxt}


class _FileSource(Source):
    """Shared machinery of the file-glob sources: sorted glob, files
    round-robin per rank, per-file batch counts cached after first parse
    so a resumed skip re-parses only the file the cursor lands in."""

    def __init__(self, pattern: Union[str, List[str]], batch_size: int,
                 shard: Optional[Tuple[int, int]] = None, mesh=None):
        super().__init__(shard, mesh)
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.batch_size = int(batch_size)
        if isinstance(pattern, str):
            files = sorted(_glob.glob(pattern))
            if not files:
                raise FileNotFoundError(
                    f"no files match input-pipeline glob {pattern!r}"
                )
        else:
            files = list(pattern)
        self.files = files[self.shard_index :: self.num_shards]
        self._batch_counts: Dict[str, int] = {}

    def _read_file(self, path: str) -> Table:
        raise NotImplementedError

    def _file_batches(self, path: str) -> int:
        if path not in self._batch_counts:
            rows = self._read_file(path).num_rows
            self._batch_counts[path] = -(-rows // self.batch_size)
        return self._batch_counts[path]

    def _batches(self, skip: int) -> Iterator[Table]:
        remaining = skip
        for path in self.files:
            # A cached batch count skips a whole file without re-parsing
            # it; an uncached one costs exactly ONE parse (there is no
            # row index in CSV/LibSVM to consult) — kept and reused when
            # the cursor lands inside this file.
            table: Optional[Table] = None
            count = self._batch_counts.get(path)
            if count is None:
                table = self._read_file(path)
                count = -(-table.num_rows // self.batch_size)
                self._batch_counts[path] = count
            if remaining >= count:
                remaining -= count
                continue
            if table is None:
                table = self._read_file(path)
            for i, batch in enumerate(table.batches(self.batch_size)):
                if i < remaining:
                    continue
                yield batch
            remaining = 0

    def _position(self, batches_read: int) -> Dict[str, Any]:
        remaining, fi = batches_read, 0
        for fi, path in enumerate(self.files):
            count = self._batch_counts.get(path)
            if count is None or remaining < count:
                break
            remaining -= count
        return {"file_index": fi, "batch_in_file": remaining}


class CSVSource(_FileSource):
    """Numeric-CSV file glob through :func:`flinkml_tpu.io.read_csv_table`
    (native multithreaded parser with pure-Python fallback). Every file
    must share one schema; columns without a header row are ``c0..cN``."""

    def __init__(self, pattern, batch_size: int, delimiter: str = ",",
                 header="auto", shard=None, mesh=None):
        super().__init__(pattern, batch_size, shard, mesh)
        self.delimiter = delimiter
        self.header = header

    def _read_file(self, path: str) -> Table:
        from flinkml_tpu.io import read_csv_table

        return read_csv_table(path, delimiter=self.delimiter,
                              header=self.header)


class LibSVMSource(_FileSource):
    """LibSVM file glob densified to a ``{features, label}`` Table via
    :func:`flinkml_tpu.io.read_libsvm_dense`. ``n_features`` pins the
    feature dim so every file (and every rank) agrees on the batch
    shape — required for the bucketed prefetcher's zero-retrace
    contract."""

    def __init__(self, pattern, batch_size: int, n_features: int,
                 features_col: str = "features", label_col: str = "label",
                 shard=None, mesh=None):
        super().__init__(pattern, batch_size, shard, mesh)
        self.n_features = int(n_features)
        self.features_col = features_col
        self.label_col = label_col

    def _read_file(self, path: str) -> Table:
        from flinkml_tpu.io import read_libsvm_dense

        x, y = read_libsvm_dense(path, n_features=self.n_features)
        return Table({self.features_col: x, self.label_col: y})
