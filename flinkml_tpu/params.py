"""Typed, validated, JSON-serializable parameters for pipeline stages.

Capability parity with the reference param system
(``flink-ml-core/.../ml/param/Param.java:33-79``,
``WithParams.java:74-125``, ``ParamValidators.java``): a ``Param[T]`` carries
name / description / default / validator and knows how to encode itself to
JSON; ``WithParams`` provides get/set with validation and a param map.

TPU-first differences: params are plain Python descriptors discovered by
class-attribute scan (no reflection over getter interfaces), and values are
restricted to JSON-representable types so that stage metadata round-trips
losslessly between hosts.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Generic, Optional, Sequence, TypeVar

T = TypeVar("T")


class ParamValidators:
    """Factory methods for common validators.

    Parity: ``ml/param/ParamValidators.java:27-`` (gt/gtEq/lt/ltEq/inRange/
    inArray/notNull), plus ``non_empty_array`` used by array-typed params.
    Each validator is a predicate ``value -> bool``.
    """

    @staticmethod
    def always_true() -> Callable[[Any], bool]:
        return lambda v: True

    @staticmethod
    def gt(lower: float) -> Callable[[Any], bool]:
        return lambda v: v is not None and v > lower

    @staticmethod
    def gt_eq(lower: float) -> Callable[[Any], bool]:
        return lambda v: v is not None and v >= lower

    @staticmethod
    def lt(upper: float) -> Callable[[Any], bool]:
        return lambda v: v is not None and v < upper

    @staticmethod
    def lt_eq(upper: float) -> Callable[[Any], bool]:
        return lambda v: v is not None and v <= upper

    @staticmethod
    def in_range(
        lower: float,
        upper: float,
        lower_inclusive: bool = True,
        upper_inclusive: bool = True,
    ) -> Callable[[Any], bool]:
        def check(v: Any) -> bool:
            if v is None:
                return False
            if not (lower <= v <= upper):
                return False
            if not lower_inclusive and v == lower:
                return False
            if not upper_inclusive and v == upper:
                return False
            return True

        return check

    @staticmethod
    def in_array(allowed: Sequence[Any]) -> Callable[[Any], bool]:
        allowed_set = list(allowed)
        return lambda v: v in allowed_set

    @staticmethod
    def not_null() -> Callable[[Any], bool]:
        return lambda v: v is not None

    @staticmethod
    def non_empty_array() -> Callable[[Any], bool]:
        return lambda v: v is not None and len(v) > 0


class Param(Generic[T]):
    """Definition of a stage parameter.

    Parity: ``ml/param/Param.java:33-79``. A ``Param`` is identified by name
    and owns JSON encode/decode of its value. Typed subclasses below mirror
    the reference's 14 typed subclasses where they change encode/decode or
    validation semantics.
    """

    def __init__(
        self,
        name: str,
        description: str = "",
        default_value: Optional[T] = None,
        validator: Optional[Callable[[Any], bool]] = None,
    ) -> None:
        self.name = name
        self.description = description
        self.validator = validator or ParamValidators.always_true()
        if default_value is not None and not self.validator(default_value):
            raise ValueError(
                f"Parameter {name} is given an invalid default value {default_value}"
            )
        self.default_value = default_value

    # -- JSON round-trip ---------------------------------------------------
    def json_encode(self, value: T) -> Any:
        return value

    def json_decode(self, json_value: Any) -> T:
        return json_value

    def validate(self, value: Any) -> None:
        if not self.validator(value):
            raise ValueError(f"Parameter {self.name} is given an invalid value {value}")

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self).__name__}({self.name!r})"


class IntParam(Param[int]):
    def json_decode(self, json_value: Any) -> int:
        return int(json_value)


class LongParam(Param[int]):
    """64-bit integer param; Python ints are unbounded so this is IntParam."""

    def json_decode(self, json_value: Any) -> int:
        return int(json_value)


class FloatParam(Param[float]):
    def json_decode(self, json_value: Any) -> float:
        return float(json_value)


# Alias matching the reference's DoubleParam naming.
DoubleParam = FloatParam


class BoolParam(Param[bool]):
    def json_decode(self, json_value: Any) -> bool:
        return bool(json_value)


class StringParam(Param[str]):
    pass


class IntArrayParam(Param[list]):
    def json_encode(self, value: list) -> Any:
        return list(value) if value is not None else None

    def json_decode(self, json_value: Any) -> list:
        return [int(v) for v in json_value]


class FloatArrayParam(Param[list]):
    def json_encode(self, value: list) -> Any:
        return list(value) if value is not None else None

    def json_decode(self, json_value: Any) -> list:
        return [float(v) for v in json_value]


DoubleArrayParam = FloatArrayParam


class FloatArrayArrayParam(Param[list]):
    """List-of-float-lists (the reference's DoubleArrayArrayParam), e.g.
    per-column bucket split arrays."""

    def json_encode(self, value: list) -> Any:
        return [list(row) for row in value] if value is not None else None

    def json_decode(self, json_value: Any) -> list:
        return [[float(v) for v in row] for row in json_value]


DoubleArrayArrayParam = FloatArrayArrayParam


class StringArrayParam(Param[list]):
    def json_encode(self, value: list) -> Any:
        return list(value) if value is not None else None

    def json_decode(self, json_value: Any) -> list:
        return [str(v) for v in json_value]


class WithParams:
    """Mixin giving a class a validated parameter map.

    Parity: ``ml/param/WithParams.java:51-125``. ``Param`` definitions are
    class attributes; instance values live in ``self._param_map``. ``set``
    validates and returns ``self`` for chaining; ``get`` falls back to the
    param's default.

    Subclasses also get snake_case ``set_<name>`` / ``get_<name>`` sugar via
    ``__getattr__`` so user code reads naturally (the reference's Java
    mixins expose camelCase setters; the Python binding maps snake→camel at
    ``flink-ml-python/pyflink/ml/core/wrapper.py:39-83`` — here Python is the
    primary API so snake_case is native).
    """

    def __init__(self) -> None:
        self._param_map: dict[Param, Any] = {}

    # Per-class cache of discovered Param definitions; Params are static
    # class attributes so one MRO scan per class suffices.
    _params_by_class: dict = {}

    # -- core accessors ----------------------------------------------------
    @classmethod
    def params(cls) -> list:
        """All Param definitions on this class, in MRO discovery order."""
        return list(cls._param_index().values())

    @classmethod
    def _param_index(cls) -> dict:
        cached = WithParams._params_by_class.get(cls)
        if cached is None:
            cached = {}
            for klass in reversed(cls.__mro__):
                for attr in vars(klass).values():
                    if isinstance(attr, Param):
                        cached[attr.name] = attr
            WithParams._params_by_class[cls] = cached
        return cached

    @classmethod
    def get_param(cls, name: str) -> Optional[Param]:
        return cls._param_index().get(name)

    def set(self, param: Param, value: Any) -> "WithParams":
        # Re-key through this class's own Param of the same name, so values
        # set via an equal-but-distinct Param (e.g. copy_params_from across
        # stage types) land where this class's accessors find them.
        own = self.get_param(param.name)
        if own is None:
            raise ValueError(
                f"Parameter {param.name} is not defined on {type(self).__name__}"
            )
        own.validate(value)
        self._ensure_map()[own] = value
        return self

    def get(self, param: Param) -> Any:
        own = self.get_param(param.name)
        if own is None:
            raise ValueError(
                f"Parameter {param.name} is not defined on {type(self).__name__}"
            )
        m = self._ensure_map()
        if own in m:
            return m[own]
        return own.default_value

    @property
    def param_map(self) -> dict:
        """Live map of explicitly-set params (param -> value)."""
        return self._ensure_map()

    def _ensure_map(self) -> dict:
        if not hasattr(self, "_param_map"):
            self._param_map = {}
        return self._param_map

    # -- snake_case sugar --------------------------------------------------
    def __getattr__(self, item: str):
        # Only called when normal lookup fails.
        if item.startswith("set_"):
            param = self._lookup_snake(item[4:])
            if param is not None:
                return lambda value: self.set(param, value)
        elif item.startswith("get_"):
            param = self._lookup_snake(item[4:])
            if param is not None:
                return lambda: self.get(param)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {item!r}"
        )

    @classmethod
    def _lookup_snake(cls, snake: str) -> Optional[Param]:
        camel = _snake_to_camel(snake)
        for p in cls.params():
            if p.name == camel or p.name == snake:
                return p
        return None

    # -- JSON round-trip ---------------------------------------------------
    def get_param_map_json(self) -> dict:
        """Encode the *effective* param map (defaults included) to JSON."""
        out = {}
        for p in self.params():
            out[p.name] = p.json_encode(self.get(p))
        return out

    def load_param_map_json(self, json_map: dict) -> "WithParams":
        for name, json_value in json_map.items():
            p = self.get_param(name)
            if p is None:
                # Unknown params are tolerated for forward compatibility.
                continue
            if json_value is None:
                continue
            self.set(p, p.json_decode(json_value))
        return self

    def copy_params_from(self, other: "WithParams") -> "WithParams":
        for p, v in other.param_map.items():
            if self.get_param(p.name) is not None:
                self.set(p, copy.deepcopy(v))
        return self


def _snake_to_camel(s: str) -> str:
    parts = s.split("_")
    return parts[0] + "".join(w.capitalize() for w in parts[1:])
