"""``python -m flinkml_tpu.analysis`` — the ahead-of-time lint gate.

Runs all three analysis passes device-free over the given targets:

  1. *graph validation*: every ``.py`` target (file or directory) is
     AST-linted for pipeline schema/ordering/collision findings;
  2. *collective order*: every ``*.trace.json`` target (a recorded
     dispatch trace, e.g. a fixture of the PR 1 threaded-kmeans deadlock)
     is checked for unlocked concurrent collective dispatch;
  2b. *sharding plans*: every ``*.plan.json`` target (a declared
     ShardingPlan + mesh + param shapes, see
     ``docs/development/sharding.md``) is validated pre-compile —
     FML501-504;
  2c. *precision policies*: every ``*.policy.json`` target (a declared
     PrecisionPolicy, optionally with an example program and a plan
     width, see ``docs/development/precision.md``) runs the
     precision-flow pass — FML601-605;
  2d. *sorted-scatter provenance*: every ``*.scatter.json`` target (a
     declarative scatter probe with a declared pack-time sorted
     guarantee, see :mod:`flinkml_tpu.analysis.sorted_scatter`) runs
     the FML404 walk;
  2e. *memory liveness*: every ``*.memory.json`` target (a mesh + plan
     + HBM budget + probe program and/or quant-tier ladder, see
     :mod:`flinkml_tpu.analysis.memory`) runs the peak-live-bytes
     pass — FML701-704;
  3. *transfer/retrace self-check*: a representative fused scaler→
     predictor chain is executed at several row counts inside one bucket
     under :class:`~flinkml_tpu.analysis.guard.TransferRetraceGuard` —
     zero cache misses and exactly one upload per transform, or findings.

Exit status: 0 when clean, 1 on any error-severity finding (or on ANY
finding with ``--fail-on-findings``). ``--format json`` emits
machine-readable findings (rule, severity, location, message — what CI
annotates from; ``--json`` is the legacy spelling), ``--suppress
FML104,...`` drops rules, ``--rules`` prints the catalog. See
``docs/development/static_analysis.md``.
"""

from __future__ import annotations

import os

# Device-free by construction: pin the CPU backend before anything can
# import jax (the TPU plugin may override JAX_PLATFORMS at import time;
# re-pinned via jax.config below for that case).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse
import sys

from flinkml_tpu.analysis.findings import RULES, Report


def _pin_cpu() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _pass_lint(py_targets, report: Report) -> None:
    from flinkml_tpu.analysis.ast_lint import lint_paths

    report.extend(lint_paths(py_targets))


def _pass_traces(trace_targets, report: Report) -> None:
    from flinkml_tpu.analysis.collectives import (
        check_dispatch_trace,
        load_trace,
    )

    for path in trace_targets:
        report.extend(
            check_dispatch_trace(load_trace(path), location=path)
        )


def _pass_plans(plan_targets, report: Report) -> None:
    from flinkml_tpu.analysis.sharding_check import check_plan_file

    for path in plan_targets:
        report.extend(check_plan_file(path))


def _pass_policies(policy_targets, report: Report) -> None:
    from flinkml_tpu.analysis.precision import check_policy_file

    _pin_cpu()  # example programs trace jaxprs (abstract, device-free)
    for path in policy_targets:
        report.extend(check_policy_file(path))


def _pass_scatters(scatter_targets, report: Report) -> None:
    from flinkml_tpu.analysis.sorted_scatter import check_scatter_file

    _pin_cpu()  # probe programs trace jaxprs (abstract, device-free)
    for path in scatter_targets:
        report.extend(check_scatter_file(path))


def _pass_features(features_targets, report: Report) -> None:
    from flinkml_tpu.analysis.features_check import check_features_file

    for path in features_targets:
        report.extend(check_features_file(path))


def _pass_memory(memory_targets, report: Report) -> None:
    from flinkml_tpu.analysis.memory import check_memory_file

    _pin_cpu()  # probe programs trace jaxprs (abstract, device-free)
    for path in memory_targets:
        report.extend(check_memory_file(path))


#: extension -> pass runner. Adding a fixture type is ONE row here: the
#: CLI arg split and the directory walk both iterate this table, so a
#: new extension can never be routed by one and silently missed by the
#: other (the four copy-pasted walk loops this replaced did exactly
#: that dance by hand).
_FIXTURE_PASSES = (
    (".trace.json", _pass_traces),
    (".plan.json", _pass_plans),
    (".policy.json", _pass_policies),
    (".scatter.json", _pass_scatters),
    (".memory.json", _pass_memory),
    (".features.json", _pass_features),
)


def _pass_retrace_selfcheck(report: Report) -> None:
    """Drive the bench's ``pipeline_fused`` chain (4 scalers + a
    LogisticRegressionModel, the 5-stage all-kernel spine ``bench.py``
    measures) across varying batch sizes within one row bucket (and one
    boundary crossing) under a zero-budget guard — the runtime half of
    the bucket-policy contract, checked device-free."""
    import numpy as np

    _pin_cpu()
    from flinkml_tpu.analysis.guard import TransferRetraceGuard
    from flinkml_tpu.models.logistic_regression import LogisticRegressionModel
    from flinkml_tpu.models.scalers import (
        MaxAbsScalerModel,
        MinMaxScalerModel,
        RobustScalerModel,
        StandardScalerModel,
    )
    from flinkml_tpu.pipeline import PipelineModel
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(0)
    n, d = 200, 8
    x = rng.normal(size=(n, d))
    table = Table({"features": x})

    stages = []
    prev = "features"
    scaler_data = {
        StandardScalerModel: {"mean": x.mean(0)[None], "std": x.std(0)[None]},
        MinMaxScalerModel: {"dataMin": x.min(0)[None],
                            "dataMax": x.max(0)[None]},
        MaxAbsScalerModel: {"maxAbs": np.abs(x).max(0)[None]},
        RobustScalerModel: {"median": np.median(x, 0)[None],
                            "range": np.ones((1, d))},
    }
    for i, (cls, data) in enumerate(scaler_data.items(), start=1):
        m = cls().set(cls.INPUT_COL, prev).set(cls.OUTPUT_COL, f"s{i}")
        m.set_model_data(Table(data))
        stages.append(m)
        prev = f"s{i}"
    lr = LogisticRegressionModel().set(
        LogisticRegressionModel.FEATURES_COL, prev
    )
    lr.set_model_data(Table({"coefficient": rng.normal(size=(1, d))}))
    stages.append(lr)
    pm = PipelineModel(stages)

    # Warmup: one compile for the 128-row bucket.
    pm.transform(table.slice(0, 100))

    guard = TransferRetraceGuard(
        allow_compiles=0,
        allow_new_buckets=True,          # crossing 128 -> 256 is policy
        allow_host_to_device=5,          # one declared upload per new table
        allow_device_to_host=0,          # nothing reads back in the loop
        raise_on_violation=False,
        location="selfcheck:pipeline_fused",
    )
    with guard:
        for rows in (100, 77, 96, 128):  # same bucket: zero compiles
            pm.transform(table.slice(0, rows))
        pm.transform(table.slice(0, 129))  # new bucket: allowed compile
    report.extend(guard.findings)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m flinkml_tpu.analysis",
        description="Ahead-of-time pipeline validation, collective-order "
                    "checking, and a transfer/retrace lint gate.",
    )
    parser.add_argument(
        "targets", nargs="*",
        help=".py files / directories to lint, *.trace.json dispatch "
             "traces, *.plan.json sharding plans, *.policy.json "
             "precision policies, *.scatter.json sorted-scatter "
             "probes, and *.memory.json memory-liveness targets to "
             "check",
    )
    parser.add_argument(
        "--fail-on-findings", action="store_true",
        help="exit non-zero on ANY finding (default: errors only)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default=None,
        help="output format: human-readable text (default) or "
             "machine-readable JSON findings (rule, severity, location, "
             "message) for CI annotation",
    )
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON (legacy spelling of "
                             "--format json)")
    parser.add_argument(
        "--suppress", default="",
        help="comma-separated rule ids to drop (e.g. FML104,FML106)",
    )
    parser.add_argument("--rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument(
        "--no-selfcheck", action="store_true",
        help="skip the transfer/retrace executor self-check pass",
    )
    args = parser.parse_args(argv)

    if args.rules:
        for rule, (sev, desc) in sorted(RULES.items()):
            print(f"{rule} [{sev}] {desc}")
        return 0

    py_targets: list = []
    buckets: dict = {ext: [] for ext, _runner in _FIXTURE_PASSES}
    for t in args.targets:
        for ext, _runner in _FIXTURE_PASSES:
            if t.endswith(ext):
                buckets[ext].append(t)
                break
        else:
            py_targets.append(t)
            if os.path.isdir(t):
                for root, _dirs, names in os.walk(t):
                    for n in sorted(names):
                        for ext, _runner in _FIXTURE_PASSES:
                            if n.endswith(ext):
                                buckets[ext].append(os.path.join(root, n))
                                break

    report = Report()
    if py_targets:
        _pass_lint(py_targets, report)
    for ext, runner in _FIXTURE_PASSES:
        if buckets[ext]:
            runner(buckets[ext], report)
    if not args.no_selfcheck:
        _pass_retrace_selfcheck(report)

    if args.suppress:
        report = report.suppress(
            [r.strip() for r in args.suppress.split(",") if r.strip()]
        )

    if args.json or args.format == "json":
        print(report.to_json())
    else:
        print(report.render())

    if args.fail_on_findings:
        return 1 if report else 0
    return 1 if report.errors() else 0


if __name__ == "__main__":
    sys.exit(main())
