"""Pass 2 — collective-order extraction and deadlock-shape detection.

Collective rendezvous (psum/ppermute/all_gather/...) requires every
participant to reach the *same* collectives in the *same* order. Two
program shapes break that:

  1. **Cross-rank divergence** (FML301): ranks compile programs whose
     collective sequences differ — rank 0 waits in a psum while rank 1
     waits in an all_gather, forever. :func:`extract_collectives` pulls
     the ordered collective sequence out of any traceable function's
     jaxpr (recursing through pjit/shard_map/scan/while/cond), and
     :func:`check_rank_order` compares sequences across ranks.

  2. **Unlocked concurrent dispatch** (FML302): two host *threads* each
     dispatch multi-device collective programs over overlapping devices.
     Per-device execution streams then see the two programs' collective
     enqueues in different orders on different devices — the exact
     intermittent wedge PR 1's ``local_execution_lock`` papers over.
     :func:`check_dispatch_trace` flags the unsafe shape statically from
     a recorded :class:`DispatchEvent` trace: any pair of multi-device
     collective dispatches from different threads over intersecting
     device sets that do not share a lock token is a potential
     rendezvous deadlock — *possibility* of interleaving is already the
     bug, no schedule enumeration needed.

Traces come from :mod:`flinkml_tpu.parallel.dispatch` observers (live
runs) or from JSON files (recorded fixtures); both are host-side only, so
the checker runs device-free.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from flinkml_tpu.analysis.findings import Finding

#: jaxpr primitives that rendezvous across devices.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
})


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in program order: primitive name + mesh axes."""

    primitive: str
    axes: Tuple[str, ...] = ()

    def to_map(self) -> dict:
        return {"primitive": self.primitive, "axes": list(self.axes)}

    @staticmethod
    def from_map(m: Mapping) -> "CollectiveOp":
        return CollectiveOp(str(m["primitive"]),
                            tuple(str(a) for a in m.get("axes", ())))


def _axes_of(params: Mapping[str, Any]) -> Tuple[str, ...]:
    for key in ("axes", "axis_name", "axis_index_groups_axis"):
        if key in params and params[key] is not None:
            v = params[key]
            if isinstance(v, (tuple, list)):
                return tuple(str(a) for a in v)
            return (str(v),)
    return ()


def _walk_jaxpr(jaxpr, out: List[CollectiveOp]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES:
            out.append(CollectiveOp(name, _axes_of(eqn.params)))
        for v in eqn.params.values():
            _walk_param(v, out)


def _walk_param(v: Any, out: List[CollectiveOp]) -> None:
    # Sub-jaxprs hide under many param names (jaxpr/call_jaxpr/branches/
    # cond_jaxpr/body_jaxpr/...); duck-type on having .eqns.
    if hasattr(v, "eqns"):
        _walk_jaxpr(v, out)
    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):  # ClosedJaxpr
        _walk_jaxpr(v.jaxpr, out)
    elif isinstance(v, (tuple, list)):
        for item in v:
            _walk_param(item, out)


def extract_collectives(fn, *example_args, **example_kwargs
                        ) -> Tuple[CollectiveOp, ...]:
    """The ordered collective sequence of ``fn``'s jaxpr, traced
    abstractly against the example arguments (shapes/dtypes only — no
    compile, no dispatch, no device). Loop bodies contribute their
    per-iteration sequence once: every device runs the same trip count in
    SPMD, so static order equality is what rendezvous consistency needs."""
    import jax

    closed = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
    out: List[CollectiveOp] = []
    _walk_jaxpr(closed.jaxpr, out)
    return tuple(out)


def check_rank_order(
    sequences: Mapping[Any, Sequence[CollectiveOp]],
    program: str = "program",
) -> List[Finding]:
    """FML301 when the per-rank collective sequences are not identical."""
    items = list(sequences.items())
    if len(items) < 2:
        return []
    ref_rank, ref = items[0]
    findings: List[Finding] = []
    for rank, seq in items[1:]:
        if tuple(seq) == tuple(ref):
            continue
        # Locate the first divergence for the message.
        i = 0
        while i < min(len(ref), len(seq)) and ref[i] == seq[i]:
            i += 1
        a = ref[i].primitive if i < len(ref) else "<end>"
        b = seq[i].primitive if i < len(seq) else "<end>"
        findings.append(Finding(
            "FML301",
            f"{program}: rank {rank} diverges from rank {ref_rank} at "
            f"collective #{i} ({b} vs {a}) — rendezvous mismatch deadlocks "
            "the mesh",
            stage=str(program),
            fix_hint="all ranks must execute one SPMD program; remove "
                     "rank-dependent branching around collectives",
        ))
    return findings


# ---------------------------------------------------------------------------
# Dispatch traces (cross-thread ordering)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DispatchEvent:
    """One host-side dispatch of a (possibly collective) program.

    ``devices`` are the device ids the program's collectives span;
    ``locks`` are the tokens of the tracked locks the dispatching thread
    held (see ``parallel.dispatch.local_execution_lock``); ``leases``
    are the tokens of active slice leases that OTHER threads held over
    these devices at dispatch time
    (``parallel.dispatch.lease_devices`` — the FML304 audit input).
    """

    thread: str
    program: str
    devices: Tuple[int, ...] = ()
    collectives: Tuple[CollectiveOp, ...] = ()
    locks: Tuple[str, ...] = ()
    leases: Tuple[str, ...] = ()

    def to_map(self) -> dict:
        return {
            "thread": self.thread,
            "program": self.program,
            "devices": list(self.devices),
            "collectives": [c.to_map() for c in self.collectives],
            "locks": list(self.locks),
            "leases": list(self.leases),
        }

    @staticmethod
    def from_map(m: Mapping) -> "DispatchEvent":
        return DispatchEvent(
            thread=str(m["thread"]),
            program=str(m.get("program", "?")),
            devices=tuple(int(d) for d in m.get("devices", ())),
            collectives=tuple(
                CollectiveOp.from_map(c) for c in m.get("collectives", ())
            ),
            locks=tuple(str(t) for t in m.get("locks", ())),
            leases=tuple(str(t) for t in m.get("leases", ())),
        )


def load_trace(path: str) -> List[DispatchEvent]:
    """Load a recorded dispatch trace (JSON list of event maps)."""
    with open(path, "r") as fh:
        data = json.load(fh)
    events = data["events"] if isinstance(data, Mapping) else data
    return [DispatchEvent.from_map(m) for m in events]


#: Dispatch-trace program prefix of serving replica-pool slices (the
#: :class:`~flinkml_tpu.serving.pool.ReplicaPool` tags each replica's
#: engine ``serving.pool/<pool>/<replica>`` — see
#: ``ServingConfig.dispatch_tag``).
POOL_PROGRAM_PREFIX = "serving.pool/"


def _is_pool_dispatch(event: DispatchEvent) -> bool:
    return event.program.startswith(POOL_PROGRAM_PREFIX)


def check_dispatch_trace(events: Iterable[DispatchEvent],
                         location: Optional[str] = None) -> List[Finding]:
    """FML302/FML303 for every pair of threads that dispatched
    multi-device collective programs over intersecting device sets
    without a common lock token. One finding per (thread pair, program
    pair) shape, not per event occurrence.

    The shape specializes to **FML303** when either side is a serving
    replica-pool slice dispatch (program prefix
    :data:`POOL_PROGRAM_PREFIX`): a pool whose mesh slices overlap a
    concurrently registered training dispatch (or another pool's slices)
    without a shared ``local_execution_lock`` — the pool-specific fix is
    to give the replicas their slice meshes (``ServingConfig.mesh``) so
    the per-slice locks compose with every overlapping set.

    **FML304** is the lease-aware shape (orthogonal to locking, so a
    shared lock does NOT clear it): a pool dispatch whose event carries
    an active foreign slice-lease token ran serving work on devices a
    training job still OWNS — the autoscaler skipped the reclaim
    handshake (``SliceLease.request_revoke`` + ``wait_released``) before
    placing the replica. One finding per (program, lease) pair."""
    events = list(events)
    findings: List[Finding] = []
    reported = set()
    for e in events:
        if not _is_pool_dispatch(e):
            continue
        for token in e.leases:
            key = ("FML304", e.program, token)
            if key in reported:
                continue
            reported.add(key)
            findings.append(Finding(
                "FML304",
                f"replica-pool dispatch {e.program!r} (thread "
                f"{e.thread!r}) runs on devices {sorted(e.devices)} "
                f"still covered by active training lease {token!r} — "
                "the slice was never reclaimed, so serving now steals "
                "cycles the trainer's lease promised it (and a shared "
                "lock only serializes the theft)",
                stage=e.program, location=location,
                fix_hint="reclaim before placing: "
                         "lease.request_revoke(reason) and "
                         "wait_released(timeout) — the trainer releases "
                         "at its next epoch boundary — or scale onto "
                         "unleased devices "
                         "(parallel.dispatch.leased_device_ids)",
            ))
    multi = [e for e in events if len(e.devices) > 1]
    for i, a in enumerate(multi):
        for b in multi[i + 1:]:
            if a.thread == b.thread:
                continue
            if not (set(a.devices) & set(b.devices)):
                continue
            if set(a.locks) & set(b.locks):
                continue
            key = frozenset(((a.thread, a.program), (b.thread, b.program)))
            if key in reported:
                continue
            reported.add(key)
            if _is_pool_dispatch(a) or _is_pool_dispatch(b):
                pool_ev, other = (
                    (a, b) if _is_pool_dispatch(a) else (b, a)
                )
                findings.append(Finding(
                    "FML303",
                    f"replica-pool slice {pool_ev.program!r} (thread "
                    f"{pool_ev.thread!r}) overlaps the concurrent dispatch "
                    f"{other.program!r} (thread {other.thread!r}) on shared "
                    "devices with no common slice lock — the replica's and "
                    "the trainer's collective enqueues may interleave and "
                    "deadlock the rendezvous",
                    stage=f"{pool_ev.program} / {other.program}",
                    location=location,
                    fix_hint="give the pool replicas their slice meshes "
                             "(ServingConfig.mesh / ReplicaPool(meshes=...)) "
                             "so every batch holds local_execution_lock("
                             "slice), which composes with overlapping "
                             "training locks",
                ))
                continue
            findings.append(Finding(
                "FML302",
                f"threads {a.thread!r} and {b.thread!r} dispatch collective "
                f"programs ({a.program!r}, {b.program!r}) over shared "
                "devices with no common lock — per-device collective "
                "enqueue order may interleave and deadlock the rendezvous",
                stage=f"{a.program} / {b.program}", location=location,
                fix_hint="hold parallel.dispatch.local_execution_lock(mesh) "
                         "around every host-driven loop that dispatches "
                         "multi-device collective programs",
            ))
    return findings
