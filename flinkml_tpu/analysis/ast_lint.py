"""Source-level pipeline lint — pass 1 for scripts.

Example/user scripts build pipelines at the top of a training run; running
them to validate them defeats the point of *ahead-of-time* checking. This
module reconstructs ``Pipeline([...])`` / ``PipelineModel([...])`` chains
from the AST instead:

  - each stage expression (``Cls().set_input_cols([...]).set(Cls.OUTPUT_COL,
    "x").fit(t)``) is peeled into a class name + param overrides;
  - the real stage class is imported from ``flinkml_tpu.models`` and
    instantiated (cheap, device-free) so **class-default column params
    participate** — chains that only connect through defaults (scaler
    default input ``"input"``/output ``"output"``) are checked for real;
  - the chain then flows through :func:`analyzer.validator.analyze_pipeline`
    with an *open* schema (source data columns are unknowable), which
    still catches output collisions (FML102) and consume-before-produce
    ordering (FML107).

Param values are resolved by a restricted constant evaluator: literals,
previously assigned module-level constants, f-strings, ``range`` list
comprehensions and arithmetic — enough for real scripts, with anything
fancier degrading to "unknown" (the affected check is skipped, never
guessed).
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Tuple

from flinkml_tpu.analysis.findings import Report
from flinkml_tpu.analysis.validator import analyze_pipeline

_PIPELINE_NAMES = {"Pipeline", "PipelineModel"}

#: Node types the restricted evaluator may execute. Anything else makes the
#: expression "unknown" rather than executed.
_SAFE_NODES = (
    ast.Expression, ast.Constant, ast.Name, ast.Load, ast.Store, ast.List,
    ast.Tuple,
    ast.Dict, ast.Set, ast.BinOp, ast.UnaryOp, ast.Add, ast.Sub, ast.Mult,
    ast.Div, ast.FloorDiv, ast.Mod, ast.USub, ast.UAdd, ast.JoinedStr,
    ast.FormattedValue, ast.ListComp, ast.comprehension, ast.Call,
    ast.Starred, ast.Subscript, ast.Slice, ast.Index if hasattr(ast, "Index") else ast.Slice,
)
_SAFE_CALLS = {"range": range, "len": len, "str": str, "int": int,
               "float": float, "list": list, "tuple": tuple}


class _Unknown:
    """Sentinel: the expression could not be resolved statically."""

    def __repr__(self):  # pragma: no cover
        return "<unknown>"


UNKNOWN_VALUE = _Unknown()


def _safe_eval(node: ast.AST, env: Dict[str, Any]) -> Any:
    """Evaluate ``node`` if it only uses whitelisted constructs and names
    from ``env``; returns :data:`UNKNOWN_VALUE` otherwise."""
    bound = {
        t.id
        for sub in ast.walk(node) if isinstance(sub, ast.comprehension)
        for t in ast.walk(sub.target) if isinstance(t, ast.Name)
    }
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            if not (isinstance(sub.func, ast.Name)
                    and sub.func.id in _SAFE_CALLS):
                return UNKNOWN_VALUE
        elif not isinstance(sub, _SAFE_NODES):
            return UNKNOWN_VALUE
        if isinstance(sub, ast.Name) and sub.id not in env \
                and sub.id not in _SAFE_CALLS and sub.id not in bound:
            return UNKNOWN_VALUE
    try:
        code = compile(ast.Expression(body=node), "<analysis>", "eval")
        return eval(  # noqa: S307 — whitelisted node types + names only
            code, {"__builtins__": dict(_SAFE_CALLS)}, dict(env)
        )
    except Exception:
        return UNKNOWN_VALUE


def _collect_constants(tree: ast.Module) -> Dict[str, Any]:
    """Module-level ``name = <resolvable>`` assignments (including tuple
    unpacking), in order, so later expressions can reference them."""
    env: Dict[str, Any] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        target = stmt.targets[0]
        if isinstance(target, ast.Name):
            v = _safe_eval(stmt.value, env)
            if v is not UNKNOWN_VALUE:
                env[target.id] = v
        elif isinstance(target, ast.Tuple) and all(
                isinstance(e, ast.Name) for e in target.elts):
            v = _safe_eval(stmt.value, env)
            if v is not UNKNOWN_VALUE:
                try:
                    vals = list(v)
                except TypeError:
                    continue
                if len(vals) == len(target.elts):
                    for name_node, val in zip(target.elts, vals):
                        env[name_node.id] = val
    return env


def _peel_chain(expr: ast.AST) -> Tuple[Optional[str], List[ast.Call]]:
    """Split ``Cls(...).m1(...).m2(...)`` into (class name, [m1, m2, ...]).

    Returns ``(None, [])`` for anything that is not a constructor-rooted
    call chain (e.g. a bare variable reference).
    """
    calls: List[ast.Call] = []
    node = expr
    while isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name):
            return f.id, list(reversed(calls))
        if isinstance(f, ast.Attribute):
            calls.append(node)
            node = f.value
        else:
            return None, []
    return None, []


def _camel(method: str) -> str:
    """``set_input_cols`` -> ``inputCols``."""
    parts = method.split("_")[1:]
    if not parts:
        return ""
    return parts[0] + "".join(p.title() for p in parts[1:])


class _OpaqueStage:
    """Placeholder for a stage the lint cannot model; analyze_pipeline
    treats it as opaque (schema goes open after it)."""

    def transform_kernel(self):
        return None


def _build_stage(cls_name: str, calls: List[ast.Call],
                 env: Dict[str, Any]):
    """Instantiate the real stage class and replay the statically
    resolvable param-setting calls onto it. Returns an _OpaqueStage when
    the class is unknown or a column param cannot be resolved."""
    import flinkml_tpu.models as models

    cls = getattr(models, cls_name, None)
    if cls is None:
        return _OpaqueStage()
    try:
        stage = cls()
        params_by_name = {p.name: p for p in cls.params()}
    except Exception:
        return _OpaqueStage()

    for call in calls:
        method = call.func.attr
        if method == "fit":
            # Estimator -> Model: column params carry over unchanged; the
            # estimator instance already holds them.
            continue
        if method == "set" and len(call.args) == 2:
            pnode, vnode = call.args
            if not isinstance(pnode, ast.Attribute):
                return _OpaqueStage()
            param = getattr(cls, pnode.attr, None)
            value = _safe_eval(vnode, env)
        elif method.startswith("set_") and len(call.args) == 1:
            param = params_by_name.get(_camel(method))
            if param is None:
                continue  # non-param fluent setter; ignore
            value = _safe_eval(call.args[0], env)
        else:
            continue
        if param is None:
            continue
        if value is UNKNOWN_VALUE:
            pname = getattr(param, "name", "")
            if "Col" in pname or "col" in pname:
                # A column wired through something we can't resolve —
                # modelling the stage with the default would produce
                # false findings; degrade to opaque.
                return _OpaqueStage()
            continue
        try:
            stage.set(param, value)
        except Exception:
            return _OpaqueStage()
    return stage


def lint_source(source: str, filename: str = "<source>") -> Report:
    """Lint one script: reconstruct every ``Pipeline([...])`` /
    ``PipelineModel([...])`` literal and validate its chain."""
    report = Report()
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        from flinkml_tpu.analysis.findings import Finding
        report.add(Finding("FML101", f"could not parse: {e}",
                           location=filename))
        return report
    env = _collect_constants(tree)

    # Stage variables assigned earlier and referenced by name inside the
    # pipeline list: remember their defining expression.
    stage_exprs: Dict[str, ast.AST] = {}
    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Call):
            stage_exprs[stmt.targets[0].id] = stmt.value

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Name, ast.Attribute))):
            continue
        fname = node.func.id if isinstance(node.func, ast.Name) \
            else node.func.attr
        if fname not in _PIPELINE_NAMES or not node.args:
            continue
        arg = node.args[0]
        if not isinstance(arg, (ast.List, ast.Tuple)):
            continue
        stages = []
        for elt in arg.elts:
            expr = elt
            if isinstance(expr, ast.Name) and expr.id in stage_exprs:
                expr = stage_exprs[expr.id]
            cls_name, calls = _peel_chain(expr)
            stages.append(
                _build_stage(cls_name, calls, env) if cls_name
                else _OpaqueStage()
            )
        location = f"{filename}:{node.lineno}"
        report.extend(analyze_pipeline(stages, schema=None,
                                       location=location))
    return report


def lint_paths(paths) -> Report:
    """Lint every ``.py`` file in ``paths`` (files or directories)."""
    report = Report()
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files.extend(
                    os.path.join(root, n) for n in sorted(names)
                    if n.endswith(".py")
                )
        elif p.endswith(".py"):
            files.append(p)
    for f in files:
        with open(f, "r") as fh:
            report.extend(lint_source(fh.read(), filename=f))
    return report
