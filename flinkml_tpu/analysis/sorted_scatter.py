"""Pass 6 — sorted-scatter provenance validation (FML404), pre-compile.

The sorted-layout contract (``docs/development/kernels.md``): sortedness
is bought ONCE at pack time — :class:`~flinkml_tpu.table
.SortedSparseColumn` carries ``indices_are_sorted=True`` as recorded
provenance — so every downstream gradient scatter is entitled to the
``indices_are_sorted=True`` fast path for free. A ``segment_sum`` (or
any scatter-add) traced with ``indices_are_sorted=False`` over indices
that CAME from a sorted-provenance source silently re-pays the sort the
pipeline already performed: XLA lowers the unsorted scatter through the
general sort-and-combine path, and the pack-time work is wasted on
every step, forever, with no error anywhere. That is FML404.

Device-free: the check walks jaxprs (``jax.make_jaxpr``), propagating a
**sorted** flag from the declared sorted inputs through the
order-preserving ops (reshape / broadcast / cast / slice / copy — the
ops the ``segment_sum`` expansion itself applies to its ids) and one
level of call primitives, and flags every scatter-add whose
scatter-indices operand is sorted-provenance while its
``indices_are_sorted`` param is ``False``.

Consumes live functions pre-compile (:func:`check_sorted_scatter_fn`)
or ``*.scatter.json`` declarative probes (:func:`check_scatter_file`,
routed by ``python -m flinkml_tpu.analysis``):

.. code-block:: json

    {"program": {"name": "segment_sum", "cells": 64, "num_segments": 16,
                 "indices_are_sorted": false},
     "sorted_guarantee": true}

``sorted_guarantee`` declares the probe's ids input as pack-time sorted
(the SortedSparseColumn provenance); ``indices_are_sorted`` is the flag
the traced scatter actually passes. ``true``/``false`` → FML404.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional, Sequence

from flinkml_tpu.analysis.findings import Finding

#: Primitives through which sorted provenance propagates: they preserve
#: element order along the (single) sorted axis. Gathers/permutes are
#: deliberately absent — ``take(ids, perm)`` yields an arbitrary order
#: unless perm itself is the sorting permutation, which this static
#: pass cannot see.
ORDER_PRESERVING = frozenset({
    "reshape",
    "broadcast_in_dim",
    "convert_element_type",
    "squeeze",
    "slice",
    "dynamic_slice",
    "copy",
    "stop_gradient",
})

#: Call primitives recursed one level (the gate / jit wrappers the
#: sparse trainers put around their scatters).
_CALL_PRIMITIVES = frozenset({"pjit", "closed_call", "core_call",
                              "custom_jvp_call", "custom_vjp_call",
                              "remat", "checkpoint"})

_SCATTER_ADD = "scatter-add"


def _is_var(v) -> bool:
    """True for jaxpr Vars (hashable, trackable); False for Literals
    (inline constants — they carry ``.val`` and are unhashable)."""
    return not hasattr(v, "val")


def _subjaxprs(params) -> Iterable:
    for v in params.values():
        if hasattr(v, "jaxpr") and hasattr(v, "consts"):  # ClosedJaxpr
            yield v.jaxpr
        elif hasattr(v, "eqns"):  # raw Jaxpr
            yield v


def _walk(jaxpr, sorted_vars: set, location: Optional[str],
          findings: List[Finding], depth: int = 0) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == _SCATTER_ADD:
            idx_var = eqn.invars[1]  # (operand, scatter_indices, updates)
            if (not eqn.params.get("indices_are_sorted", False)
                    and _is_var(idx_var) and idx_var in sorted_vars):
                findings.append(Finding(
                    "FML404",
                    "scatter-add traced with indices_are_sorted=False "
                    "over indices with pack-time sorted provenance: the "
                    "pipeline already sorted these ids (SortedSparseColumn "
                    "contract) and this scatter re-pays the sort on every "
                    "step",
                    location=location,
                    fix_hint="pass indices_are_sorted=True to segment_sum "
                             "(read the column's indices_are_sorted "
                             "attribute instead of hardcoding False)",
                ))
        elif name in ORDER_PRESERVING:
            if any(_is_var(v) and v in sorted_vars for v in eqn.invars):
                sorted_vars.update(eqn.outvars)
        elif name in _CALL_PRIMITIVES and depth < 1:
            for sub in _subjaxprs(eqn.params):
                inner_sorted = {
                    iv for iv, ov in zip(sub.invars, eqn.invars)
                    if _is_var(ov) and ov in sorted_vars
                }
                # Approximation: invars of pjit map positionally onto
                # the sub-jaxpr's invars (true for the wrappers we
                # recurse; consts ride constvars).
                _walk(sub, inner_sorted | sorted_vars, location,
                      findings, depth + 1)


def check_sorted_scatter_jaxpr(closed_jaxpr, sorted_argnums: Sequence[int],
                               location: Optional[str] = None
                               ) -> List[Finding]:
    """FML404 findings for a closed jaxpr whose inputs at
    ``sorted_argnums`` carry the pack-time sorted guarantee."""
    jaxpr = closed_jaxpr.jaxpr
    sorted_vars = {jaxpr.invars[i] for i in sorted_argnums
                   if i < len(jaxpr.invars)}
    findings: List[Finding] = []
    _walk(jaxpr, sorted_vars, location, findings)
    return findings


def check_sorted_scatter_fn(fn, args, sorted_argnums: Sequence[int],
                            location: Optional[str] = None
                            ) -> List[Finding]:
    """Trace ``fn(*args)`` (abstract, device-free) and run the FML404
    walk with the arguments at ``sorted_argnums`` declared as sorted-
    provenance inputs (a SortedSparseColumn's ``segment_ids``, a
    pack-time ``ell_sort_tables`` output, ...)."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    return check_sorted_scatter_jaxpr(closed, sorted_argnums, location)


def _probe_program(program: dict):
    """Build the declarative probe named by ``program`` — a tiny traced
    function plus its abstract args and which argnum is the ids input.

    ``segment_sum``: the gradient-scatter shape itself.
    ``gathered_segment_sum``: the SortedSparseColumn consumer shape —
    ``segment_sum(take(contrib, perm), segment_ids, ...)`` (the gather
    permutes VALUES, not ids; the ids input keeps its provenance).
    """
    import jax.numpy as jnp

    name = program.get("name", "segment_sum")
    cells = int(program.get("cells", 64))
    num_segments = int(program.get("num_segments", 16))
    flag = bool(program.get("indices_are_sorted", False))
    vals = jnp.zeros(cells, jnp.float32)
    ids = jnp.zeros(cells, jnp.int32)
    if name == "segment_sum":
        import jax

        def fn(v, i):
            return jax.ops.segment_sum(v, i, num_segments=num_segments,
                                       indices_are_sorted=flag)

        return fn, (vals, ids), 1
    if name == "gathered_segment_sum":
        import jax

        perm = jnp.zeros(cells, jnp.int32)

        def fn(v, p, i):
            return jax.ops.segment_sum(jnp.take(v, p), i,
                                       num_segments=num_segments,
                                       indices_are_sorted=flag)

        return fn, (vals, perm, ids), 2
    raise ValueError(f"unknown scatter probe program {name!r} "
                     "(known: segment_sum, gathered_segment_sum)")


def check_scatter_file(path: str) -> List[Finding]:
    """Validate a ``*.scatter.json`` probe (schema in the module
    docstring). Unreadable or malformed files report one FML404
    finding naming the path — the gate must fail loudly, not skip
    silently."""
    try:
        with open(path, "r") as fh:
            doc = json.load(fh)
        program = doc["program"]
        sorted_guarantee = bool(doc.get("sorted_guarantee", False))
        fn, args, ids_argnum = _probe_program(program)
    except (OSError, ValueError, KeyError, TypeError) as e:
        return [Finding(
            "FML404",
            f"sorted-scatter file {path} is unreadable or malformed: "
            f"{e!r}",
            location=path,
            fix_hint="see flinkml_tpu/analysis/sorted_scatter.py for "
                     "the *.scatter.json schema",
        )]
    sorted_argnums = (ids_argnum,) if sorted_guarantee else ()
    return check_sorted_scatter_fn(fn, args, sorted_argnums, location=path)
