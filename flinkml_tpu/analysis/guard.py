"""Pass 3 — transfer/retrace guard for hot loops.

The fused executor's performance contract is: after warmup, a hot
``transform`` loop costs **zero** compiles (the shape-bucketed cache
serves every row count in a bucket) and no surprise host↔device traffic.
Nothing enforced that contract at runtime — a fingerprint regression or a
stage silently falling back to the host path would only show up as a
latency cliff in production.

:class:`TransferRetraceGuard` instruments the region it wraps:

  - **compiles** (FML402): every fused-cache compile inside the region is
    checked against the bucket policy. A compile whose chain (cache key
    minus the bucket component) was already compiled — before or inside
    the region — is a legitimate *new-bucket* compile and is allowed by
    default (``allow_new_buckets``). Any other compile counts against
    ``allow_compiles`` (default 0: warm up before entering the guard).
  - **cache aliasing** (FML403): two in-region compiles with identical
    input specs and bucket but different chain fingerprints indicate an
    unstable fingerprint churning the cache.
  - **transfers** (FML401): deltas of the ``pipeline.fusion``
    host→device counters and the ``table`` device→host materialization
    counters, checked against declared budgets (``None`` = unchecked).

Use as a context manager (raises :class:`GuardViolation` listing the
findings) or with ``raise_on_violation=False`` and read ``.findings``.
The pytest marker ``@pytest.mark.no_retrace`` (see ``tests/conftest.py``)
wraps a test in this guard.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from flinkml_tpu.analysis.findings import Finding


class GuardViolation(AssertionError):
    """Raised when a guarded region breaks its transfer/retrace budget."""

    def __init__(self, findings: List[Finding]):
        self.findings = list(findings)
        super().__init__(
            "transfer/retrace guard violated:\n"
            + "\n".join(f.render() for f in self.findings)
        )


def _chain_identity(key: Tuple) -> Tuple:
    """A fused-cache key minus its row bucket (index 4 of the layout
    ``(chain fp, ext specs, const specs, out names, bucket, policy,
    kernel backend)``): the identity under which a compile at a NEW
    bucket is policy-allowed. The precision policy AND the kernel
    backend STAY in the identity — flipping either compiles a genuinely
    different program."""
    return key[:4] + key[5:]


def _counters(group: str) -> Dict[str, float]:
    from flinkml_tpu.utils.metrics import metrics

    return dict(metrics.group(group).snapshot()["counters"])


class TransferRetraceGuard:
    """Budget-checked instrumentation of a fused-execution region."""

    def __init__(
        self,
        allow_compiles: int = 0,
        allow_new_buckets: bool = True,
        allow_host_to_device: Optional[int] = None,
        allow_device_to_host: Optional[int] = None,
        raise_on_violation: bool = True,
        location: Optional[str] = None,
    ):
        self.allow_compiles = int(allow_compiles)
        self.allow_new_buckets = bool(allow_new_buckets)
        self.allow_host_to_device = allow_host_to_device
        self.allow_device_to_host = allow_device_to_host
        self.raise_on_violation = bool(raise_on_violation)
        self.location = location
        self.findings: List[Finding] = []
        self._compiled_keys: List[Tuple] = []

    # -- region lifecycle --------------------------------------------------
    def __enter__(self) -> "TransferRetraceGuard":
        from flinkml_tpu import pipeline_fusion

        self._fusion_before = _counters("pipeline.fusion")
        self._table_before = _counters("table")
        # Chains already compiled before the region: compiles for these at
        # NEW buckets are policy-allowed, not retraces.
        with pipeline_fusion._LOCK:
            self._known_chains = {
                _chain_identity(k) for k in pipeline_fusion._CACHE
                if "__specs__" not in k
            }
        self._compiled_keys = []
        pipeline_fusion.on_compile.append(self._compiled_keys.append)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        from flinkml_tpu import pipeline_fusion

        try:
            pipeline_fusion.on_compile.remove(self._compiled_keys.append)
        except ValueError:
            # A test hook reset on_compile inside the region; fine.
            pass
        self.findings = self._evaluate()
        if exc_type is None and self.findings and self.raise_on_violation:
            raise GuardViolation(self.findings)
        return False

    # -- evaluation --------------------------------------------------------
    def _evaluate(self) -> List[Finding]:
        findings: List[Finding] = []

        # Compile policy. Key layout (pipeline_fusion._run_program):
        # (chain fingerprint, ext specs, const specs, out names, bucket,
        # precision policy, kernel backend).
        counted = 0
        seen_chains = set(self._known_chains)
        # Fingerprint-churn detection: keyed by everything EXCEPT the
        # chain fingerprint. Two legitimately different chains almost
        # always differ in const specs or output names too; an unstable
        # fingerprint differs ONLY in the fingerprint, every call —
        # requiring 3+ distinct fingerprints keeps a deliberate pair of
        # alternative chains (budgeted via allow_compiles) unflagged.
        by_shape: Dict[Tuple, set] = {}
        for key in self._compiled_keys:
            chain_fp, ext_specs, consts, outs, bucket, policy, backend = key
            by_shape.setdefault(
                (ext_specs, consts, outs, bucket, policy, backend), set()
            ).add(chain_fp)
        for (_ext, _consts, _outs, bucket, _pol, _be), fps in \
                by_shape.items():
            if len(fps) >= 3:
                findings.append(Finding(
                    "FML403",
                    f"{len(fps)} compiles share input/constant specs, "
                    f"outputs, and bucket {bucket} but differ only in "
                    "chain fingerprint — an unstable fingerprint is "
                    "churning the compile cache",
                    location=self.location,
                    fix_hint="make transform_kernel fingerprints a pure "
                             "function of stage config",
                ))
        for key in self._compiled_keys:
            chain = _chain_identity(key)
            # The identity is bucket-independent (but policy-INCLUSIVE:
            # a policy flip is a genuinely new program), so a chain seen
            # at ANY bucket (pre-region cache or earlier in-region
            # compile) makes this a new-bucket compile of a known chain.
            if chain in seen_chains:
                if not self.allow_new_buckets:
                    counted += 1
            else:
                counted += 1
                seen_chains.add(chain)
        if counted > self.allow_compiles:
            findings.append(Finding(
                "FML402",
                f"{counted} compile(s) of new chains in a guarded region "
                f"(budget {self.allow_compiles}) — a hot loop retraced "
                "beyond the declared bucket policy",
                location=self.location,
                fix_hint="warm the chain up before the guarded region, or "
                         "raise allow_compiles if new chains are expected",
            ))

        fusion_after = _counters("pipeline.fusion")
        table_after = _counters("table")

        def delta(before, after, key):
            return after.get(key, 0.0) - before.get(key, 0.0)

        if self.allow_host_to_device is not None:
            h2d = delta(self._fusion_before, fusion_after,
                        "host_to_device_transfers")
            if h2d > self.allow_host_to_device:
                findings.append(Finding(
                    "FML401",
                    f"{int(h2d)} host->device transfer(s) in a guarded "
                    f"region (budget {self.allow_host_to_device})",
                    location=self.location,
                    fix_hint="keep hot-loop inputs device-resident "
                             "(reuse the same Table; fused outputs stay "
                             "on device)",
                ))
        if self.allow_device_to_host is not None:
            d2h = delta(self._table_before, table_after,
                        "device_to_host_materializations")
            if d2h > self.allow_device_to_host:
                findings.append(Finding(
                    "FML401",
                    f"{int(d2h)} device->host materialization(s) in a "
                    f"guarded region (budget {self.allow_device_to_host})",
                    location=self.location,
                    fix_hint="an intermediate is being read back to host "
                             "inside the loop — read results once outside, "
                             "or budget the reads explicitly",
                ))
        return findings


def transfer_retrace_guard(**kwargs) -> TransferRetraceGuard:
    """Convenience alias: ``with transfer_retrace_guard(...):``."""
    return TransferRetraceGuard(**kwargs)
