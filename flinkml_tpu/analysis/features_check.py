"""FML505 — hash front end vs embedding table sizing (pre-compile).

A hashed feature front end and the embedding table it feeds share one
integer: ``num_buckets`` IS the table's vocab row count. When they
drift — a retuned hash space without a resized table, or vice versa —
the failure is silent and data-dependent: ids beyond ``vocab`` corrupt
the lookup (or crash only on the first unlucky key), and ids *under* it
quietly strand rows that can never be addressed. So the mismatch is
priced as a plan-band ERROR and refused before anything compiles, the
same shape as the FML501–504 layout gates.

Config shape (``*.features.json``, the fixture/CI gate format)::

    {"hash":  {"seed": 42, "numBuckets": 4096},
     "table": {"vocab": 4096, "dim": 16}}

``tables`` (a list) is accepted for multi-table fronts; every table must
match the hash space. The live half of the gate is
:func:`flinkml_tpu.features.hashing.check_hash_vocab`, which model
constructors call with the same FML505 message.
"""

from __future__ import annotations

import json
from typing import List

from flinkml_tpu.analysis.findings import Finding

_HINT = ("size the embedding table's vocab to exactly the hash space "
         "(vocab = num_buckets); see docs/operators/features.md")


def check_features_file(path: str) -> List[Finding]:
    """Validate one ``*.features.json`` config. Unreadable or malformed
    files report one FML505 finding naming the path — the gate must
    fail loudly, not skip silently."""
    try:
        with open(path, "r") as fh:
            doc = json.load(fh)
        hash_cfg = doc["hash"]
        num_buckets = int(hash_cfg["numBuckets"])
        raw_tables = doc.get("tables")
        if raw_tables is None:
            raw_tables = [doc["table"]] if "table" in doc else []
        tables = [(str(t.get("name", f"table[{i}]")), int(t["vocab"]))
                  for i, t in enumerate(raw_tables)]
    except (OSError, ValueError, KeyError, TypeError) as e:
        return [Finding(
            "FML505",
            f"features file {path} is unreadable or malformed: {e!r}",
            location=path,
            fix_hint="see flinkml_tpu/analysis/features_check.py for the "
                     "*.features.json schema",
        )]
    findings: List[Finding] = []
    if num_buckets < 1:
        findings.append(Finding(
            "FML505",
            f"hash front end declares num_buckets={num_buckets} (< 1)",
            location=path, fix_hint=_HINT,
        ))
    if not tables:
        findings.append(Finding(
            "FML505",
            "features file names a hash front end but no embedding "
            "table to check it against",
            location=path, fix_hint=_HINT,
        ))
    for name, vocab in tables:
        if vocab != num_buckets:
            findings.append(Finding(
                "FML505",
                f"hash num_buckets={num_buckets} != embedding table "
                f"{name!r} vocab={vocab}: hashed ids would "
                f"{'overrun' if num_buckets > vocab else 'strand'} "
                f"{abs(num_buckets - vocab)} rows",
                location=path, stage=name, fix_hint=_HINT,
            ))
    return findings
