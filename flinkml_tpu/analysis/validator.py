"""Pass 1 — ahead-of-time pipeline/graph validation.

Validates :class:`~flinkml_tpu.pipeline.Pipeline` /
:class:`~flinkml_tpu.pipeline.PipelineModel` stage chains and
:class:`~flinkml_tpu.graph.Graph` DAGs **before** any device dispatch:

  - schema flow: every column a stage reads must exist in its input
    schema (FML101), reads of columns only a later stage produces are
    ordering errors (FML107), and outputs that overwrite existing
    columns are flagged (FML102);
  - kernel abstract evaluation: kernel-capable stages are traced with
    ``jax.eval_shape`` over :class:`ColumnSpec`s — shape/dtype
    mismatches between stages surface as FML103 without touching a
    device, and the resulting output specs feed the next stage's check;
  - fusion topology: a non-kernel stage sandwiched between kernel-capable
    neighbours splits one fused program into two (FML104);
  - kernel contract: ``transform_kernel`` must return a stable, hashable
    fingerprint across calls (FML105 — an unstable fingerprint defeats
    the fused compile cache, retracing on every transform);
  - dtype hygiene: an output column wider than every input it was
    computed from is a silent float64 promotion (FML106).

Everything here is abstract — ``jax.eval_shape`` never allocates a
buffer, so validation runs identically under ``JAX_PLATFORMS=cpu`` on a
machine with no accelerator.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flinkml_tpu.analysis.findings import Finding, Report

#: Abstract-eval row count. Any value works (shapes are row-polymorphic in
#: the validator's eyes); 8 matches the executor's MIN_ROW_BUCKET.
EVAL_ROWS = 8


@dataclasses.dataclass(frozen=True)
class ColumnSpec:
    """Abstract column type: dtype + trailing (per-row) shape.

    ``dtype None`` means unknown — produced by stages the validator cannot
    abstract-evaluate; checks that need the spec are skipped rather than
    guessed.
    """

    dtype: Optional[np.dtype] = None
    tail: Optional[Tuple[int, ...]] = None

    @property
    def known(self) -> bool:
        # Object (ragged/row-wise Vector) columns have a dtype but no
        # abstract-evaluable type: the runtime fuser skips them per-table
        # (``_dense_in_table``), so the validator must not feed them to
        # jax.eval_shape either.
        return (self.dtype is not None and self.tail is not None
                and self.dtype.kind != "O")


UNKNOWN = ColumnSpec()

#: TableSchema: column name -> ColumnSpec.
TableSchema = Dict[str, ColumnSpec]


def schema_of(table) -> TableSchema:
    """The :class:`ColumnSpec` schema of a live Table (device columns
    included — no materialization happens)."""
    out: TableSchema = {}
    for name in table.column_names:
        col = table._raw_column(name)
        out[name] = ColumnSpec(np.dtype(col.dtype), tuple(col.shape[1:]))
    return out


# ---------------------------------------------------------------------------
# Stage I/O introspection (param-based; works on any WithParams stage)
# ---------------------------------------------------------------------------

_INPUT_COL_PARAMS = {"inputCol", "featuresCol", "labelCol", "weightCol"}
_INPUT_COLS_PARAMS = {"inputCols"}
_OUTPUT_COL_PARAMS = {"outputCol", "predictionCol", "rawPredictionCol"}
_OUTPUT_COLS_PARAMS = {"outputCols"}


@dataclasses.dataclass(frozen=True)
class StageIO:
    """Columns a stage reads/writes, derived from its Has*Col params.

    ``opaque``: the stage declares no recognized column params — its
    reads/writes are unknowable, so schema tracking goes open after it.
    ``resets``: the stage replaces the table wholesale (evaluators emit a
    metrics table) — downstream schema is unknown.
    """

    inputs: Tuple[str, ...]
    outputs: Tuple[str, ...]
    opaque: bool = False
    resets: bool = False


def stage_io(stage) -> StageIO:
    """Derive :class:`StageIO` from a stage's params.

    Evaluator-family stages (class name contains ``Evaluator``) consume
    their prediction/rawPrediction columns rather than producing them, and
    replace the table with a metrics table.
    """
    is_eval = "Evaluator" in type(stage).__name__
    inputs: List[str] = []
    outputs: List[str] = []
    recognized = False
    try:
        params = type(stage).params()
    except Exception:
        return StageIO((), (), opaque=True)
    for p in params:
        name = getattr(p, "name", None)
        try:
            v = stage.get(p)
        except Exception:
            continue
        if v is None:
            continue
        if name in _INPUT_COL_PARAMS:
            inputs.append(v)
            recognized = True
        elif name in _INPUT_COLS_PARAMS:
            inputs.extend(v)
            recognized = True
        elif name in _OUTPUT_COL_PARAMS or name in _OUTPUT_COLS_PARAMS:
            vals = list(v) if name in _OUTPUT_COLS_PARAMS else [v]
            (inputs if is_eval else outputs).extend(vals)
            recognized = True
    return StageIO(
        tuple(dict.fromkeys(inputs)),
        tuple(dict.fromkeys(outputs)),
        opaque=not recognized,
        resets=is_eval,
    )


# ---------------------------------------------------------------------------
# Kernel abstract evaluation
# ---------------------------------------------------------------------------

def kernel_output_specs(kernel, schema: TableSchema,
                        rows: int = EVAL_ROWS) -> TableSchema:
    """Abstract-evaluate a :class:`ColumnKernel` over ``schema`` via
    ``jax.eval_shape`` (no device, no compile) in the fused executor's
    trace context (x64 enabled, float32 validity mask). Raises whatever
    the kernel's math raises on incompatible shapes/dtypes."""
    import jax

    cols = {}
    for c in kernel.input_cols:
        spec = schema[c]
        cols[c] = jax.ShapeDtypeStruct((rows,) + spec.tail, spec.dtype)
    consts = {
        k: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype)
        for k, v in kernel.constants.items()
    }
    valid = jax.ShapeDtypeStruct((rows,), np.float32)
    with jax.experimental.enable_x64(True):
        out = jax.eval_shape(kernel.fn, cols, consts, valid)
    return {
        name: ColumnSpec(np.dtype(s.dtype), tuple(s.shape[1:]))
        for name, s in out.items()
    }


def _stable_kernel(stage):
    """Fetch a stage's kernel twice; returns ``(kernel, finding_or_None)``
    covering the FML105 contract (equal, hashable fingerprints)."""
    label = type(stage).__name__
    try:
        k1 = stage.transform_kernel()
        k2 = stage.transform_kernel()
    except Exception as e:  # a raising gate is itself a contract breach
        return None, Finding(
            "FML105", f"transform_kernel raised: {e!r}", stage=label,
            fix_hint="gate unfusable configurations by returning None, "
                     "not by raising",
        )
    if k1 is None:
        return None, None
    try:
        hash(k1.fingerprint)
    except TypeError:
        return k1, Finding(
            "FML105",
            f"kernel fingerprint {k1.fingerprint!r} is unhashable",
            stage=label,
            fix_hint="fingerprints must be hashable tuples of static "
                     "config (they key the fused compile cache)",
        )
    if k2 is not None and k1.fingerprint != k2.fingerprint:
        return k1, Finding(
            "FML105",
            "fingerprint differs between two transform_kernel() calls "
            f"({k1.fingerprint!r} != {k2.fingerprint!r})",
            stage=label,
            fix_hint="derive the fingerprint from stage config only — an "
                     "unstable fingerprint retraces the fused program on "
                     "every transform",
        )
    return k1, None


_WIDE_FLOATS = (np.dtype(np.float64),)


def _promotion_findings(stage_label, in_specs, out_specs) -> List[Finding]:
    """FML106: every known input is a narrow float but an output came back
    float64 — the widening happened inside the stage, silently."""
    known_in = [s.dtype for s in in_specs if s.known]
    if not known_in or any(d.kind != "f" or d.itemsize >= 8 for d in known_in):
        return []
    out: List[Finding] = []
    for name, spec in out_specs.items():
        if spec.known and spec.dtype in _WIDE_FLOATS:
            out.append(Finding(
                "FML106",
                f"inputs are {', '.join(str(d) for d in known_in)} but "
                f"output {name!r} is float64 (silent promotion)",
                stage=stage_label, column=name,
                fix_hint="cast explicitly or preserve the input dtype; "
                         "float64 on the CPU fallback path doubles "
                         "bandwidth and memory",
            ))
    return out


# ---------------------------------------------------------------------------
# Pipeline chain validation
# ---------------------------------------------------------------------------

def analyze_pipeline(pipeline, schema: Optional[TableSchema] = None,
                     location: Optional[str] = None) -> Report:
    """Validate a Pipeline / PipelineModel / stage sequence against an
    input :data:`TableSchema` (``schema_of(table)``), or against an *open*
    schema (``None`` — any column may pre-exist; only ordering and
    collision checks apply)."""
    from flinkml_tpu.api import AlgoOperator

    stages = list(getattr(pipeline, "stages", pipeline))
    report = Report()
    closed = schema is not None
    current: TableSchema = dict(schema) if schema else {}
    external: set = set(current)
    produced_at: Dict[str, int] = {}
    pending_reads: List[Tuple[int, str, str]] = []  # (stage idx, label, col)
    kernel_capable: List[bool] = []

    for i, stage in enumerate(stages):
        label = f"[{i}] {type(stage).__name__}"
        kernel = None
        if isinstance(stage, AlgoOperator):
            kernel, f = _stable_kernel(stage)
            if f is not None:
                report.add(dataclasses.replace(f, stage=label,
                                               location=location))
        kernel_capable.append(kernel is not None)

        io = None
        if kernel is not None:
            reads, writes = kernel.input_cols, kernel.output_cols
        else:
            io = stage_io(stage)
            reads, writes = io.inputs, io.outputs

        # -- reads ---------------------------------------------------------
        for c in reads:
            if c in current:
                continue
            if closed:
                report.add(Finding(
                    "FML101",
                    f"reads column {c!r} which is not in the schema "
                    f"(available: {sorted(current)})",
                    stage=label, column=c, location=location,
                    fix_hint="rename the column param or add an upstream "
                             "stage producing it",
                ))
            else:
                # Open schema: assume external unless a later stage turns
                # out to be the producer (FML107, resolved after the walk).
                pending_reads.append((i, label, c))
                external.add(c)
                current[c] = UNKNOWN

        # -- writes / collisions -------------------------------------------
        for c in writes:
            if c in current:
                if c in reads:
                    msg = f"overwrites its own input column {c!r} in place"
                    hint = ("in-place overwrite loses the pre-stage values "
                            "for every later stage; use a distinct output "
                            "column name")
                elif c in external:
                    msg = (f"output column {c!r} silently overwrites a "
                           "source-data column")
                    hint = "pick an output column name absent from the input"
                else:
                    prev = produced_at.get(c)
                    msg = (f"output column {c!r} collides with the output "
                           f"of stage {prev}" if prev is not None else
                           f"output column {c!r} overwrites an existing column")
                    hint = "give each stage a distinct output column name"
                report.add(Finding("FML102", msg, stage=label, column=c,
                                   location=location, fix_hint=hint))
            produced_at[c] = i

        # -- abstract evaluation / schema update ---------------------------
        in_specs = [current.get(c, UNKNOWN) for c in reads]
        if kernel is not None and all(s.known for s in in_specs):
            try:
                out_specs = kernel_output_specs(kernel, current)
            except Exception as e:
                report.add(Finding(
                    "FML103",
                    f"kernel abstract evaluation failed: {e}",
                    stage=label, location=location,
                    fix_hint="the stage's kernel cannot consume the "
                             "upstream schema — fix the column shapes/"
                             "dtypes or the stage wiring",
                ))
                out_specs = {c: UNKNOWN for c in writes}
            else:
                for f in _promotion_findings(label, in_specs, out_specs):
                    report.add(dataclasses.replace(f, location=location))
            current.update(out_specs)
        else:
            if kernel is None:
                # A kernel-capable stage's writes are exact (from the
                # kernel) even when specs are unknown; only kernel-less
                # stages can reset or open the schema.
                if io.resets:
                    # Evaluator: the output table is a fresh metrics table.
                    current = {}
                    external = set()
                    closed = False
                elif io.opaque:
                    # Unknown stage: it may add/drop anything.
                    closed = False
            for c in writes:
                current[c] = UNKNOWN

    # FML107: open-schema reads whose producer turned out to be later.
    for idx, label, c in pending_reads:
        j = produced_at.get(c)
        if j is not None and j > idx:
            report.add(Finding(
                "FML107",
                f"reads column {c!r} which only stage {j} produces "
                "(stage ordering error)",
                stage=label, column=c, location=location,
                fix_hint="reorder the stages so producers precede consumers",
            ))

    # FML104: a non-kernel stage strictly between kernel-capable stages.
    stages_list = list(stages)
    for i in range(1, len(kernel_capable) - 1):
        if (not kernel_capable[i]) and kernel_capable[i - 1] \
                and kernel_capable[i + 1]:
            report.add(Finding(
                "FML104",
                "non-fusable stage splits a kernel chain into two fused "
                "programs (extra dispatch + device round-trip)",
                stage=f"[{i}] {type(stages_list[i]).__name__}",
                location=location,
                fix_hint="implement transform_kernel for this stage or "
                         "move it to the edge of the chain",
            ))
    return report


# ---------------------------------------------------------------------------
# Graph wiring validation
# ---------------------------------------------------------------------------

def analyze_graph(graph, location: Optional[str] = None) -> Report:
    """Static executability of a Graph / GraphModel DAG: every node's
    inputs must be producible (FML201), graph outputs must be produced
    (FML202), and no two nodes may claim one output id (FML203) — the
    checks ``_execute_nodes`` performs at runtime, moved to build time."""
    report = Report()
    nodes = list(graph._nodes)

    if hasattr(graph, "_estimator_input_ids"):  # Graph (estimator)
        given = set(t.id for t in graph._estimator_input_ids)
        given |= set(t.id for t in graph._model_input_ids)
    else:  # GraphModel
        given = set(t.id for t in graph._input_ids)
    if getattr(graph, "_input_model_data_ids", None):
        given |= set(t.id for t in graph._input_model_data_ids)

    claimed: Dict[int, int] = {}
    for node in nodes:
        out_ids = [t.id for t in node.output_ids]
        if node.output_model_data_ids:
            out_ids += [t.id for t in node.output_model_data_ids]
        for tid in out_ids:
            if tid in claimed and claimed[tid] != node.node_id:
                report.add(Finding(
                    "FML203",
                    f"TableId({tid}) is claimed by nodes "
                    f"{claimed[tid]} and {node.node_id}",
                    stage=f"node {node.node_id}", location=location,
                    fix_hint="every output TableId must have exactly one "
                             "producing node",
                ))
            claimed.setdefault(tid, node.node_id)

    # Fixed-point readiness — the static analog of runtime execution.
    available = set(given)
    pending = list(nodes)
    progress = True
    while progress:
        progress = False
        for node in list(pending):
            if all(t.id in available for t in node.all_input_ids()):
                pending.remove(node)
                available.update(t.id for t in node.output_ids)
                if node.output_model_data_ids:
                    available.update(
                        t.id for t in node.output_model_data_ids
                    )
                progress = True
    for node in pending:
        missing = [t.id for t in node.all_input_ids()
                   if t.id not in available]
        report.add(Finding(
            "FML201",
            f"node {node.node_id} "
            f"({type(node.stage).__name__ if node.stage else '?'}) waits "
            f"on TableId(s) {missing} which no node produces "
            "(cycle or missing input table)",
            stage=f"node {node.node_id}", location=location,
            fix_hint="wire the missing TableIds to a producing stage or "
                     "to the graph inputs",
        ))

    out_ids = getattr(graph, "_output_ids", [])
    for t in out_ids:
        if t.id not in available:
            report.add(Finding(
                "FML202",
                f"graph output TableId({t.id}) is never produced",
                location=location,
                fix_hint="graph outputs must be outputs of some node (or "
                         "graph inputs)",
            ))
    return report
