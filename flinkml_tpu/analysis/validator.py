"""Pass 1 — ahead-of-time pipeline/graph validation.

Validates :class:`~flinkml_tpu.pipeline.Pipeline` /
:class:`~flinkml_tpu.pipeline.PipelineModel` stage chains and
:class:`~flinkml_tpu.graph.Graph` DAGs **before** any device dispatch:

  - schema flow: every column a stage reads must exist in its input
    schema (FML101), reads of columns only a later stage produces are
    ordering errors (FML107), and outputs that overwrite existing
    columns are flagged (FML102);
  - kernel abstract evaluation: kernel-capable stages are traced with
    ``jax.eval_shape`` over :class:`ColumnSpec`s — shape/dtype
    mismatches between stages surface as FML103 without touching a
    device, and the resulting output specs feed the next stage's check;
  - fusion topology: a non-kernel stage sandwiched between kernel-capable
    neighbours splits one fused program into two (FML104);
  - kernel contract: ``transform_kernel`` must return a stable, hashable
    fingerprint across calls (FML105 — an unstable fingerprint defeats
    the fused compile cache, retracing on every transform);
  - dtype hygiene: an output column wider than every input it was
    computed from is a silent float64 promotion (FML106).

Everything here is abstract — ``jax.eval_shape`` never allocates a
buffer, so validation runs identically under ``JAX_PLATFORMS=cpu`` on a
machine with no accelerator.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flinkml_tpu.analysis.findings import Finding, Report

#: Abstract-eval row count. Any value works (shapes are row-polymorphic in
#: the validator's eyes); 8 matches the executor's MIN_ROW_BUCKET.
EVAL_ROWS = 8


@dataclasses.dataclass(frozen=True)
class ColumnSpec:
    """Abstract column type: dtype + trailing (per-row) shape.

    ``dtype None`` means unknown — produced by stages the validator cannot
    abstract-evaluate; checks that need the spec are skipped rather than
    guessed.
    """

    dtype: Optional[np.dtype] = None
    tail: Optional[Tuple[int, ...]] = None

    @property
    def known(self) -> bool:
        # Object (ragged/row-wise Vector) columns have a dtype but no
        # abstract-evaluable type: the runtime fuser skips them per-table
        # (``_dense_in_table``), so the validator must not feed them to
        # jax.eval_shape either.
        return (self.dtype is not None and self.tail is not None
                and self.dtype.kind != "O")


UNKNOWN = ColumnSpec()

#: TableSchema: column name -> ColumnSpec.
TableSchema = Dict[str, ColumnSpec]


def schema_of(table) -> TableSchema:
    """The :class:`ColumnSpec` schema of a live Table (device columns
    included — no materialization happens)."""
    out: TableSchema = {}
    for name in table.column_names:
        col = table._raw_column(name)
        out[name] = ColumnSpec(np.dtype(col.dtype), tuple(col.shape[1:]))
    return out


# ---------------------------------------------------------------------------
# Stage I/O introspection (param-based; works on any WithParams stage)
# ---------------------------------------------------------------------------

_INPUT_COL_PARAMS = {"inputCol", "featuresCol", "labelCol", "weightCol"}
_INPUT_COLS_PARAMS = {"inputCols"}
_OUTPUT_COL_PARAMS = {"outputCol", "predictionCol", "rawPredictionCol"}
_OUTPUT_COLS_PARAMS = {"outputCols"}


@dataclasses.dataclass(frozen=True)
class StageIO:
    """Columns a stage reads/writes, derived from its Has*Col params.

    ``opaque``: the stage declares no recognized column params — its
    reads/writes are unknowable, so schema tracking goes open after it.
    ``resets``: the stage replaces the table wholesale (evaluators emit a
    metrics table) — downstream schema is unknown.
    """

    inputs: Tuple[str, ...]
    outputs: Tuple[str, ...]
    opaque: bool = False
    resets: bool = False


def stage_io(stage) -> StageIO:
    """Derive :class:`StageIO` from a stage's params.

    Evaluator-family stages (class name contains ``Evaluator``) consume
    their prediction/rawPrediction columns rather than producing them, and
    replace the table with a metrics table.
    """
    is_eval = "Evaluator" in type(stage).__name__
    inputs: List[str] = []
    outputs: List[str] = []
    recognized = False
    try:
        params = type(stage).params()
    except Exception:
        return StageIO((), (), opaque=True)
    for p in params:
        name = getattr(p, "name", None)
        try:
            v = stage.get(p)
        except Exception:
            continue
        if v is None:
            continue
        if name in _INPUT_COL_PARAMS:
            inputs.append(v)
            recognized = True
        elif name in _INPUT_COLS_PARAMS:
            inputs.extend(v)
            recognized = True
        elif name in _OUTPUT_COL_PARAMS or name in _OUTPUT_COLS_PARAMS:
            vals = list(v) if name in _OUTPUT_COLS_PARAMS else [v]
            (inputs if is_eval else outputs).extend(vals)
            recognized = True
    return StageIO(
        tuple(dict.fromkeys(inputs)),
        tuple(dict.fromkeys(outputs)),
        opaque=not recognized,
        resets=is_eval,
    )


# ---------------------------------------------------------------------------
# Kernel abstract evaluation
# ---------------------------------------------------------------------------

def kernel_output_specs(kernel, schema: TableSchema,
                        rows: int = EVAL_ROWS) -> TableSchema:
    """Abstract-evaluate a :class:`ColumnKernel` over ``schema`` via
    ``jax.eval_shape`` (no device, no compile) in the fused executor's
    trace context (x64 enabled, float32 validity mask). Raises whatever
    the kernel's math raises on incompatible shapes/dtypes."""
    import jax

    cols = {}
    for c in kernel.input_cols:
        spec = schema[c]
        cols[c] = jax.ShapeDtypeStruct((rows,) + spec.tail, spec.dtype)
    consts = {
        k: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype)
        for k, v in kernel.constants.items()
    }
    valid = jax.ShapeDtypeStruct((rows,), np.float32)
    with jax.experimental.enable_x64(True):
        out = jax.eval_shape(kernel.fn, cols, consts, valid)
    return {
        name: ColumnSpec(np.dtype(s.dtype), tuple(s.shape[1:]))
        for name, s in out.items()
    }


def _stable_kernel(stage):
    """Fetch a stage's kernel twice; returns ``(kernel, finding_or_None)``
    covering the FML105 contract (equal, hashable fingerprints)."""
    label = type(stage).__name__
    try:
        k1 = stage.transform_kernel()
        k2 = stage.transform_kernel()
    except Exception as e:  # a raising gate is itself a contract breach
        return None, Finding(
            "FML105", f"transform_kernel raised: {e!r}", stage=label,
            fix_hint="gate unfusable configurations by returning None, "
                     "not by raising",
        )
    if k1 is None:
        return None, None
    try:
        hash(k1.fingerprint)
    except TypeError:
        return k1, Finding(
            "FML105",
            f"kernel fingerprint {k1.fingerprint!r} is unhashable",
            stage=label,
            fix_hint="fingerprints must be hashable tuples of static "
                     "config (they key the fused compile cache)",
        )
    if k2 is not None and k1.fingerprint != k2.fingerprint:
        return k1, Finding(
            "FML105",
            "fingerprint differs between two transform_kernel() calls "
            f"({k1.fingerprint!r} != {k2.fingerprint!r})",
            stage=label,
            fix_hint="derive the fingerprint from stage config only — an "
                     "unstable fingerprint retraces the fused program on "
                     "every transform",
        )
    return k1, None


def _kernel_jaxpr(kernel, schema: TableSchema, rows: int = EVAL_ROWS):
    """The closed jaxpr of one kernel under the fused executor's trace
    context (x64, f32 mask) — what lets the shared FML106 path localize
    the widening primitive. None when the trace fails (the FML103 check
    already reported that)."""
    import jax

    try:
        cols = {
            c: jax.ShapeDtypeStruct((rows,) + schema[c].tail,
                                    schema[c].dtype)
            for c in kernel.input_cols
        }
        consts = {
            k: jax.ShapeDtypeStruct(np.asarray(v).shape,
                                    np.asarray(v).dtype)
            for k, v in kernel.constants.items()
        }
        valid = jax.ShapeDtypeStruct((rows,), np.float32)
        with jax.experimental.enable_x64(True):
            return jax.make_jaxpr(kernel.fn)(cols, consts, valid)
    except Exception:
        return None


def _promotion_findings(stage_label, in_specs, out_specs,
                        closed=None) -> List[Finding]:
    """FML106 — delegates to the ONE dtype-flow code path
    (:func:`flinkml_tpu.analysis.precision.promotion_findings`), which
    also serves the fused multi-stage check in :func:`analyze_pipeline`.
    ``closed`` (the kernel's jaxpr, or a lazy zero-arg thunk producing
    it, optional) localizes the widening primitive in the message."""
    from flinkml_tpu.analysis.precision import promotion_findings

    return promotion_findings(
        closed,
        [s.dtype if s.known else None for s in in_specs],
        {name: (s.dtype if s.known else None)
         for name, s in out_specs.items()},
        stage=stage_label,
    )


# ---------------------------------------------------------------------------
# Pipeline chain validation
# ---------------------------------------------------------------------------

def analyze_pipeline(pipeline, schema: Optional[TableSchema] = None,
                     location: Optional[str] = None) -> Report:
    """Validate a Pipeline / PipelineModel / stage sequence against an
    input :data:`TableSchema` (``schema_of(table)``), or against an *open*
    schema (``None`` — any column may pre-exist; only ordering and
    collision checks apply)."""
    from flinkml_tpu.api import AlgoOperator

    stages = list(getattr(pipeline, "stages", pipeline))
    report = Report()
    closed = schema is not None
    current: TableSchema = dict(schema) if schema else {}
    external: set = set(current)
    produced_at: Dict[str, int] = {}
    pending_reads: List[Tuple[int, str, str]] = []  # (stage idx, label, col)
    kernel_capable: List[bool] = []
    kernels: List = []                 # per-stage kernel (None = unfusable)
    schema_before: List[TableSchema] = []  # schema snapshot at each stage

    for i, stage in enumerate(stages):
        label = f"[{i}] {type(stage).__name__}"
        kernel = None
        if isinstance(stage, AlgoOperator):
            kernel, f = _stable_kernel(stage)
            if f is not None:
                report.add(dataclasses.replace(f, stage=label,
                                               location=location))
        kernel_capable.append(kernel is not None)
        kernels.append(kernel)
        schema_before.append(dict(current))

        io = None
        if kernel is not None:
            reads, writes = kernel.input_cols, kernel.output_cols
        else:
            io = stage_io(stage)
            reads, writes = io.inputs, io.outputs

        # -- reads ---------------------------------------------------------
        for c in reads:
            if c in current:
                continue
            if closed:
                report.add(Finding(
                    "FML101",
                    f"reads column {c!r} which is not in the schema "
                    f"(available: {sorted(current)})",
                    stage=label, column=c, location=location,
                    fix_hint="rename the column param or add an upstream "
                             "stage producing it",
                ))
            else:
                # Open schema: assume external unless a later stage turns
                # out to be the producer (FML107, resolved after the walk).
                pending_reads.append((i, label, c))
                external.add(c)
                current[c] = UNKNOWN

        # -- writes / collisions -------------------------------------------
        for c in writes:
            if c in current:
                if c in reads:
                    msg = f"overwrites its own input column {c!r} in place"
                    hint = ("in-place overwrite loses the pre-stage values "
                            "for every later stage; use a distinct output "
                            "column name")
                elif c in external:
                    msg = (f"output column {c!r} silently overwrites a "
                           "source-data column")
                    hint = "pick an output column name absent from the input"
                else:
                    prev = produced_at.get(c)
                    msg = (f"output column {c!r} collides with the output "
                           f"of stage {prev}" if prev is not None else
                           f"output column {c!r} overwrites an existing column")
                    hint = "give each stage a distinct output column name"
                report.add(Finding("FML102", msg, stage=label, column=c,
                                   location=location, fix_hint=hint))
            produced_at[c] = i

        # -- abstract evaluation / schema update ---------------------------
        in_specs = [current.get(c, UNKNOWN) for c in reads]
        if kernel is not None and all(s.known for s in in_specs):
            try:
                out_specs = kernel_output_specs(kernel, current)
            except Exception as e:
                report.add(Finding(
                    "FML103",
                    f"kernel abstract evaluation failed: {e}",
                    stage=label, location=location,
                    fix_hint="the stage's kernel cannot consume the "
                             "upstream schema — fix the column shapes/"
                             "dtypes or the stage wiring",
                ))
                out_specs = {c: UNKNOWN for c in writes}
            else:
                # The jaxpr thunk is LAZY: promotion_findings only traces
                # it when a finding is certain, so clean stages (the
                # common case) pay no localization trace.
                for f in _promotion_findings(
                    label, in_specs, out_specs,
                    closed=lambda k=kernel, s=dict(current):
                        _kernel_jaxpr(k, s),
                ):
                    report.add(dataclasses.replace(f, location=location))
            current.update(out_specs)
        else:
            if kernel is None:
                # A kernel-capable stage's writes are exact (from the
                # kernel) even when specs are unknown; only kernel-less
                # stages can reset or open the schema.
                if io.resets:
                    # Evaluator: the output table is a fresh metrics table.
                    current = {}
                    external = set()
                    closed = False
                elif io.opaque:
                    # Unknown stage: it may add/drop anything.
                    closed = False
            for c in writes:
                current[c] = UNKNOWN

    # FML107: open-schema reads whose producer turned out to be later.
    for idx, label, c in pending_reads:
        j = produced_at.get(c)
        if j is not None and j > idx:
            report.add(Finding(
                "FML107",
                f"reads column {c!r} which only stage {j} produces "
                "(stage ordering error)",
                stage=label, column=c, location=location,
                fix_hint="reorder the stages so producers precede consumers",
            ))

    # FML104: a non-kernel stage strictly between kernel-capable stages.
    stages_list = list(stages)
    for i in range(1, len(kernel_capable) - 1):
        if (not kernel_capable[i]) and kernel_capable[i - 1] \
                and kernel_capable[i + 1]:
            report.add(Finding(
                "FML104",
                "non-fusable stage splits a kernel chain into two fused "
                "programs (extra dispatch + device round-trip)",
                stage=f"[{i}] {type(stages_list[i]).__name__}",
                location=location,
                fix_hint="implement transform_kernel for this stage or "
                         "move it to the edge of the chain",
            ))

    # FML106 over the FUSED program: each maximal kernel run (>= 2
    # stages — what the executor actually compiles as one jaxpr) walks
    # through the shared dtype-flow path in analysis.precision, which
    # localizes the widening primitive; per-stage findings above came
    # through the SAME code path, so (column-keyed) dedupe keeps one
    # report. Catches widenings the per-stage abstract eval can see only
    # in the assembled program (cross-stage const promotion under the
    # executor's x64 trace).
    flagged = {f.column for f in report if f.rule == "FML106"}
    for start, end in _kernel_runs(kernel_capable):
        for f in _fused_promotion_findings(
            kernels[start:end], schema_before[start],
            f"fused[{start}..{end - 1}]",
        ):
            if f.column not in flagged:
                flagged.add(f.column)
                report.add(dataclasses.replace(f, location=location))
    return report


def _kernel_runs(kernel_capable: Sequence[bool]):
    """Maximal runs of >= 2 consecutive kernel-capable stages — the
    executor's fusion unit (``pipeline.py`` fuses exactly these)."""
    runs = []
    i = 0
    while i < len(kernel_capable):
        if not kernel_capable[i]:
            i += 1
            continue
        j = i
        while j < len(kernel_capable) and kernel_capable[j]:
            j += 1
        if j - i >= 2:
            runs.append((i, j))
        i = j
    return runs


def _fused_promotion_findings(run_kernels, schema: TableSchema,
                              label: str) -> List[Finding]:
    """Trace the run's REAL fused chain function (the executor's
    ``_chain_fn``) abstractly and run the shared FML106 dtype-flow check
    over the whole multi-stage program."""
    import jax

    from flinkml_tpu import pipeline_fusion
    from flinkml_tpu.analysis.precision import promotion_findings

    ext = pipeline_fusion.external_inputs(run_kernels)
    ext_specs = [schema.get(c, UNKNOWN) for c in ext]
    if not all(s.known for s in ext_specs):
        return []
    out_names = []
    for k in run_kernels:
        out_names.extend(c for c in k.output_cols if c not in out_names)
    try:
        chain = pipeline_fusion._chain_fn(
            run_kernels, ext, out_names, EVAL_ROWS
        )
        ext_vals = tuple(
            jax.ShapeDtypeStruct((EVAL_ROWS,) + s.tail, s.dtype)
            for s in ext_specs
        )
        const_vals = tuple(
            tuple(
                jax.ShapeDtypeStruct(np.asarray(k.constants[c]).shape,
                                     np.asarray(k.constants[c]).dtype)
                for c in sorted(k.constants)
            )
            for k in run_kernels
        )
        with jax.experimental.enable_x64(True):
            abstract = jax.eval_shape(
                chain, ext_vals, const_vals, np.int32(EVAL_ROWS)
            )
        out_dtypes = {name: v.dtype for name, v in abstract.items()}

        def closed():
            # Lazy: the localization jaxpr is only traced once a
            # finding is certain (promotion_findings' contract). A
            # trace failure degrades to an unlocalized message.
            try:
                with jax.experimental.enable_x64(True):
                    return jax.make_jaxpr(chain)(
                        ext_vals, const_vals, np.int32(EVAL_ROWS)
                    )
            except Exception:
                return None
    except Exception:
        # An untraceable chain already surfaced as FML103 per stage.
        return []
    return promotion_findings(
        closed, [s.dtype for s in ext_specs], out_dtypes, stage=label,
    )


# ---------------------------------------------------------------------------
# Graph wiring validation
# ---------------------------------------------------------------------------

def analyze_graph(graph, location: Optional[str] = None) -> Report:
    """Static executability of a Graph / GraphModel DAG: every node's
    inputs must be producible (FML201), graph outputs must be produced
    (FML202), and no two nodes may claim one output id (FML203) — the
    checks ``_execute_nodes`` performs at runtime, moved to build time."""
    report = Report()
    nodes = list(graph._nodes)

    if hasattr(graph, "_estimator_input_ids"):  # Graph (estimator)
        given = set(t.id for t in graph._estimator_input_ids)
        given |= set(t.id for t in graph._model_input_ids)
    else:  # GraphModel
        given = set(t.id for t in graph._input_ids)
    if getattr(graph, "_input_model_data_ids", None):
        given |= set(t.id for t in graph._input_model_data_ids)

    claimed: Dict[int, int] = {}
    for node in nodes:
        out_ids = [t.id for t in node.output_ids]
        if node.output_model_data_ids:
            out_ids += [t.id for t in node.output_model_data_ids]
        for tid in out_ids:
            if tid in claimed and claimed[tid] != node.node_id:
                report.add(Finding(
                    "FML203",
                    f"TableId({tid}) is claimed by nodes "
                    f"{claimed[tid]} and {node.node_id}",
                    stage=f"node {node.node_id}", location=location,
                    fix_hint="every output TableId must have exactly one "
                             "producing node",
                ))
            claimed.setdefault(tid, node.node_id)

    # Fixed-point readiness — the static analog of runtime execution.
    available = set(given)
    pending = list(nodes)
    progress = True
    while progress:
        progress = False
        for node in list(pending):
            if all(t.id in available for t in node.all_input_ids()):
                pending.remove(node)
                available.update(t.id for t in node.output_ids)
                if node.output_model_data_ids:
                    available.update(
                        t.id for t in node.output_model_data_ids
                    )
                progress = True
    for node in pending:
        missing = [t.id for t in node.all_input_ids()
                   if t.id not in available]
        report.add(Finding(
            "FML201",
            f"node {node.node_id} "
            f"({type(node.stage).__name__ if node.stage else '?'}) waits "
            f"on TableId(s) {missing} which no node produces "
            "(cycle or missing input table)",
            stage=f"node {node.node_id}", location=location,
            fix_hint="wire the missing TableIds to a producing stage or "
                     "to the graph inputs",
        ))

    out_ids = getattr(graph, "_output_ids", [])
    for t in out_ids:
        if t.id not in available:
            report.add(Finding(
                "FML202",
                f"graph output TableId({t.id}) is never produced",
                location=location,
                fix_hint="graph outputs must be outputs of some node (or "
                         "graph inputs)",
            ))
    return report
