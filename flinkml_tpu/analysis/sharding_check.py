"""Pass 4 — sharding-plan validation (FML5xx), before any compile.

A :class:`~flinkml_tpu.sharding.plan.ShardingPlan` is a promise about
how a program will lay state out over a mesh; this pass checks the
promise device-free, against axis *sizes* alone, so a bad plan fails in
milliseconds with a rule id instead of minutes later inside XLA (or
worse, at the first cross-world restore):

  - **FML501** — the plan references a mesh axis that does not exist,
    or uses one illegally (the same axis twice in one parameter's
    spec — jax rejects duplicate PartitionSpec axes at compile time;
    we reject them at plan time).
  - **FML502** — a mesh axis (product) does not divide the parameter
    dimension it shards: the placement would be ragged.
  - **FML503** — a REPLICATED family whose parameter + optimizer-state
    bytes exceed the per-device HBM budget: the plan would OOM exactly
    where FSDP sharding is the fix.
  - **FML504** — two plans inside one program imply conflicting
    collective orders. Each plan's gradient-sync sequence is derived
    as ordered :class:`~flinkml_tpu.analysis.collectives.CollectiveOp`
    pseudo-programs (all-gather over the shard axes + reduce-scatter
    over the batch axes for sharded families; one psum for replicated
    ones) and the sequences are compared by the SAME machinery as the
    cross-rank FML301 checker (:func:`~flinkml_tpu.analysis.
    collectives.check_rank_order`) — a divergence that would deadlock
    ranks also deadlocks two plan-compiled programs sharing a dispatch.

Inputs come from live plan objects (``check_plan`` / ``check_program``)
or from ``*.plan.json`` fixtures (``check_plan_file`` — what the CLI
and the CI fixture gate consume). See ``docs/development/sharding.md``
for the rule catalog with examples and suppressions.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from flinkml_tpu.analysis.collectives import CollectiveOp, check_rank_order
from flinkml_tpu.analysis.findings import Finding


def _axis_sizes(mesh) -> Dict[str, int]:
    from flinkml_tpu.sharding.plan import _axis_sizes as impl

    return impl(mesh)


def _plan_params(plan, param_shapes: Optional[Mapping[str, Sequence[int]]]
                 ) -> List[Tuple[str, Optional[Tuple[int, ...]]]]:
    """The parameter universe to validate: the caller's shapes when
    given, else the plan's own family patterns (shape-free checks
    only)."""
    if param_shapes:
        return [(n, tuple(int(d) for d in s))
                for n, s in param_shapes.items()]
    return [(pattern, None) for pattern, _ in plan.rules]


def check_plan(
    plan,
    mesh,
    param_shapes: Optional[Mapping[str, Sequence[int]]] = None,
    hbm_budget_bytes: Optional[int] = None,
    dtype_bytes: int = 4,
    optimizer_slots: int = 1,
    location: Optional[str] = None,
) -> List[Finding]:
    """FML501/502/503 for one plan against one mesh.

    ``param_shapes`` (name -> shape) enables the divisibility (FML502)
    and footprint (FML503) checks; without it only the axis checks run.
    ``hbm_budget_bytes`` enables FML503; ``optimizer_slots`` counts
    same-shaped optimizer companions (1 = SGD momentum, 2 = Adam m/v).
    """
    from flinkml_tpu.sharding.plan import entry_axes

    sizes = _axis_sizes(mesh)
    findings: List[Finding] = []

    # -- FML501: unknown axes (batch + every family spec) ------------------
    for axis in plan.batch_axes:
        if axis not in sizes:
            findings.append(Finding(
                "FML501",
                f"plan {plan.name!r} shards batches over axis {axis!r}, "
                f"which the mesh {dict(sizes)} does not have",
                stage=plan.name, column="batch", location=location,
                fix_hint="add the axis to the mesh (DeviceMesh.for_plan) "
                         "or drop it from batch_axes",
            ))
    specs = tuple(plan.rules) + (("<default>", plan.default_spec),)
    for pattern, spec in specs:
        seen_axes: set = set()
        for entry in spec:
            for axis in entry_axes(entry):
                if axis not in sizes:
                    findings.append(Finding(
                        "FML501",
                        f"plan {plan.name!r} family {pattern!r} shards "
                        f"over axis {axis!r}, which the mesh "
                        f"{dict(sizes)} does not have",
                        stage=plan.name, column=pattern, location=location,
                        fix_hint="name one of the mesh's axes, or build "
                                 "the mesh with DeviceMesh.for_plan(plan)",
                    ))
                if axis in seen_axes:
                    findings.append(Finding(
                        "FML501",
                        f"plan {plan.name!r} family {pattern!r} uses axis "
                        f"{axis!r} on two dimensions of one parameter — "
                        "a PartitionSpec axis may appear at most once",
                        stage=plan.name, column=pattern, location=location,
                        fix_hint="shard each dim over distinct axes",
                    ))
                seen_axes.add(axis)

    # -- FML502 + FML503: shape-aware checks -------------------------------
    from flinkml_tpu.sharding.plan import is_embedding_param

    for name, shape in _plan_params(plan, param_shapes):
        if shape is None:
            continue
        spec = plan.spec_for(name, ndim=len(shape))
        embedding = is_embedding_param(name)
        sharded_factor = 1
        sharded_axes: List[str] = []
        for dim_idx, entry in enumerate(spec):
            axes = entry_axes(entry)
            if not axes:
                continue
            factor = 1
            for axis in axes:
                factor *= sizes.get(axis, 1)
            sharded_factor *= factor
            sharded_axes.extend(axes)
            if shape[dim_idx] % factor != 0:
                findings.append(Finding(
                    "FML502",
                    f"plan {plan.name!r} shards {name!r} dim {dim_idx} "
                    f"(extent {shape[dim_idx]}) over axes {axes} of total "
                    f"size {factor}, which does not divide it"
                    + (" (the embedding family's vocab axis must divide "
                       "the shard product — EmbeddingTable pads its vocab "
                       "to a multiple automatically; a hand-written plan "
                       "must pad too)" if embedding and dim_idx == 0
                       else ""),
                    stage=plan.name, column=name, location=location,
                    fix_hint="pad the dimension to a multiple of the axis "
                             "size, or shard a different dim",
                ))
        if hbm_budget_bytes is not None:
            # Per-DEVICE footprint of parameter + optimizer state: the
            # LARGEST slice (per-dim ceil — the same model infer_plan
            # and EmbeddingTable's padded placement use, so the three
            # can never disagree at a budget boundary; the replicated
            # case is factor == 1). Embedding-family tables are the
            # reason the sharded branch exists: a 100M-row vocab's
            # PER-SHARD slice plus its same-layout optimizer slots must
            # fit, not just divide (the original FML503 only caught the
            # replicated case, so an under-sharded embedding plan OOM'd
            # inside XLA instead of failing here).
            from flinkml_tpu.sharding.plan import (
                human_bytes,
                shard_slice_elems,
            )

            per_device = shard_slice_elems(plan, sizes, name, shape) \
                * dtype_bytes * (1 + optimizer_slots)
            if per_device > int(hbm_budget_bytes):
                if sharded_factor == 1:
                    findings.append(Finding(
                        "FML503",
                        f"plan {plan.name!r} replicates {name!r} "
                        f"({tuple(shape)}): {human_bytes(per_device)} of "
                        f"parameter + optimizer state per device exceeds "
                        f"the HBM budget of "
                        f"{human_bytes(hbm_budget_bytes)}",
                        stage=plan.name, column=name, location=location,
                        fix_hint="shard the family over an fsdp (or "
                                 "fsdp,tp) axis, or use infer_plan to "
                                 "pick a fitting preset",
                    ))
                else:
                    findings.append(Finding(
                        "FML503",
                        f"plan {plan.name!r} shards {name!r} "
                        f"({tuple(shape)}) over axes {sharded_axes} "
                        f"(product {sharded_factor}), but the per-device "
                        f"shard still costs {human_bytes(per_device)} of "
                        f"parameter + optimizer state against the HBM "
                        f"budget of {human_bytes(hbm_budget_bytes)}",
                        stage=plan.name, column=name, location=location,
                        fix_hint="grow the shard axes (a larger fsdp×tp "
                                 "product), shrink the table, or raise "
                                 "the budget",
                    ))
    return findings


def plan_collective_signature(
    plan,
    mesh,
    param_shapes: Optional[Mapping[str, Sequence[int]]] = None,
) -> Tuple[CollectiveOp, ...]:
    """The ordered gradient-sync pseudo-program ``plan`` implies: per
    parameter (sorted by name — the deterministic program order), an
    all-gather over its shard axes plus a reduce-scatter over the batch
    axes when sharded, one psum over the batch axes when replicated.
    Two plans whose signatures diverge would enqueue collectives in
    different orders inside one program — the FML301 rendezvous-
    mismatch shape, detected by the same comparator."""
    params = sorted(_plan_params(plan, param_shapes), key=lambda p: p[0])
    sig: List[CollectiveOp] = []
    for name, shape in params:
        ndim = len(shape) if shape is not None else None
        axes = plan.param_axes(name, ndim=ndim)
        if axes:
            sig.append(CollectiveOp("all_gather", tuple(axes)))
            sig.append(CollectiveOp("reduce_scatter",
                                    tuple(plan.batch_axes) + tuple(axes)))
        else:
            sig.append(CollectiveOp("psum", tuple(plan.batch_axes)))
    return tuple(sig)


def check_cross_plan(
    plans: Sequence,
    mesh,
    param_shapes: Optional[Mapping[str, Sequence[int]]] = None,
    location: Optional[str] = None,
) -> List[Finding]:
    """FML504 when two plans in one program imply conflicting collective
    orders — composed from the FML301 checker over the plans' derived
    signatures."""
    if len(plans) < 2:
        return []
    # Keys must be unique per PLAN, not per name: two distinct plans
    # sharing a name would otherwise collapse into one dict entry and
    # skip exactly the conflict this rule exists to catch.
    sequences = {
        f"{plan.name}[{i}]" if sum(
            1 for p in plans if p.name == plan.name) > 1 else plan.name:
        plan_collective_signature(plan, mesh, param_shapes)
        for i, plan in enumerate(plans)
    }
    out: List[Finding] = []
    for f in check_rank_order(sequences, program="sharding plans"):
        # Rewrite the cross-RANK finding as the cross-PLAN rule: same
        # divergence machinery, different program shape.
        out.append(Finding(
            "FML504",
            f.message.replace("rank ", "plan ") + " (two plans in one "
            "program must imply one collective order; split them into "
            "separate dispatches or reconcile the family tables)",
            stage=f.stage, location=location,
            fix_hint="use ONE plan per program, or make both plans shard "
                     "every shared family identically",
        ))
    return out


def check_program(
    plans: Sequence,
    mesh,
    param_shapes: Optional[Mapping[str, Sequence[int]]] = None,
    hbm_budget_bytes: Optional[int] = None,
    dtype_bytes: int = 4,
    optimizer_slots: int = 1,
    location: Optional[str] = None,
) -> List[Finding]:
    """The full FML5xx pass over every plan a program uses: per-plan
    FML501-503 plus the cross-plan FML504."""
    findings: List[Finding] = []
    for plan in plans:
        findings.extend(check_plan(
            plan, mesh, param_shapes=param_shapes,
            hbm_budget_bytes=hbm_budget_bytes, dtype_bytes=dtype_bytes,
            optimizer_slots=optimizer_slots, location=location,
        ))
    findings.extend(
        check_cross_plan(plans, mesh, param_shapes, location=location)
    )
    return findings


def check_plan_file(path: str) -> List[Finding]:
    """Validate a ``*.plan.json`` fixture/config:

    .. code-block:: json

        {"mesh": {"data": 1, "fsdp": 8},
         "param_shapes": {"coef": [4096]},
         "hbm_budget_bytes": 16384,
         "optimizer_slots": 1,
         "plans": [{"name": "...", "rules": [...], "batch_axes": [...]}]}

    (``plan`` with a single object is accepted too.) Unreadable or
    malformed files report one FML501 finding naming the path — the
    gate must fail loudly, not skip silently.
    """
    from flinkml_tpu.sharding.plan import ShardingPlan

    try:
        with open(path, "r") as fh:
            doc = json.load(fh)
        raw_plans = doc.get("plans")
        if raw_plans is None:
            raw_plans = [doc["plan"]] if "plan" in doc else []
        plans = [ShardingPlan.from_json_dict(p) for p in raw_plans]
        mesh = {str(k): int(v) for k, v in (doc.get("mesh") or {}).items()}
        shapes = {
            str(k): tuple(int(d) for d in v)
            for k, v in (doc.get("param_shapes") or {}).items()
        } or None
        budget = doc.get("hbm_budget_bytes")
        slots = int(doc.get("optimizer_slots", 1))
        dtype_bytes = int(doc.get("dtype_bytes", 4))
    except (OSError, ValueError, KeyError, TypeError) as e:
        return [Finding(
            "FML501",
            f"sharding-plan file {path} is unreadable or malformed: {e!r}",
            location=path,
            fix_hint="see docs/development/sharding.md for the "
                     "*.plan.json schema",
        )]
    if not plans:
        return [Finding(
            "FML501",
            f"sharding-plan file {path} declares no plans",
            location=path,
            fix_hint="add a 'plan' object or a 'plans' list",
        )]
    return check_program(
        plans, mesh, param_shapes=shapes, hbm_budget_bytes=budget,
        dtype_bytes=dtype_bytes, optimizer_slots=slots, location=path,
    )
