"""Structured findings — the common currency of every analysis pass.

Each pass (graph validator, collective-order checker, transfer/retrace
guard) reports :class:`Finding`s: a stable rule id from :data:`RULES`, a
severity, the stage/column the finding anchors to, and a fix hint. A
:class:`Report` aggregates findings, applies suppressions, and renders
them for humans (CLI) or machines (``--format json``).

Rule ids are permanent: a released id is never reused for a different
check, so suppression lists stay meaningful across versions. Add new
rules at the end of their band (1xx schema, 2xx graph wiring, 3xx
collectives, 4xx transfer/retrace, 5xx sharding plans, 6xx precision
flow, 7xx memory liveness).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List, Optional, Sequence

ERROR = "error"
WARNING = "warning"

#: Rule catalog: id -> (default severity, one-line description).
RULES = {
    # -- 1xx: schema / pipeline validation ---------------------------------
    "FML101": (ERROR, "stage reads a column absent from its input schema"),
    "FML102": (WARNING, "stage output column silently overwrites an existing column"),
    "FML103": (ERROR, "stage kernel fails abstract evaluation (shape/dtype mismatch)"),
    "FML104": (WARNING, "non-fusable stage breaks a kernel chain into separate programs"),
    "FML105": (ERROR, "transform_kernel fingerprint is not stable across calls"),
    "FML106": (WARNING, "silent dtype promotion: output column is wider than every input"),
    "FML107": (ERROR, "stage consumes a column that only a later stage produces"),
    # -- 2xx: graph wiring -------------------------------------------------
    "FML201": (ERROR, "graph node input TableId is never produced (cycle or missing input)"),
    "FML202": (ERROR, "graph output TableId is never produced by any node"),
    "FML203": (ERROR, "two graph nodes claim the same output TableId"),
    # -- 3xx: collectives --------------------------------------------------
    "FML301": (ERROR, "cross-rank collective sequences diverge (rendezvous mismatch)"),
    "FML302": (ERROR, "concurrent multi-device collective dispatch without a common lock"),
    "FML303": (ERROR, "serving replica-pool mesh slice overlaps a concurrent dispatch without a shared slice lock"),
    "FML304": (ERROR, "serving replica-pool dispatch on devices under an active training slice lease that was never reclaimed"),
    # -- 4xx: transfer / retrace guard -------------------------------------
    "FML401": (ERROR, "host<->device transfer beyond the declared budget in a guarded region"),
    "FML402": (ERROR, "compile-cache miss beyond the declared bucket policy in a guarded region"),
    "FML403": (ERROR, "two compiles share input specs and bucket but differ in chain fingerprint"),
    "FML404": (ERROR, "scatter-add traced with indices_are_sorted=False over indices carrying the pack-time sorted guarantee (re-pays the sort every step)"),
    # -- 5xx: sharding plans -----------------------------------------------
    "FML501": (ERROR, "sharding plan references an unknown mesh axis (or uses one illegally)"),
    "FML502": (ERROR, "mesh axis size does not divide the parameter dimension it shards"),
    "FML503": (ERROR, "replicated parameter (+ optimizer state) exceeds the per-device HBM budget"),
    "FML504": (ERROR, "two sharding plans in one program imply conflicting collective orders"),
    "FML505": (ERROR, "hash front-end num_buckets does not match the embedding table's vocab rows"),
    # -- 6xx: precision flow -------------------------------------------------
    "FML601": (ERROR, "reduction/accumulation (sum, dot accumulator, state update) runs narrower than policy.accum"),
    "FML602": (ERROR, "silent upcast in the compute region: a strong wide constant promotes policy.compute work"),
    "FML603": (ERROR, "parameter or optimizer-state leaf stored narrower than policy.params"),
    "FML604": (ERROR, "cross-rank collective runs narrower than policy.accum without an explicit pre-cast"),
    "FML605": (ERROR, "sharding-plan HBM math assumed a parameter width different from policy.params"),
    "FML606": (ERROR, "quantized (int8) parameters accumulate at integer width without a dequant scale"),
    "FML607": (ERROR, "int8-quantized parameter leaf served under a non-quantized policy (degraded params republished as the full-width tier)"),
    # -- 7xx: memory liveness ----------------------------------------------
    "FML701": (ERROR, "estimated per-device peak live bytes exceed the HBM budget"),
    "FML702": (ERROR, "vocab-scale intermediate materialized on the hot path (the embedding contract promises batch-sized payloads)"),
    "FML703": (WARNING, "same-shape parameter/carry update whose input buffer is not donated (missed donate_argnums doubles peak at the worst moment)"),
    "FML704": (ERROR, "no quant tier in the f32 -> bf16 -> int8 ladder fits the per-device HBM budget"),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer result, anchored to a rule and (optionally) a stage."""

    rule: str
    message: str
    stage: Optional[str] = None
    column: Optional[str] = None
    fix_hint: Optional[str] = None
    location: Optional[str] = None

    @property
    def severity(self) -> str:
        return RULES.get(self.rule, (ERROR, ""))[0]

    def render(self) -> str:
        where = " @ ".join(p for p in (self.location, self.stage) if p)
        head = f"{self.rule} [{self.severity}]"
        if where:
            head += f" {where}"
        if self.column:
            head += f" (column {self.column!r})"
        out = f"{head}: {self.message}"
        if self.fix_hint:
            out += f"\n    fix: {self.fix_hint}"
        return out

    def to_map(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "stage": self.stage,
            "column": self.column,
            "fixHint": self.fix_hint,
            "location": self.location,
        }


class Report:
    """An ordered collection of findings with suppression filtering."""

    def __init__(self, findings: Iterable[Finding] = ()):
        self.findings: List[Finding] = list(findings)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def suppress(self, rules: Sequence[str]) -> "Report":
        dropped = set(rules)
        return Report(f for f in self.findings if f.rule not in dropped)

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    def __len__(self) -> int:
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def __bool__(self) -> bool:
        return bool(self.findings)

    def render(self) -> str:
        if not self.findings:
            return "no findings"
        lines = [f.render() for f in self.findings]
        lines.append(
            f"{len(self.errors())} error(s), {len(self.warnings())} warning(s)"
        )
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps([f.to_map() for f in self.findings], indent=2)
