"""Ahead-of-time static analysis for flinkml_tpu pipelines.

Three passes, all device-free (run them under ``JAX_PLATFORMS=cpu`` on
any host):

  1. **Graph validator** (:mod:`.validator`, :mod:`.ast_lint`) — schema
     flow, kernel abstract evaluation via ``jax.eval_shape``, fusion
     topology, fingerprint stability; over live ``Pipeline`` /
     ``PipelineModel`` / ``Graph`` objects or over scripts' ASTs.
  2. **Collective-order checker** (:mod:`.collectives`) — extracts
     ordered collective sequences from jaxprs, compares them across
     ranks, and flags unlocked concurrent multi-device dispatch (the
     PR 1 rendezvous-deadlock shape) from recorded dispatch traces.
  3. **Transfer/retrace guard** (:mod:`.guard`) — runtime budget checks
     for hot loops: compile-cache misses beyond the bucket policy and
     host↔device transfers beyond declared budgets; backs the
     ``no_retrace`` pytest marker.
  4. **Sharding-plan validation** (:mod:`.sharding_check`) — FML5xx:
     validates :class:`~flinkml_tpu.sharding.plan.ShardingPlan`s against
     a mesh BEFORE any compile (unknown/illegal axis, non-dividing
     shard, replicated-but-huge family vs the HBM budget, conflicting
     cross-plan collective orders); consumes live plans or
     ``*.plan.json`` fixtures.
  6. **Sorted-scatter provenance** (:mod:`.sorted_scatter`) — FML404:
     walks jaxprs propagating the pack-time sorted guarantee
     (:class:`~flinkml_tpu.table.SortedSparseColumn`) and flags any
     scatter-add traced with ``indices_are_sorted=False`` over
     sorted-provenance indices — the silent re-pay-the-sort-every-step
     shape; consumes live functions or ``*.scatter.json`` probes.
  5. **Precision-flow validation** (:mod:`.precision`) — FML6xx:
     abstract-interprets jaxprs tracking per-value dtype provenance
     against a declared
     :class:`~flinkml_tpu.precision.PrecisionPolicy` (narrow
     accumulation, silent compute-region upcast, narrow-stored
     parameters, narrow collectives, policy/plan width conflicts);
     consumes live functions pre-compile or ``*.policy.json``
     fixtures, and hosts the shared dtype-flow walk behind FML106.
  7. **Memory liveness** (:mod:`.memory`) — FML7xx: walks jaxprs
     device-free computing a per-device peak-live-bytes estimate under
     a ``(ShardingPlan, quant tier)`` pair — per-leaf param widths,
     optimizer slots from the actual state, activation liveness with
     last-use frees, sharded extents via the same ceil math the padded
     runtime layout uses; flags over-budget peaks (FML701),
     vocab-scale hot-path intermediates (FML702), undonated same-shape
     state updates (FML703), and a quant ladder with no fitting rung
     (FML704); consumes live functions or ``*.memory.json`` targets,
     and backs the serving engine's load-time budget gate.

CLI: ``python -m flinkml_tpu.analysis <paths...> [--fail-on-findings]``
(see :mod:`.__main__`); rule catalog in :data:`.findings.RULES` and
``docs/development/static_analysis.md``.
"""

from flinkml_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    Finding,
    Report,
    RULES,
    WARNING,
)
from flinkml_tpu.analysis.validator import (  # noqa: F401
    ColumnSpec,
    StageIO,
    analyze_graph,
    analyze_pipeline,
    kernel_output_specs,
    schema_of,
    stage_io,
)
from flinkml_tpu.analysis.ast_lint import lint_paths, lint_source  # noqa: F401
from flinkml_tpu.analysis.collectives import (  # noqa: F401
    COLLECTIVE_PRIMITIVES,
    CollectiveOp,
    DispatchEvent,
    check_dispatch_trace,
    check_rank_order,
    extract_collectives,
    load_trace,
)
from flinkml_tpu.analysis.guard import (  # noqa: F401
    GuardViolation,
    TransferRetraceGuard,
    transfer_retrace_guard,
)
from flinkml_tpu.analysis.sharding_check import (  # noqa: F401
    check_cross_plan,
    check_plan,
    check_plan_file,
    check_program,
    plan_collective_signature,
)
from flinkml_tpu.analysis.precision import (  # noqa: F401
    check_closed_jaxpr,
    check_policy_file,
    check_policy_plan,
    check_precision_fn,
    promotion_findings,
    validate_precision,
)
from flinkml_tpu.analysis.sorted_scatter import (  # noqa: F401
    ORDER_PRESERVING,
    check_scatter_file,
    check_sorted_scatter_fn,
    check_sorted_scatter_jaxpr,
)
from flinkml_tpu.analysis.memory import (  # noqa: F401
    MemoryEstimate,
    check_memory_file,
    check_memory_fn,
    check_tier_ladder,
    estimate_fn_memory,
    estimate_serving_bytes,
)
