"""Pass 5 — precision-flow validation (FML6xx), before any compile.

A :class:`~flinkml_tpu.precision.PrecisionPolicy` is a promise about
where a program is allowed to round: ``compute`` is where the hot work
runs (bf16 on TPU), ``accum`` is the floor under every accumulation,
``params`` is the storage width of parameters and optimizer state. This
pass abstract-interprets jaxprs — device-free, recursing through
pjit/scan/while/cond exactly like the collective extractor
(:mod:`flinkml_tpu.analysis.collectives`) — tracking per-value **dtype
provenance** against the declared policy:

  - **FML601** — a reduction/accumulation (``reduce_sum``/``cumsum``, a
    ``dot_general`` accumulator, an optimizer moment/parameter update —
    any add/mul chain still carrying parameter-or-carry provenance)
    runs in a dtype narrower than ``policy.accum``. bf16 accumulation is
    THE silent-corruption shape mixed precision must not introduce.
  - **FML602** — a silent upcast inside the compute region: a stray
    strong-typed f32/f64 constant promotes a ``policy.compute``-width
    value wider, defeating exactly the bandwidth/MXU savings the policy
    declared (the mirror of FML106's f64 promotion, policy-scoped).
  - **FML603** — a parameter or optimizer-state leaf is *stored*
    narrower than ``policy.params`` (bf16 master weights: each step
    rounds the state, divergence compounds).
  - **FML604** — a cross-rank collective (psum/all-gather/...) operates
    on a dtype narrower than ``accum`` without an explicit pre-cast:
    reduction order across ranks is already nondeterministic, doing it
    in bf16 compounds rounding with topology. An explicit narrowing
    cast immediately before the collective (the deliberate
    bandwidth-for-precision trade) is allowed.
  - **FML605** — policy/plan conflict: a
    :class:`~flinkml_tpu.sharding.plan.ShardingPlan` whose HBM-budget
    math (``infer_plan``/FML503 ``dtype_bytes``) assumed a different
    parameter width than the policy declares — the budget that
    "fit" was computed for a model that will not exist.

**Provenance rules.** Input leaves are labeled ``param`` (parameters +
optimizer state) or ``data`` (batches); literals/constvars are
``const``; scan/while carries gain ``carry``. Provenance flows through
every eqn — EXCEPT through a *narrowing* ``convert_element_type``,
which resets to ``data``: casting a parameter down to ``compute`` at a
step boundary is the sanctioned contract (SNIPPETS.md [3]'s
``to_bf16``), and everything derived from the cast is compute-region
work, not state math. Anything still carrying ``param``/``carry``
provenance at a narrow width therefore IS state math running narrow.

Inputs come from live functions (:func:`check_precision_fn` — what the
fused executor, the plan trainers, and serving call pre-compile) or
from ``*.policy.json`` fixtures (:func:`check_policy_file` — what the
CLI and the CI fixture gate consume). The same dtype-flow walk also
backs the FML106 silent-f64-promotion check
(:func:`promotion_findings`), so single-stage and fused multi-stage
programs share one code path. See ``docs/development/precision.md``.
"""

from __future__ import annotations

import json
from typing import (
    Any,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from flinkml_tpu.analysis.collectives import COLLECTIVE_PRIMITIVES
from flinkml_tpu.analysis.findings import Finding
from flinkml_tpu.precision import (
    PrecisionPolicy,
    is_narrower,
    significand_bits,
)

#: Primitives that reduce/accumulate across elements — their output
#: dtype IS their accumulator dtype.
REDUCTION_PRIMITIVES = frozenset({
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
    "add_any",
})

#: Elementwise arithmetic that, when still carrying param/carry
#: provenance at a narrow width, is a state/accumulator update.
_UPDATE_PRIMITIVES = frozenset({"add", "sub", "mul", "div", "add_any"})

#: Binary arithmetic checked for the stray-wide-constant promotion shape.
_PROMOTION_PRIMITIVES = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "atan2", "rem",
})

_PARAMISH = frozenset({"param", "carry"})


def _is_float(dtype) -> bool:
    try:
        return np.dtype(dtype).kind == "f" or "bfloat16" in str(dtype)
    except TypeError:
        return False


def _is_int8(dtype) -> bool:
    try:
        return np.dtype(dtype) == np.dtype(np.int8)
    except TypeError:
        return False


def _is_integer(dtype) -> bool:
    try:
        return np.dtype(dtype).kind in "iu"
    except TypeError:
        return False


def _bits(dtype) -> int:
    return significand_bits(dtype)


class _Flow:
    """One dtype-provenance walk over a closed jaxpr (and its
    sub-jaxprs), accumulating FML601/602/604 findings."""

    def __init__(self, policy: PrecisionPolicy, program: str,
                 location: Optional[str]):
        self.policy = policy
        self.program = program
        self.location = location
        self.findings: List[Finding] = []
        self._seen: set = set()
        # var -> provenance frozenset; vars absent (constvars) are const.
        self.prov: Dict[Any, frozenset] = {}
        # var -> significand bits it was widened FROM / narrowed FROM by
        # a convert_element_type (for the FML602/FML604 shapes).
        self.widened_from: Dict[Any, int] = {}
        self.narrowed_from: Dict[Any, int] = {}

    # -- provenance helpers ------------------------------------------------
    @staticmethod
    def _is_var(atom) -> bool:
        # Literals are unhashable in some jax versions — never dict keys.
        return hasattr(atom, "aval") and type(atom).__name__ != "Literal"

    def prov_of(self, atom) -> frozenset:
        if not self._is_var(atom):
            return frozenset({"const"})
        return self.prov.get(atom, frozenset({"const"}))

    def _widened_from(self, atom) -> int:
        return self.widened_from.get(atom, 0) if self._is_var(atom) else 0

    def _narrowed_from(self, atom) -> int:
        return self.narrowed_from.get(atom, 0) if self._is_var(atom) else 0

    def _dtype(self, atom):
        return atom.aval.dtype if hasattr(atom, "aval") else np.dtype(
            np.asarray(atom).dtype)

    def _add(self, rule: str, key: tuple, message: str, fix: str,
             column: Optional[str] = None) -> None:
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(
            rule, message, stage=self.program, column=column,
            location=self.location, fix_hint=fix,
        ))

    # -- the walk ----------------------------------------------------------
    def walk(self, jaxpr, invar_prov: Sequence[frozenset]) -> List[frozenset]:
        """Walk one (open) jaxpr with the given per-invar provenance;
        returns per-outvar provenance."""
        for var, p in zip(jaxpr.invars, invar_prov):
            self.prov[var] = p
        for eqn in jaxpr.eqns:
            self._eqn(eqn)
        return [self.prov_of(v) for v in jaxpr.outvars]

    def _eqn(self, eqn) -> None:
        name = eqn.primitive.name
        in_provs = [self.prov_of(a) for a in eqn.invars]
        joined = frozenset().union(*in_provs) if in_provs else frozenset()

        if name == "convert_element_type":
            self._convert(eqn, joined)
            return
        if self._recurse(eqn, name, in_provs, joined):
            return

        accum_bits = _bits(self.policy.accum)
        out = eqn.outvars[0] if eqn.outvars else None
        out_dt = out.aval.dtype if out is not None and hasattr(out, "aval") \
            else None
        out_is_float = out_dt is not None and _is_float(out_dt)

        # FML604 — narrow cross-rank collective without explicit pre-cast.
        if name in COLLECTIVE_PRIMITIVES:
            for a in eqn.invars:
                dt = self._dtype(a)
                if not _is_float(dt) or _bits(dt) >= accum_bits:
                    continue
                if self._narrowed_from(a) >= accum_bits:
                    continue  # deliberate bandwidth cast right before
                self._add(
                    "FML604", ("FML604", name, str(dt)),
                    f"collective {name!r} operates on {dt} — narrower "
                    f"than policy.accum ({self.policy.accum}) — without "
                    "an explicit pre-cast; cross-rank reduction order is "
                    "already nondeterministic, rounding it at "
                    f"{dt} compounds with topology",
                    fix="accumulate collectives at policy.accum, or cast "
                        "down EXPLICITLY right before the collective to "
                        "declare the bandwidth-for-precision trade",
                )

        # FML606 — quantized params accumulated at integer width. The
        # int8 tier's contract is dequant-THEN-accumulate: a reduction
        # or dot accumulator whose operands include int8 param/carry
        # state and whose output is still integer ran the accumulation
        # unscaled — an int8 accumulator wraps at ±127, and even a
        # widened int32 sum is missing its per-column scales (the values
        # are dimensionless codes until multiplied by scale).
        if (
            (name in REDUCTION_PRIMITIVES or name == "dot_general")
            and out_dt is not None and _is_integer(out_dt)
            and (joined & _PARAMISH)
            and any(_is_int8(self._dtype(a)) for a in eqn.invars)
        ):
            self._add(
                "FML606", ("FML606", name, str(out_dt)),
                f"{name} accumulates int8-quantized parameters at "
                f"{out_dt} without a dequant scale — int8 accumulation "
                "wraps at ±127, and unscaled integer codes are not "
                "values",
                fix="dequantize first (q.astype(policy.compute) * scale, "
                    "the sanctioned int8-tier shape — "
                    "flinkml_tpu.precision.quantize_absmax) so the "
                    "accumulation runs at policy.accum on scaled floats",
            )

        # FML601(a/b) — reductions and dot accumulators.
        if out_is_float and _bits(out_dt) < accum_bits:
            if name in REDUCTION_PRIMITIVES:
                self._add(
                    "FML601", ("FML601", name, str(out_dt)),
                    f"{name} accumulates in {out_dt}, narrower than "
                    f"policy.accum ({self.policy.accum})",
                    fix="cast the operand up before reducing (or use "
                        "preferred_element_type) so the running sum "
                        "carries policy.accum precision",
                )
            elif name == "dot_general":
                self._add(
                    "FML601", ("FML601", name, str(out_dt)),
                    f"dot_general accumulator runs at {out_dt}, narrower "
                    f"than policy.accum ({self.policy.accum})",
                    fix="pass preferred_element_type=policy.accum to the "
                        "matmul so the MXU/accumulator output carries "
                        "full precision (inputs may stay at "
                        "policy.compute)",
                )
            # FML601(c) — state/accumulator update still carrying
            # param/carry provenance at a narrow width.
            elif name in _UPDATE_PRIMITIVES and (joined & _PARAMISH):
                self._add(
                    "FML601", ("FML601", "update", name, str(out_dt)),
                    f"parameter/optimizer-state update ({name}) runs at "
                    f"{out_dt}, narrower than policy.accum "
                    f"({self.policy.accum}) — each step rounds the "
                    "state, divergence compounds",
                    fix="store state at policy.params, cast to "
                        "policy.compute at the step boundary for the "
                        "forward work, and run every state update at "
                        "policy.accum",
                )

        # FML602 — stray wide constant promotes the compute region.
        if (out_is_float and name in _PROMOTION_PRIMITIVES
                and _bits(out_dt) > _bits(self.policy.compute)):
            compute_bits = _bits(self.policy.compute)
            has_widened = any(
                self._widened_from(a) == compute_bits for a in eqn.invars
            )
            wide_const = any(
                self.prov_of(a) <= frozenset({"const"})
                and _is_float(self._dtype(a))
                and _bits(self._dtype(a)) > compute_bits
                for a in eqn.invars
            )
            if has_widened and wide_const:
                self._add(
                    "FML602", ("FML602", name, str(out_dt)),
                    f"a strong-typed {out_dt} constant promotes a "
                    f"{self.policy.compute} value to {out_dt} inside the "
                    f"compute region ({name}) — the whole downstream "
                    "chain runs wide, defeating the bf16 savings the "
                    "policy declared",
                    fix="make the constant weak-typed (a python scalar) "
                        "or cast it to policy.compute; promotion against "
                        "strong constants is silent",
                )

        for ov in eqn.outvars:
            self.prov[ov] = joined

    def _convert(self, eqn, joined: frozenset) -> None:
        (a,) = eqn.invars
        (out,) = eqn.outvars
        in_dt, out_dt = self._dtype(a), out.aval.dtype
        if _is_float(in_dt) and _is_float(out_dt):
            if _bits(out_dt) < _bits(in_dt):
                # Sanctioned step-boundary down-cast: drop param/carry
                # taint — downstream is compute-region work.
                self.narrowed_from[out] = _bits(in_dt)
                self.prov[out] = frozenset({"data"})
                return
            if _bits(out_dt) > _bits(in_dt):
                self.widened_from[out] = _bits(in_dt)
        self.prov[out] = joined

    def _recurse(self, eqn, name: str, in_provs: List[frozenset],
                 joined: frozenset) -> bool:
        """Walk sub-jaxprs of control-flow/call primitives, mapping
        operand provenance onto their invars (scan/while carries gain
        the ``carry`` tag). Returns True when handled."""
        params = eqn.params
        if name == "scan":
            closed = params["jaxpr"]
            nc, ncar = params["num_consts"], params["num_carry"]
            inner = list(in_provs)
            for i in range(nc, nc + ncar):
                if i < len(inner):
                    inner[i] = inner[i] | {"carry"}
            out_provs = self.walk(closed.jaxpr, inner)
        elif name == "while":
            body = params["body_jaxpr"]
            bn = params["body_nconsts"]
            cn = params["cond_nconsts"]
            carry_provs = [p | {"carry"} for p in in_provs[cn + bn:]]
            self.walk(params["cond_jaxpr"].jaxpr,
                      in_provs[:cn] + carry_provs)
            out_provs = self.walk(body.jaxpr,
                                  in_provs[cn:cn + bn] + carry_provs)
        elif name == "cond":
            branches = params["branches"]
            out_provs = None
            for br in branches:
                provs = self.walk(br.jaxpr, in_provs[1:])
                out_provs = provs if out_provs is None else [
                    a | b for a, b in zip(out_provs, provs)
                ]
            out_provs = out_provs or []
        elif "jaxpr" in params and hasattr(
                getattr(params["jaxpr"], "jaxpr", None), "eqns"):
            # pjit / closed_call / checkpoint-style wrappers.
            out_provs = self.walk(params["jaxpr"].jaxpr, in_provs)
        elif "call_jaxpr" in params:
            cj = params["call_jaxpr"]
            out_provs = self.walk(getattr(cj, "jaxpr", cj), in_provs)
        else:
            return False
        for ov, p in zip(eqn.outvars, out_provs):
            self.prov[ov] = p
        return True


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def _path_name(path) -> str:
    parts = []
    for k in path:
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return "/".join(parts)


def check_closed_jaxpr(
    closed,
    policy: PrecisionPolicy,
    invar_roles: Optional[Sequence[str]] = None,
    invar_names: Optional[Sequence[str]] = None,
    program: str = "program",
    location: Optional[str] = None,
) -> List[Finding]:
    """FML601/602/603/604 over one closed jaxpr. ``invar_roles`` labels
    each invar ``"param"`` or ``"data"`` (default: all data);
    ``invar_names`` names the leaves for FML603 messages."""
    jaxpr = closed.jaxpr
    roles = list(invar_roles or ())
    roles += ["data"] * (len(jaxpr.invars) - len(roles))
    names = list(invar_names or ())
    names += [f"arg{i}" for i in range(len(names), len(jaxpr.invars))]

    flow = _Flow(policy, program, location)
    params_bits = _bits(policy.params)
    quant = getattr(policy, "quant", None)
    for var, role, name in zip(jaxpr.invars, roles, names):
        dt = var.aval.dtype
        if role == "param" and _is_float(dt) and _bits(dt) < params_bits:
            flow._add(
                "FML603", ("FML603", name),
                f"parameter/optimizer-state leaf {name!r} is stored as "
                f"{dt}, narrower than policy.params ({policy.params})",
                fix="keep master weights and optimizer moments at "
                    "policy.params; cast to policy.compute only at the "
                    "step boundary (to_bf16/to_fp32)",
                column=name,
            )
        # FML607 — int8-quantized params under a policy that never
        # declared quantization: the values are absmax-degraded codes,
        # and serving them as the full-width tier republishes the
        # quality loss without the policy paper trail.
        if role == "param" and _is_int8(dt) and quant is None:
            flow._add(
                "FML607", ("FML607", name),
                f"parameter leaf {name!r} is stored as int8 but policy "
                f"{policy.name!r} declares no quantization scheme — "
                "quantized params are republished as the full-width "
                f"({policy.params}) tier",
                fix="serve quantized models under the int8 tier "
                    "(PrecisionPolicy quant='int8', preset "
                    "'int8_inference') or republish the full-width "
                    "master weights",
                column=name,
            )
    flow.walk(
        jaxpr,
        [frozenset({r}) for r in roles[:len(jaxpr.invars)]],
    )
    return flow.findings


def check_precision_fn(
    fn,
    *example_args,
    policy: PrecisionPolicy,
    param_argnums: Iterable[int] = (),
    program: str = "program",
    location: Optional[str] = None,
    axis_env: Optional[Sequence[Tuple[str, int]]] = None,
) -> List[Finding]:
    """Trace ``fn`` abstractly (shapes/dtypes only — no compile, no
    device) and run the precision-flow pass. ``param_argnums`` marks
    which positional arguments hold parameters/optimizer state (their
    leaves are checked against ``policy.params`` and taint the update
    chain for FML601)."""
    import jax

    closed = jax.make_jaxpr(fn, axis_env=list(axis_env or ()))(*example_args)
    param_set = set(param_argnums)
    roles: List[str] = []
    names: List[str] = []
    for i, arg in enumerate(example_args):
        leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(arg)
        role = "param" if i in param_set else "data"
        for path, _leaf in leaves_with_paths:
            roles.append(role)
            names.append(_path_name(path) or f"arg{i}")
    if len(roles) != len(closed.jaxpr.invars):
        # Structure mismatch (kwargs, donated args, ...): fall back to
        # unlabeled flow — FML601/602/604 still run, FML603 cannot.
        roles, names = [], []
    return check_closed_jaxpr(
        closed, policy, invar_roles=roles, invar_names=names,
        program=program, location=location,
    )


def validate_precision(
    fn,
    *example_args,
    policy: PrecisionPolicy,
    param_argnums: Iterable[int] = (),
    program: str = "program",
    location: Optional[str] = None,
    axis_env=None,
    extra_findings: Iterable[Finding] = (),
) -> None:
    """Run the pass and raise the typed
    :class:`~flinkml_tpu.precision.PrecisionValidationError` on any
    error-severity finding — the pre-compile gate every policy-threaded
    entry point calls (the FML5xx ``PlanValidationError`` shape)."""
    from flinkml_tpu.precision import PrecisionValidationError

    findings = list(extra_findings) + check_precision_fn(
        fn, *example_args, policy=policy, param_argnums=param_argnums,
        program=program, location=location, axis_env=axis_env,
    )
    errors = [f for f in findings if f.severity == "error"]
    if errors:
        raise PrecisionValidationError(
            f"program {program!r} failed precision-flow validation "
            f"against policy {policy.describe()}:\n"
            + "\n".join(f.render() for f in errors),
            findings=errors,
        )


# ---------------------------------------------------------------------------
# FML605 — policy / sharding-plan conflict
# ---------------------------------------------------------------------------


def check_policy_plan(
    policy: PrecisionPolicy,
    dtype_bytes: Optional[int] = None,
    plan_name: Optional[str] = None,
    location: Optional[str] = None,
) -> List[Finding]:
    """FML605 when a plan's HBM-budget math assumed a parameter width
    different from ``policy.params``. ``dtype_bytes`` is the width the
    plan validation (``infer_plan``/FML503) used."""
    if dtype_bytes is None:
        return []
    want = int(policy.params_dtype.itemsize)
    if int(dtype_bytes) == want:
        return []
    label = f"plan {plan_name!r}" if plan_name else "the sharding plan"
    return [Finding(
        "FML605",
        f"{label} budgets parameters at {int(dtype_bytes)} B/elem but the "
        f"policy stores params as {policy.params} ({want} B/elem) — the "
        "HBM footprint the plan validated is not the footprint that will "
        "exist",
        stage=plan_name, location=location,
        fix_hint="validate the plan with dtype_bytes = "
                 "np.dtype(policy.params).itemsize (and re-run infer_plan "
                 "— a budget that fit at 2 B may not fit at 4 B)",
    )]


# ---------------------------------------------------------------------------
# *.policy.json fixtures / configs
# ---------------------------------------------------------------------------


def _example_program(spec: Mapping):
    """Build a named example program for a policy file: ``(fn,
    example_args, param_argnums, axis_env)``. The trainer programs are
    the REAL in-repo step builders, so a fixture exercises the same
    jaxpr the product compiles."""
    import jax

    name = str(spec.get("name", ""))
    dim = int(spec.get("dim", 8))
    rows = int(spec.get("rows", 8))
    dtype = np.dtype(spec.get("dtype", "float32")) if \
        spec.get("dtype") != "bfloat16" else _bf16()

    if name in ("sgd_step", "adam_step"):
        from flinkml_tpu.sharding.apply import (
            init_linear_state,
            linear_step_fn,
        )

        optimizer = "sgd" if name == "sgd_step" else "adam"
        step = linear_step_fn(
            loss=str(spec.get("loss", "logistic")), optimizer=optimizer,
            dtype_name=np.dtype(dtype).name, learning_rate=0.1,
            momentum=0.9, reg_l2=0.0, reg_l1=0.0, policy=None,
        )
        state = init_linear_state(dim, optimizer, dtype)
        batch = jax.ShapeDtypeStruct((rows, dim), dtype)
        vec = jax.ShapeDtypeStruct((rows,), dtype)
        return step, (state, batch, vec, vec), (0,), None
    if name == "stray_constant_chain":
        const = np.float32(float(spec.get("constant", 1.5)))

        def chain(x):
            return x * const

        return chain, (jax.ShapeDtypeStruct((rows, dim), dtype),), (), None
    if name == "state_passthrough":
        # Pure identity: the ONLY thing checkable is how the state is
        # STORED (the invar dtypes) — isolates FML603 from FML601.
        def ident(state):
            return state

        state = {"coef": jax.ShapeDtypeStruct((dim,), dtype),
                 "momentum": jax.ShapeDtypeStruct((dim,), dtype)}
        return ident, (state,), (0,), None
    if name == "psum_gradient":
        axis = str(spec.get("axis", "data"))

        def grad_sync(g):
            return jax.lax.psum(g, axis)

        return (grad_sync, (jax.ShapeDtypeStruct((dim,), dtype),), (),
                [(axis, int(spec.get("axis_size", 8)))])
    if name == "int8_unscaled_matmul":
        # The FML606 shape: int8-quantized weights matmul'd while still
        # integer codes — the accumulator wraps and the scales never
        # apply. The good twin dequantizes first (see
        # docs/development/precision.md).
        import jax.numpy as jnp

        def unscaled(q, x):
            return jnp.dot(x, q)

        q = jax.ShapeDtypeStruct((dim, dim), np.int8)
        x = jax.ShapeDtypeStruct((rows, dim), np.int8)
        return unscaled, (q, x), (0,), None
    if name == "int8_state_passthrough":
        # The FML607 shape: int8-STORED params under whatever policy the
        # file declares — flagged unless the policy declares quant.
        def ident(state):
            return state

        state = {"coef_q": jax.ShapeDtypeStruct((dim, dim), np.int8),
                 "coef_scale": jax.ShapeDtypeStruct((dim,), np.float32)}
        return ident, (state,), (0,), None
    raise ValueError(
        f"unknown example program {name!r} (known: sgd_step, adam_step, "
        "stray_constant_chain, state_passthrough, psum_gradient, "
        "int8_unscaled_matmul, int8_state_passthrough)"
    )


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def check_policy_file(path: str) -> List[Finding]:
    """Validate a ``*.policy.json`` fixture/config:

    .. code-block:: json

        {"policy": {"name": "mixed", "compute": "bfloat16",
                    "accum": "float32", "params": "float32"},
         "program": {"name": "sgd_step", "dim": 8, "dtype": "bfloat16"},
         "plan": {"name": "fsdp", "dtype_bytes": 2}}

    ``program`` (optional) names an example program traced against the
    policy (FML601-604); ``plan`` (optional) supplies the width the
    plan's HBM math used (FML605). Unreadable or malformed files report
    one FML601 finding naming the path — the gate must fail loudly,
    not skip silently.
    """
    try:
        with open(path, "r") as fh:
            doc = json.load(fh)
        policy = PrecisionPolicy.from_json_dict(doc["policy"])
        program = doc.get("program")
        plan = doc.get("plan") or {}
    except (OSError, ValueError, KeyError, TypeError) as e:
        return [Finding(
            "FML601",
            f"precision-policy file {path} is unreadable or malformed: "
            f"{e!r}",
            location=path,
            fix_hint="see docs/development/precision.md for the "
                     "*.policy.json schema",
        )]
    findings: List[Finding] = []
    if program is not None:
        # The guard spans the TRACE too: example programs validate some
        # fields only when traced (e.g. the loss name inside the step),
        # and a trace-time error must become this file's one finding,
        # not a traceback that aborts the run with later targets
        # unchecked.
        try:
            fn, args, param_argnums, axis_env = _example_program(program)
            file_findings = check_precision_fn(
                fn, *args, policy=policy, param_argnums=param_argnums,
                program=str(program.get("name")), location=path,
                axis_env=axis_env,
            )
        except (ValueError, TypeError) as e:
            return [Finding(
                "FML601",
                f"precision-policy file {path} names a bad program: {e}",
                location=path,
                fix_hint="see docs/development/precision.md",
            )]
        findings.extend(file_findings)
    findings.extend(check_policy_plan(
        policy,
        dtype_bytes=plan.get("dtype_bytes"),
        plan_name=plan.get("name"),
        location=path,
    ))
    return findings


# ---------------------------------------------------------------------------
# FML106 — silent f64 promotion, through the same dtype-flow walk
# ---------------------------------------------------------------------------

_WIDE = np.dtype(np.float64)


def _widening_sites(jaxpr, out: List[str]) -> None:
    """Primitive names of eqns that produce float64 from all-narrower
    float operands — the exact point a silent promotion happens
    (recursive over sub-jaxprs)."""
    for eqn in jaxpr.eqns:
        outs = [v.aval.dtype for v in eqn.outvars if hasattr(v, "aval")]
        if any(np.dtype(d) == _WIDE for d in outs if _is_float(d)):
            in_floats = [
                np.dtype(a.aval.dtype) for a in eqn.invars
                if hasattr(a, "aval") and _is_float(a.aval.dtype)
            ]
            if in_floats and all(d != _WIDE for d in in_floats):
                out.append(eqn.primitive.name)
        for v in eqn.params.values():
            _walk_widening_param(v, out)


def _walk_widening_param(v: Any, out: List[str]) -> None:
    if hasattr(v, "eqns"):
        _widening_sites(v, out)
    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
        _widening_sites(v.jaxpr, out)
    elif isinstance(v, (tuple, list)):
        for item in v:
            _walk_widening_param(item, out)


def promotion_findings(
    closed,
    input_dtypes: Sequence,
    output_dtypes: Mapping[str, Any],
    stage: Optional[str] = None,
    location: Optional[str] = None,
) -> List[Finding]:
    """FML106 over one (possibly fused multi-stage) program jaxpr: every
    known float input is narrow but an output came back float64 — the
    widening happened inside, silently. The ONE code path behind both
    the per-stage validator check and the fused-run check. ``closed``
    (the jaxpr that localizes the first widening primitive for the
    message) may be a zero-arg CALLABLE — it is only invoked once a
    finding is certain, so the clean-pipeline common case never pays a
    trace for localization."""
    known_in = [np.dtype(d) for d in input_dtypes if d is not None]
    # Any non-float or already-wide known input legitimizes a float64
    # output (int64→float conversion gives f64 under x64) — bail, same
    # as the validator's original per-stage check.
    if not known_in or any(not _is_float(d) or d == _WIDE
                           for d in known_in):
        return []
    wide_outs = [
        name for name, d in output_dtypes.items()
        if d is not None and np.dtype(d) == _WIDE
    ]
    if not wide_outs:
        return []
    sites: List[str] = []
    if callable(closed):
        closed = closed()
    if closed is not None:
        _widening_sites(closed.jaxpr, sites)
    at = f" (widened at {sites[0]!r})" if sites else ""
    ins = ", ".join(sorted({str(d) for d in known_in}))
    return [Finding(
        "FML106",
        f"inputs are {ins} but output {name!r} is float64 "
        f"(silent promotion){at}",
        stage=stage, column=name, location=location,
        fix_hint="cast explicitly or preserve the input dtype; float64 "
                 "on the CPU fallback path doubles bandwidth and memory",
    ) for name in wide_outs]
