"""Pass 7 — static per-device memory-liveness analysis (FML70x).

HBM capacity is the axis the rest of the analyzer reasons about worst:
FML503 screens parameters + optimizer slots at one scalar width, blind
to per-leaf precision, the int8 tier, and every activation a program
materializes. This pass walks jaxprs device-free (``jax.make_jaxpr``,
recursing pjit/scan/while/cond exactly like the precision pass) and
computes a **per-device peak-live-bytes estimate** for a program under
a ``(ShardingPlan, quant tier)`` pair:

  - **parameters + optimizer slots** are sized per LEAF from the traced
    avals (the actual storage widths — a bf16-stored momentum costs
    2 B/elem, an int8 table 1 B/elem), sharded through the same per-dim
    ceil as :func:`~flinkml_tpu.sharding.plan.shard_slice_elems`, so
    this model, FML503, and the :class:`~flinkml_tpu.embeddings
    .EmbeddingTable` padded layout agree at every budget boundary;
  - **activation liveness** runs over the equation schedule: a value's
    buffer is live from the eqn that produces it to its last use, peak
    = the maximum of the live set over the schedule (undonated argument
    buffers are resident for the whole program — XLA cannot reuse a
    buffer the caller still owns);
  - **batch-sharded intermediates** divide their leading dim by the
    plan's batch-axes product (ceil) — the SPMD layout data-parallel
    activations actually get.

Rules:

  - **FML701** — the estimated peak exceeds the per-device HBM budget
    (the activation-aware generalization of FML503, which stays as the
    fast params-only screen).
  - **FML702** — a vocab-scale intermediate is materialized on the hot
    path: an eqn output carrying a full embedding-table extent (a
    one-hot densification, a full-table gather/psum/dequant) where the
    embedding contract promises batch-sized payloads. State OUTPUTS are
    exempt (a scatter-add'd new table is the update, not a leak).
  - **FML703** — a same-shape parameter/carry update whose input buffer
    is not donated: the old and new state coexist at exactly the peak
    moment, doubling state memory for the price of a missing
    ``donate_argnums``.
  - **FML704** — no quant tier in the f32 -> bf16 -> int8 ladder fits
    the budget under any candidate plan; the finding lists every tier's
    footprint (:class:`~flinkml_tpu.sharding.plan.NoFeasiblePlanError`
    rendered as a finding).

The estimate is **measured, not guessed**: ``bench.py``'s ``memory_cpu``
stage pins it against XLA's own ``Compiled.memory_analysis()``
(temp + argument + output bytes) on the fused 5-stage chain and the
plan-sharded SGD step, and CI trips outside a 0.5x-2.0x band.

Inputs come from live functions pre-compile (:func:`check_memory_fn`,
:func:`estimate_fn_memory`) or ``*.memory.json`` fixtures
(:func:`check_memory_file`, routed by ``python -m flinkml_tpu
.analysis``). See ``docs/development/static_analysis.md``.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import (
    Any,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from flinkml_tpu.analysis.findings import Finding
from flinkml_tpu.sharding.plan import (
    NoFeasiblePlanError,
    QUANT_TIER_LADDER,
    REPLICATED,
    PRESETS,
    ShardingPlan,
    _axis_sizes,
    human_bytes,
    infer_plan,
    is_embedding_param,
    shard_slice_elems,
)

#: Same-shape update leaves smaller than this are not worth a donation
#: finding: donating a scalar step counter saves nothing, and the loss
#: scalar would false-positive against it.
DONATION_MIN_ELEMS = 256

#: Leading extents below this never count as "vocab-scale" — a tiny
#: test table's whole-row intermediate is not the densification shape.
VOCAB_SCALE_MIN_ROWS = 1024


@dataclasses.dataclass(frozen=True)
class MemoryEstimate:
    """One program's per-device memory model under a plan.

    ``peak_bytes`` is the headline: the maximum, over the equation
    schedule, of resident (undonated arguments + already-produced
    outputs) plus live intermediates plus control-flow scratch.
    ``argument_bytes``/``output_bytes``/``param_bytes`` break the
    resident set down; ``temp_peak_bytes`` is the intermediate-only
    peak (the analogue of XLA's ``temp_size_in_bytes``)."""

    peak_bytes: int
    argument_bytes: int
    output_bytes: int
    param_bytes: int
    temp_peak_bytes: int

    def render(self) -> str:
        return (
            f"peak {human_bytes(self.peak_bytes)}/device "
            f"(arguments {human_bytes(self.argument_bytes)}, of which "
            f"params+slots {human_bytes(self.param_bytes)}; outputs "
            f"{human_bytes(self.output_bytes)}; intermediate peak "
            f"{human_bytes(self.temp_peak_bytes)})"
        )


def _dtype_itemsize(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        return 2 if "bfloat16" in str(dtype) else 4


def _is_var(atom) -> bool:
    # Literals are unhashable in some jax versions — never dict keys.
    return hasattr(atom, "aval") and type(atom).__name__ != "Literal"


class _LiveWalk:
    """One liveness walk over a closed jaxpr and its sub-jaxprs,
    accumulating the peak-live estimate and the FML702 sites."""

    def __init__(self, plan: ShardingPlan, axis_sizes: Mapping[str, int],
                 vocab_extents: frozenset):
        self.plan = plan
        self.axis_sizes = dict(axis_sizes)
        self.vocab_extents = vocab_extents
        batch = 1
        for axis in plan.batch_axes:
            batch *= int(self.axis_sizes.get(axis, 1))
        self.batch_factor = max(1, batch)
        # (primitive, shape, dtype) of every vocab-scale intermediate.
        self.vocab_sites: List[Tuple[str, Tuple[int, ...], str]] = []
        self._seen_sites: set = set()

    # -- sizing ------------------------------------------------------------
    def param_bytes(self, name: str, aval) -> int:
        """A named parameter leaf: sharded by its plan family spec."""
        elems = shard_slice_elems(
            self.plan, self.axis_sizes, name, aval.shape
        )
        return elems * _dtype_itemsize(aval.dtype)

    def value_bytes(self, aval) -> int:
        """An activation/intermediate: leading dim divides (ceil) by the
        plan's batch-axes product — the layout data-parallel activations
        get under SPMD; trailing dims stay whole."""
        shape = tuple(getattr(aval, "shape", ()))
        if not shape:
            return _dtype_itemsize(getattr(aval, "dtype", np.float32))
        elems = math.ceil(int(shape[0]) / self.batch_factor)
        for d in shape[1:]:
            elems *= int(d)
        return elems * _dtype_itemsize(aval.dtype)

    # -- FML702 ------------------------------------------------------------
    def _note_vocab_site(self, eqn, exempt_outvars: frozenset) -> None:
        if not self.vocab_extents:
            return
        for ov in eqn.outvars:
            if ov in exempt_outvars or not hasattr(ov, "aval"):
                continue
            shape = tuple(getattr(ov.aval, "shape", ()))
            hit = [d for d in shape if d in self.vocab_extents]
            if not hit:
                continue
            # One finding per offending SHAPE: a one-hot densification
            # drags a convert/transpose/dot train behind it, and six
            # findings for one leak is noise, not signal.
            key = shape
            if key in self._seen_sites:
                continue
            self._seen_sites.add(key)
            self.vocab_sites.append(
                (eqn.primitive.name, shape, str(ov.aval.dtype))
            )

    # -- the walk ----------------------------------------------------------
    def walk(self, jaxpr, invar_bytes: Sequence[int],
             freeable_invars: Sequence[bool],
             exempt_outvars: frozenset = frozenset()) -> Tuple[int, int]:
        """Peak live bytes of one (open) jaxpr given per-invar sizes.

        ``freeable_invars[i]`` marks invar ``i``'s buffer as freeable at
        its last use (donated argument, or an operand owned by the
        enclosing scope's schedule); undonated top-level arguments are
        resident to the end. An eqn's OUTPUT may reuse the buffer of a
        freeable operand dying at that eqn — XLA's buffer assignment
        does exactly this for the fused elementwise trains the 5-stage
        chain compiles to, and it is what ``donate_argnums`` buys for a
        state update (the new state is written over the old). Undonated
        arguments are never reusable (the caller still owns them) —
        which is why a missed donation shows up as a bigger peak
        (FML703). ``exempt_outvars`` are vars whose materialization is
        sanctioned state output (FML702 exemption). Returns
        ``(peak, temp_peak)`` where ``temp_peak`` excludes the resident
        argument floor."""
        last_use: Dict[Any, int] = {}
        for k, eqn in enumerate(jaxpr.eqns):
            for a in eqn.invars:
                if _is_var(a):
                    last_use[a] = k
        outvar_set = frozenset(v for v in jaxpr.outvars if _is_var(v))

        sizes: Dict[Any, int] = {}
        freeable: Dict[Any, bool] = {}
        live = 0
        for var, nbytes, free in zip(jaxpr.invars, invar_bytes,
                                     freeable_invars):
            sizes[var] = int(nbytes)
            freeable[var] = bool(free) and var not in outvar_set
            live += int(nbytes)
        resident_floor = sum(
            sizes[v] for v in jaxpr.invars if not freeable[v]
        )
        peak = live
        for k, eqn in enumerate(jaxpr.eqns):
            self._note_vocab_site(eqn, exempt_outvars)
            out_bytes = 0
            for ov in eqn.outvars:
                if not hasattr(ov, "aval"):
                    continue
                nbytes = self.value_bytes(ov.aval)
                sizes[ov] = nbytes
                freeable[ov] = ov not in outvar_set
                out_bytes += nbytes
            scratch = self._eqn_scratch(eqn, sizes, exempt_outvars)
            # Buffer reuse: a freeable operand dying HERE donates its
            # buffer to the output (up to the output's size).
            dying = sum(
                sizes[a]
                for a in set(a for a in eqn.invars if _is_var(a))
                if last_use.get(a) == k and freeable.get(a, False)
                and a in sizes
            )
            peak = max(peak, live + max(0, out_bytes - dying) + scratch)
            live += out_bytes
            for a in eqn.invars:
                if (_is_var(a) and last_use.get(a) == k
                        and freeable.get(a, False) and a in sizes):
                    live -= sizes.pop(a)
                    freeable[a] = False  # freed once
        peak = max(peak, live)
        return peak, max(0, peak - resident_floor)

    def _eqn_scratch(self, eqn, sizes: Dict[Any, int],
                     exempt_outvars: frozenset) -> int:
        """Extra scratch a control-flow/call eqn needs beyond its
        operand and output buffers: the sub-program's own intermediate
        peak. Operand buffers alias the outer live set, so the inner
        peak is discounted by the operand bytes already counted."""
        name = eqn.primitive.name
        params = eqn.params
        operand_bytes = sum(
            sizes.get(a, 0) for a in eqn.invars if _is_var(a)
        )
        inner_exempt = frozenset()
        if any(ov in exempt_outvars for ov in eqn.outvars):
            # Direct chain: a pjit whose outputs ARE the program's state
            # outputs passes the exemption to its sub-jaxpr outvars.
            pass  # handled per-branch below via _map_exempt

        def sub_peak(sub_jaxpr, invar_bytes, exempt=frozenset()):
            inner_free = [True] * len(sub_jaxpr.invars)
            p, _ = self.walk(sub_jaxpr, invar_bytes, inner_free, exempt)
            return p

        def _map_exempt(sub_jaxpr):
            return frozenset(
                iv for iv, ov in zip(sub_jaxpr.outvars, eqn.outvars)
                if _is_var(iv) and ov in exempt_outvars
            ) or inner_exempt

        if name == "scan":
            closed = params["jaxpr"]
            sub = closed.jaxpr
            inner_bytes = [
                self.value_bytes(v.aval) if hasattr(v, "aval") else 0
                for v in sub.invars
            ]
            inner = sub_peak(sub, inner_bytes, _map_exempt(sub))
        elif name == "while":
            body = params["body_jaxpr"].jaxpr
            cond = params["cond_jaxpr"].jaxpr
            body_bytes = [
                self.value_bytes(v.aval) if hasattr(v, "aval") else 0
                for v in body.invars
            ]
            cond_bytes = [
                self.value_bytes(v.aval) if hasattr(v, "aval") else 0
                for v in cond.invars
            ]
            inner = max(sub_peak(body, body_bytes, _map_exempt(body)),
                        sub_peak(cond, cond_bytes))
        elif name == "cond":
            inner = 0
            for br in params["branches"]:
                sub = br.jaxpr
                inner_bytes = [
                    self.value_bytes(v.aval) if hasattr(v, "aval") else 0
                    for v in sub.invars
                ]
                inner = max(inner,
                            sub_peak(sub, inner_bytes, _map_exempt(sub)))
        elif "jaxpr" in params and hasattr(
                getattr(params["jaxpr"], "jaxpr", None), "eqns"):
            sub = params["jaxpr"].jaxpr  # pjit / closed_call wrappers
            inner_bytes = [
                (sizes[a] if _is_var(a) and a in sizes
                 else self.value_bytes(v.aval) if hasattr(v, "aval") else 0)
                for a, v in zip(eqn.invars, sub.invars)
            ]
            inner = sub_peak(sub, inner_bytes, _map_exempt(sub))
        elif "call_jaxpr" in params:
            cj = params["call_jaxpr"]
            sub = getattr(cj, "jaxpr", cj)
            inner_bytes = [
                (sizes[a] if _is_var(a) and a in sizes
                 else self.value_bytes(v.aval) if hasattr(v, "aval") else 0)
                for a, v in zip(eqn.invars, sub.invars)
            ]
            inner = sub_peak(sub, inner_bytes, _map_exempt(sub))
        else:
            return 0
        return max(0, inner - operand_bytes)


def _invar_names_roles(closed, example_args, param_argnums):
    """Per-invar (role, name) from the example pytrees — the precision
    pass's labeling, shared verbatim so both passes name leaves the same
    way (and fall back to unlabeled on a structure mismatch)."""
    import jax

    param_set = set(param_argnums)
    roles: List[str] = []
    names: List[str] = []
    for i, arg in enumerate(example_args):
        leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(arg)
        role = "param" if i in param_set else "data"
        for path, _leaf in leaves_with_paths:
            roles.append(role)
            from flinkml_tpu.analysis.precision import _path_name

            names.append(_path_name(path) or f"arg{i}")
    if len(roles) != len(closed.jaxpr.invars):
        roles = ["data"] * len(closed.jaxpr.invars)
        names = [f"arg{i}" for i in range(len(closed.jaxpr.invars))]
    return roles, names


def estimate_closed_jaxpr(
    closed,
    plan: Optional[ShardingPlan] = None,
    mesh: Optional[Any] = None,
    invar_roles: Optional[Sequence[str]] = None,
    invar_names: Optional[Sequence[str]] = None,
    donate_argnums: Sequence[int] = (),
) -> Tuple[MemoryEstimate, List[Tuple[str, Tuple[int, ...], str]]]:
    """The peak-live estimate for one closed jaxpr, plus the vocab-scale
    sites the walk recorded (for FML702). ``invar_roles`` labels each
    invar ``"param"``/``"data"``; ``donate_argnums`` indexes INVARS
    whose buffers the caller donates."""
    plan = plan if plan is not None else REPLICATED
    axis_sizes = _axis_sizes(mesh) if mesh is not None else {}
    jaxpr = closed.jaxpr
    n = len(jaxpr.invars)
    roles = list(invar_roles or [])
    roles += ["data"] * (n - len(roles))
    names = list(invar_names or [])
    names += [f"arg{i}" for i in range(len(names), n)]
    donated = set(int(i) for i in donate_argnums)

    vocab_extents = frozenset(
        int(v.aval.shape[0])
        for v, role, name in zip(jaxpr.invars, roles, names)
        if role == "param" and is_embedding_param(name)
        and hasattr(v, "aval") and len(getattr(v.aval, "shape", ())) >= 2
        and int(v.aval.shape[0]) >= VOCAB_SCALE_MIN_ROWS
    )
    walk = _LiveWalk(plan, axis_sizes, vocab_extents)

    invar_bytes: List[int] = []
    param_bytes = 0
    for i, (var, role, name) in enumerate(zip(jaxpr.invars, roles, names)):
        if not hasattr(var, "aval"):
            invar_bytes.append(0)
            continue
        if role == "param":
            nbytes = walk.param_bytes(name, var.aval)
            param_bytes += nbytes
        else:
            nbytes = walk.value_bytes(var.aval)
        invar_bytes.append(nbytes)
    freeable = [i in donated for i in range(n)]
    exempt = frozenset(v for v in jaxpr.outvars if _is_var(v))
    peak, temp_peak = walk.walk(jaxpr, invar_bytes, freeable, exempt)

    out_bytes = 0
    for v in jaxpr.outvars:
        if hasattr(v, "aval"):
            out_bytes += walk.value_bytes(v.aval)
    estimate = MemoryEstimate(
        peak_bytes=int(peak),
        argument_bytes=int(sum(invar_bytes)),
        output_bytes=int(out_bytes),
        param_bytes=int(param_bytes),
        temp_peak_bytes=int(temp_peak),
    )
    return estimate, walk.vocab_sites


def estimate_fn_memory(
    fn,
    *example_args,
    plan: Optional[ShardingPlan] = None,
    mesh: Optional[Any] = None,
    param_argnums: Sequence[int] = (),
    donate_argnums: Sequence[int] = (),
    axis_env: Optional[Sequence[Tuple[str, int]]] = None,
) -> MemoryEstimate:
    """Trace ``fn`` abstractly (no compile, no device) and estimate its
    per-device peak live bytes under ``plan``. ``param_argnums`` marks
    the state arguments (sized by their plan family; optimizer slots are
    just more param leaves, so the slot count is whatever the actual
    state pytree holds); ``donate_argnums`` marks arguments whose
    buffers the caller donates (freed at last use instead of resident
    to the end)."""
    import jax

    closed = jax.make_jaxpr(fn, axis_env=list(axis_env or ()))(*example_args)
    roles, names = _invar_names_roles(closed, example_args, param_argnums)
    # Map ARGUMENT donation to INVAR donation through the same flatten.
    donated_invars: List[int] = []
    donate_set = set(donate_argnums)
    idx = 0
    for i, arg in enumerate(example_args):
        n_leaves = len(jax.tree_util.tree_leaves(arg))
        if i in donate_set:
            donated_invars.extend(range(idx, idx + n_leaves))
        idx += n_leaves
    if idx != len(closed.jaxpr.invars):
        donated_invars = []
    estimate, _ = estimate_closed_jaxpr(
        closed, plan=plan, mesh=mesh, invar_roles=roles,
        invar_names=names, donate_argnums=donated_invars,
    )
    return estimate


def check_memory_fn(
    fn,
    *example_args,
    plan: Optional[ShardingPlan] = None,
    mesh: Optional[Any] = None,
    hbm_budget_bytes: Optional[int] = None,
    param_argnums: Sequence[int] = (),
    donate_argnums: Sequence[int] = (),
    program: str = "program",
    location: Optional[str] = None,
    axis_env: Optional[Sequence[Tuple[str, int]]] = None,
) -> List[Finding]:
    """The full pass-7 check over one live function: FML701 (budget),
    FML702 (vocab-scale intermediates), FML703 (undonated same-shape
    state updates)."""
    import jax

    closed = jax.make_jaxpr(fn, axis_env=list(axis_env or ()))(*example_args)
    roles, names = _invar_names_roles(closed, example_args, param_argnums)
    donate_set = set(donate_argnums)
    donated_invars: List[int] = []
    idx = 0
    for i, arg in enumerate(example_args):
        n_leaves = len(jax.tree_util.tree_leaves(arg))
        if i in donate_set:
            donated_invars.extend(range(idx, idx + n_leaves))
        idx += n_leaves
    if idx != len(closed.jaxpr.invars):
        donated_invars = []
    estimate, vocab_sites = estimate_closed_jaxpr(
        closed, plan=plan, mesh=mesh, invar_roles=roles,
        invar_names=names, donate_argnums=donated_invars,
    )
    findings: List[Finding] = []
    plan_name = (plan or REPLICATED).name

    if hbm_budget_bytes is not None and \
            estimate.peak_bytes > int(hbm_budget_bytes):
        findings.append(Finding(
            "FML701",
            f"program {program!r} under plan {plan_name!r}: estimated "
            f"{estimate.render()} exceeds the per-device HBM budget of "
            f"{human_bytes(hbm_budget_bytes)}",
            stage=program, location=location,
            fix_hint="shard further (a larger fsdp x tp product), drop "
                     "to a narrower quant tier (infer_plan's "
                     "quant_tiers= mode walks f32 -> bf16 -> int8), "
                     "donate the state buffers, or raise the budget",
        ))

    for prim, shape, dtype in vocab_sites:
        findings.append(Finding(
            "FML702",
            f"program {program!r}: {prim} materializes a vocab-scale "
            f"intermediate of shape {shape} ({dtype}) on the hot path — "
            "the embedding contract promises batch-sized payloads "
            "(lookup gathers rows, the gradient exchange moves "
            "batch-many rows), never a full-table value",
            stage=program, location=location,
            fix_hint="gather/scatter by ids instead of densifying "
                     "(one_hot @ table and full-table psum are the "
                     "shapes flinkml_tpu.embeddings exists to avoid)",
        ))

    # FML703 — same-shape state update without donation, at top level.
    donated = set(donated_invars)
    out_avals = [
        (tuple(v.aval.shape), str(v.aval.dtype))
        for v in closed.jaxpr.outvars if hasattr(v, "aval")
    ]
    flagged: set = set()
    for i, (var, role, name) in enumerate(
            zip(closed.jaxpr.invars, roles, names)):
        if role != "param" or i in donated or not hasattr(var, "aval"):
            continue
        shape = tuple(var.aval.shape)
        elems = 1
        for d in shape:
            elems *= int(d)
        if elems < DONATION_MIN_ELEMS or name in flagged:
            continue
        if (shape, str(var.aval.dtype)) in out_avals:
            flagged.add(name)
            findings.append(Finding(
                "FML703",
                f"program {program!r}: state leaf {name!r} "
                f"({shape}, {var.aval.dtype}) has a same-shape output "
                "(its update) but its input buffer is not donated — the "
                "old and new state coexist at the peak moment, doubling "
                "state memory",
                stage=program, column=name, location=location,
                fix_hint="pass donate_argnums for the state argument "
                         "(jax.jit(step, donate_argnums=(0,))) so XLA "
                         "writes the update in place",
            ))
    return findings


# ---------------------------------------------------------------------------
# FML704 — the tier ladder has no fitting rung
# ---------------------------------------------------------------------------


def check_tier_ladder(
    mesh,
    param_shapes: Mapping[str, Sequence[int]],
    hbm_budget_bytes: int,
    optimizer_slots: int = 1,
    tiers: Sequence[str] = QUANT_TIER_LADDER,
    location: Optional[str] = None,
) -> List[Finding]:
    """FML704 when no ``(plan, quant_tier)`` pair fits the budget — the
    finding carries :func:`~flinkml_tpu.sharding.plan.infer_plan`'s full
    per-tier footprint listing so the operator sees exactly how far off
    every rung of the ladder is."""
    try:
        infer_plan(
            mesh, param_shapes, hbm_budget_bytes,
            optimizer_slots=optimizer_slots, quant_tiers=tuple(tiers),
        )
    except NoFeasiblePlanError as e:
        return [Finding(
            "FML704",
            str(e),
            location=location,
            fix_hint="grow the mesh's fsdp/tp product, shrink the "
                     "vocab/model, or raise the per-device budget — "
                     "quantization alone cannot close this gap",
        )]
    return []


# ---------------------------------------------------------------------------
# Serving load-time gate
# ---------------------------------------------------------------------------


def estimate_serving_bytes(
    model: Any,
    schema: Mapping[str, Tuple[Any, Tuple[int, ...]]],
    max_batch_rows: int,
    policy: Optional[Any] = None,
) -> int:
    """A device-free upper-ish estimate of one serving replica's HBM
    footprint: every learned model array at the width the engine's
    precision tier actually stores it (int8 codes + scales under a
    ``quant`` policy, ``policy.compute`` under a mixed policy — the
    fused executor casts constants in-program), plus three live
    batch-sized buffers (input, one intermediate, output) at the
    largest dispatch bucket. The :class:`~flinkml_tpu.serving.engine
    .ServingEngine` load-time budget gate consumes this BEFORE the
    active-model flip, so a refused swap keeps the old model serving."""
    from flinkml_tpu.precision import quantizable, resolve_policy
    from flinkml_tpu.recovery.sentinel import _iter_stage_arrays

    policy = resolve_policy(policy)
    const_bytes = 0
    for _name, arr in _iter_stage_arrays(model):
        a = np.asarray(arr)
        if policy is not None and policy.quant == "int8" \
                and quantizable(a):
            cols = int(a.shape[-1]) if a.ndim >= 2 else 1
            const_bytes += a.size + 4 * cols
        elif policy is not None and policy.mixed:
            const_bytes += a.size * int(policy.compute_dtype.itemsize)
        else:
            const_bytes += int(a.nbytes)
    batch_bytes = 0
    for _col, (dtype, trailing) in schema.items():
        elems = int(max_batch_rows)
        for d in trailing:
            elems *= int(d)
        width = (
            int(policy.compute_dtype.itemsize)
            if policy is not None and policy.mixed
            else _dtype_itemsize(dtype)
        )
        batch_bytes += elems * width
    return int(const_bytes + 3 * batch_bytes)


# ---------------------------------------------------------------------------
# *.memory.json fixtures / configs
# ---------------------------------------------------------------------------


def _probe_program(spec: Mapping):
    """Build the probe named by ``spec`` — ``(fn, example_args,
    param_argnums, donate_argnums)``. The trainer probes are the REAL
    in-repo step builders (the ``*.policy.json`` precedent), so a
    fixture exercises the same jaxpr the product compiles.

    ``sgd_step``/``adam_step``: :func:`~flinkml_tpu.sharding.apply
    .linear_step_fn` over the real optimizer state (``donate`` declares
    whether the state buffer is donated — ``false`` is the FML703
    shape). ``embedding_lookup``: the batch-sized contract (clean).
    ``embedding_dense_grad``: the one-hot densified gradient — the
    FML702 shape.
    """
    import jax
    import jax.numpy as jnp

    name = str(spec.get("name", ""))
    dim = int(spec.get("dim", 8))
    rows = int(spec.get("rows", 8))
    dtype = np.dtype(str(spec.get("dtype", "float32")))

    if name in ("sgd_step", "adam_step"):
        from flinkml_tpu.sharding.apply import (
            init_linear_state,
            linear_step_fn,
        )

        optimizer = "sgd" if name == "sgd_step" else "adam"
        step = linear_step_fn(
            loss=str(spec.get("loss", "logistic")), optimizer=optimizer,
            dtype_name=dtype.name, learning_rate=0.1, momentum=0.9,
            reg_l2=0.0, reg_l1=0.0, policy=None,
        )
        state = init_linear_state(dim, optimizer, dtype)
        batch = jax.ShapeDtypeStruct((rows, dim), dtype)
        vec = jax.ShapeDtypeStruct((rows,), dtype)
        donate = (0,) if bool(spec.get("donate", False)) else ()
        return step, (state, batch, vec, vec), (0,), donate
    if name == "embedding_lookup":
        vocab = int(spec.get("vocab", 4096))

        def lookup(state, ids):
            return jnp.take(state["emb/embedding"], ids, axis=0)

        table = jax.ShapeDtypeStruct((vocab, dim), dtype)
        ids = jax.ShapeDtypeStruct((rows,), np.int32)
        return lookup, ({"emb/embedding": table}, ids), (0,), ()
    if name == "embedding_dense_grad":
        vocab = int(spec.get("vocab", 4096))

        def dense_grad(state, ids, grad):
            table = state["emb/embedding"]
            onehot = jax.nn.one_hot(ids, table.shape[0],
                                    dtype=table.dtype)
            return {"emb/embedding": table + onehot.T @ grad}

        table = jax.ShapeDtypeStruct((vocab, dim), dtype)
        ids = jax.ShapeDtypeStruct((rows,), np.int32)
        grad = jax.ShapeDtypeStruct((rows, dim), dtype)
        return dense_grad, ({"emb/embedding": table}, ids, grad), (0,), ()
    raise ValueError(
        f"unknown memory probe program {name!r} (known: sgd_step, "
        "adam_step, embedding_lookup, embedding_dense_grad)"
    )


def _resolve_plan(raw) -> ShardingPlan:
    if raw is None:
        return REPLICATED
    if isinstance(raw, str):
        try:
            return PRESETS[raw]
        except KeyError:
            raise ValueError(
                f"unknown plan preset {raw!r} (presets: {sorted(PRESETS)})"
            ) from None
    return ShardingPlan.from_json_dict(raw)


def check_memory_file(path: str) -> List[Finding]:
    """Validate a ``*.memory.json`` fixture/config:

    .. code-block:: json

        {"mesh": {"data": 1, "fsdp": 4, "tp": 2},
         "plan": "embedding",
         "hbm_budget_bytes": 1048576,
         "program": {"name": "sgd_step", "dim": 65536, "rows": 64,
                     "donate": false},
         "param_shapes": {"emb/embedding": [1048576, 64]},
         "optimizer_slots": 1,
         "tiers": ["float32", "bfloat16", "int8"]}

    ``program`` (optional) names a probe traced under the plan and
    checked for FML701/702/703 against the budget; ``tiers`` (optional,
    with ``param_shapes``) walks the quant ladder and reports FML704
    when no tier fits. ``plan`` is a preset name or a full plan object.
    Unreadable or malformed files report one FML701 finding naming the
    path — the gate must fail loudly, not skip silently.
    """
    try:
        with open(path, "r") as fh:
            doc = json.load(fh)
        plan = _resolve_plan(doc.get("plan"))
        mesh = {str(k): int(v) for k, v in (doc.get("mesh") or {}).items()}
        budget = doc.get("hbm_budget_bytes")
        program = doc.get("program")
        shapes = {
            str(k): tuple(int(d) for d in v)
            for k, v in (doc.get("param_shapes") or {}).items()
        }
        slots = int(doc.get("optimizer_slots", 1))
        tiers = doc.get("tiers")
        if program is None and tiers is None:
            raise ValueError(
                "a *.memory.json target needs a 'program' probe, a "
                "'tiers' ladder check, or both"
            )
    except (OSError, ValueError, KeyError, TypeError) as e:
        return [Finding(
            "FML701",
            f"memory file {path} is unreadable or malformed: {e!r}",
            location=path,
            fix_hint="see docs/development/static_analysis.md for the "
                     "*.memory.json schema",
        )]
    findings: List[Finding] = []
    if program is not None:
        try:
            fn, args, param_argnums, donate = _probe_program(program)
            findings.extend(check_memory_fn(
                fn, *args, plan=plan, mesh=mesh,
                hbm_budget_bytes=budget, param_argnums=param_argnums,
                donate_argnums=donate,
                program=str(program.get("name")), location=path,
            ))
        except (ValueError, TypeError) as e:
            return [Finding(
                "FML701",
                f"memory file {path} names a bad probe program: {e}",
                location=path,
                fix_hint="see docs/development/static_analysis.md",
            )]
    if tiers is not None and shapes and budget is not None:
        findings.extend(check_tier_ladder(
            mesh, shapes, int(budget), optimizer_slots=slots,
            tiers=[str(t) for t in tiers], location=path,
        ))
    return findings
