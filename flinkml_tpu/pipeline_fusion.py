"""Fused device-resident pipeline execution.

The per-stage transform path pays, for an N-stage :class:`PipelineModel`,
N host→device uploads, N separate XLA dispatches, and N device→host
downloads — exactly the per-stage materialization the columnar data plane
exists to avoid. This module makes the *pipeline* the unit of compilation:
a run of kernel-capable stages (stages exposing
:meth:`flinkml_tpu.api.AlgoOperator.transform_kernel`) compiles into ONE
``jax.jit`` program; intermediate columns never leave device memory, and
the result :class:`~flinkml_tpu.table.Table` carries device-resident output
columns that materialize to host lazily.

Compile cache and row bucketing
-------------------------------

Programs are cached under a key of

  ``(chain fingerprint, external input col specs, constant specs,
  requested output columns, bucket, policy, kernel backend)``

where the chain fingerprint is the tuple of each kernel's ``fingerprint``,
input col specs are ``(name, dtype, trailing shape)`` of every column the
run reads from the table, constant specs are the shapes/dtypes of each
kernel's model data, and ``bucket`` is the row count padded up to a power
of two (≥ :data:`MIN_ROW_BUCKET`). Padding rows to the bucket plus a
float32 validity mask means one compiled program serves every batch size
within the bucket — repeated ``transform`` calls with differing row counts
cause **zero recompiles** until a call crosses a power-of-two boundary.
Padded rows may compute garbage; the executor slices them off before
returning, and kernels with cross-row reductions apply the mask.

Model data (coefficients, fitted statistics) is passed as *traced
arguments*, so refreshing model data — or loading a different model of the
same shape — reuses the compiled program.

Lazy intermediates (dead-code elimination)
------------------------------------------

A run's eager program returns only its *terminal* columns (those no later
kernel of the run consumes); XLA dead-code-eliminates the rest, so unread
intermediate columns are never even written to memory. Intermediates land
in the result table as :class:`~flinkml_tpu.table.LazyDeviceColumn`: shape
and dtype come from an abstract trace, and the first read executes a
DCE'd program for just that column through the same compile cache. Typical
inference (read the prediction column only) therefore costs one program
that computes nothing it doesn't need.

Precision: programs trace and execute under ``jax.experimental.enable_x64``
so kernels reproduce each stage's host-path dtypes exactly (scalers run in
float64 like their numpy transform; predict kernels capture the *ambient*
x64 flag at kernel-build time and cast to the same dtypes ``jnp.asarray``
would give the per-stage path under it). Fused output is bit-identical to
the per-stage path for exactly-rounded ops always, and for everything
under x64 (the framework's test/golden configuration — pinned by the test
suite). The one carve-out: under ambient float32, outputs of
``pin_inputs`` kernels (matmul/transcendental stages) are numerically
equivalent rather than bitwise — f32 matmul reassociation differs between
the bucket-padded fused shape and the exact-row per-stage shape.

Mixed precision (the FML6xx policy gate)
----------------------------------------

An active :class:`~flinkml_tpu.precision.PrecisionPolicy`
(:func:`set_policy` / :func:`precision_scope`; serving threads it via
``ServingConfig.precision``) changes the fused program in exactly the
declared way: every float external input column and every float model
constant is cast to ``policy.compute`` at the program boundary (the
upload stays at storage width; the savings are device-side), the
validity mask is built at ``policy.compute``, and kernel math follows
jax dtype propagation from there. The policy joins BOTH cache keys —
program and abstract-spec — so a bf16 and an f32 program never alias
one executable. Every fresh cache key is validated against the policy
by the FML6xx precision-flow pass
(:mod:`flinkml_tpu.analysis.precision`) BEFORE the program is built:
a chain whose kernels accumulate below ``policy.accum`` (or smuggle a
strong wide constant into the compute region) raises
:class:`~flinkml_tpu.precision.PrecisionValidationError` instead of
compiling. No active policy (the default) leaves every path untouched.

Kernel backend (the Pallas gate)
--------------------------------

Each program's chain lowers through one of two backends: the plain
``jax.jit`` XLA path (default), or ONE row-tiled Pallas kernel per
bucket (:mod:`flinkml_tpu.kernels.chain`), selected by the kernel gate
(``FLINKML_TPU_KERNELS`` env var > the autotune table's
``kernel_backend_fused_chain`` knob > ``"xla"``). The backend joins
BOTH the in-memory program key and the AOT compile-cache identity, so
a Pallas program can never alias an XLA one, and the FML6xx pre-compile
validation always runs against the XLA-reference chain (identical math
— the Pallas body executes the same kernel fns). Unsupported
dtypes/shapes refuse loudly on an explicit request and fall back with
one warning on a table-chosen backend
(:mod:`flinkml_tpu.kernels._gate`).

Instrumentation (``metrics.group("pipeline.fusion")``): ``compiles`` /
``cache_hits`` / ``pallas_compiles`` counters, ``fused_segments`` / ``fused_stages``,
``host_to_device_transfers`` / ``host_to_device_bytes``, and
``host_transfer_bytes_avoided`` (bytes of intermediate columns that would
have round-tripped host↔device under per-stage execution). Tests can hook
compilation via :data:`on_compile`.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Callable, Dict, List, NamedTuple, Sequence, Tuple

import numpy as np

from flinkml_tpu.api import ColumnKernel
from flinkml_tpu.linalg import next_pow2
from flinkml_tpu.table import LazyDeviceColumn, PaddedDeviceColumn, Table
from flinkml_tpu.utils.metrics import metrics

#: Smallest row bucket: tiny tables all share one program.
MIN_ROW_BUCKET = 8

#: Callbacks invoked with the cache key whenever a new fused program is
#: compiled (test hook: assert zero retraces across row counts).
on_compile: List[Callable[[Tuple], None]] = []

_CACHE: Dict[Tuple, Callable] = {}
_LOCK = threading.Lock()
_ENABLED = [True]
# Per-THREAD policy slot: a ServingEngine scopes its own dispatcher
# thread's dispatches without clobbering a concurrently-transforming
# trainer thread's ambient policy (and vice versa).
_POLICY = threading.local()


def enabled() -> bool:
    """Fusion master switch: the ``FLINKML_TPU_DISABLE_FUSION=1`` env var or
    :func:`set_enabled` (used by the bench's unfused baseline) turns the
    fused executor off, restoring pure per-stage execution."""
    return _ENABLED[0] and os.environ.get("FLINKML_TPU_DISABLE_FUSION") != "1"


def set_enabled(flag: bool) -> None:
    _ENABLED[0] = bool(flag)


def active_policy():
    """The :class:`~flinkml_tpu.precision.PrecisionPolicy` fused programs
    compile and validate under on THIS thread (None: plain full-width
    execution). Thread-scoped: each dispatching thread carries its own
    slot, so a serving engine's policy never leaks into a concurrent
    trainer thread's transforms."""
    return getattr(_POLICY, "value", None)


def set_policy(policy) -> None:
    """Install a :class:`~flinkml_tpu.precision.PrecisionPolicy` (object,
    preset name, JSON dict, or None) as THIS thread's fused-executor
    policy. Prefer :func:`precision_scope` for bounded use."""
    from flinkml_tpu.precision import resolve_policy

    _POLICY.value = resolve_policy(policy)


class precision_scope:
    """Context manager scoping an ambient fused-executor policy:

    .. code-block:: python

        with pipeline_fusion.precision_scope("mixed_inference"):
            (out,) = model.transform(table)

    Every fused program compiled inside the scope is FML6xx-validated
    against the policy pre-compile and keyed by it (bf16/f32 programs
    never alias); programs compiled OUTSIDE the scope are untouched and
    untouchable from inside (distinct cache keys). The scope is
    THREAD-scoped (enter/exit on the thread that transforms), so
    concurrent threads — a serving dispatcher beside a training loop —
    never clobber each other's policy."""

    def __init__(self, policy):
        from flinkml_tpu.precision import resolve_policy

        self._policy = resolve_policy(policy)
        self._prev = None

    def __enter__(self):
        self._prev = active_policy()
        _POLICY.value = self._policy
        return self._policy

    def __exit__(self, *exc):
        _POLICY.value = self._prev
        return False


def reset_cache() -> None:
    """Drop every compiled program (tests; never needed in production).
    Also drops the active compile-cache store's in-MEMORY artifact layer
    — compile-counting tests expect a clean slate — while on-disk
    artifacts (the persistent cache) survive."""
    with _LOCK:
        _CACHE.clear()
    with _QUANT_LOCK:
        _QUANT_CONST_CACHE.clear()
    from flinkml_tpu import compile_cache

    store = compile_cache.active_store()
    if store is not None:
        store.drop_memory()


def compiled_program_count() -> int:
    """Number of compiled programs in the cache (shape-spec entries from
    the abstract trace don't count — they cost no compile)."""
    with _LOCK:
        return sum(1 for k in _CACHE if "__specs__" not in k)


def row_bucket(n: int) -> int:
    """Padded row count for ``n`` rows: next power of two, floored at
    :data:`MIN_ROW_BUCKET`."""
    return max(MIN_ROW_BUCKET, next_pow2(n))


class QuantizedConst(NamedTuple):
    """One int8 post-training-quantized model constant as the fused
    program receives it: the per-column absmax-scaled int8 buffer plus
    its float32 scales (:func:`flinkml_tpu.precision.quantize_absmax`).
    A NamedTuple so it rides the constant pytrees through jit/eval_shape
    unchanged; the chain body dequantizes it to ``policy.compute`` width
    in-program, where XLA fuses the two ops into the consumer."""

    q: Any
    scale: Any


def _quant_min_elems() -> int:
    """The int8 tier's minimum-constant-size threshold, with the
    standard gate precedence: explicit ``FLINKML_TPU_INT8_MIN_CONST``
    env var > the mesh-keyed ``int8_min_const_elems`` autotune knob >
    the static default — degraded to the static default on a
    non-numeric/non-positive value (the serving-knob contract: a table
    typo must not take the executor down; a bad EXPLICIT value is
    degraded too, logged by the table layer)."""
    from flinkml_tpu.autotune import tuned_default
    from flinkml_tpu.precision import INT8_MIN_CONST_ELEMS

    env = os.environ.get("FLINKML_TPU_INT8_MIN_CONST")
    if env is not None:
        try:
            v = int(env)
        except ValueError:
            v = 0
        if v >= 1:
            return v
        # An EXPLICIT-but-invalid override degrades to the STATIC
        # default (never silently to the table's value — that would be
        # a third party neither the operator nor the docs named),
        # logged once.
        if env not in _QUANT_ENV_WARNED:
            _QUANT_ENV_WARNED.add(env)
            from flinkml_tpu.utils.logging import get_logger

            get_logger("pipeline.fusion").warning(
                "FLINKML_TPU_INT8_MIN_CONST=%r is not a positive "
                "integer; using the static default %d",
                env, INT8_MIN_CONST_ELEMS,
            )
        return INT8_MIN_CONST_ELEMS
    try:
        v = int(tuned_default("int8_min_const_elems", INT8_MIN_CONST_ELEMS))
    except (TypeError, ValueError):
        return INT8_MIN_CONST_ELEMS
    return v if v >= 1 else INT8_MIN_CONST_ELEMS


_QUANT_ENV_WARNED: set = set()

# Quantized-constant memo: model constants are immutable per fitted
# model, but execute_kernel_chain runs per DISPATCH — re-running the
# absmax passes (abs/max/divide/rint/clip over every weight) on the
# serving hot path would tax exactly the tier sold as a bandwidth
# optimization. Keyed by the host array's identity (the strong ref in
# the value pins the object alive, so an id can never be reused while
# its entry exists); a refreshed model is a NEW array object and
# misses. Bounded TRUE LRU (hits refresh recency) — a hot model's
# constants stay resident while old models' entries (and their device
# buffers) age out.
_QUANT_CONST_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_QUANT_CONST_MAX = 128
_QUANT_LOCK = threading.Lock()


def warmup_transform(
    model,
    example: Table,
    row_counts: Sequence[int],
    output_cols: Sequence[str] = (),
) -> Tuple[List[int], Tuple[str, ...]]:
    """Precompile ``model.transform``'s fused programs for every row
    bucket covering ``row_counts``, so a latency-sensitive caller (the
    serving engine's load path) pays every compile up front and steady
    state is zero-retrace.

    ``example`` supplies the input schema: its host columns are tiled
    row-cyclically to each bucket's exact row count and pushed through
    the real ``transform`` path — the same cache keys production traffic
    will hit (same column specs, same constant specs, same requested
    outputs). ``output_cols`` (default: every column ``transform`` adds)
    are materialized to host afterwards, forcing any lazy-column program
    the caller will read. Returns ``(buckets, read_cols)`` — the sorted
    buckets warmed and the output columns read (the requested ones, or
    the discovered added columns: callers that defaulted ``output_cols``
    learn the schema without paying another transform).
    """
    buckets = sorted({row_bucket(int(n)) for n in row_counts})
    host_cols = {name: np.asarray(example.column(name))
                 for name in example.column_names}
    read = tuple(output_cols)
    for bucket in buckets:
        tiled = Table({
            name: np.resize(col, (bucket,) + col.shape[1:])
            for name, col in host_cols.items()
        })
        (out,) = model.transform(tiled)
        if not read:
            read = tuple(
                c for c in out.column_names if c not in example.column_names
            )
        for c in read:
            out.column(c)
    return buckets, read


def _dense_in_table(table: Table, name: str) -> bool:
    """Whether ``name`` is a column the executor can place on device."""
    if name not in table:
        return False
    if table.is_device_resident(name):
        return True
    return table.column(name).dtype.kind in "fiub"


def collect_run(table: Table, stages: Sequence, start: int):
    """Longest run of kernel-capable stages beginning at ``stages[start]``
    whose external inputs are dense columns of ``table`` (or products of
    earlier kernels in the run). Returns ``(kernels, next_index)`` —
    ``kernels`` empty when ``stages[start]`` cannot join a run."""
    kernels: List[ColumnKernel] = []
    produced: set = set()
    i = start
    while i < len(stages):
        kernel = stages[i].transform_kernel()
        if kernel is None:
            break
        if any(
            c not in produced and not _dense_in_table(table, c)
            for c in kernel.input_cols
        ):
            break
        kernels.append(kernel)
        produced.update(kernel.output_cols)
        i += 1
    return kernels, i


def external_inputs(kernels: Sequence[ColumnKernel]) -> List[str]:
    """Columns a run reads from the table (not produced inside the run),
    in first-use order."""
    ext: List[str] = []
    produced: set = set()
    for k in kernels:
        for c in k.input_cols:
            if c not in produced and c not in ext:
                ext.append(c)
        produced.update(k.output_cols)
    return ext


def _output_cols(kernels: Sequence[ColumnKernel]) -> List[str]:
    out: List[str] = []
    for k in kernels:
        for c in k.output_cols:
            if c not in out:
                out.append(c)
    return out


def _closure_outputs(kernels: Sequence[ColumnKernel],
                     requested: Sequence[str]) -> Tuple[str, ...]:
    """``requested`` plus the materialization pins its dependency closure
    demands: for every kernel with ``pin_inputs`` that the requested
    columns (transitively) depend on, the kernel's chain-produced input
    columns join the program outputs — materializing them pins the fusion
    boundary so the kernel's context-sensitive ops (transcendentals,
    matmuls) lower exactly as in the stand-alone per-stage program.
    Kernels outside the closure stay dead code."""
    producer = {}
    for j, k in enumerate(kernels):
        for c in k.output_cols:
            producer[c] = j
    needed: set = set()
    stack = [producer[c] for c in requested if c in producer]
    while stack:
        j = stack.pop()
        if j in needed:
            continue
        needed.add(j)
        stack.extend(
            producer[c] for c in kernels[j].input_cols if c in producer
        )
    pins: List[str] = []
    for j in sorted(needed):
        if kernels[j].pin_inputs:
            for c in kernels[j].input_cols:
                if c in producer and c not in pins:
                    pins.append(c)
    return tuple(dict.fromkeys([*pins, *requested]))


def _chain_fn(kernels: Sequence[ColumnKernel], ext_names: Sequence[str],
              out_names: Sequence[str], bucket: int, policy=None):
    """The pure cols→cols chain function for ``kernels``, returning only
    ``out_names``. Constants arrive as traced arguments (sorted by name
    per kernel) so model-data value changes reuse the compiled
    executable, and the row count arrives as a traced scalar (the
    validity mask is built on device, so differing row counts within a
    bucket share one program AND allocate nothing host-side). Columns NOT
    in ``out_names`` — and every kernel feeding only such columns — are
    dead code XLA eliminates, which is how lazy intermediate columns cost
    nothing until someone reads them.

    A mixed ``policy`` casts every float input and constant down to
    ``policy.compute`` at the program boundary (the sanctioned
    step-boundary down-cast the FML6xx walker recognizes) and builds the
    validity mask at ``policy.compute`` so the mask multiply doesn't
    silently promote the whole chain back to f32. A quantized policy
    (``policy.quant == "int8"``) receives eligible model constants as
    :class:`QuantizedConst` pairs and dequantizes them to
    ``policy.compute`` here — int8 in HBM/transfer, float in the math,
    never an integer accumulation (the FML606 contract)."""
    import jax
    import jax.numpy as jnp

    kernels = tuple(kernels)
    ext_names = tuple(ext_names)
    out_names = tuple(out_names)
    # A mixed policy (compute narrower than params) casts every float
    # boundary value to compute; the QUANTIZED tier does too (its
    # declared compute width is where the dequant-fused math runs —
    # under x64, f64 activations must come down to f32 or the tier
    # silently runs double-width). A plain FULL/None policy stays
    # inert (the PR 10 contract: no policy, no change).
    casts = policy is not None and (policy.mixed or policy.quant is not None)
    mask_dt = (
        jnp.dtype(policy.compute_dtype) if casts else jnp.float32
    )
    compute_dt = (
        jnp.dtype(policy.compute_dtype) if policy is not None
        else jnp.float32
    )

    def _to_compute(v):
        if casts and jnp.issubdtype(v.dtype, jnp.floating) \
                and v.dtype != mask_dt:
            return v.astype(mask_dt)
        return v

    def _const_to_compute(v):
        if isinstance(v, QuantizedConst):
            # Dequant at compute width, in-program: XLA fuses the
            # convert+mul into the consuming matmul/elementwise op.
            return v.q.astype(compute_dt) * v.scale.astype(compute_dt)
        return _to_compute(v)

    def run(ext_vals, const_vals, n_valid):
        # Kernels resolve active_policy() at TRACE time, and this body
        # runs at trace time — on whatever thread first calls the jitted
        # program. A lazy column's deferred trace (another thread, or
        # after the scope exited) would otherwise compile under the
        # READER's ambient policy while cached and validated under the
        # CAPTURED key, so the captured policy is pinned for the trace.
        prev = active_policy()
        _POLICY.value = policy
        try:
            valid = (jnp.arange(bucket) < n_valid).astype(mask_dt)
            ext_vals = tuple(_to_compute(v) for v in ext_vals)
            const_vals = tuple(
                tuple(_const_to_compute(v) for v in cv) for cv in const_vals
            )
            cols = dict(zip(ext_names, ext_vals))
            last = len(kernels) - 1
            for i, (kernel, cv) in enumerate(zip(kernels, const_vals)):
                consts = dict(zip(sorted(kernel.constants), cv))
                outs = kernel.fn(
                    {c: cols[c] for c in kernel.input_cols}, consts, valid
                )
                if i != last:
                    # Pin per-stage rounding: without the barrier XLA's
                    # algebraic simplifier rewrites across stage
                    # boundaries (e.g. two chained scaler divisions
                    # (x/s1)/s2 become x/(s1*s2)), breaking the
                    # bit-parity contract with the per-stage path. Still
                    # ONE program / one dispatch; only cross-stage op
                    # rewriting is fenced.
                    outs = jax.lax.optimization_barrier(outs)
                cols.update(outs)
            return {c: cols[c] for c in out_names}
        finally:
            _POLICY.value = prev

    return run


def _validate_chain(chain, ext_vals, const_vals, kernels, policy) -> None:
    """The fused executor's pre-compile FML6xx gate: trace ``chain``
    abstractly over the real (padded) buffers and check the jaxpr
    against the active policy. External columns are ``data``, model-data
    constants are ``param`` (an f16/bf16-STORED coefficient fails
    FML603), and any narrow accumulation or smuggled wide constant
    inside a kernel fails FML601/FML602 — all BEFORE jit sees the
    chain. Raises
    :class:`~flinkml_tpu.precision.PrecisionValidationError`."""
    import jax
    import numpy as _np

    from flinkml_tpu.analysis.precision import validate_precision

    validate_precision(
        chain, tuple(ext_vals), tuple(const_vals), _np.int32(1),
        policy=policy, param_argnums=(1,),
        program="pipeline_fusion["
                + "+".join(type(k).__name__ for k in kernels) + "]",
    )


def _chain_support_checked(kernels, ext_names, out_names, bucket, policy,
                           ext_vals, const_vals, backend: str,
                           explicit: bool) -> str:
    """The Pallas support check for one chain program: pay the
    abstract trace only for a resolved ``pallas`` choice, refusing
    loudly (explicit request) or falling back with one warning
    (table-chosen). Called on cache MISSES only — a steady-state hit
    never traces."""
    if backend != "pallas":
        return backend
    import jax

    from flinkml_tpu.kernels import _gate
    from flinkml_tpu.kernels import chain as _pchain

    if policy is not None and policy.quant is not None:
        # The Pallas chain body has no dequant path for QuantizedConst
        # pairs; an int8-tier program lowers through XLA.
        return _gate.refuse_or_fallback(
            "fused_chain", explicit,
            f"quantized ({policy.quant}) model constants are not "
            "supported by the pallas chain backend",
        )

    with jax.experimental.enable_x64(True):
        reason = _pchain.unsupported_reason(
            kernels, ext_names, out_names, bucket, policy,
            ext_vals, const_vals, _gate.interpret_mode(),
        )
    if reason is not None:
        return _gate.refuse_or_fallback("fused_chain", explicit, reason)
    return "pallas"


def _chain_backend(kernels, ext_names, out_names, bucket, policy,
                   ext_vals, const_vals) -> str:
    """Gate resolution + support check in one step (the executor defers
    the check to cache misses; this combined form serves tests and
    one-shot callers). The returned name joins the program cache key
    AND the AOT store identity."""
    from flinkml_tpu.kernels import _gate

    backend, explicit = _gate.resolve_backend("fused_chain")
    return _chain_support_checked(
        kernels, ext_names, out_names, bucket, policy, ext_vals,
        const_vals, backend, explicit,
    )


def _build_chain(kernels, ext_names, out_names, bucket, policy,
                 backend: str):
    """The chain callable for ``backend`` — ``_chain_fn`` under XLA,
    the row-tiled Pallas kernel otherwise (same cols→cols contract)."""
    if backend == "pallas":
        from flinkml_tpu.kernels.chain import pallas_chain_fn

        return pallas_chain_fn(kernels, ext_names, out_names, bucket,
                               policy)
    return _chain_fn(kernels, ext_names, out_names, bucket, policy)


def _placement_ids(ext_vals) -> Tuple[int, ...]:
    """Device ids the chain's inputs sit on — the placement signature
    the AOT cache keys a loaded executable by (a compiled artifact is
    bound to one placement; ``jax.jit`` would silently recompile per
    placement, a ``Compiled`` must be retarget-loaded instead)."""
    import jax

    for v in ext_vals:
        devices = getattr(v, "devices", None)
        if callable(devices):
            try:
                ids = tuple(sorted(d.id for d in v.devices()))
            except Exception:  # noqa: BLE001 — fall through to default
                continue
            if ids:
                return ids
    # jax_default_device may be a Device, a platform-name STRING (e.g.
    # JAX_DEFAULT_DEVICE=cpu), or None — only a Device carries an id.
    default_id = getattr(jax.config.jax_default_device, "id", None)
    return (default_id if default_id is not None
            else jax.devices()[0].id,)


def _run_program(kernels, ext_names, out_names, ext_specs, const_specs,
                 ext_vals, const_vals, bucket: int, n: int, policy=None):
    """Compile-or-reuse the program for (chain, requested outputs,
    bucket, policy) and execute it; returns the dict of bucket-padded
    output buffers. ``policy`` is captured ONCE per
    :func:`execute_kernel_chain` and passed down explicitly, so a lazy
    column's deferred program — possibly materialized on another thread
    or after the scope exited — compiles under the SAME policy as its
    eager siblings.

    With an active :mod:`flinkml_tpu.compile_cache` store the program is
    AOT-compiled (``jit(...).lower(...).compile()``) through the store:
    a fresh process LOADS the serialized executable instead of paying
    the XLA compile, and one replica's compile serves every other
    replica via retargeted loads. Loaded programs are placement-bound,
    so the in-memory key grows the input placement signature; without a
    store the jit path (and its key) is exactly as before."""
    import jax

    from flinkml_tpu import compile_cache

    from flinkml_tpu.kernels import _gate

    group = metrics.group("pipeline.fusion")
    store = compile_cache.active_store()
    backend, backend_explicit = _gate.resolve_backend("fused_chain")

    def _key_for(chosen: str):
        return (
            tuple(k.fingerprint for k in kernels),
            tuple(ext_specs),
            const_specs,
            tuple(out_names),
            bucket,
            policy,
            chosen,
        )

    key = _key_for(backend)
    devsig = _placement_ids(ext_vals) if store is not None else None
    cache_key = key if store is None else key + (devsig,)
    with _LOCK:
        program = _CACHE.get(cache_key)
    if program is None and backend == "pallas":
        # Support check on MISSES only — a cached Pallas program was
        # checked when it was built, so steady-state hits never pay the
        # abstract trace. A refused chain re-keys to (and may hit) the
        # XLA program.
        checked = _chain_support_checked(
            kernels, ext_names, out_names, bucket, policy,
            ext_vals, const_vals, backend, backend_explicit,
        )
        if checked != backend:
            backend = checked
            key = _key_for(backend)
            cache_key = key if store is None else key + (devsig,)
            with _LOCK:
                program = _CACHE.get(cache_key)
    if program is None and policy is not None:
        # Refusal precedes compile AND caching: a failing chain leaves
        # no executable behind (re-entry revalidates — validation is an
        # abstract trace, compile-free and cheap next to a compile).
        # This also gates AOT *loads*: a cached artifact only executes
        # in a process whose policy gate admits the same chain.
        # Validation ALWAYS walks the XLA-reference chain — the Pallas
        # backend runs the same kernel fns, and the FML6xx jaxpr walker
        # must see their math, not an opaque pallas_call.
        with jax.experimental.enable_x64(True):
            _validate_chain(
                _chain_fn(kernels, ext_names, out_names, bucket, policy),
                ext_vals, const_vals, kernels, policy,
            )
    compiled = False
    if program is None and store is not None:
        def _build():
            with jax.experimental.enable_x64(True):
                return jax.jit(
                    _build_chain(kernels, ext_names, out_names, bucket,
                                 policy, backend)
                ).lower(tuple(ext_vals), const_vals, np.int32(n)).compile()

        program, outcome = store.get_or_compile(
            ("pipeline_fusion", key), _build, device_ids=devsig,
        )
        with _LOCK:
            program = _CACHE.setdefault(cache_key, program)
        compiled = outcome in ("compiled", "uncached")
        if not compiled:
            group.counter("aot_loads")
    elif program is None:
        with _LOCK:
            program = _CACHE.get(cache_key)
            if program is None:
                program = jax.jit(
                    _build_chain(kernels, ext_names, out_names, bucket,
                                 policy, backend)
                )
                _CACHE[cache_key] = program
                compiled = True
    if compiled:
        group.counter("compiles")
        if backend == "pallas":
            group.counter("pallas_compiles")
        for hook in list(on_compile):
            hook(key)
    else:
        group.counter("cache_hits")
    with jax.experimental.enable_x64(True):
        return program(
            tuple(ext_vals), const_vals, np.int32(n)
        )


def execute_kernel_chain(table: Table, kernels: Sequence[ColumnKernel]) -> Table:
    """Run ``kernels`` over ``table`` as one fused program.

    One host→device upload per external host-resident input column, zero
    host transfers for device-resident inputs and intermediates, and a
    result table whose new columns are device-resident (host copy deferred
    to :meth:`Table.column`).
    """
    import jax
    import jax.numpy as jnp

    if not kernels:
        return table
    group = metrics.group("pipeline.fusion")
    n = table.num_rows
    bucket = row_bucket(n)
    ext = external_inputs(kernels)
    out_names = _output_cols(kernels)

    # Partition outputs: a column consumed by a later kernel of the run is
    # an *intermediate* — nobody may ever read it, so it is not computed
    # eagerly. The eager program returns only terminal columns, XLA
    # dead-code-eliminates the rest (on the CPU fallback this alone is the
    # difference between ~1x and ~3x over per-stage execution: four unread
    # [rows, dim] float64 buffers never get written). Intermediates become
    # LazyDeviceColumns: first access runs a DCE'd program for just that
    # column, through the same compile cache.
    producer = {}
    for j, k in enumerate(kernels):
        for c in k.output_cols:
            producer[c] = j
    terminal = [
        c for c in out_names
        if not any(
            c in kernels[j].input_cols
            for j in range(producer[c] + 1, len(kernels))
        )
    ]
    # Terminals plus the pinned inputs their closure demands (pin_inputs
    # kernels need their input columns materialized for bit parity).
    eager_names = list(_closure_outputs(kernels, terminal))
    lazy_names = [c for c in out_names if c not in eager_names]

    with jax.experimental.enable_x64(True):
        ext_vals = []
        ext_specs = []
        for name in ext:
            if not table.has_device_copy(name):
                # The upload below is a real host→device copy; further
                # transforms over this (immutable) table hit the cache.
                group.counter("host_to_device_transfers")
                group.counter(
                    "host_to_device_bytes", float(table.column(name).nbytes)
                )
            arr = table.device_column_padded(name, bucket)
            ext_vals.append(arr)
            ext_specs.append((name, str(arr.dtype), tuple(arr.shape[1:])))

        # The active policy is key material AND decides the constant
        # representation: under a quantized (int8) tier, eligible model
        # constants upload as per-column absmax int8 + f32 scales — the
        # bandwidth tier — and dequantize inside the program.
        policy = active_policy()
        quant_min = (
            _quant_min_elems()
            if policy is not None and policy.quant == "int8" else None
        )

        def _const_entry(name, raw):
            if quant_min is not None:
                from flinkml_tpu import precision as _precision

                host = np.asarray(raw)
                if _precision.quantizable(host, quant_min):
                    key = (id(host), host.shape, str(host.dtype),
                           quant_min, name)
                    with _QUANT_LOCK:
                        hit = _QUANT_CONST_CACHE.get(key)
                        if hit is not None and hit[0] is host:
                            _QUANT_CONST_CACHE.move_to_end(key)
                            return hit[1], hit[2]
                    q, s = _precision.quantize_absmax(host)
                    val = QuantizedConst(jnp.asarray(q), jnp.asarray(s))
                    # The spec names the QUANTIZED representation (plus
                    # the original shape): a genuinely-int8 constant can
                    # never alias a quantized-float one, and the autotune
                    # threshold changing which constants quantize re-keys
                    # the program through these specs.
                    spec = (name, "int8[absmax]", False,
                            tuple(host.shape))
                    with _QUANT_LOCK:
                        _QUANT_CONST_CACHE[key] = (host, val, spec)
                        _QUANT_CONST_CACHE.move_to_end(key)
                        while len(_QUANT_CONST_CACHE) > _QUANT_CONST_MAX:
                            _QUANT_CONST_CACHE.popitem(last=False)
                    return val, spec
            v = jnp.asarray(raw)
            return v, (name, str(v.dtype),
                       bool(getattr(v, "weak_type", False)),
                       tuple(v.shape))

        # weak_type is part of the spec: a python-scalar constant
        # (float64 weak) and an array constant (float64 strong) promote
        # DIFFERENTLY inside the program (weak * f32 -> f32, strong * f32
        # -> f64), so two chains differing only there must not alias one
        # cached executable.
        const_pairs = tuple(
            tuple(_const_entry(c, k.constants[c]) for c in sorted(k.constants))
            for k in kernels
        )
        const_vals = tuple(tuple(v for v, _ in kc) for kc in const_pairs)
        const_specs = tuple(tuple(s for _, s in kc) for kc in const_pairs)

        # Abstract trace (no compile, no compute): padded shape/dtype of
        # every output, for lazy-column construction and the bytes-avoided
        # accounting. Cached alongside the programs. The active policy is
        # key material here too: a mixed program's outputs ARE narrower.
        spec_key = (
            tuple(k.fingerprint for k in kernels),
            tuple(ext_specs),
            const_specs,
            "__specs__",
            bucket,
            policy,
        )
        with _LOCK:
            specs = _CACHE.get(spec_key)
        if specs is None:
            abstract = jax.eval_shape(
                _chain_fn(kernels, ext, out_names, bucket, policy),
                tuple(ext_vals), const_vals, np.int32(n),
            )
            specs = {
                c: (tuple(v.shape), v.dtype) for c, v in abstract.items()
            }
            with _LOCK:
                _CACHE[spec_key] = specs

    outs = _run_program(
        kernels, ext, eager_names, ext_specs, const_specs,
        ext_vals, const_vals, bucket, n, policy,
    )

    group.counter("fused_segments")
    group.counter("fused_stages", float(len(kernels)))
    # Per-stage execution would download every intermediate column and
    # re-upload it for the next stage; fused, those bytes never move.
    avoided = 0.0
    for name in lazy_names:
        shape, dtype = specs[name]
        row = int(np.prod(shape[1:], dtype=np.int64))
        avoided += 2.0 * n * row * np.dtype(dtype).itemsize
    if avoided:
        group.counter("host_transfer_bytes_avoided", avoided)

    # Outputs stay bucket-padded behind PaddedDeviceColumn: result
    # construction costs no device work; the prefix slice (and any
    # device→host copy) happens lazily at column access. Intermediates go
    # one step lazier: even their compute waits for the first read.
    result = table
    for name in eager_names:
        result = result.with_column(
            name, PaddedDeviceColumn(outs[name], n)
        )
    for name in lazy_names:
        shape, dtype = specs[name]

        def thunk(name=name, policy=policy):
            try:
                return _run_program(
                    kernels, ext, _closure_outputs(kernels, (name,)),
                    ext_specs, const_specs, ext_vals, const_vals, bucket, n,
                    policy,
                )[name]
            except RuntimeError as e:
                if "deleted" in str(e).lower() or "donat" in str(e).lower():
                    raise RuntimeError(
                        f"lazy intermediate column {name!r} cannot be "
                        "materialized: a source device buffer was donated "
                        "or freed before its first read. Read the column "
                        "(table.column(name)) before donating/deleting the "
                        "buffers the fused program captured."
                    ) from e
                raise

        result = result.with_column(
            name, LazyDeviceColumn(thunk, n, shape, dtype)
        )
    return result
