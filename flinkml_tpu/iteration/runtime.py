"""Epoch-synchronized iteration runtime.

Capability parity with ``flink-ml-iteration`` (SURVEY.md §2.2) with the
mechanism inverted (§7): the reference spends ~10k LoC making a DAG engine
loop — ``HeadOperator``/``TailOperator`` feedback edges, epoch-watermark
lattices (``OperatorEpochWatermarkTracker``), a JobManager-side
``SharedProgressAligner``, draft-environment graph rewriting, and feedback-
channel checkpoint logging. On TPU the loop is the program: a host ``for``
around one jitted SPMD step. What survives of the reference is its
*semantics*:

  - **variable streams** → the loop-carried ``state`` pytree (the feedback
    edge IS the loop carry; ``Iterations.java:118-170``).
  - **replayed data streams** → the per-epoch ``data`` provider (bounded
    mode re-presents the same batches each epoch — the ``ReplayOperator``
    without the disk cache; unbounded mode consumes a stream —
    ``iterateUnboundedStreams``).
  - **epoch watermarks + global alignment** → implicit: SPMD lockstep means
    every device is always at the same epoch; ``SubtaskAlignedEvent`` /
    ``GloballyAlignedEvent`` RPC (``SharedProgressAligner.java:127-158``)
    has no equivalent because there is nothing to align.
  - **termination criteria stream** → a criteria *value* returned by the
    step; ``TerminateOnMaxIter(OrTol)`` mirror
    ``ml/common/iteration/TerminateOnMaxIter.java:34-56`` /
    ``TerminateOnMaxIterOrTol.java:34-72`` ("criteria stream produced no
    records" ⇒ "criteria predicate says stop").
  - **IterationListener epoch callbacks** (``IterationListener.java:49-60``)
    → ``on_epoch_watermark_incremented`` / ``on_iteration_terminated``
    called on the host at epoch boundaries.
  - **per-round operator lifecycle** (``forEachRound``, per-round wrappers)
    → per-epoch temporaries inside the step function; fresh aggregation
    state per epoch is just a local variable in a functional step.
  - **feedback-edge checkpointing** (``Checkpoints.java:43-211``) →
    snapshot of the loop carry via ``CheckpointManager`` every N epochs;
    resume restores (state, epoch, rng) exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np


# ---------------------------------------------------------------------------
# Termination criteria
# ---------------------------------------------------------------------------

class TerminationCriterion:
    """Decides, at the END of epoch ``epoch`` (0-based), whether to stop.

    ``criteria_value`` is whatever the step returned as its criterion (e.g.
    the epoch loss); criteria may ignore it.
    """

    def should_terminate(self, epoch: int, criteria_value: Optional[float]) -> bool:
        raise NotImplementedError


class TerminateOnMaxIter(TerminationCriterion):
    """Stop after ``max_iter`` epochs.

    Parity: ``TerminateOnMaxIter.java:34-56`` (emits a continue-record while
    ``epochWatermark + 1 < maxIter``).
    """

    def __init__(self, max_iter: int):
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        self.max_iter = max_iter

    def should_terminate(self, epoch: int, criteria_value: Optional[float]) -> bool:
        return epoch + 1 >= self.max_iter


class TerminateOnMaxIterOrTol(TerminationCriterion):
    """Stop after ``max_iter`` epochs or when the criterion drops below tol.

    Parity: ``TerminateOnMaxIterOrTol.java:34-72``.
    """

    def __init__(self, max_iter: int, tol: float):
        if max_iter < 1:
            raise ValueError(f"max_iter must be >= 1, got {max_iter}")
        self.max_iter = max_iter
        self.tol = float(tol)

    def should_terminate(self, epoch: int, criteria_value: Optional[float]) -> bool:
        if epoch + 1 >= self.max_iter:
            return True
        if criteria_value is None:
            return False
        return float(criteria_value) <= self.tol


# ---------------------------------------------------------------------------
# Listeners / config
# ---------------------------------------------------------------------------

class IterationListener:
    """Epoch-boundary callbacks. Parity: ``IterationListener.java:49-60``.

    Callbacks run on the host between epochs (where the reference invoked
    them inside wrapped operators when the epoch watermark advanced).

    A listener that publishes or persists the state mid-stream (e.g.
    :class:`flinkml_tpu.serving.SnapshotPublisher`) sets the class
    attribute ``needs_materialized_state = True``: the runtime then
    blocks on the loop carry before the epoch callbacks fire, so the
    listener sees a *consistent, fully computed* snapshot rather than
    in-flight async dispatch futures — the mid-stream model-emission
    hook the reference's unbounded ``Iterations`` gets from per-round
    model emission.
    """

    #: Set True when epoch callbacks must observe a fully computed state
    #: (the runtime calls ``jax.block_until_ready`` on the carry first).
    #: Listeners that only act on SOME epochs should also implement
    #: ``wants_epoch_state(epoch) -> bool`` so idle epochs keep the
    #: async-dispatch pipeline intact (no per-epoch device sync).
    needs_materialized_state = False

    def wants_epoch_state(self, epoch: int) -> bool:
        """Whether this listener will actually consume a materialized
        state at ``epoch`` (only consulted when
        ``needs_materialized_state`` is set)."""
        return True

    def on_epoch_watermark_incremented(self, epoch: int, state: Any) -> None:
        ...

    def on_iteration_terminated(self, state: Any) -> None:
        ...


def notify_epoch_listeners(
    listeners: Sequence["IterationListener"], epoch: int, state: Any
) -> Any:
    """Fire ``on_epoch_watermark_incremented`` on every listener,
    materializing ``state`` once first if any listener declares
    ``needs_materialized_state`` AND will act this epoch
    (``wants_epoch_state``; see :class:`IterationListener`) — a
    publisher on a 10-epoch cadence costs a device sync once per
    publish, not per epoch. Returns the (possibly materialized) state.
    Shared by :func:`iterate` and the hand-rolled ``train_*_stream``
    epoch loops, so mid-stream snapshot publication behaves identically
    in both."""
    if listeners and any(
        getattr(l, "needs_materialized_state", False)
        and getattr(l, "wants_epoch_state", lambda e: True)(epoch)
        for l in listeners
    ):
        import jax

        state = jax.block_until_ready(state)
    for listener in listeners:
        listener.on_epoch_watermark_incremented(epoch, state)
    return state


class ForwardInputsOfLastRound(IterationListener):
    """Capture only the final round's value and expose it after termination.

    Parity: ``ml/common/iteration/ForwardInputsOfLastRound.java:34-60`` —
    the reference buffers each epoch's records and discards them when the
    next epoch's watermark arrives, emitting only the last round's buffer at
    termination (KMeans uses it to emit final centroids,
    ``KMeans.java:197-198``). Here each epoch's captured value simply
    overwrites the previous one; ``value`` is valid once
    ``on_iteration_terminated`` has fired (``terminated`` is True).

    ``extract`` maps the loop state to the value to forward (default:
    identity).
    """

    def __init__(self, extract: Optional[Callable[[Any], Any]] = None):
        self._extract = extract if extract is not None else (lambda s: s)
        self.value: Any = None
        self.terminated = False

    def on_iteration_terminated(self, state: Any) -> None:
        # Extracting once here is observationally identical to the
        # reference's buffer-per-epoch-discard-on-advance: intermediate
        # rounds are never visible, so don't pay extract() (often a
        # device→host transfer) for them.
        self.value = self._extract(state)
        self.terminated = True


@dataclasses.dataclass
class IterationConfig:
    """Runtime knobs. Parity: ``IterationConfig.java:22-66`` +
    checkpointing options (the reference gets those from Flink's env).

    The reference's ``OperatorLifeCycle ALL_ROUND | PER_ROUND`` has no
    runtime knob here: all-round state is the loop carry, per-round state is
    a step-local temporary — both are expressed in the step function itself.
    """

    termination: TerminationCriterion = dataclasses.field(
        default_factory=lambda: TerminateOnMaxIter(20)
    )
    # Snapshot the loop carry every N epochs (0 = disabled).
    checkpoint_interval: int = 0
    checkpoint_manager: Optional[Any] = None
    # How a resumed run re-aligns an *iterable* data stream:
    #   "replay":   the iterable restarts from the beginning on each run
    #               (list, file reader, DataCache) — skip the batches the
    #               pre-failure run already consumed so epoch k always
    #               sees batch k.
    #   "continue": the iterable is a live one-shot stream already
    #               positioned at "now" (online learning) — consume from
    #               the front; skipping would silently DROP real data.
    stream_resume: str = "replay"
    # Preemption watchdog polled at every epoch boundary; None uses the
    # ambient installed one (utils.preemption.active()). On preemption the
    # loop stops cleanly, commits one final checkpoint (manager permitting)
    # and drains the watchdog's registered serving engines.
    watchdog: Optional[Any] = None
    # Numerics sentinel (flinkml_tpu.recovery.NumericsSentinel): a fused
    # on-device finiteness/magnitude verdict over loss + carry at every
    # epoch boundary, BEFORE the state can be checkpointed or handed to
    # listeners. Raises a typed NumericsError when it trips; with
    # `recovery` set the raise is healed in-loop instead.
    sentinel: Optional[Any] = None
    # Self-healing policy (flinkml_tpu.recovery.RecoveryPolicy): on a
    # sentinel verdict, roll back to the newest VALID snapshot
    # (restore_latest walk-back), quarantine the offending source batch
    # (ledgered in the snapshot `extra` so resume honors it), and retry
    # with jittered backoff. Implies a default NumericsSentinel when
    # `sentinel` is unset. See docs/development/fault_tolerance.md,
    # "Self-healing".
    recovery: Optional[Any] = None

    def __post_init__(self):
        if self.stream_resume not in ("replay", "continue"):
            raise ValueError(
                "stream_resume must be 'replay' or 'continue', "
                f"got {self.stream_resume!r}"
            )


@dataclasses.dataclass
class IterationResult:
    state: Any
    epochs: int
    criteria_history: List[Optional[float]]
    outputs: List[Any]
    # True when a PreemptionWatchdog stopped the loop early; the final
    # state was checkpointed (manager permitting) and resumes cleanly.
    preempted: bool = False
    # The recovery session's summary when IterationConfig.recovery is
    # set ({"rollbacks", "retries", "quarantined", "quarantine_ranges",
    # "stopped_early"}); None otherwise.
    recovery: Optional[dict] = None


# ---------------------------------------------------------------------------
# The loop
# ---------------------------------------------------------------------------

StepFn = Callable[..., Tuple]
DataProvider = Union[None, Any, Callable[[int], Any], Iterable]


def _epoch_data(data: DataProvider, index: int, it: Optional[Iterator]) -> Tuple[Any, bool]:
    """Resolve the data for one epoch; returns (batch, exhausted).
    ``index`` is the SOURCE batch index for callable providers (equal to
    the epoch until a quarantine skips a batch)."""
    if data is None:
        return None, False
    if callable(data):
        batch = data(index)
        return batch, batch is None
    if it is not None:
        try:
            return next(it), False
        except StopIteration:
            return None, True
    # Static pytree: bounded replay — same data every epoch.
    return data, False


def _source_position(delivered: int, ledger) -> int:
    """Delivered-batch watermark -> source watermark (the quarantined
    batches below it were read and discarded, so the feed must
    fast-forward past them too)."""
    if ledger is None:
        return delivered
    return ledger.source_position(delivered)


def _feed_replayable(data: DataProvider, config: IterationConfig) -> bool:
    """Whether a rollback can re-position this feed: Datasets and
    ElasticFeeds replay from their cursor, restartable sequences and
    callables replay by index; a live one-shot iterator (or
    stream_resume='continue') cannot be rewound."""
    if data is None or callable(data) or not _is_stream(data):
        return True
    try:
        from flinkml_tpu.data import Dataset, ElasticFeed

        if isinstance(data, (Dataset, ElasticFeed)):
            return True
    except ImportError:  # pragma: no cover — data subsystem always ships
        pass
    return isinstance(data, list) and config.stream_resume == "replay"


def iterate(
    step_fn: StepFn,
    init_state: Any,
    data: DataProvider = None,
    config: Optional[IterationConfig] = None,
    listeners: Sequence[IterationListener] = (),
    resume: bool = False,
) -> IterationResult:
    """Run an epoch-synchronized iteration to termination.

    Parity: ``Iterations.iterateBoundedStreamsUntilTermination`` /
    ``iterateUnboundedStreams`` (``Iterations.java:118-170``).

    Args:
        step_fn: ``step_fn(state, epoch_data, epoch) -> (new_state, criteria)``
            or ``-> (new_state, criteria, output)``. Typically a ``jax.jit``
            function closed over the mesh; ``criteria`` (a scalar or None)
            feeds the termination criterion, ``output`` (optional) is
            collected per epoch. ``epoch_data`` is omitted from the call when
            ``data`` is None (pure variable iteration).
        init_state: loop-carried pytree (the "variable streams").
        data: None, a static pytree (bounded replay — every epoch sees the
            same data), a callable ``epoch -> batch`` (returns None to end —
            unbounded/online mode), or an iterable of per-epoch batches
            (unbounded; termination when exhausted). A
            :class:`flinkml_tpu.data.Dataset` is accepted anywhere an
            iterable is: the loop then checkpoints the pipeline's
            :class:`~flinkml_tpu.data.Cursor` alongside the state (in the
            snapshot's ``extra`` manifest) and a resumed run reopens the
            Dataset at the exact batch the crash interrupted.
        config: termination + checkpointing + self-healing. With
            ``config.sentinel`` set, every epoch's post-step state (and
            loss) passes a fused on-device numerics verdict; with
            ``config.recovery`` also set, a bad verdict is healed
            in-loop — rollback to the newest valid snapshot, quarantine
            of the offending source batch (the ledger rides every
            snapshot's ``extra``, so a kill mid-recovery resumes with
            the skips intact), jittered-backoff retry. On a retry the
            returned ``criteria_history``/``outputs``/``epochs`` cover
            the final attempt only; ``IterationResult.recovery``
            carries the session summary.
        listeners: epoch-boundary callbacks.
        resume: restore (state, epoch) from ``config.checkpoint_manager``
            and continue mid-training.
    """
    config = config or IterationConfig()
    state = init_state
    start_epoch = 0
    restored = False
    manager = config.checkpoint_manager
    if resume:
        if manager is None:
            raise ValueError("resume=True requires config.checkpoint_manager")
        # restore_latest verifies integrity and falls back past torn or
        # corrupt snapshots to the newest valid one (checkpoint.py).
        r = manager.restore_latest(like=init_state)
        if r is not None:
            state, start_epoch = r
            restored = True

    # Quarantine ledger: honored whenever the restored snapshot recorded
    # one (a resumed self-healed run keeps its skips even without a
    # policy configured); owned and extended by the recovery session.
    ledger = None
    if restored:
        recorded_q = (
            getattr(manager, "last_restored_extra", None) or {}
        ).get("quarantine")
        if recorded_q:
            from flinkml_tpu.recovery.policy import QuarantineLedger

            ledger = QuarantineLedger.from_json_dict(recorded_q)

    sentinel = config.sentinel
    session = None
    if config.recovery is not None:
        from flinkml_tpu.recovery.engine import RecoverySession
        from flinkml_tpu.recovery.policy import QuarantineLedger
        from flinkml_tpu.recovery.sentinel import NumericsSentinel

        if sentinel is None:
            sentinel = NumericsSentinel()
        if ledger is None:
            ledger = QuarantineLedger()
        session = RecoverySession(
            config.recovery, manager, sentinel, ledger, init_state,
            replayable=_feed_replayable(data, config),
            initially_restored=restored,
        )

    initial_epoch = start_epoch
    while True:
        try:
            result = _run_attempt(
                step_fn, state, data, config, listeners, start_epoch,
                restored, sentinel, ledger, session,
            )
            if session is not None:
                result.recovery = session.summary()
            return result
        except RuntimeError as err:
            if session is None:
                raise
            from flinkml_tpu.recovery.sentinel import NumericsError

            if not isinstance(err, NumericsError):
                raise
            verb, state, start_epoch, restored = session.handle(err)
            if verb == "stop":
                # stop_at_last_valid: terminate with the newest valid
                # model (already durable on disk — no terminal rewrite).
                for listener in listeners:
                    listener.on_iteration_terminated(state)
                return IterationResult(
                    state=state,
                    epochs=max(0, start_epoch - initial_epoch),
                    criteria_history=[],
                    outputs=[],
                    preempted=False,
                    recovery=session.summary(),
                )


def _run_attempt(
    step_fn: StepFn,
    state: Any,
    data: DataProvider,
    config: IterationConfig,
    listeners: Sequence[IterationListener],
    start_epoch: int,
    restored: bool,
    sentinel,
    ledger,
    session=None,
) -> IterationResult:
    """One pass of the epoch loop from ``start_epoch`` (the whole run
    when no recovery retry intervenes). ``ledger`` batches are read past
    and never stepped; ``sentinel`` verdicts raise before the state can
    be checkpointed or handed to listeners."""
    source_skip = _source_position(start_epoch, ledger)
    data_iter: Optional[Iterator] = None
    dataset_iter = None  # tracked flinkml_tpu.data iterator (cursor owner)
    # Source index of the NEXT batch to pull (None: no positional stream
    # — static/None data, or a live 'continue' stream whose indices are
    # unknowable, where quarantine does not apply).
    src_index: Optional[int] = None
    if data is not None and not callable(data) and _is_stream(data):
        dataset_iter = _open_dataset(data, start_epoch, config, ledger)
        if dataset_iter is not None:
            data_iter = dataset_iter
            src_index = source_skip
        else:
            data_iter = iter(data)
            if config.stream_resume == "replay":
                # The iterable restarts from the beginning: fast-forward
                # past the batches the pre-failure run consumed —
                # delivered epochs PLUS quarantined skips. For a live
                # one-shot stream this would drop real data — set
                # stream_resume='continue' there.
                for _ in range(source_skip):
                    try:
                        next(data_iter)
                    except StopIteration:
                        break
                src_index = source_skip
    elif callable(data):
        src_index = source_skip

    criteria_history: List[Optional[float]] = []
    outputs: List[Any] = []
    epoch = start_epoch
    terminated = False
    preempted = False
    # The last epoch a snapshot committed for (resume counts: the restored
    # epoch IS on disk) — lets the terminal save skip redundant rewrites.
    last_saved = start_epoch if (restored and start_epoch > 0) else None
    from flinkml_tpu.utils import preemption

    watchdog = (
        config.watchdog if config.watchdog is not None else preemption.active()
    )
    # Criteria-less loops never touch host values, so nothing would bound
    # in-flight dispatch on a multi-process mesh — the guard is the
    # framework backpressure policy (no-op single-process). Loops that
    # return a criteria already sync via float(criteria) each epoch.
    # (Lazy imports: utils.metrics imports this module for
    # IterationListener, so runtime's own utils/faults imports cannot be
    # top-level without a cycle.)
    import flinkml_tpu.faults as faults
    from flinkml_tpu.parallel.dispatch import DispatchGuard

    guard = DispatchGuard()
    try:
        while not terminated:
            if faults.ACTIVE is not None:  # scripted-crash seam (pre-batch)
                faults.fire("iteration.epoch", epoch=epoch)
                # Elastic seam: a scripted RankLost marks a peer dead at
                # this epoch boundary; the watchdog (in ctx) converts it
                # into a clean shrink-triggering preemption stop.
                faults.fire("rank.lost", epoch=epoch, watchdog=watchdog)
            if watchdog is not None and watchdog.requested:
                # Epoch boundaries are the globally consistent points in
                # SPMD lockstep — stop here, snapshot below, drain, hand
                # back.
                preempted = True
                break
            if src_index is None:
                batch, exhausted = _epoch_data(data, epoch, data_iter)
                idx = None
            else:
                while True:
                    batch, exhausted = _epoch_data(data, src_index, data_iter)
                    if exhausted:
                        idx = None
                        break
                    idx, src_index = src_index, src_index + 1
                    if ledger is None or idx not in ledger:
                        break
                    # Quarantined source batch: read past (advancing the
                    # cursor watermark), never stepped, never an epoch.
            if exhausted:
                break

            if faults.ACTIVE is not None and data is not None:
                # train.step pre seam: a PoisonBatch replaces the batch.
                fctx = {"phase": "pre", "epoch": epoch,
                        "source_index": idx, "batch": batch}
                faults.fire_into("train.step", fctx)
                batch = fctx["batch"]

            if data is None:
                result = step_fn(state, epoch)
            else:
                result = step_fn(state, batch, epoch)
            if not isinstance(result, tuple):
                state, criteria = result, None
            elif len(result) == 2:
                state, criteria = result
            else:
                state, criteria, output = result
                outputs.append(output)

            if faults.ACTIVE is not None:
                # train.step post seam: NaNGrad poisons the state,
                # InfLoss the loss.
                fctx = {"phase": "post", "epoch": epoch,
                        "source_index": idx, "state": state,
                        "criteria": criteria}
                faults.fire_into("train.step", fctx)
                state, criteria = fctx["state"], fctx["criteria"]

            criteria_value = None if criteria is None else float(criteria)
            if sentinel is not None:
                # The numerics verdict — BEFORE the state can be
                # checkpointed, published (listeners), or counted.
                sentinel.check(state, criteria_value, epoch=epoch,
                               source_index=idx)
            if criteria_value is None:
                guard.after_dispatch(state)
            criteria_history.append(criteria_value)

            state = notify_epoch_listeners(listeners, epoch, state)

            terminated = config.termination.should_terminate(
                epoch, criteria_value
            )
            epoch += 1

            if (
                config.checkpoint_interval > 0
                and config.checkpoint_manager is not None
                and epoch % config.checkpoint_interval == 0
            ):
                config.checkpoint_manager.save(
                    state, epoch, extra=_snapshot_extra(dataset_iter, ledger)
                )
                last_saved = epoch
                if session is not None:
                    # This run's commit: a legitimate rollback target
                    # (even over a dirty pre-existing directory).
                    session.note_saved(epoch)
    finally:
        # A Dataset's prefetch stage runs a worker thread; an injected
        # crash (or any raise) must not strand it. close() is idempotent
        # and keeps the iterator's cursor readable for the terminal save.
        if dataset_iter is not None:
            dataset_iter.close()

    guard.flush(state)  # back-to-back phases must not stack in-flight work
    if config.checkpoint_manager is not None and last_saved != epoch:
        # Terminal snapshot — at termination, stream exhaustion, or
        # preemption, whenever a manager is configured (even with
        # checkpoint_interval=0): a finished run always leaves its final
        # state durable, so resume is a no-op and a preempted run loses
        # nothing (the "one final agreed checkpoint" of the preemption
        # contract; single-process commit — the hand-rolled multi-process
        # loops go through checkpoint.save_agreed instead).
        config.checkpoint_manager.save(
            state, epoch, extra=_snapshot_extra(dataset_iter, ledger)
        )
    if config.checkpoint_manager is not None and hasattr(
        config.checkpoint_manager, "wait"
    ):
        # Drain any in-flight async write so a failed final snapshot
        # surfaces here rather than vanishing at interpreter exit.
        config.checkpoint_manager.wait()
    for listener in listeners:
        listener.on_iteration_terminated(state)
    if preempted and watchdog is not None:
        # Only AFTER the final snapshot is durable: drain serving engines.
        watchdog.finalize()

    return IterationResult(
        state=state,
        epochs=epoch - start_epoch,
        criteria_history=criteria_history,
        outputs=outputs,
        preempted=preempted,
    )


def _open_dataset(data: Any, start_epoch: int, config: IterationConfig,
                  ledger=None):
    """When ``data`` is a :class:`flinkml_tpu.data.Dataset` (or an
    :class:`~flinkml_tpu.data.ElasticFeed` — the world-parallel
    global-order feed), open a TRACKED iteration positioned at
    ``start_epoch`` and return it (else None and the caller falls back
    to plain iteration). An ElasticFeed's cursor records the world that
    wrote it, so a resumed run at a DIFFERENT world re-splits the feed
    across the new readers (the elastic-resume path).

    A Dataset is restartable and deterministic, so resume is always the
    'replay' contract regardless of ``stream_resume``: the chain
    fast-forwards to the watermark (pushed down to the source when the
    chain is skip-transparent) and the consumer sees the exact
    uninterrupted sequence — shuffle order included. When the restored
    snapshot recorded a cursor (``extra['data_cursor']``, written by the
    checkpoint saves below), it seeds the reopen; the restored epoch
    stays authoritative if the two disagree (the cursor may be from an
    in-flight write the epoch superseded).

    ``ledger`` (a quarantine ledger) converts the delivered epoch into
    the SOURCE watermark the reopened feed must fast-forward to:
    delivered batches plus every quarantined batch interleaved below
    them (those were read and discarded, and the cursor counts them) —
    "advancing the cursor watermark past the quarantined range".
    """
    try:
        from flinkml_tpu.data import Cursor, Dataset, ElasticFeed
    except ImportError:  # pragma: no cover — data subsystem always ships
        return None
    if not isinstance(data, (Dataset, ElasticFeed)):
        return None
    expected_source = _source_position(start_epoch, ledger)
    cursor = None
    if start_epoch > 0:
        extra = getattr(
            config.checkpoint_manager, "last_restored_extra", None
        ) or {}
        recorded = extra.get("data_cursor")
        if recorded is not None:
            cursor = Cursor.from_json_dict(recorded)
            if cursor.emitted != expected_source:
                # The restored epoch stays authoritative; shift the
                # recorded global watermark by the same number of
                # lockstep rounds (one batch per shard per round; a
                # global-order cursor advances one batch per round).
                watermark = cursor.global_watermark
                if watermark is not None:
                    per_round = (cursor.num_shards
                                 if cursor.shard_index is not None
                                 and cursor.num_shards is not None else 1)
                    watermark += (expected_source - cursor.emitted) * per_round
                cursor = dataclasses.replace(
                    cursor, emitted=expected_source,
                    global_watermark=watermark,
                )
        else:
            cursor = Cursor(emitted=expected_source)
    return data.iterate(cursor)


def _snapshot_extra(dataset_iter, ledger=None) -> Optional[dict]:
    """The checkpoint ``extra`` payload: the input-pipeline cursor (for
    Dataset streams) and the quarantine ledger (when any batch is
    quarantined) — the two records a resumed run needs to reconstruct
    the exact delivered sequence."""
    extra: dict = {}
    if dataset_iter is not None:
        extra["data_cursor"] = dataset_iter.cursor().to_json_dict()
    if ledger:
        extra["quarantine"] = ledger.to_json_dict()
    return extra or None


def _is_stream(data: Any) -> bool:
    """True for per-epoch batch streams (list/generator of batches).

    Static pytrees (dict/tuple/array) mean bounded replay; lists, iterators
    and generators mean one-batch-per-epoch.
    """
    if isinstance(data, (list, Iterator)):
        return True
    return hasattr(data, "__iter__") and not isinstance(
        data, (dict, tuple, str, bytes, np.ndarray)
    ) and not hasattr(data, "shape")


class Iterations:
    """Namespace matching the reference's entrypoints
    (``Iterations.java:118-170``)."""

    @staticmethod
    def iterate_bounded_streams_until_termination(
        step_fn: StepFn,
        init_state: Any,
        replayed_data: Any = None,
        config: Optional[IterationConfig] = None,
        listeners: Sequence[IterationListener] = (),
    ) -> IterationResult:
        """Bounded mode: ``replayed_data`` is re-presented every epoch."""
        return iterate(step_fn, init_state, replayed_data, config, listeners)

    @staticmethod
    def iterate_unbounded_streams(
        step_fn: StepFn,
        init_state: Any,
        stream: Iterable,
        config: Optional[IterationConfig] = None,
        listeners: Sequence[IterationListener] = (),
    ) -> IterationResult:
        """Unbounded/online mode: one batch per epoch until exhausted."""
        return iterate(step_fn, init_state, iter(stream), config, listeners)
