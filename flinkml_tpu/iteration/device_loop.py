"""Fully on-device iteration via ``lax.while_loop``.

For iteration bodies that are pure jax functions over device-resident data,
the whole loop compiles into ONE XLA program: zero host round-trips per
epoch, collectives fused into the loop body. This is the highest-performance
mode — the host runtime (``flinkml_tpu.iteration.runtime``) exists for
bodies that need per-epoch host work (data feeding, listeners, checkpoints).

The reference has no analog: its loop must round-trip every epoch through
feedback channels and coordinator RPC (SURVEY.md §3.2 runtime trace).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("step_fn", "max_iter", "has_tol"))
def _device_iterate(
    step_fn, init_state, max_iter: int, tol, has_tol: bool
):
    def cond(carry):
        state, epoch, criteria, done = carry
        return jnp.logical_and(epoch < max_iter, jnp.logical_not(done))

    def body(carry):
        state, epoch, _, _ = carry
        new_state, criteria = step_fn(state, epoch)
        criteria = jnp.asarray(criteria, dtype=jnp.float32)
        done = (criteria <= tol) if has_tol else jnp.asarray(False)
        return new_state, epoch + 1, criteria, done

    init = (
        init_state,
        jnp.asarray(0, dtype=jnp.int32),
        jnp.asarray(jnp.inf, dtype=jnp.float32),
        jnp.asarray(False),
    )
    state, epochs, criteria, _ = jax.lax.while_loop(cond, body, init)
    return state, epochs, criteria


def device_iterate(
    step_fn: Callable[[Any, jax.Array], Tuple[Any, jax.Array]],
    init_state: Any,
    max_iter: int,
    tol: Optional[float] = None,
):
    """Run ``step_fn(state, epoch) -> (state, criteria)`` on device.

    Terminates after ``max_iter`` epochs or when ``criteria <= tol`` (when
    ``tol`` is given) — the on-device ``TerminateOnMaxIterOrTol``. Shapes
    must be static across epochs (XLA requirement).

    Returns ``(final_state, epochs_run, last_criteria)``.
    """
    has_tol = tol is not None
    tol_val = jnp.asarray(0.0 if tol is None else tol, dtype=jnp.float32)
    return _device_iterate(step_fn, init_state, int(max_iter), tol_val, has_tol)
