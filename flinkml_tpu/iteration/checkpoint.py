"""Loop-carry checkpointing: snapshot/restore of (state pytree, epoch).

Parity mapping (SURVEY.md §5 checkpoint/resume): the reference's deepest
subsystem — aligned barriers, feedback-record logging between alignment and
the tail's BARRIER record (``Checkpoints.java:43-211``), coordinator-
serialized snapshots, per-round live-instance tracking — exists because its
loop state lives *inside a running dataflow*. Here the loop state is an
explicit pytree on the host/device boundary, so a checkpoint is: pull the
carry to host, write arrays + a JSON manifest atomically, done. Restore is
exact (bit-identical arrays, epoch, rng keys included in the carry).

Layout per checkpoint: ``<dir>/ckpt-<epoch>/arrays.npz`` + ``meta.json``;
a checkpoint is visible only after an atomic rename, so a kill mid-write
never corrupts the latest checkpoint (the fault-tolerance contract the
reference gets from Flink's two-phase checkpoint commit).

Defensive restore (ISSUE 4): every snapshot records a sha256 content
fingerprint over its arrays (the scheme of
``io.read_write.content_fingerprint``), and :meth:`CheckpointManager.
restore_latest` verifies manifest + arrays + fingerprint, falling back to
the newest VALID snapshot when the latest is torn or corrupt —
:class:`CheckpointIntegrityError` only when no snapshot survives. Fault
seams (``checkpoint.write`` / ``checkpoint.committed``,
:mod:`flinkml_tpu.faults`) let tests script torn writes and
kill-after-commit deterministically.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

import flinkml_tpu.faults as faults
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("checkpoint")


class CheckpointIntegrityError(ValueError):
    """A committed checkpoint failed restore-time verification — its
    manifest is unreadable, its arrays are missing/unloadable, or the
    content fingerprint does not match. ``restore_latest`` treats this as
    "try the previous snapshot"; it surfaces only when every snapshot is
    damaged."""


class LayoutConflictError(ValueError):
    """A caller passed BOTH ``plan=`` and an explicit ``layouts=`` to
    :meth:`CheckpointManager.save` and they disagree. The plan is the
    single source of layout truth (ISSUE 7): stale hand-written tags
    used to win silently and poison elastic resume with the wrong
    re-layout — now the conflict is loud and names the first leaf where
    the two disagree. Drop the ``layouts=`` override (the plan derives
    them), or fix the plan."""


class RescaleError(ValueError):
    """Restoring a snapshot under a different world size was refused —
    either the manager's :class:`RescalePolicy` rejects rescaling
    outright, or the policy permits resharding but a leaf is genuinely
    rank-entangled (``per_rank`` layout, or a sharded extent that does
    not divide across the new rank count). The message always names the
    snapshot directory, the epoch, both world sizes, and the policy
    outcome, so fleet-log triage never has to guess which snapshot of
    which job refused to come back."""


# -- per-leaf layout tags -----------------------------------------------------
#
# Snapshots record how each loop-carry leaf relates to the world size that
# wrote it, which is what makes restore reshard-aware:
#
#   ``replicated``     identical on every rank (coefficients, centroids,
#                      moments, versions) — restores at ANY world for free;
#   ``sharded:<axis>`` the recorded array is the assembled GLOBAL value,
#                      laid out in world-size chunks along <axis> — restore
#                      at world M revalidates the chunking (and
#                      :func:`reshard_rank_state` reassembles/resplits the
#                      rank-scoped variant);
#   ``per_rank``       rank-local state with no global assembly (GBT's
#                      per-row margins on the rank owning the rows) —
#                      genuinely rank-entangled; restore under a different
#                      world raises :class:`RescaleError` under every
#                      policy.

LAYOUT_REPLICATED = "replicated"
LAYOUT_PER_RANK = "per_rank"


def sharded(axis: int = 0) -> str:
    """The ``sharded:<axis>`` layout tag (see module layout notes)."""
    return f"sharded:{int(axis)}"


def _parse_layout(tag: str) -> Tuple[str, Optional[int]]:
    """``tag -> (kind, axis)``; raises on an unknown tag."""
    if tag == LAYOUT_REPLICATED:
        return "replicated", None
    if tag == LAYOUT_PER_RANK:
        return "per_rank", None
    if isinstance(tag, str) and tag.startswith("sharded:"):
        try:
            return "sharded", int(tag.split(":", 1)[1])
        except ValueError:
            pass
    raise ValueError(
        f"unknown checkpoint leaf layout tag {tag!r}; expected "
        f"'{LAYOUT_REPLICATED}', '{LAYOUT_PER_RANK}', or 'sharded:<axis>'"
    )


@dataclasses.dataclass(frozen=True)
class RescalePolicy:
    """What :meth:`CheckpointManager.restore` does when the snapshot's
    recorded world size differs from the restoring mesh's.

    ``on_mismatch``:

    - ``"reject"`` (default): raise :class:`RescaleError` — the
      reference's recovery guard (``HeadOperator.java:130-146``), kept as
      the safe default for state of unknown layout.
    - ``"reshard"``: re-lay-out the carry per the snapshot's leaf layout
      tags — ``replicated`` leaves restore for free, ``sharded:<axis>``
      leaves are revalidated against (and re-split across) the new rank
      count, and only genuinely rank-entangled ``per_rank`` leaves raise.
      This is the elastic-resume policy: a snapshot committed at world N
      resumes at world M.
    - ``"allow"``: restore as-is with no validation (the legacy
      ``allow_rescale=True`` escape hatch) — correct only when the caller
      KNOWS every leaf is world-independent.
    """

    on_mismatch: str = "reject"

    def __post_init__(self):
        if self.on_mismatch not in ("reject", "allow", "reshard"):
            raise ValueError(
                "RescalePolicy.on_mismatch must be 'reject', 'allow' or "
                f"'reshard', got {self.on_mismatch!r}"
            )

    @staticmethod
    def reject() -> "RescalePolicy":
        return RescalePolicy("reject")

    @staticmethod
    def allow() -> "RescalePolicy":
        return RescalePolicy("allow")

    @staticmethod
    def reshard() -> "RescalePolicy":
        return RescalePolicy("reshard")

    @staticmethod
    def coerce(value) -> "RescalePolicy":
        """Normalize a policy spec: a :class:`RescalePolicy`, one of its
        mode strings, a legacy bool (``allow_rescale``), or None
        (reject)."""
        if value is None:
            return RescalePolicy.reject()
        if isinstance(value, RescalePolicy):
            return value
        if isinstance(value, bool):
            return RescalePolicy.allow() if value else RescalePolicy.reject()
        if isinstance(value, str):
            return RescalePolicy(value)
        raise TypeError(
            f"cannot interpret {value!r} as a RescalePolicy"
        )


def _leaves_fingerprint(host_leaves) -> str:
    """The PR 3 content-fingerprint scheme applied to checkpoint leaves
    (names/dtypes/shapes/bytes all contribute)."""
    from flinkml_tpu.io.read_write import content_fingerprint

    return content_fingerprint(
        {f"leaf_{i}": leaf for i, leaf in enumerate(host_leaves)}
    )


def begin_resume(manager: Optional["CheckpointManager"], resume: bool,
                 world_size: int) -> Optional[int]:
    """Step 1 of the streamed-trainer checkpoint protocol (shared by the
    linear/KMeans/GBT/GMM streamed fits): validate the resume/manager
    pairing and pin the rescale guard to the mesh that trains (NOT the
    process-global device count). Returns the epoch to restore from, or
    None for a fresh start — returned *before* any data pass so callers
    can skip init work (reservoir sampling, seeding) whose result a
    restore would discard."""
    if resume and manager is None:
        raise ValueError("resume=True requires a checkpoint_manager")
    if manager is None:
        return None
    manager.world_size = world_size
    return manager.latest_epoch() if resume else None


def save_agreed(manager: "CheckpointManager", state: Any, epoch: int,
                mesh=None, per_rank: bool = False,
                extra: Optional[dict] = None, layouts=None,
                plan=None) -> None:
    """Multi-process-safe checkpoint save with an agreed commit barrier.

    ``per_rank=False`` (replicated state — coefficients, centroids, EM
    stats, identical on every host): rank 0 writes to the shared
    directory (each rank writing its own copy would race on the atomic
    rename). ``per_rank=True`` (rank-local state — GBT's per-row
    margins): every rank writes to its own rank-scoped directory
    (:func:`rank_scoped`). Either way, the agreement IS the commit
    barrier: no rank trains past an uncommitted snapshot (the
    crash-resume contract; the role of the reference's two-phase
    checkpoint commit, ``Checkpoints.java:43-211``), and a write failure
    aborts EVERY rank together — a bare barrier would strand the others
    when the writing rank raises before reaching it. Single-process this
    is exactly ``manager.save`` (async write preserved; no barrier).
    """
    # layouts/plan are forwarded only when set: None already means
    # replicated, and manager subclasses predating layout tags keep
    # working.
    kw = {} if layouts is None else {"layouts": layouts}
    if plan is not None:
        kw["plan"] = plan
    if jax.process_count() == 1:
        manager.save(state, epoch, extra=extra, **kw)
        return
    from flinkml_tpu.iteration.stream_sync import agree_all_ok

    err = None
    if per_rank or jax.process_index() == 0:
        try:
            manager.save(state, epoch, extra=extra, **kw)
            manager.wait()  # durable before anyone trains past it
        except Exception as e:  # noqa: BLE001 — agreed below
            err = e
    try:
        agree_all_ok(err is None, mesh, "checkpoint commit")
    except ValueError:
        if err is not None:
            raise err
        raise


def save_replicated(manager: "CheckpointManager", state: Any, epoch: int,
                    mesh=None, extra: Optional[dict] = None) -> None:
    """Rank-0-writes commit of a REPLICATED state (see :func:`save_agreed`).
    The default layout tag already records every leaf as replicated."""
    save_agreed(manager, state, epoch, mesh, per_rank=False, extra=extra)


def rank_scoped(manager: "CheckpointManager") -> "CheckpointManager":
    """A per-process view of a shared checkpoint directory, for trainers
    whose snapshot includes RANK-LOCAL state (GBT's per-row margins and
    node assignments live on the rank that owns those rows): every rank
    saves its own shard under ``<dir>/rank-<i>``, so saves never collide
    on the shared filesystem and each rank restores exactly its rows.
    Single-process: returns the manager unchanged. Commit ordering across
    ranks is the caller's job (agree the save outcome — see the GBT
    snapshot path).

    ``max_to_keep`` is floored at 2: a crash between one rank's save of
    epoch e+1 (which, at keep=1, prunes its epoch e) and the agreed
    commit on the others would otherwise leave the ranks with DISJOINT
    epoch sets — no common epoch to resume from, all progress lost."""
    if jax.process_count() == 1:
        return manager
    return CheckpointManager(
        os.path.join(manager.directory, f"rank-{jax.process_index()}"),
        max_to_keep=max(manager.max_to_keep, 2),
        rescale=manager.rescale_policy,
        world_size=manager.world_size,
        async_write=manager.async_write,
    )


def should_snapshot(manager: Optional["CheckpointManager"], interval: int,
                    step: int, total: int, terminal: bool = False) -> bool:
    """Step 2 of the protocol — the save cadence: snapshot every
    ``interval`` completed steps, and ALWAYS at the run's end (the final
    step, or a ``terminal=True`` early stop) whenever a manager is
    configured — even with ``interval=0`` — so a finished run always
    leaves its terminal snapshot and resumes as a no-op (the linear
    family's documented contract, now uniform). ``step`` counts
    completed units (1-based), ``total`` is the run length in the same
    units."""
    if manager is None:
        return False
    if terminal or step == total:
        return True
    return interval > 0 and step % interval == 0


class CheckpointManager:
    """Numbered checkpoints of an arbitrary pytree under one directory.

    Each checkpoint records the world size that wrote it AND a per-leaf
    layout tag (``replicated`` / ``sharded:<axis>`` / ``per_rank`` — see
    the module layout notes); what happens when the restoring mesh's
    world size differs is governed by ``rescale`` (a
    :class:`RescalePolicy`): the default rejects with a typed
    :class:`RescaleError` (the reference's recovery guard,
    ``HeadOperator.java:130-146`` ``parallelismState``), while
    ``rescale="reshard"`` re-lays-out the carry — replicated leaves
    restore for free, sharded leaves revalidate against the new rank
    count, and only genuinely rank-entangled ``per_rank`` leaves refuse.
    ``allow_rescale=True`` remains as the legacy unvalidated escape
    hatch (equivalent to ``rescale="allow"``).

    ``world_size`` should be the device count of the mesh the loop runs
    on; it defaults to ``jax.device_count()``, which over-counts when
    training on a subset mesh. Contract: trainers that own a mesh
    (re-)pin ``manager.world_size`` to their mesh size at the start of
    every run, so a manager reused across meshes always guards against
    the mesh that actually wrote the checkpoint.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 allow_rescale: bool = False,
                 world_size: Optional[int] = None,
                 async_write: bool = False,
                 rescale=None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.rescale_policy = RescalePolicy.coerce(
            rescale if rescale is not None else allow_rescale
        )
        self.world_size = world_size
        self.async_write = async_write
        self._executor: Optional[ThreadPoolExecutor] = None
        self._pending: Optional[Future] = None
        #: The ``extra`` manifest dict of the snapshot the most recent
        #: successful :meth:`restore` returned (``{}`` before any
        #: restore). The iteration runtime reads the input-pipeline
        #: cursor (``data_cursor``) from here after ``restore_latest``.
        self.last_restored_extra: dict = {}
        os.makedirs(directory, exist_ok=True)

    def _world_size(self) -> int:
        return self.world_size if self.world_size is not None else jax.device_count()

    @property
    def allow_rescale(self) -> bool:
        """Legacy view of the policy: True when a world-size mismatch
        does not hard-reject (``allow`` or ``reshard``)."""
        return self.rescale_policy.on_mismatch != "reject"

    def _layout_list(self, layouts, num_leaves: int,
                     treedef) -> List[str]:
        """Normalize ``layouts`` (None | one tag for every leaf | a
        pytree of tags matching ``state``) to a validated per-leaf
        list. None means replicated — the dominant carry layout here
        (and what pre-layout snapshots are interpreted as)."""
        if layouts is None:
            return [LAYOUT_REPLICATED] * num_leaves
        if isinstance(layouts, str):
            _parse_layout(layouts)
            return [layouts] * num_leaves
        tag_leaves, tag_def = jax.tree_util.tree_flatten(layouts)
        if tag_def != treedef:
            raise ValueError(
                "layouts pytree structure does not match the state: "
                f"{tag_def} vs {treedef}"
            )
        for tag in tag_leaves:
            _parse_layout(tag)
        return list(tag_leaves)

    def _plan_layouts(self, plan, state, layouts, treedef,
                      num_leaves: int):
        """Derive the per-leaf layout tags from a ShardingPlan (the
        authoritative source), and verify any explicit ``layouts=``
        override agrees — :class:`LayoutConflictError` otherwise.
        Returns the derived tag pytree (state-shaped)."""
        from flinkml_tpu.sharding.plan import layouts_for, state_names

        derived_tree = layouts_for(plan, state)
        derived = list(jax.tree_util.tree_flatten(derived_tree)[0])
        for tag in derived:
            _parse_layout(tag)
        if layouts is not None:
            explicit = self._layout_list(layouts, num_leaves, treedef)
            if explicit != derived:
                names = [n for n, _ in state_names(state)]
                for i, (d, e) in enumerate(zip(derived, explicit)):
                    if d != e:
                        raise LayoutConflictError(
                            f"explicit layouts= disagree with plan "
                            f"{plan.name!r} at leaf {i} "
                            f"({names[i] if i < len(names) else '?'}): "
                            f"plan derives {d!r}, caller passed {e!r}. "
                            "The plan is authoritative — drop the "
                            "layouts= override or fix the plan."
                        )
        return derived_tree

    # -- save --------------------------------------------------------------
    def save(self, state: Any, epoch: int, extra: Optional[dict] = None,
             layouts=None, plan=None) -> str:
        """Snapshot ``state`` at ``epoch``. ``layouts`` tags each leaf's
        world-size relationship (see the module layout notes) — None
        records every leaf as ``replicated``.

        ``plan`` (a :class:`~flinkml_tpu.sharding.plan.ShardingPlan`) is
        the AUTHORITATIVE layout source when given: tags are derived
        from the plan's family table (``sharded:<dim>`` per sharded
        family), so plan-sharded training and elastic resharded resume
        share one source of truth. An explicit ``layouts=`` passed
        alongside a plan must agree exactly — a conflicting override
        raises :class:`LayoutConflictError` instead of silently
        shipping stale hand-written tags into the next resume.

        With ``async_write=True`` the device→host transfer happens here
        (so the snapshot is consistent) but serialization + the atomic
        publish run on a background thread, overlapping checkpoint IO
        with the next training chunk (the orbax-style async pattern; the
        reference overlaps the same way via Flink's async snapshots). At
        most one write is in flight — a new save first drains the
        previous one, re-raising any failure.
        """
        leaves, treedef = jax.tree_util.tree_flatten(state)
        if plan is not None:
            layouts = self._plan_layouts(plan, state, layouts, treedef,
                                         len(leaves))
        if self.async_write:
            # np.asarray is a zero-copy VIEW for numpy inputs; the caller
            # may mutate those buffers while the background write runs,
            # so async snapshots must own their memory.
            host_leaves = [np.array(leaf) for leaf in leaves]
        else:
            host_leaves = [np.asarray(leaf) for leaf in leaves]
        meta = {
            "epoch": int(epoch),
            "num_leaves": len(host_leaves),
            "treedef": str(treedef),
            "world_size": self._world_size(),
            "layouts": self._layout_list(layouts, len(host_leaves), treedef),
            "extra": extra or {},
        }
        final_dir = os.path.join(self.directory, f"ckpt-{epoch}")
        if not self.async_write:
            self._write(host_leaves, meta, final_dir)
            return final_dir
        self.wait()  # serialize in-flight writes; surface prior failures
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-write"
            )
        self._pending = self._executor.submit(
            self._write, host_leaves, meta, final_dir
        )
        return final_dir

    def wait(self) -> None:
        """Block until the in-flight async write (if any) has committed;
        re-raises its exception. No-op for synchronous managers."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def close(self) -> None:
        """Drain the in-flight write and release the writer thread.
        Idempotent; the manager stays usable (a later save re-creates
        the executor)."""
        try:
            self.wait()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None

    def _write(self, host_leaves, meta, final_dir) -> None:
        # Fingerprint here, not in save(): for async managers this runs on
        # the writer thread, keeping the sha256 off the training loop.
        meta = dict(meta, fingerprint=_leaves_fingerprint(host_leaves))
        tmp_dir = tempfile.mkdtemp(dir=self.directory, prefix=".tmp-ckpt-")
        try:
            np.savez(
                os.path.join(tmp_dir, "arrays.npz"),
                **{f"leaf_{i}": leaf for i, leaf in enumerate(host_leaves)},
            )
            with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
                json.dump(meta, f)
            if faults.ACTIVE is not None:  # torn-write seam: pre-commit
                faults.fire("checkpoint.write", epoch=meta["epoch"],
                            directory=self.directory, path=tmp_dir)
            if os.path.exists(final_dir):
                shutil.rmtree(final_dir)
            os.rename(tmp_dir, final_dir)  # atomic publish
        except Exception:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        _log.info("checkpoint committed: epoch %s -> %s (%d leaves)",
                  meta["epoch"], final_dir, meta["num_leaves"])
        if faults.ACTIVE is not None:  # kill/corrupt-after-commit seam
            faults.fire("checkpoint.committed", epoch=meta["epoch"],
                        directory=self.directory, path=final_dir)
        self._prune()

    # -- restore -----------------------------------------------------------
    def all_epochs(self) -> List[int]:
        self.wait()  # readers always see the committed state
        return self._list_epochs()

    def _list_epochs(self) -> List[int]:
        """Directory listing without draining the writer — safe to call
        from inside the background write itself (``_prune``); ``wait()``
        here would self-join the in-flight future and deadlock."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt-"):
                try:
                    out.append(int(name[len("ckpt-") :]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_epoch(self) -> Optional[int]:
        epochs = self.all_epochs()
        return epochs[-1] if epochs else None

    def _read_meta(self, ckpt_dir: str) -> dict:
        """The verified manifest of a committed snapshot (raises
        :class:`CheckpointIntegrityError` on damage)."""
        try:
            with open(os.path.join(ckpt_dir, "meta.json")) as f:
                meta = json.load(f)
            if not isinstance(meta, dict) or "num_leaves" not in meta:
                raise CheckpointIntegrityError(
                    f"checkpoint manifest at {ckpt_dir} is not a valid "
                    "snapshot manifest"
                )
        except (OSError, ValueError) as e:
            if isinstance(e, CheckpointIntegrityError):
                raise
            raise CheckpointIntegrityError(
                f"checkpoint manifest at {ckpt_dir} is unreadable: {e!r}"
            ) from e
        return meta

    def _read_leaves(self, ckpt_dir: str, meta: dict) -> List[np.ndarray]:
        """The fingerprint-verified host leaves of a committed snapshot
        (raises :class:`CheckpointIntegrityError` on damage)."""
        try:
            with np.load(os.path.join(ckpt_dir, "arrays.npz")) as z:
                host_leaves = [
                    z[f"leaf_{i}"] for i in range(meta["num_leaves"])
                ]
        except Exception as e:  # noqa: BLE001 — any load failure is damage
            raise CheckpointIntegrityError(
                f"checkpoint arrays at {ckpt_dir} are unloadable "
                f"(torn write or disk corruption): {e!r}"
            ) from e
        recorded = meta.get("fingerprint")
        if recorded is not None:
            actual = _leaves_fingerprint(host_leaves)
            if actual != recorded:
                raise CheckpointIntegrityError(
                    f"checkpoint at {ckpt_dir} fails integrity verification "
                    f"(recorded fingerprint {recorded[:12]}..., actual "
                    f"{actual[:12]}...): the persisted arrays were modified "
                    "after commit"
                )
        return host_leaves

    def _rescale_error(self, ckpt_dir: str, meta: dict, outcome: str
                       ) -> RescaleError:
        """Build (and log, rank-tagged) the triage-ready rescale
        refusal: snapshot dir + epoch + both worlds + policy outcome."""
        msg = (
            f"cannot restore checkpoint {ckpt_dir} (epoch "
            f"{meta.get('epoch')}): snapshot was written at world_size="
            f"{meta.get('world_size')} but the restoring mesh has "
            f"world_size={self._world_size()}; RescalePolicy("
            f"{self.rescale_policy.on_mismatch!r}) outcome: {outcome}. "
            "Pass rescale='reshard' for layout-tagged elastic resume, or "
            "rescale='allow' only if every carry leaf is "
            "world-independent (reference parity: "
            "HeadOperator.java:130-146)."
        )
        _log.error("%s", msg)
        return RescaleError(msg)

    def _reshard_leaves(self, host_leaves: List[np.ndarray], meta: dict,
                        ckpt_dir: str) -> List[np.ndarray]:
        """The ``reshard`` policy's re-layout: replicated leaves pass
        through untouched (world-independent by definition), sharded
        leaves keep their assembled global value but are revalidated
        against the new rank count (the re-split at placement must come
        out even), and ``per_rank`` leaves — genuinely rank-entangled —
        refuse. Rank-scoped per-rank snapshot FAMILIES reassemble via
        :func:`reshard_rank_state` instead."""
        new_world = self._world_size()
        layouts = meta.get("layouts") or [LAYOUT_REPLICATED] * len(host_leaves)
        counts = {"replicated": 0, "sharded": 0}
        for i, (leaf, tag) in enumerate(zip(host_leaves, layouts)):
            kind, axis = _parse_layout(tag)
            if kind == "per_rank":
                raise self._rescale_error(
                    ckpt_dir, meta,
                    f"leaf {i} is per_rank (rank-entangled state cannot "
                    "be re-laid-out; reassemble the rank-scoped family "
                    "with reshard_rank_state, or resume at the original "
                    "world)",
                )
            if kind == "sharded":
                extent = (np.asarray(leaf).shape[axis]
                          if axis < np.asarray(leaf).ndim else -1)
                if extent < 0 or extent % new_world != 0:
                    raise self._rescale_error(
                        ckpt_dir, meta,
                        f"leaf {i} is sharded:{axis} with extent {extent}, "
                        f"which does not divide across {new_world} ranks",
                    )
            counts[kind] += 1
        _log.info(
            "resharded restore: %s (epoch %s) world %s -> %s "
            "(%d replicated, %d sharded leaves re-laid-out)",
            ckpt_dir, meta.get("epoch"), meta.get("world_size"),
            new_world, counts["replicated"], counts["sharded"],
        )
        return host_leaves

    def restore(self, epoch: int, like: Any) -> Tuple[Any, int]:
        """Restore the checkpoint at ``epoch``; ``like`` provides the pytree
        structure (e.g. the init state).

        Restore is verified: an unreadable manifest, missing/unloadable
        arrays, or a content-fingerprint mismatch raise
        :class:`CheckpointIntegrityError` (the signal
        :meth:`restore_latest` uses to fall back to an older snapshot).
        A world-size mismatch is governed by the manager's
        :class:`RescalePolicy`: rejected with a typed
        :class:`RescaleError` by default (a configuration error —
        silently restoring an OLDER epoch under the wrong parallelism
        would be worse), re-laid-out per the snapshot's leaf layout tags
        under ``rescale="reshard"``, or passed through unvalidated under
        the legacy ``rescale="allow"``.
        """
        self.wait()
        ckpt_dir = os.path.join(self.directory, f"ckpt-{epoch}")
        meta = self._read_meta(ckpt_dir)
        saved_world = meta.get("world_size")
        rescaling = (
            saved_world is not None and saved_world != self._world_size()
        )
        if rescaling and self.rescale_policy.on_mismatch == "reject":
            raise self._rescale_error(
                ckpt_dir, meta, "rejected (rescaling an in-flight "
                "iteration is refused by policy)",
            )
        host_leaves = self._read_leaves(ckpt_dir, meta)
        if rescaling:
            if self.rescale_policy.on_mismatch == "reshard":
                host_leaves = self._reshard_leaves(host_leaves, meta,
                                                   ckpt_dir)
            else:  # "allow" — the legacy unvalidated escape hatch
                _log.warning(
                    "rescaled restore WITHOUT layout validation: %s "
                    "(epoch %s) world %s -> %s (policy 'allow')",
                    ckpt_dir, meta.get("epoch"), saved_world,
                    self._world_size(),
                )
        treedef = jax.tree_util.tree_structure(like)
        if treedef.num_leaves != len(host_leaves):
            raise ValueError(
                f"checkpoint has {len(host_leaves)} leaves but the provided "
                f"structure has {treedef.num_leaves}"
            )
        state = jax.tree_util.tree_unflatten(treedef, host_leaves)
        self.last_restored_extra = meta.get("extra") or {}
        return state, int(meta["epoch"])

    def verify(self, epoch: int) -> bool:
        """Integrity verification WITHOUT a restore: True when the
        snapshot at ``epoch`` has a readable manifest, loadable arrays,
        and a matching content fingerprint. Pytree-structure- and
        world-size-independent — this is what elastic survivors use to
        nominate snapshots before agreeing a resume point
        (:func:`flinkml_tpu.parallel.distributed.agree_resume_epoch`)."""
        self._drain_quietly()
        ckpt_dir = os.path.join(self.directory, f"ckpt-{epoch}")
        try:
            meta = self._read_meta(ckpt_dir)
            self._read_leaves(ckpt_dir, meta)
        except CheckpointIntegrityError:
            return False
        return True

    def newest_valid_epoch(self) -> Optional[int]:
        """The newest epoch that passes :meth:`verify` (None when the
        directory holds no valid snapshot)."""
        self._drain_quietly()
        for epoch in reversed(self.all_epochs()):
            if self.verify(epoch):
                return epoch
        return None

    def read_extra(self, epoch: int) -> dict:
        """The ``extra`` manifest of the committed snapshot at ``epoch``
        WITHOUT restoring arrays — pytree-structure-independent, so
        consumers of sidecar records (the data cursor, the quarantine
        ledger) need no knowledge of the carry's shape. Raises
        :class:`CheckpointIntegrityError` on a damaged manifest."""
        self._drain_quietly()
        ckpt_dir = os.path.join(self.directory, f"ckpt-{int(epoch)}")
        return self._read_meta(ckpt_dir).get("extra") or {}

    def discard(self, epoch: int) -> None:
        """Remove the committed snapshot at ``epoch``. For snapshots
        known to be WORSE than absent — e.g. the recovery engine's
        rollback walk-back found a non-finite carry committed inside a
        sentinel interval window: left on disk it would be the newest
        epoch a finiteness-unaware ``restore_latest`` hands a resumed
        run. Logged; idempotent."""
        self.wait()
        path = os.path.join(self.directory, f"ckpt-{int(epoch)}")
        shutil.rmtree(path, ignore_errors=True)
        _log.warning("checkpoint discarded: epoch %s (%s)", epoch, path)

    def _drain_quietly(self) -> None:
        """Drain a pending async write WITHOUT re-raising its failure —
        a parked write error belongs to ``save()``, not to the
        committed on-disk state the verification queries inspect (the
        crash path they exist for is precisely "the last write died");
        it is logged, and the queries report what IS on disk."""
        try:
            self.wait()
        except Exception as e:  # noqa: BLE001 — the write's failure, logged
            _log.warning(
                "pending checkpoint write failed (%r); verifying the "
                "committed snapshots anyway", e,
            )

    def restore_latest(self, like: Any) -> Optional[Tuple[Any, int]]:
        """Restore the newest snapshot that passes integrity verification,
        walking backwards past torn/corrupt ones (each fallback is
        logged). Returns None when the directory holds no checkpoints;
        raises :class:`CheckpointIntegrityError` when checkpoints exist
        but NONE survives verification — losing all progress must be an
        explicit decision, never a silent fresh start."""
        epochs = self.all_epochs()
        failures = []
        for epoch in reversed(epochs):
            try:
                return self.restore(epoch, like)
            except CheckpointIntegrityError as e:
                failures.append((epoch, e))
                _log.warning(
                    "checkpoint epoch %s failed verification (%s); falling "
                    "back to the previous snapshot", epoch, e,
                )
        if failures:
            raise CheckpointIntegrityError(
                f"no valid checkpoint under {self.directory}: all of epochs "
                f"{[e for e, _ in failures]} failed verification "
                f"(newest failure: {failures[0][1]})"
            )
        return None

    def _prune(self) -> None:
        epochs = self._list_epochs()
        for epoch in epochs[: -self.max_to_keep]:
            _log.info("pruning checkpoint epoch %s (max_to_keep=%d)",
                      epoch, self.max_to_keep)
            shutil.rmtree(
                os.path.join(self.directory, f"ckpt-{epoch}"), ignore_errors=True
            )


# -- elastic re-layout of rank-scoped snapshot families ----------------------


def _rank_dirs(directory: str) -> List[Tuple[int, str]]:
    """The ``rank-<i>`` subdirectories of a shared checkpoint root (the
    :func:`rank_scoped` layout), sorted by rank."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if name.startswith("rank-"):
            try:
                out.append((int(name[len("rank-"):]),
                            os.path.join(directory, name)))
            except ValueError:
                continue
    return sorted(out)


def reshard_rank_state(directory: str, epoch: int, like: Any,
                       new_shard: Tuple[int, int],
                       layouts=None) -> Any:
    """Reassemble-and-resplit a :func:`rank_scoped` snapshot family.

    Reads every ``rank-<i>`` subdirectory's snapshot at ``epoch`` (the
    old world = the number of rank directories), then re-lays-out each
    leaf for ``new_shard = (new_rank, new_world)`` per its recorded
    layout tag:

    - ``replicated``: every rank must hold the identical value (verified
      bit-exact); the reassembled value is that value;
    - ``sharded:<axis>``: the per-rank chunks concatenate in rank order
      into the global array, which is re-split into ``new_world`` equal
      chunks along ``axis`` — ``new_rank``'s chunk is returned (the
      global extent must divide ``new_world`` evenly, else
      :class:`RescaleError`);
    - ``per_rank``: genuinely rank-entangled — :class:`RescaleError`.

    ``layouts`` overrides the tags recorded in the snapshots (same
    pytree/str convention as :meth:`CheckpointManager.save`).

    Returns the re-laid-out state pytree for the new rank. This is the
    state-re-layout primitive the elastic shrink path composes with
    :func:`~flinkml_tpu.parallel.distributed.agree_resume_epoch`.
    """
    new_rank, new_world = int(new_shard[0]), int(new_shard[1])
    if new_world < 1 or not (0 <= new_rank < new_world):
        raise ValueError(f"invalid new shard assignment {new_shard!r}")
    ranks = _rank_dirs(directory)
    if not ranks:
        raise ValueError(
            f"no rank-scoped snapshot family under {directory} "
            "(expected rank-<i> subdirectories)"
        )
    if [r for r, _ in ranks] != list(range(len(ranks))):
        raise RescaleError(
            f"rank-scoped family under {directory} is not contiguous "
            f"(found ranks {[r for r, _ in ranks]}); a missing rank's "
            "shard cannot be reassembled"
        )
    old_world = len(ranks)
    treedef = jax.tree_util.tree_structure(like)
    per_rank_leaves: List[List[np.ndarray]] = []
    metas = []
    for _, rank_dir in ranks:
        mgr = CheckpointManager(rank_dir, rescale="allow")
        ckpt_dir = os.path.join(rank_dir, f"ckpt-{epoch}")
        meta = mgr._read_meta(ckpt_dir)
        leaves = mgr._read_leaves(ckpt_dir, meta)
        if len(leaves) != treedef.num_leaves:
            raise ValueError(
                f"rank snapshot {ckpt_dir} has {len(leaves)} leaves but "
                f"the provided structure has {treedef.num_leaves}"
            )
        per_rank_leaves.append(leaves)
        metas.append(meta)
    tags = (
        CheckpointManager(directory, rescale="allow")._layout_list(
            layouts, treedef.num_leaves, treedef
        )
        if layouts is not None
        else (metas[0].get("layouts")
              or [LAYOUT_REPLICATED] * treedef.num_leaves)
    )
    out_leaves: List[np.ndarray] = []
    for i, tag in enumerate(tags):
        kind, axis = _parse_layout(tag)
        chunks = [np.asarray(leaves[i]) for leaves in per_rank_leaves]
        if kind == "per_rank":
            raise RescaleError(
                f"leaf {i} of the family under {directory} (epoch "
                f"{epoch}) is per_rank: rank-entangled state has no "
                f"global assembly — world {old_world} -> {new_world} "
                "resume must rebuild it from data"
            )
        if kind == "replicated":
            for r, chunk in enumerate(chunks[1:], start=1):
                if not np.array_equal(chunk, chunks[0]):
                    raise RescaleError(
                        f"replicated leaf {i} diverges between rank 0 "
                        f"and rank {r} under {directory} (epoch {epoch}):"
                        " the family is not a consistent snapshot"
                    )
            out_leaves.append(chunks[0])
            continue
        global_arr = np.concatenate(chunks, axis=axis)
        extent = global_arr.shape[axis]
        if extent % new_world != 0:
            raise RescaleError(
                f"sharded leaf {i} under {directory} (epoch {epoch}) has "
                f"global extent {extent} along axis {axis}, which does "
                f"not divide across {new_world} ranks"
            )
        out_leaves.append(
            np.split(global_arr, new_world, axis=axis)[new_rank]
        )
    _log.info(
        "reshard_rank_state: %s epoch %s world %d -> %d (rank %d), "
        "%d leaves re-laid-out", directory, epoch, old_world, new_world,
        new_rank, len(out_leaves),
    )
    return jax.tree_util.tree_unflatten(treedef, out_leaves)
