"""Loop-carry checkpointing: snapshot/restore of (state pytree, epoch).

Parity mapping (SURVEY.md §5 checkpoint/resume): the reference's deepest
subsystem — aligned barriers, feedback-record logging between alignment and
the tail's BARRIER record (``Checkpoints.java:43-211``), coordinator-
serialized snapshots, per-round live-instance tracking — exists because its
loop state lives *inside a running dataflow*. Here the loop state is an
explicit pytree on the host/device boundary, so a checkpoint is: pull the
carry to host, write arrays + a JSON manifest atomically, done. Restore is
exact (bit-identical arrays, epoch, rng keys included in the carry).

Layout per checkpoint: ``<dir>/ckpt-<epoch>/arrays.npz`` + ``meta.json``;
a checkpoint is visible only after an atomic rename, so a kill mid-write
never corrupts the latest checkpoint (the fault-tolerance contract the
reference gets from Flink's two-phase checkpoint commit).

Defensive restore (ISSUE 4): every snapshot records a sha256 content
fingerprint over its arrays (the scheme of
``io.read_write.content_fingerprint``), and :meth:`CheckpointManager.
restore_latest` verifies manifest + arrays + fingerprint, falling back to
the newest VALID snapshot when the latest is torn or corrupt —
:class:`CheckpointIntegrityError` only when no snapshot survives. Fault
seams (``checkpoint.write`` / ``checkpoint.committed``,
:mod:`flinkml_tpu.faults`) let tests script torn writes and
kill-after-commit deterministically.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

import flinkml_tpu.faults as faults
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("checkpoint")


class CheckpointIntegrityError(ValueError):
    """A committed checkpoint failed restore-time verification — its
    manifest is unreadable, its arrays are missing/unloadable, or the
    content fingerprint does not match. ``restore_latest`` treats this as
    "try the previous snapshot"; it surfaces only when every snapshot is
    damaged."""


def _leaves_fingerprint(host_leaves) -> str:
    """The PR 3 content-fingerprint scheme applied to checkpoint leaves
    (names/dtypes/shapes/bytes all contribute)."""
    from flinkml_tpu.io.read_write import content_fingerprint

    return content_fingerprint(
        {f"leaf_{i}": leaf for i, leaf in enumerate(host_leaves)}
    )


def begin_resume(manager: Optional["CheckpointManager"], resume: bool,
                 world_size: int) -> Optional[int]:
    """Step 1 of the streamed-trainer checkpoint protocol (shared by the
    linear/KMeans/GBT/GMM streamed fits): validate the resume/manager
    pairing and pin the rescale guard to the mesh that trains (NOT the
    process-global device count). Returns the epoch to restore from, or
    None for a fresh start — returned *before* any data pass so callers
    can skip init work (reservoir sampling, seeding) whose result a
    restore would discard."""
    if resume and manager is None:
        raise ValueError("resume=True requires a checkpoint_manager")
    if manager is None:
        return None
    manager.world_size = world_size
    return manager.latest_epoch() if resume else None


def save_agreed(manager: "CheckpointManager", state: Any, epoch: int,
                mesh=None, per_rank: bool = False,
                extra: Optional[dict] = None) -> None:
    """Multi-process-safe checkpoint save with an agreed commit barrier.

    ``per_rank=False`` (replicated state — coefficients, centroids, EM
    stats, identical on every host): rank 0 writes to the shared
    directory (each rank writing its own copy would race on the atomic
    rename). ``per_rank=True`` (rank-local state — GBT's per-row
    margins): every rank writes to its own rank-scoped directory
    (:func:`rank_scoped`). Either way, the agreement IS the commit
    barrier: no rank trains past an uncommitted snapshot (the
    crash-resume contract; the role of the reference's two-phase
    checkpoint commit, ``Checkpoints.java:43-211``), and a write failure
    aborts EVERY rank together — a bare barrier would strand the others
    when the writing rank raises before reaching it. Single-process this
    is exactly ``manager.save`` (async write preserved; no barrier).
    """
    if jax.process_count() == 1:
        manager.save(state, epoch, extra=extra)
        return
    from flinkml_tpu.iteration.stream_sync import agree_all_ok

    err = None
    if per_rank or jax.process_index() == 0:
        try:
            manager.save(state, epoch, extra=extra)
            manager.wait()  # durable before anyone trains past it
        except Exception as e:  # noqa: BLE001 — agreed below
            err = e
    try:
        agree_all_ok(err is None, mesh, "checkpoint commit")
    except ValueError:
        if err is not None:
            raise err
        raise


def save_replicated(manager: "CheckpointManager", state: Any, epoch: int,
                    mesh=None, extra: Optional[dict] = None) -> None:
    """Rank-0-writes commit of a REPLICATED state (see :func:`save_agreed`)."""
    save_agreed(manager, state, epoch, mesh, per_rank=False, extra=extra)


def rank_scoped(manager: "CheckpointManager") -> "CheckpointManager":
    """A per-process view of a shared checkpoint directory, for trainers
    whose snapshot includes RANK-LOCAL state (GBT's per-row margins and
    node assignments live on the rank that owns those rows): every rank
    saves its own shard under ``<dir>/rank-<i>``, so saves never collide
    on the shared filesystem and each rank restores exactly its rows.
    Single-process: returns the manager unchanged. Commit ordering across
    ranks is the caller's job (agree the save outcome — see the GBT
    snapshot path).

    ``max_to_keep`` is floored at 2: a crash between one rank's save of
    epoch e+1 (which, at keep=1, prunes its epoch e) and the agreed
    commit on the others would otherwise leave the ranks with DISJOINT
    epoch sets — no common epoch to resume from, all progress lost."""
    if jax.process_count() == 1:
        return manager
    return CheckpointManager(
        os.path.join(manager.directory, f"rank-{jax.process_index()}"),
        max_to_keep=max(manager.max_to_keep, 2),
        allow_rescale=manager.allow_rescale,
        world_size=manager.world_size,
        async_write=manager.async_write,
    )


def should_snapshot(manager: Optional["CheckpointManager"], interval: int,
                    step: int, total: int, terminal: bool = False) -> bool:
    """Step 2 of the protocol — the save cadence: snapshot every
    ``interval`` completed steps, and ALWAYS at the run's end (the final
    step, or a ``terminal=True`` early stop) whenever a manager is
    configured — even with ``interval=0`` — so a finished run always
    leaves its terminal snapshot and resumes as a no-op (the linear
    family's documented contract, now uniform). ``step`` counts
    completed units (1-based), ``total`` is the run length in the same
    units."""
    if manager is None:
        return False
    if terminal or step == total:
        return True
    return interval > 0 and step % interval == 0


class CheckpointManager:
    """Numbered checkpoints of an arbitrary pytree under one directory.

    Each checkpoint records the world size that wrote it; restoring under
    a different world size raises unless ``allow_rescale=True`` — the
    reference's recovery guard (``HeadOperator.java:130-146``
    ``parallelismState``: rescaling an in-flight iteration is explicitly
    rejected, because sharded loop carries and data shards are laid out
    for a specific parallelism).

    ``world_size`` should be the device count of the mesh the loop runs
    on; it defaults to ``jax.device_count()``, which over-counts when
    training on a subset mesh. Contract: trainers that own a mesh
    (re-)pin ``manager.world_size`` to their mesh size at the start of
    every run, so a manager reused across meshes always guards against
    the mesh that actually wrote the checkpoint.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 allow_rescale: bool = False,
                 world_size: Optional[int] = None,
                 async_write: bool = False):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.allow_rescale = allow_rescale
        self.world_size = world_size
        self.async_write = async_write
        self._executor: Optional[ThreadPoolExecutor] = None
        self._pending: Optional[Future] = None
        #: The ``extra`` manifest dict of the snapshot the most recent
        #: successful :meth:`restore` returned (``{}`` before any
        #: restore). The iteration runtime reads the input-pipeline
        #: cursor (``data_cursor``) from here after ``restore_latest``.
        self.last_restored_extra: dict = {}
        os.makedirs(directory, exist_ok=True)

    def _world_size(self) -> int:
        return self.world_size if self.world_size is not None else jax.device_count()

    # -- save --------------------------------------------------------------
    def save(self, state: Any, epoch: int, extra: Optional[dict] = None) -> str:
        """Snapshot ``state`` at ``epoch``.

        With ``async_write=True`` the device→host transfer happens here
        (so the snapshot is consistent) but serialization + the atomic
        publish run on a background thread, overlapping checkpoint IO
        with the next training chunk (the orbax-style async pattern; the
        reference overlaps the same way via Flink's async snapshots). At
        most one write is in flight — a new save first drains the
        previous one, re-raising any failure.
        """
        leaves, treedef = jax.tree_util.tree_flatten(state)
        if self.async_write:
            # np.asarray is a zero-copy VIEW for numpy inputs; the caller
            # may mutate those buffers while the background write runs,
            # so async snapshots must own their memory.
            host_leaves = [np.array(leaf) for leaf in leaves]
        else:
            host_leaves = [np.asarray(leaf) for leaf in leaves]
        meta = {
            "epoch": int(epoch),
            "num_leaves": len(host_leaves),
            "treedef": str(treedef),
            "world_size": self._world_size(),
            "extra": extra or {},
        }
        final_dir = os.path.join(self.directory, f"ckpt-{epoch}")
        if not self.async_write:
            self._write(host_leaves, meta, final_dir)
            return final_dir
        self.wait()  # serialize in-flight writes; surface prior failures
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-write"
            )
        self._pending = self._executor.submit(
            self._write, host_leaves, meta, final_dir
        )
        return final_dir

    def wait(self) -> None:
        """Block until the in-flight async write (if any) has committed;
        re-raises its exception. No-op for synchronous managers."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def close(self) -> None:
        """Drain the in-flight write and release the writer thread.
        Idempotent; the manager stays usable (a later save re-creates
        the executor)."""
        try:
            self.wait()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None

    def _write(self, host_leaves, meta, final_dir) -> None:
        # Fingerprint here, not in save(): for async managers this runs on
        # the writer thread, keeping the sha256 off the training loop.
        meta = dict(meta, fingerprint=_leaves_fingerprint(host_leaves))
        tmp_dir = tempfile.mkdtemp(dir=self.directory, prefix=".tmp-ckpt-")
        try:
            np.savez(
                os.path.join(tmp_dir, "arrays.npz"),
                **{f"leaf_{i}": leaf for i, leaf in enumerate(host_leaves)},
            )
            with open(os.path.join(tmp_dir, "meta.json"), "w") as f:
                json.dump(meta, f)
            if faults.ACTIVE is not None:  # torn-write seam: pre-commit
                faults.fire("checkpoint.write", epoch=meta["epoch"],
                            directory=self.directory, path=tmp_dir)
            if os.path.exists(final_dir):
                shutil.rmtree(final_dir)
            os.rename(tmp_dir, final_dir)  # atomic publish
        except Exception:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        _log.info("checkpoint committed: epoch %s -> %s (%d leaves)",
                  meta["epoch"], final_dir, meta["num_leaves"])
        if faults.ACTIVE is not None:  # kill/corrupt-after-commit seam
            faults.fire("checkpoint.committed", epoch=meta["epoch"],
                        directory=self.directory, path=final_dir)
        self._prune()

    # -- restore -----------------------------------------------------------
    def all_epochs(self) -> List[int]:
        self.wait()  # readers always see the committed state
        return self._list_epochs()

    def _list_epochs(self) -> List[int]:
        """Directory listing without draining the writer — safe to call
        from inside the background write itself (``_prune``); ``wait()``
        here would self-join the in-flight future and deadlock."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt-"):
                try:
                    out.append(int(name[len("ckpt-") :]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_epoch(self) -> Optional[int]:
        epochs = self.all_epochs()
        return epochs[-1] if epochs else None

    def restore(self, epoch: int, like: Any) -> Tuple[Any, int]:
        """Restore the checkpoint at ``epoch``; ``like`` provides the pytree
        structure (e.g. the init state).

        Restore is verified: an unreadable manifest, missing/unloadable
        arrays, or a content-fingerprint mismatch raise
        :class:`CheckpointIntegrityError` (the signal
        :meth:`restore_latest` uses to fall back to an older snapshot).
        A world-size mismatch stays a plain ``ValueError`` — that is a
        configuration error, and silently restoring an OLDER epoch under
        the wrong parallelism would be worse than failing.
        """
        self.wait()
        ckpt_dir = os.path.join(self.directory, f"ckpt-{epoch}")
        try:
            with open(os.path.join(ckpt_dir, "meta.json")) as f:
                meta = json.load(f)
            if not isinstance(meta, dict) or "num_leaves" not in meta:
                raise CheckpointIntegrityError(
                    f"checkpoint manifest at {ckpt_dir} is not a valid "
                    "snapshot manifest"
                )
        except (OSError, ValueError) as e:
            if isinstance(e, CheckpointIntegrityError):
                raise
            raise CheckpointIntegrityError(
                f"checkpoint manifest at {ckpt_dir} is unreadable: {e!r}"
            ) from e
        saved_world = meta.get("world_size")
        if (
            saved_world is not None
            and saved_world != self._world_size()
            and not self.allow_rescale
        ):
            raise ValueError(
                f"checkpoint was written with {saved_world} devices but "
                f"{self._world_size()} are in the restoring mesh; rescaling an in-flight "
                "iteration is rejected (reference parity: "
                "HeadOperator.java:130-146). Pass allow_rescale=True only "
                "if the loop carry is replicated/device-count-independent."
            )
        try:
            with np.load(os.path.join(ckpt_dir, "arrays.npz")) as z:
                host_leaves = [
                    z[f"leaf_{i}"] for i in range(meta["num_leaves"])
                ]
        except Exception as e:  # noqa: BLE001 — any load failure is damage
            raise CheckpointIntegrityError(
                f"checkpoint arrays at {ckpt_dir} are unloadable "
                f"(torn write or disk corruption): {e!r}"
            ) from e
        recorded = meta.get("fingerprint")
        if recorded is not None:
            actual = _leaves_fingerprint(host_leaves)
            if actual != recorded:
                raise CheckpointIntegrityError(
                    f"checkpoint at {ckpt_dir} fails integrity verification "
                    f"(recorded fingerprint {recorded[:12]}..., actual "
                    f"{actual[:12]}...): the persisted arrays were modified "
                    "after commit"
                )
        treedef = jax.tree_util.tree_structure(like)
        if treedef.num_leaves != len(host_leaves):
            raise ValueError(
                f"checkpoint has {len(host_leaves)} leaves but the provided "
                f"structure has {treedef.num_leaves}"
            )
        state = jax.tree_util.tree_unflatten(treedef, host_leaves)
        self.last_restored_extra = meta.get("extra") or {}
        return state, int(meta["epoch"])

    def restore_latest(self, like: Any) -> Optional[Tuple[Any, int]]:
        """Restore the newest snapshot that passes integrity verification,
        walking backwards past torn/corrupt ones (each fallback is
        logged). Returns None when the directory holds no checkpoints;
        raises :class:`CheckpointIntegrityError` when checkpoints exist
        but NONE survives verification — losing all progress must be an
        explicit decision, never a silent fresh start."""
        epochs = self.all_epochs()
        failures = []
        for epoch in reversed(epochs):
            try:
                return self.restore(epoch, like)
            except CheckpointIntegrityError as e:
                failures.append((epoch, e))
                _log.warning(
                    "checkpoint epoch %s failed verification (%s); falling "
                    "back to the previous snapshot", epoch, e,
                )
        if failures:
            raise CheckpointIntegrityError(
                f"no valid checkpoint under {self.directory}: all of epochs "
                f"{[e for e, _ in failures]} failed verification "
                f"(newest failure: {failures[0][1]})"
            )
        return None

    def _prune(self) -> None:
        epochs = self._list_epochs()
        for epoch in epochs[: -self.max_to_keep]:
            _log.info("pruning checkpoint epoch %s (max_to_keep=%d)",
                      epoch, self.max_to_keep)
            shutil.rmtree(
                os.path.join(self.directory, f"ckpt-{epoch}"), ignore_errors=True
            )
